"""Text pipeline stages: tokenizer, n-grams, stop-words, count/hashing TF,
IDF, string indexing, similarity, language/MIME/email/name detection.

Reference stages replaced (core/.../stages/impl/feature/):
  * TextTokenizer.scala — Lucene per-language analyzers → locale-light regex
    tokenizer (utils/text.py) with the same defaults (lowercase, min length).
  * OpNGram.scala — Spark NGram: n-grams joined by spaces.
  * OpStopWordsRemover.scala — Spark StopWordsRemover (english defaults).
  * OpCountVectorizer.scala — Spark CountVectorizer (vocabSize, minDF).
  * OpHashingTF.scala — term hashing to a fixed width (murmur3).
  * (Spark IDF via sparkwrappers) — OpIDF estimator here.
  * OpStringIndexer{,NoFilter}.scala / OpIndexToString{,NoFilter}.scala —
    frequency-ordered label indexing and its inverse.
  * JaccardSimilarity.scala — |A∩B| / |A∪B| over token sets.
  * NGramSimilarity.scala — character-n-gram similarity (Lucene
    NGramDistance replaced by a Jaccard over char n-grams).
  * LangDetector.scala — Optimaize profiles → nlp/langid.py (script census
    + function-word/diacritic voting, ~55 languages; measured per-language
    accuracy in PARITY.md; same output shape RealMap[lang → confidence]).
  * MimeTypeDetector.scala — Tika → magic-byte table over common formats.
  * ValidEmailTransformer.scala — RFC-lite regex validation.
  * HumanNameDetector.scala / NameEntityRecognizer.scala — OpenNLP models →
    dictionary+shape heuristics emitting the same NameStats / entity-map
    shapes (documented divergence).
"""
from __future__ import annotations

import base64
import binascii
import re
from functools import lru_cache as _lru_cache
from typing import Any

import numpy as np

from ..stages.base import Estimator, Model, Transformer
from ..stages.metadata import ColumnMeta, VectorMetadata
from ..types import (
    Binary,
    MultiPickListMap,
    NameStats,
    OPVector,
    PickList,
    PickListMap,
    Real,
    RealMap,
    RealNN,
    Text,
    TextList,
)
from ..types.columns import (
    Column,
    ListColumn,
    MapColumn,
    NumericColumn,
    TextColumn,
    VectorColumn,
)
from ..featurize.interning import (
    InternedTextList,
    TokenCodes,
    interned_of,
    tokenize_text_column,
)
from ..utils.text import tokenize


class TextTokenizer(Transformer):
    """Text → TextList (TextTokenizer.scala; defaults ToLowercase=true,
    MinTokenLength=1, AutoDetectLanguage=false, DefaultLanguage=Unknown →
    the standard analyzer).

    With ``language`` set (or ``auto_detect_language``), tokens run through
    the per-language analyzer — stopword filter + stemmer matching the
    reference's Lucene analyzers for its 7 shipped languages
    (utils/analyzers.py; LuceneTextAnalyzer.scala:1-236)."""

    input_types = (Text,)
    output_type = TextList

    def __init__(
        self,
        to_lowercase: bool = True,
        min_token_length: int = 1,
        language: str | None = None,
        auto_detect_language: bool = False,
        uid: str | None = None,
    ):
        super().__init__("tokenized", uid=uid)
        self.to_lowercase = to_lowercase
        self.min_token_length = min_token_length
        self.language = language
        self.auto_detect_language = auto_detect_language

    def get_params(self):
        return {
            "to_lowercase": self.to_lowercase,
            "min_token_length": self.min_token_length,
            "language": self.language,
            "auto_detect_language": self.auto_detect_language,
        }

    def transform_columns(self, *cols: Column, num_rows: int) -> ListColumn:
        col = cols[0]
        assert isinstance(col, TextColumn)
        if self.language or self.auto_detect_language:
            from ..utils.analyzers import analyze

            out = [
                analyze(
                    v, language=self.language,
                    auto_detect=self.auto_detect_language,
                    to_lowercase=self.to_lowercase,
                    min_token_length=self.min_token_length,
                ) if v else []
                for v in col.values
            ]
            return ListColumn(TextList, out)
        # interned hot path: ONE native tokenize+intern pass over the
        # column; downstream text stages consume the code arrays and the
        # list-of-lists view only materializes if something asks for it
        return InternedTextList(
            TextList,
            tokenize_text_column(
                col.values, self.to_lowercase, self.min_token_length
            ),
        )


class OpNGram(Transformer):
    """TextList → TextList of space-joined n-grams (OpNGram.scala; Spark
    NGram default n=2)."""

    input_types = (TextList,)
    output_type = TextList

    def __init__(self, n: int = 2, uid: str | None = None):
        super().__init__("ngram", uid=uid)
        if n < 1:
            raise ValueError("n must be >= 1")
        self.n = n

    def get_params(self):
        return {"n": self.n}

    def transform_columns(self, *cols: Column, num_rows: int) -> ListColumn:
        col = cols[0]
        assert isinstance(col, ListColumn)
        n = self.n
        tc = interned_of(col)
        if n == 1:  # 1-grams are the tokens themselves
            return InternedTextList(TextList, tc)
        counts = tc.row_counts()
        out_counts = np.maximum(counts - (n - 1), 0)
        offsets = np.zeros(tc.num_rows + 1, dtype=np.int64)
        np.cumsum(out_counts, out=offsets[1:])
        total = int(offsets[-1])
        if total == 0:
            return InternedTextList(
                TextList, TokenCodes(np.zeros(0, np.int32), offsets, [])
            )
        # window start positions (global token index per emitted n-gram)
        starts = (
            np.arange(total, dtype=np.int64)
            - np.repeat(offsets[:-1], out_counts)
            + np.repeat(tc.offsets[:-1], out_counts)
        )
        windows = tc.codes[starts[:, None] + np.arange(n, dtype=np.int64)]
        uniq, inverse = np.unique(windows, axis=0, return_inverse=True)
        vocab_arr = tc.vocab_array()
        ngram_vocab = [" ".join(vocab_arr[win]) for win in uniq]
        return InternedTextList(
            TextList,
            TokenCodes(
                inverse.astype(np.int32, copy=False), offsets, ngram_vocab
            ),
        )


# Spark's StopWordsRemover english default list (org.apache.spark.ml.feature,
# itself from the public "Glasgow stop words" set) — abridged to the tokens
# that affect typical feature engineering.
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for from
further had hadn't has hasn't have haven't having he he'd he'll he's her here
here's hers herself him himself his how how's i i'd i'll i'm i've if in into
is isn't it it's its itself let's me more most mustn't my myself no nor not of
off on once only or other ought our ours ourselves out over own same shan't
she she'd she'll she's should shouldn't so some such than that that's the
their theirs them themselves then there there's these they they'd they'll
they're they've this those through to too under until up very was wasn't we
we'd we'll we're we've were weren't what what's when when's where where's
which while who who's whom why why's with won't would wouldn't you you'd
you'll you're you've your yours yourself yourselves
""".split())


class OpStopWordsRemover(Transformer):
    """TextList → TextList without stop words (OpStopWordsRemover.scala;
    Spark default: english, caseSensitive=false)."""

    input_types = (TextList,)
    output_type = TextList

    def __init__(
        self,
        stop_words=ENGLISH_STOP_WORDS,
        case_sensitive: bool = False,
        uid: str | None = None,
    ):
        super().__init__("stopWordsRemoved", uid=uid)
        self.stop_words = frozenset(stop_words)
        self.case_sensitive = case_sensitive
        self._lowered = frozenset(w.lower() for w in self.stop_words)
        #: token -> is-stop-word, filled lazily: the case-insensitive path
        #: lowercases each DISTINCT token at most once per process instead
        #: of every token on every transform call
        self._member_cache: dict[str, bool] = {}

    def get_params(self):
        return {
            "stop_words": sorted(self.stop_words),
            "case_sensitive": self.case_sensitive,
        }

    def _is_stop(self, token: str) -> bool:
        if self.case_sensitive:
            return token in self.stop_words
        got = self._member_cache.get(token)
        if got is None:
            if len(self._member_cache) >= 65536:
                # long-lived serving processes see unbounded distinct
                # tokens — bound the memo instead of leaking
                self._member_cache.clear()
            got = self._member_cache[token] = token.lower() in self._lowered
        return got

    def transform_columns(self, *cols: Column, num_rows: int) -> ListColumn:
        col = cols[0]
        assert isinstance(col, ListColumn)
        tc = interned_of(col)
        # membership is decided once per DISTINCT token (a boolean mask
        # over the batch vocabulary), then the drop is one vectorized
        # filter over the code array
        drop = np.fromiter(
            (self._is_stop(t) for t in tc.vocab), bool, len(tc.vocab)
        )
        if not drop.any():
            return InternedTextList(TextList, tc)
        keep = ~drop[tc.codes]
        kept_cum = np.zeros(len(keep) + 1, dtype=np.int64)
        np.cumsum(keep, out=kept_cum[1:])
        offsets = kept_cum[tc.offsets]
        return InternedTextList(
            TextList, TokenCodes(tc.codes[keep], offsets, tc.vocab)
        )


def _term_vector_metas(output_name: str, feature, vocab: list[str]):
    metas = tuple(
        ColumnMeta(
            parent_names=(feature.name,),
            parent_type=feature.ftype.__name__,
            grouping=feature.name,
            indicator_value=t,
            index=i,
        )
        for i, t in enumerate(vocab)
    )
    return VectorMetadata(output_name, metas)


class OpCountVectorizer(Estimator):
    """TextList → OPVector of term counts with a learned vocabulary
    (OpCountVectorizer.scala; Spark defaults vocabSize 2^18, minDF 1)."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(
        self,
        vocab_size: int = 1 << 18,
        min_df: float = 1.0,
        binary: bool = False,
        uid: str | None = None,
    ):
        super().__init__("countVectorized", uid=uid)
        self.vocab_size = vocab_size
        self.min_df = min_df
        self.binary = binary

    def get_params(self):
        return {
            "vocab_size": self.vocab_size,
            "min_df": self.min_df,
            "binary": self.binary,
        }

    def fit_model(self, dataset) -> "OpCountVectorizerModel":
        col = dataset[self.input_names[0]]
        assert isinstance(col, ListColumn)
        # interned fit: term frequency is one bincount over the code
        # array; document frequency one bincount over the distinct
        # (row, code) pairs — no per-row/token dict churn
        from ..featurize.kernels import distinct_pair_bincount

        tc = interned_of(col)
        nv = len(tc.vocab)
        tf = np.bincount(tc.codes, minlength=nv) if nv else np.zeros(0, int)
        if tc.num_tokens:
            df = distinct_pair_bincount(tc.row_index(), tc.codes, nv)
        else:
            df = np.zeros(nv, dtype=np.int64)
        n = len(col)
        min_docs = self.min_df if self.min_df >= 1 else self.min_df * n
        # d > 0: the shared interned vocabulary can carry tokens an
        # upstream stage filtered out of every row (e.g. stop words) —
        # the historical per-row df dict never saw those, so min_df <= 0
        # must not admit them
        terms = [t for t, d in zip(tc.vocab, df) if d >= min_docs and d > 0]
        # highest total frequency first, ties lexicographic (stable vocab)
        tf_of = {t: int(c) for t, c in zip(tc.vocab, tf)}
        terms.sort(key=lambda t: (-tf_of[t], t))
        vocab = terms[: self.vocab_size]
        self.metadata["vocabSize"] = len(vocab)
        return OpCountVectorizerModel(vocab, self.binary)


class OpCountVectorizerModel(Model):
    output_type = OPVector

    def __init__(self, vocab: list[str], binary: bool = False, uid: str | None = None):
        super().__init__("countVectorized", uid=uid)
        self.vocab = list(vocab)
        self.binary = binary
        self._index = {t: i for i, t in enumerate(self.vocab)}

    def get_params(self):
        return {"vocab": self.vocab, "binary": self.binary}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(params["vocab"], params.get("binary", False))

    def transform_columns(self, *cols: Column, num_rows: int) -> VectorColumn:
        from ..featurize import kernels as FK

        col = cols[0]
        assert isinstance(col, ListColumn)
        tc = interned_of(col)
        code_to_col = FK.map_vocab(tc.vocab, self._index)
        width = len(self.vocab)
        if width > FK.dense_vocab_max():
            # Spark-default vocab_size is 2^18: a dense [N, 2^18] float32
            # transform allocates ~1 GB per 1k rows — wide vocabularies
            # stay COO (the SparseMatrix path every assembler supports)
            values: Any = FK.term_count_sparse(
                tc, code_to_col, width, binary=self.binary
            )
        else:
            values = FK.term_count_block(
                tc, code_to_col, width, binary=self.binary
            )
        return VectorColumn(
            OPVector, values,
            _term_vector_metas(
                self.output_name, self.input_features[0], self.vocab
            ),
        )


class OpHashingTF(Transformer):
    """TextList → OPVector via term hashing (OpHashingTF.scala). Spark's
    default width is 2^18 over a sparse vector; this column is dense
    ([N, D] float32 shipping to device), so the default follows the
    Transmogrifier text-hash width (512, TransmogrifierDefaults
    DefaultNumOfFeatures) — pass num_features explicitly for more."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(
        self, num_features: int = 512, binary: bool = False, uid: str | None = None
    ):
        super().__init__("hashingTF", uid=uid)
        self.num_features = num_features
        self.binary = binary

    def get_params(self):
        return {"num_features": self.num_features, "binary": self.binary}

    def transform_columns(self, *cols: Column, num_rows: int) -> VectorColumn:
        from ..featurize import kernels as FK

        col = cols[0]
        assert isinstance(col, ListColumn)
        tc = interned_of(col)
        # each DISTINCT term is murmur3-hashed once; occurrences ride the
        # code array through the native bincount scatter
        bucket_of = FK.hash_vocab(tc.vocab, self.num_features)
        values = FK.term_count_block(
            tc, bucket_of, self.num_features, binary=self.binary
        )
        f = self.input_features[0]
        metas = tuple(
            ColumnMeta(
                parent_names=(f.name,),
                parent_type=f.ftype.__name__,
                grouping=f.name,
                index=i,
            )
            for i in range(self.num_features)
        )
        return VectorColumn(
            OPVector, values, VectorMetadata(self.output_name, metas)
        )


class OpIDF(Estimator):
    """OPVector (term counts) → OPVector (tf·idf); Spark IDF semantics:
    idf = ln((n_docs + 1) / (df + 1)), minDocFreq 0."""

    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, min_doc_freq: int = 0, uid: str | None = None):
        super().__init__("idf", uid=uid)
        self.min_doc_freq = min_doc_freq

    def get_params(self):
        return {"min_doc_freq": self.min_doc_freq}

    def fit_model(self, dataset) -> "OpIDFModel":
        from ..types.columns import SparseMatrix

        col = dataset[self.input_names[0]]
        assert isinstance(col, VectorColumn)
        if isinstance(col.values, SparseMatrix):
            # document frequency without densifying the wide term plane:
            # one bincount over the distinct (row, term) pairs
            from ..featurize.kernels import distinct_pair_bincount

            sm = col.values
            n, width = sm.shape
            df = distinct_pair_bincount(
                sm.rows, sm.cols, width
            ).astype(np.float64)
        else:
            x = np.asarray(col.values)
            df = (x > 0).sum(axis=0).astype(np.float64)
            n = x.shape[0]
        idf = np.log((n + 1.0) / (df + 1.0))
        idf = np.where(df >= self.min_doc_freq, idf, 0.0)
        return OpIDFModel(idf)


class OpIDFModel(Model):
    output_type = OPVector

    def __init__(self, idf, uid: str | None = None):
        super().__init__("idf", uid=uid)
        self.idf = np.asarray(idf, dtype=np.float64)

    def get_arrays(self):
        return {"idf": self.idf}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["idf"])

    def transform_columns(self, *cols: Column, num_rows: int) -> VectorColumn:
        from ..types.columns import SparseMatrix

        col = cols[0]
        assert isinstance(col, VectorColumn)
        if isinstance(col.values, SparseMatrix):
            # keep the wide term plane COO: accumulate duplicate pairs into
            # counts first so each nonzero is ONE float64 product rounded
            # to float32 — bit-identical to the dense multiply
            sm = col.values
            n, width = sm.shape
            flat = sm.rows.astype(np.int64) * width + sm.cols.astype(np.int64)
            if sm.vals is None:
                uniq, counts = np.unique(flat, return_counts=True)
                weights = counts.astype(np.float64)
            else:
                order = np.argsort(flat, kind="stable")
                uniq, starts = np.unique(flat[order], return_index=True)
                weights = np.add.reduceat(
                    sm.vals[order].astype(np.float64), starts
                ) if len(uniq) else np.zeros(0)
            rows_u = (uniq // width).astype(np.int32)
            cols_u = (uniq % width).astype(np.int32)
            vals = (weights * self.idf[uniq % width]).astype(np.float32)
            return VectorColumn(
                OPVector,
                SparseMatrix(rows_u, cols_u, (n, width), vals),
                col.metadata,
            )
        values = (np.asarray(col.values) * self.idf[None, :]).astype(np.float32)
        return VectorColumn(OPVector, values, col.metadata)


class OpStringIndexer(Estimator):
    """Text → RealNN index ordered by descending frequency
    (OpStringIndexer.scala). handle_invalid: 'error' | 'skip'-as-NaN |
    'keep' (unseen → num_labels), reference default NoFilter keeps."""

    input_types = (Text,)
    output_type = RealNN

    def __init__(self, handle_invalid: str = "keep", uid: str | None = None):
        super().__init__("strIdx", uid=uid)
        if handle_invalid not in ("error", "skip", "keep"):
            raise ValueError(f"bad handle_invalid {handle_invalid}")
        self.handle_invalid = handle_invalid

    def get_params(self):
        return {"handle_invalid": self.handle_invalid}

    def fit_model(self, dataset) -> "OpStringIndexerModel":
        col = dataset[self.input_names[0]]
        assert isinstance(col, TextColumn)
        counts: dict[str, int] = {}
        for v in col.values:
            if v is not None:
                counts[v] = counts.get(v, 0) + 1
        labels = sorted(counts, key=lambda t: (-counts[t], t))
        self.metadata["labels"] = labels
        return OpStringIndexerModel(labels, self.handle_invalid)


class OpStringIndexerModel(Model):
    output_type = RealNN

    def __init__(self, labels: list[str], handle_invalid: str = "keep", uid=None):
        super().__init__("strIdx", uid=uid)
        self.labels = list(labels)
        self.handle_invalid = handle_invalid
        self._index = {t: i for i, t in enumerate(self.labels)}

    def get_params(self):
        return {"labels": self.labels, "handle_invalid": self.handle_invalid}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(params["labels"], params.get("handle_invalid", "keep"))

    def transform_columns(self, *cols: Column, num_rows: int) -> NumericColumn:
        from ..featurize.interning import intern_values

        col = cols[0]
        assert isinstance(col, TextColumn)
        unseen = float(len(self.labels))
        # label columns repeat a handful of distinct values: intern once,
        # resolve each DISTINCT value against the fitted index, then one
        # vectorized gather maps every row (non-str values — possible on
        # hand-built columns — take interning's raw-keyed dict fallback,
        # preserving the historical per-row lookup semantics)
        present = np.fromiter(
            (v is not None for v in col.values), bool, num_rows
        )
        texts = [v for v in col.values if v is not None]
        codes, uniques, _ = intern_values(texts)
        uniq_idx = np.fromiter(
            (
                -1 if (j := self._index.get(u)) is None else j
                for u in uniques
            ),
            np.int64, len(uniques),
        )
        mapped = np.full(num_rows, -1, dtype=np.int64)
        if texts:
            mapped[present] = uniq_idx[codes]
        vals = mapped.astype(np.float64)
        mask = np.ones(num_rows, dtype=bool)
        miss = mapped < 0
        if miss.any():
            if self.handle_invalid == "keep":
                vals[miss] = unseen
            elif self.handle_invalid == "skip":
                vals[miss] = 0.0
                mask[miss] = False
            else:
                bad = int(np.nonzero(miss)[0][0])
                raise ValueError(f"Unseen label {col.values[bad]!r}")
        return NumericColumn(RealNN, vals, mask)


class OpIndexToString(Transformer):
    """RealNN index → Text label (OpIndexToString{,NoFilter}.scala)."""

    input_types = (RealNN,)
    output_type = Text

    def __init__(self, labels: list[str], unseen: str = "UnseenIndex", uid=None):
        super().__init__("idxToStr", uid=uid)
        self.labels = list(labels)
        self.unseen = unseen

    def get_params(self):
        return {"labels": self.labels, "unseen": self.unseen}

    def transform_columns(self, *cols: Column, num_rows: int) -> TextColumn:
        col = cols[0]
        assert isinstance(col, NumericColumn)
        out = np.empty(num_rows, dtype=object)
        for i, (v, m) in enumerate(zip(col.values, col.mask)):
            j = int(v)
            if m and 0 <= j < len(self.labels):
                out[i] = self.labels[j]
            else:
                out[i] = self.unseen
        return TextColumn(Text, out)


class JaccardSimilarity(Transformer):
    """Two set/list features → RealNN |A∩B|/|A∪B| (JaccardSimilarity.scala;
    both empty → 1.0)."""

    output_type = RealNN

    def __init__(self, uid: str | None = None):
        super().__init__("jacSim", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> NumericColumn:
        a_vals = cols[0].to_list()
        b_vals = cols[1].to_list()
        out = np.zeros(num_rows, dtype=np.float64)
        for i, (a, b) in enumerate(zip(a_vals, b_vals)):
            sa = set(a) if a else set()
            sb = set(b) if b else set()
            if not sa and not sb:
                out[i] = 1.0
            else:
                union = len(sa | sb)
                out[i] = len(sa & sb) / union if union else 1.0
        return NumericColumn(RealNN, out, np.ones(num_rows, dtype=bool))


class NGramSimilarity(Transformer):
    """Two text features → RealNN char-n-gram similarity
    (NGramSimilarity.scala; default n=3; Lucene NGramDistance replaced by
    Jaccard over padded char n-grams — same range, both-empty → 0)."""

    output_type = RealNN

    def __init__(self, n: int = 3, uid: str | None = None):
        super().__init__("ngramSim", uid=uid)
        self.n = n

    def get_params(self):
        return {"n": self.n}

    def _grams(self, s: str) -> set:
        s = f"{'_' * (self.n - 1)}{s.lower()}{'_' * (self.n - 1)}"
        return {s[i : i + self.n] for i in range(len(s) - self.n + 1)}

    def transform_columns(self, *cols: Column, num_rows: int) -> NumericColumn:
        def as_text(v):
            if isinstance(v, list):
                v = " ".join(v)
            return v or ""

        a_vals, b_vals = cols[0].to_list(), cols[1].to_list()
        out = np.zeros(num_rows, dtype=np.float64)
        for i in range(num_rows):
            a, b = as_text(a_vals[i]), as_text(b_vals[i])
            if not a or not b:
                out[i] = 0.0
                continue
            ga, gb = self._grams(a), self._grams(b)
            union = len(ga | gb)
            out[i] = len(ga & gb) / union if union else 0.0
        return NumericColumn(RealNN, out, np.ones(num_rows, dtype=bool))


# ------------------------------------------------------------------ detectors

# language detection lives in nlp/langid.py (script census +
# function-word voting, ~55 languages)


class LangDetector(Transformer):
    """Text → RealMap[language → confidence] (LangDetector.scala; the
    Optimaize profile model is replaced by nlp/langid.py — script census +
    function-word/diacritic voting over ~55 languages; measured per-language
    accuracy in PARITY.md, same output shape/keying)."""

    input_types = (Text,)
    output_type = RealMap

    def __init__(self, uid: str | None = None):
        super().__init__("langDetected", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        from ..nlp.langid import detect_scores

        col = cols[0]
        assert isinstance(col, TextColumn)
        out = [detect_scores(v) if v else {} for v in col.values]
        return MapColumn(RealMap, out)


_MAGIC_BYTES: list[tuple[bytes, str]] = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF87a", "image/gif"),
    (b"GIF89a", "image/gif"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"BM", "image/bmp"),
    (b"ID3", "audio/mpeg"),
    (b"RIFF", "audio/x-wav"),
    (b"\xd0\xcf\x11\xe0", "application/x-ole-storage"),
    (b"<?xml", "application/xml"),
    (b"<html", "text/html"),
    (b"<!DOCTYPE html", "text/html"),
]


def detect_mime(b64: str | None) -> str | None:
    """Magic-byte MIME detection of a base64 payload (shared by the scalar
    and map detectors); None for missing/undecodable."""
    if not b64:
        return None
    try:
        data = base64.b64decode(b64, validate=True)
    except (binascii.Error, ValueError):
        return None
    if not data:
        return None
    head = data[:32]
    for magic, mime in _MAGIC_BYTES:
        if head.startswith(magic):
            return mime
    try:
        data[:512].decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


class MimeTypeDetector(Transformer):
    """Base64 → Text MIME type (MimeTypeDetector.scala; Tika replaced by a
    magic-byte table; undecodable/unknown → 'application/octet-stream',
    decodable text → 'text/plain')."""

    output_type = Text

    def __init__(self, uid: str | None = None):
        super().__init__("mimeDetected", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> TextColumn:
        col = cols[0]
        assert isinstance(col, TextColumn)
        out = np.empty(num_rows, dtype=object)
        out[:] = [detect_mime(v) for v in col.values]
        return TextColumn(Text, out)


class MimeTypeMapDetector(Transformer):
    """Base64Map → PickListMap of MIME types per key
    (RichMapFeature.detectMimeTypes, RichMapFeature.scala:129) — the map
    form of MimeTypeDetector; undetectable values drop out of the row."""

    output_type = PickListMap

    def __init__(self, uid: str | None = None):
        super().__init__("mimeMapDetected", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        col = cols[0]
        assert isinstance(col, MapColumn)
        out = []
        for m in col.to_list():
            if not m:
                out.append({})
                continue
            row = {}
            for k, v in m.items():
                mime = detect_mime(v)
                if mime is not None:
                    row[k] = mime
            out.append(row)
        return MapColumn(PickListMap, out)


_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@"
    r"[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?"
    r"(?:\.[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?)+$"
)


class ValidEmailTransformer(Transformer):
    """Email → Binary validity (ValidEmailTransformer.scala)."""

    output_type = Binary

    def __init__(self, uid: str | None = None):
        super().__init__("validEmail", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> NumericColumn:
        col = cols[0]
        assert isinstance(col, TextColumn)
        vals = [
            bool(_EMAIL_RE.match(v)) if v is not None else None
            for v in col.values
        ]
        from ..types.columns import column_from_values

        return column_from_values(Binary, vals)


# A compact sample of high-frequency given names (US census top names,
# public domain). The reference ships full census dictionaries in its
# models module; extend via the `names` ctor arg.
_COMMON_NAMES = frozenset("""
james john robert michael william david richard joseph thomas charles mary
patricia jennifer linda elizabeth barbara susan jessica sarah karen nancy
lisa margaret betty sandra ashley kimberly emily donna michelle carol amanda
daniel matthew anthony mark donald steven paul andrew joshua kenneth kevin
brian george timothy ronald edward jason jeffrey ryan jacob gary nicholas
eric jonathan stephen larry justin scott brandon benjamin samuel gregory
frank alexander raymond patrick jack dennis jerry tyler aaron jose adam
henry nathan douglas zachary peter kyle ethan walter noah jeremy christian
keith roger terry sean austin carl arthur lawrence dylan jesse jordan bryan
emma olivia ava isabella sophia charlotte mia amelia harper evelyn abigail
ella scarlett grace chloe victoria riley aria lily aubrey zoey penelope
lillian addison layla natalie camila hannah brooklyn zoe nora leah savannah
audrey claire eleanor skylar anna caroline maria christopher chad georgia
virginia chelsea sierra india dakota israel francis diana sofia lucas
gabriel julian isaac juan luis carlos miguel antonio angel diego alejandro
""".split())


#: NameDetectUtils.scala:260-262 — honorific tokens (used both for the
#: name decision and for FindHonorific gender detection)
_MALE_HONORIFICS = frozenset({"mr", "mister", "sir"})
_FEMALE_HONORIFICS = frozenset({"ms", "mrs", "miss", "madam"})
_HONORIFICS = _MALE_HONORIFICS | _FEMALE_HONORIFICS


#: tokens that mark a NON-name context (street/geo designators): surnames
#: inside "McDaniel Avenue" / "Phelan Road" must not read as people — the
#: OpenNLP chunker got this from sentence context; measured on the
#: reference's testkit streets/cities/countries in tools/nlp_agreement.py
_NON_NAME_CONTEXT = frozenset(
    """avenue street road lane boulevard blvd drive court plaza terrace
    highway route way circle square expressway freeway parkway alley pike
    city town village county state province republic kingdom united states
    islands island coast bay lake river mount mountains valley beach port
    north south east west upper lower new old fort""".split()
)


def _is_name_token(t: str, names: frozenset, use_model: bool) -> bool:
    """Dictionary OR trained char-model hit (nlp/name_model.py — the
    OpenNLP replacement; the model generalizes to names outside any
    dictionary by character shape)."""
    if t in names or t in _HONORIFICS:
        return True
    if use_model:
        from ..nlp.name_model import is_probable_name

        return is_probable_name(t, threshold=0.7)
    return False


#: all UN-member (plus common observer/territory) country names, tokenized —
#: 'Ecuador' or 'United States' must not read as a person no matter how
#: name-shaped the characters are
_COUNTRY_NAMES = """
afghanistan albania algeria andorra angola antigua barbuda argentina armenia
australia austria azerbaijan bahamas bahrain bangladesh barbados belarus
belgium belize benin bhutan bolivia bosnia herzegovina botswana brazil brunei
bulgaria burkina faso burundi cambodia cameroon canada verde chad chile china
colombia comoros congo costa rica croatia cuba cyprus czechia denmark
djibouti dominica dominican ecuador egypt salvador eritrea estonia eswatini
ethiopia fiji finland france gabon gambia georgia germany ghana greece
grenada guatemala guinea bissau guyana haiti honduras hungary iceland india
indonesia iran iraq ireland israel italy jamaica japan jordan kazakhstan
kenya kiribati korea kosovo kuwait kyrgyzstan laos latvia lebanon lesotho
liberia libya liechtenstein lithuania luxembourg madagascar malawi malaysia
maldives mali malta mauritania mauritius mexico micronesia moldova monaco
mongolia montenegro morocco mozambique myanmar namibia nauru nepal
netherlands zealand nicaragua niger nigeria macedonia norway oman pakistan
palau panama papua paraguay peru philippines poland portugal qatar romania
russia rwanda lucia samoa marino senegal serbia seychelles sierra leone
singapore slovakia slovenia solomon somalia spain lanka sudan suriname
sweden switzerland syria taiwan tajikistan tanzania thailand timor togo
tonga trinidad tobago tunisia turkey turkmenistan tuvalu uganda ukraine
emirates uruguay uzbekistan vanuatu venezuela vietnam yemen zambia zimbabwe
federation swaziland sao tome principe burma zaire czechoslovakia yugoslavia
ivory
""".split()


@_lru_cache(maxsize=1)
def _country_tokens() -> frozenset:
    """Country-name tokens: the authored list above plus the phone plane's
    region → name table (localized spellings like España ride along)."""
    from .phone import DEFAULT_COUNTRY_CODES

    toks = set(_COUNTRY_NAMES)
    for name in DEFAULT_COUNTRY_CODES.values():
        for t in tokenize(name):
            toks.add(t)
    return frozenset(toks)


def _row_is_name(text: str, names: frozenset, use_model: bool) -> bool:
    """Row-level decision: any name token AND no geo/street designator or
    country-name token (context veto — see _NON_NAME_CONTEXT). A token that
    is ALSO a dictionary name never vetoes: 'Jordan Smith' and 'Georgia
    Brown' are people even though Jordan/Georgia are countries (name
    particles like de/la/san were dropped from the veto list for the same
    reason — Hispanic compound surnames must keep their recall)."""
    toks = tokenize(text)
    if not toks:
        return False
    if any(
        (t in _NON_NAME_CONTEXT or t in _country_tokens()) and t not in names
        for t in toks
    ):
        return False
    return any(_is_name_token(t, names, use_model) for t in toks)


class HumanNameDetector(Estimator):
    """Text → NameStats (HumanNameDetector.scala): decides whether a text
    column contains person names (name-token hit-rate >= threshold over
    the data) and emits per-row name stats with FindHonorific gender
    (NameDetectUtils.scala:104-108). The OpenNLP binaries are replaced by
    a dictionary PLUS a trained character-level model
    (nlp/name_model.py) — the model carries names the dictionary misses;
    fixtures in tests/test_nlp_fixture_agreement.py."""

    input_types = (Text,)
    output_type = NameStats

    def __init__(
        self,
        threshold: float = 0.5,
        names: frozenset = _COMMON_NAMES,
        use_model: bool = True,
        uid: str | None = None,
    ):
        super().__init__("humanNameDetector", uid=uid)
        self.threshold = threshold
        self.names = frozenset(n.lower() for n in names)
        self.use_model = use_model

    def get_params(self):
        return {"threshold": self.threshold, "use_model": self.use_model}

    def fit_model(self, dataset) -> "HumanNameDetectorModel":
        col = dataset[self.input_names[0]]
        assert isinstance(col, TextColumn)
        hits = total = 0
        for v in col.values:
            if not v:
                continue
            total += 1
            if _row_is_name(v, self.names, self.use_model):
                hits += 1
        is_name = total > 0 and (hits / total) >= self.threshold
        self.metadata["treatAsName"] = bool(is_name)
        self.metadata["predictedNameProb"] = (hits / total) if total else 0.0
        return HumanNameDetectorModel(
            bool(is_name), self.names, use_model=self.use_model
        )


class HumanNameDetectorModel(Model):
    output_type = NameStats

    def __init__(self, treat_as_name: bool, names: frozenset,
                 use_model: bool = True, uid=None):
        super().__init__("humanNameDetector", uid=uid)
        self.treat_as_name = treat_as_name
        self.names = names
        self.use_model = use_model

    def get_params(self):
        return {"treat_as_name": self.treat_as_name,
                "names": sorted(self.names),
                "use_model": self.use_model}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(params["treat_as_name"], frozenset(params["names"]),
                   params.get("use_model", True))

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        col = cols[0]
        assert isinstance(col, TextColumn)
        out = []
        for v in col.values:
            if not self.treat_as_name or not v:
                out.append({"isName": "false"} if v else {})
                continue
            toks = tokenize(v)
            # same row predicate as fit (context veto included) — fit and
            # transform must agree on what counts as a name row
            is_name = _row_is_name(v, self.names, self.use_model)
            stats = {"isName": "true" if is_name else "false"}
            if is_name:
                first = next(
                    (t for t in toks
                     if _is_name_token(t, self.names, self.use_model)
                     and t not in _HONORIFICS),
                    "",
                )
                if first:
                    stats["firstName"] = first
                # FindHonorific gender (NameDetectUtils.scala:104-108)
                gender = next(
                    (
                        "Male" if t in _MALE_HONORIFICS else "Female"
                        for t in toks
                        if t in _HONORIFICS
                    ),
                    None,
                )
                if gender:
                    stats["gender"] = gender
            out.append(stats)
        return MapColumn(NameStats, out)


class NameEntityRecognizer(Transformer):
    """Text → MultiPickListMap[entity-kind → tokens]
    (NameEntityRecognizer.scala): OpenNLP NER replaced by shape heuristics —
    capitalized token runs become entities, tagged Person when a token is in
    the name dictionary, else Organization/Location by suffix hints."""

    input_types = (Text,)
    output_type = MultiPickListMap

    _ORG_HINTS = ("inc", "corp", "llc", "ltd", "co", "company", "corporation")
    _LOC_HINTS = ("city", "county", "street", "avenue", "lake", "river",
                  "north", "south", "east", "west")
    # capital class matches sentences.py's opener class (A-ZÀ-ÖØ-Þ — the
    # À-Þ range alone would admit × U+00D7) plus Latin-Extended-A capitals
    # (Š, Č, Ł, İ, …) so cs/pl/tr/hr entity runs are detected consistently
    _CAP = "A-ZÀ-ÖØ-Þ" + "".join(
        chr(c) for c in range(0x100, 0x180) if chr(c).isupper()
    )

    def __init__(self, names: frozenset = _COMMON_NAMES,
                 use_model: bool = True, uid: str | None = None):
        super().__init__("nameEntityRecognizer", uid=uid)
        self.names = frozenset(n.lower() for n in names)
        self.use_model = use_model

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        # reference pipeline shape: sentence-split -> tokenize -> find
        # (NameEntityRecognizer.scala with the OpenNLP sentence model —
        # here nlp/sentences.py): a capitalized SENTENCE OPENER is only an
        # entity when the dictionary/char-model recognizes it, which kills
        # the 'every sentence start is a Misc entity' false positives of
        # whole-text capital-run scanning
        from ..nlp.langid import detect
        from ..nlp.sentences import split_sentences

        col = cols[0]
        assert isinstance(col, TextColumn)
        out = []
        for v in col.values:
            if not v:
                out.append({})
                continue
            ents: dict[str, set] = {}
            for sent in split_sentences(v, language=detect(v) or "en"):
                # index of the first non-quote/bracket char: the opener
                # discount must also apply to '"The dog barked."'
                lead = 0
                while lead < len(sent) and sent[lead] in "\"'«“‘([":
                    lead += 1
                for m in re.finditer(
                    rf"[{self._CAP}][\w'-]*(?:\s+(?:(?:van|de|der|den|ter|te|la|del|da|di|von|el)\s+)*[{self._CAP}][\w'-]*)*", sent
                ):
                    toks = m.group(0).split()
                    lows = [t.lower() for t in toks]
                    if (
                        m.start() == lead
                        and len(toks) == 1
                        and not _is_name_token(
                            lows[0], self.names, self.use_model
                        )
                        and lows[0] not in self._ORG_HINTS
                        and lows[0] not in self._LOC_HINTS
                    ):
                        continue  # bare sentence opener, not an entity
                    if any(
                        _is_name_token(t, self.names, self.use_model)
                        for t in lows
                    ):
                        kind = "Person"
                    elif any(t in self._ORG_HINTS for t in lows):
                        kind = "Organization"
                    elif any(t in self._LOC_HINTS for t in lows):
                        kind = "Location"
                    else:
                        kind = "Misc"
                    ents.setdefault(kind, set()).update(lows)
            out.append({k: frozenset(s) for k, s in ents.items()})
        return MapColumn(MultiPickListMap, out)
