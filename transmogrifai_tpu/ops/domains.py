"""Email/URL domain extraction transformers.

Reference: core/.../stages/impl/feature/EmailToPickListMapTransformer.scala
(Email → PickList of its domain) and UrlMapToPickListMapTransformer.scala
(URLMap → PickListMap of valid URLs' domains).
"""
from __future__ import annotations

import re
from urllib.parse import urlparse

import numpy as np

from ..stages.base import Transformer
from ..types import Email, OPMap, PickList, PickListMap
from ..types.columns import Column, MapColumn, TextColumn

_URL_SCHEME_RE = re.compile(r"^(https?|ftp)://", re.IGNORECASE)


def email_domain(v: str | None) -> str | None:
    """Email.domain: the part after a single '@' (Email.scala)."""
    if not v or v.count("@") != 1:
        return None
    prefix, domain = v.split("@")
    return domain if prefix and domain else None


def url_domain(v: str | None) -> str | None:
    """URL.domain for valid http/https/ftp URLs (URL.scala)."""
    if not v or not _URL_SCHEME_RE.match(v):
        return None
    try:
        host = urlparse(v).hostname
    except ValueError:
        return None
    return host or None


class EmailToPickListTransformer(Transformer):
    """Email → PickList of the email's domain
    (EmailToPickListMapTransformer.scala:50)."""

    input_types = (Email,)
    output_type = PickList

    def __init__(self, uid: str | None = None):
        super().__init__("emailToPickList", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> TextColumn:
        col = cols[0]
        assert isinstance(col, TextColumn)
        out = np.empty(num_rows, dtype=object)
        out[:] = [email_domain(v) for v in col.values]
        return TextColumn(PickList, out)


class UrlMapToPickListMapTransformer(Transformer):
    """URLMap → PickListMap of valid URLs' domains
    (UrlMapToPickListMapTransformer.scala:37)."""

    input_types = (OPMap,)
    output_type = PickListMap

    def __init__(self, uid: str | None = None):
        super().__init__("urlMapToPickListMap", uid=uid)

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        col = cols[0]
        assert isinstance(col, MapColumn)
        out = []
        for m in col.values:
            kept = {}
            for k, v in (m or {}).items():
                d = url_domain(v)
                if d is not None:
                    kept[k] = d
            out.append(kept)
        return MapColumn(PickListMap, out)
