"""Date/DateTime vectorizer: circular encodings + days-since-reference.

Reference: dsl/RichDateFeature.scala:108-120 — vectorize = per-period unit
circle (DateToUnitCircleTransformer.scala, sin/cos pairs for HourOfDay,
DayOfWeek, DayOfMonth, DayOfYear) combined with DateList SinceLast pivot
(days from the value to the reference date) + null indicator. Date values are
epoch milliseconds (joda convention).

Missing dates encode as (0, 0) on the unit circle (the reference maps empty
to the zero vector) and 0 days-since with the null indicator set.
"""
from __future__ import annotations

import datetime as _dt
from typing import Sequence

import numpy as np

from ..stages.metadata import NULL_STRING, ColumnMeta
from ..types.columns import Column, NumericColumn
from .base import VectorizerTransformer
from .defaults import DEFAULTS

_MS_PER_DAY = 86_400_000.0

_PERIOD_SIZE = {
    "HourOfDay": 24.0,
    "DayOfWeek": 7.0,
    "DayOfMonth": 31.0,
    "DayOfYear": 366.0,
}


def _period_values(ms: np.ndarray, period: str) -> np.ndarray:
    """Extract the integer time-period component from epoch-ms values."""
    if period == "HourOfDay":
        return (ms // 3_600_000) % 24
    if period == "DayOfWeek":
        days = ms // 86_400_000
        return ((days + 3) % 7) + 1  # epoch day 0 = Thursday; joda Mon=1
    dts = [
        _dt.datetime.fromtimestamp(m / 1000.0, tz=_dt.timezone.utc) for m in ms
    ]
    if period == "DayOfMonth":
        return np.array([d.day for d in dts], dtype=np.float64)
    if period == "DayOfYear":
        return np.array([d.timetuple().tm_yday for d in dts], dtype=np.float64)
    raise ValueError(f"Unknown time period {period}")


def unit_circle(ms: np.ndarray, mask: np.ndarray, period: str) -> np.ndarray:
    """[N, 2] (sin, cos) encoding; missing -> (0, 0)
    (DateToUnitCircleTransformer.scala)."""
    vals = _period_values(ms.astype(np.int64), period).astype(np.float64)
    radians = 2.0 * np.pi * vals / _PERIOD_SIZE[period]
    out = np.stack([np.sin(radians), np.cos(radians)], axis=1)
    out[~mask] = 0.0
    return out


class DateVectorizer(VectorizerTransformer):
    """Sequence transformer for Date/DateTime features."""

    def __init__(
        self,
        reference_date_ms: int | None = None,
        circular_reps: Sequence[str] = DEFAULTS.CircularDateRepresentations,
        track_nulls: bool = True,
        uid: str | None = None,
    ):
        super().__init__("vecDate", uid=uid)
        if reference_date_ms is None:
            # Fixed at stage construction (TransmogrifierDefaults.ReferenceDate
            # = DateTimeUtils.now()).
            reference_date_ms = int(
                _dt.datetime.now(tz=_dt.timezone.utc).timestamp() * 1000
            )
        self.reference_date_ms = reference_date_ms
        self.circular_reps = tuple(circular_reps)
        self.track_nulls = track_nulls

    def get_params(self):
        return {
            "reference_date_ms": self.reference_date_ms,
            "circular_reps": list(self.circular_reps),
            "track_nulls": self.track_nulls,
        }

    def blocks_for(self, cols: Sequence[Column], num_rows: int):
        blocks, metas = [], []
        for col, feat in zip(cols, self.input_features):
            assert isinstance(col, NumericColumn)
            parts = []
            metas_f: list[ColumnMeta] = []
            for period in self.circular_reps:
                parts.append(unit_circle(col.values, col.mask, period))
                for comp in ("x", "y"):
                    metas_f.append(
                        ColumnMeta(
                            (feat.name,),
                            feat.ftype.__name__,
                            descriptor_value=f"{comp}_{period}",
                        )
                    )
            # SinceLast: days from value to reference date (DateListPivot)
            days = (self.reference_date_ms - col.values.astype(np.float64)) / _MS_PER_DAY
            days = np.where(col.mask, days, 0.0)
            parts.append(days[:, None])
            metas_f.append(
                ColumnMeta(
                    (feat.name,), feat.ftype.__name__, descriptor_value="SinceLast"
                )
            )
            if self.track_nulls:
                parts.append((~col.mask).astype(np.float64)[:, None])
                metas_f.append(
                    ColumnMeta(
                        (feat.name,),
                        feat.ftype.__name__,
                        grouping=feat.name,
                        indicator_value=NULL_STRING,
                    )
                )
            blocks.append(np.concatenate(parts, axis=1))
            metas.append(metas_f)
        return blocks, metas
