"""Date/DateTime vectorizer: circular encodings + days-since-reference.

Reference: dsl/RichDateFeature.scala:108-120 — vectorize = per-period unit
circle (DateToUnitCircleTransformer.scala, sin/cos pairs for HourOfDay,
DayOfWeek, DayOfMonth, DayOfYear) combined with DateList SinceLast pivot
(days from the value to the reference date) + null indicator. Date values are
epoch milliseconds (joda convention).

Missing dates encode as (0, 0) on the unit circle (the reference maps empty
to the zero vector) and 0 days-since with the null indicator set.
"""
from __future__ import annotations

import datetime as _dt
from typing import Sequence

import numpy as np

from ..stages.metadata import NULL_STRING, ColumnMeta
from ..types.columns import Column, NumericColumn
from .base import VectorizerTransformer
from .defaults import DEFAULTS

_MS_PER_DAY = 86_400_000.0

#: period size = the joda TimePeriodVal max (DateToUnitCircleTransformer
#: .scala getPeriodWithSize); True = 1-based (min == 1 → shift so the
#: first period has angle 0)
_PERIOD_SIZE: dict[str, tuple[float, bool]] = {
    "HourOfDay": (24.0, False),
    "DayOfWeek": (7.0, True),
    "DayOfMonth": (31.0, True),
    "DayOfYear": (366.0, True),
    "MonthOfYear": (12.0, True),
    "WeekOfMonth": (6.0, True),
    "WeekOfYear": (53.0, True),
}


def _period_values(ms: np.ndarray, period: str) -> np.ndarray:
    """Extract the integer time-period component from epoch-ms values
    (shared calendar conventions live in ops/time_period.period_value)."""
    from .time_period import period_value

    if period == "HourOfDay":
        return (ms // 3_600_000) % 24
    if period == "DayOfWeek":
        days = ms // 86_400_000
        return ((days + 3) % 7) + 1  # epoch day 0 = Thursday; joda Mon=1
    return np.array(
        [period_value(int(m), period) for m in ms], dtype=np.float64
    )


def unit_circle(ms: np.ndarray, mask: np.ndarray, period: str) -> np.ndarray:
    """[N, 2] (cos, sin) encoding; missing → (0, 0).

    DateToUnitCircle.convertToRandians semantics
    (DateToUnitCircleTransformer.scala:109-120): 1-based periods shift by
    one so the first period always has angle 0, and the components are
    ordered (cos, sin) — the x_/y_ column pair."""
    size, one_based = _PERIOD_SIZE[period]
    vals = _period_values(ms.astype(np.int64), period).astype(np.float64)
    if one_based:
        vals = vals - 1.0
    radians = 2.0 * np.pi * vals / size
    out = np.stack([np.cos(radians), np.sin(radians)], axis=1)
    out[~mask] = 0.0
    return out


class DateToUnitCircleTransformer(VectorizerTransformer):
    """Date/DateTime → OPVector [cos, sin] (the x_/y_ pair) for ONE time
    period (DateToUnitCircleTransformer.scala; dsl
    ``date.to_unit_circle()``, RichDateFeature / RichMapFeature
    toUnitCircle). All 7 reference TimePeriods are accepted."""

    def __init__(self, time_period: str = "HourOfDay", uid: str | None = None):
        super().__init__("toUnitCircle", uid=uid)
        if time_period not in _PERIOD_SIZE:
            raise ValueError(
                f"time_period must be one of {sorted(_PERIOD_SIZE)}"
            )
        self.time_period = time_period

    def get_params(self):
        return {"time_period": self.time_period}

    def blocks_for(self, cols: Sequence[Column], num_rows: int):
        blocks, metas = [], []
        for col, feat in zip(cols, self.input_features):
            assert isinstance(col, NumericColumn)
            blocks.append(unit_circle(col.values, col.mask, self.time_period))
            metas.append([
                ColumnMeta(
                    (feat.name,), feat.ftype.__name__,
                    # x_HourOfDay / y_HourOfDay — DateToUnitCircle
                    # .metadataValues order, same as DateVectorizer's
                    descriptor_value=f"{comp}_{self.time_period}",
                )
                for comp in ("x", "y")
            ])
        return blocks, metas


class DateVectorizer(VectorizerTransformer):
    """Sequence transformer for Date/DateTime features."""

    def __init__(
        self,
        reference_date_ms: int | None = None,
        circular_reps: Sequence[str] = DEFAULTS.CircularDateRepresentations,
        track_nulls: bool = True,
        uid: str | None = None,
    ):
        super().__init__("vecDate", uid=uid)
        if reference_date_ms is None:
            # Fixed at stage construction (TransmogrifierDefaults.ReferenceDate
            # = DateTimeUtils.now()).
            reference_date_ms = int(
                _dt.datetime.now(tz=_dt.timezone.utc).timestamp() * 1000
            )
        self.reference_date_ms = reference_date_ms
        self.circular_reps = tuple(circular_reps)
        self.track_nulls = track_nulls

    def get_params(self):
        return {
            "reference_date_ms": self.reference_date_ms,
            "circular_reps": list(self.circular_reps),
            "track_nulls": self.track_nulls,
        }

    def blocks_for(self, cols: Sequence[Column], num_rows: int):
        blocks, metas = [], []
        for col, feat in zip(cols, self.input_features):
            assert isinstance(col, NumericColumn)
            parts = []
            metas_f: list[ColumnMeta] = []
            for period in self.circular_reps:
                parts.append(unit_circle(col.values, col.mask, period))
                for comp in ("x", "y"):
                    metas_f.append(
                        ColumnMeta(
                            (feat.name,),
                            feat.ftype.__name__,
                            descriptor_value=f"{comp}_{period}",
                        )
                    )
            # SinceLast: days from value to reference date (DateListPivot)
            days = (self.reference_date_ms - col.values.astype(np.float64)) / _MS_PER_DAY
            days = np.where(col.mask, days, 0.0)
            parts.append(days[:, None])
            metas_f.append(
                ColumnMeta(
                    (feat.name,), feat.ftype.__name__, descriptor_value="SinceLast"
                )
            )
            if self.track_nulls:
                parts.append((~col.mask).astype(np.float64)[:, None])
                metas_f.append(
                    ColumnMeta(
                        (feat.name,),
                        feat.ftype.__name__,
                        grouping=feat.name,
                        indicator_value=NULL_STRING,
                    )
                )
            blocks.append(np.concatenate(parts, axis=1))
            metas.append(metas_f)
        return blocks, metas
