"""Transmogrifier defaults — mirrored exactly from the reference
(core/.../stages/impl/feature/Transmogrifier.scala:52-88)."""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class TransmogrifierDefaults:
    DefaultNumOfFeatures: int = 512
    MaxNumOfFeatures: int = 1 << 17
    TopK: int = 20
    MinSupport: int = 10
    FillValue: float = 0.0
    BinaryFillValue: bool = False
    HashWithIndex: bool = False
    PrependFeatureName: bool = True
    CleanText: bool = True
    CleanKeys: bool = False
    BinaryFreq: bool = False
    FillWithMode: bool = True
    FillWithMean: bool = True
    TrackNulls: bool = True
    TrackInvalid: bool = False
    TrackTextLen: bool = False
    MinDocFrequency: int = 0
    MaxCategoricalCardinality: int = 30
    CoveragePct: float = 0.90
    MinTokenLength: int = 1
    ToLowercase: bool = True
    HashSeed: int = 42
    #: circular date encodings (TimePeriod.{HourOfDay,DayOfWeek,DayOfMonth,DayOfYear})
    CircularDateRepresentations: tuple[str, ...] = (
        "HourOfDay",
        "DayOfWeek",
        "DayOfMonth",
        "DayOfYear",
    )
    #: reference date for days-since encodings; fixed at fit time.
    #: (The reference uses DateTimeUtils.now() at stage construction.)
    ReferenceDateMs: int | None = None


DEFAULTS = TransmogrifierDefaults()
