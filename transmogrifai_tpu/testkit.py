"""testkit — deterministic random generators for every feature type.

Reference: testkit/src/main/scala/com/salesforce/op/testkit/ (RandomReal,
RandomText, RandomBinary, RandomIntegral, RandomMap, RandomList, RandomSet,
RandomVector, ProbabilityOfEmpty, InfiniteStream, RandomData). Each
generator is an infinite, seeded stream of typed values with a
probability-of-empty control; ``limit(n)`` materializes n values and
``to_column(n)`` / ``random_dataset`` produce the columnar form directly.
"""
from __future__ import annotations

import base64
import string
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from . import types as T
from .dataset import Dataset
from .types.columns import Column, column_from_values


class _StatefulProducer:
    """Marks a producer that carries per-stream state (e.g. a counter):
    ``factory()`` builds a fresh producer for each stream so repeated
    ``limit``/``to_column`` calls stay reproducible."""

    def __init__(self, factory: Callable[[], Callable]):
        self.factory = factory


class RandomGenerator:
    """Base: infinite seeded stream with probability-of-empty
    (ProbabilityOfEmpty.scala, InfiniteStream.scala)."""

    def __init__(
        self,
        ftype: type,
        producer: Callable[[np.random.Generator], Any] | _StatefulProducer,
        probability_of_empty: float = 0.0,
        seed: int = 42,
    ):
        self.ftype = ftype
        self._producer = producer
        self.probability_of_empty = probability_of_empty
        self.seed = seed

    def with_probability_of_empty(self, p: float) -> "RandomGenerator":
        """ProbabilityOfEmpty.withProbabilityOfEmpty."""
        return RandomGenerator(self.ftype, self._producer, p, self.seed)

    def with_seed(self, seed: int) -> "RandomGenerator":
        return RandomGenerator(
            self.ftype, self._producer, self.probability_of_empty, seed
        )

    def stream(self) -> Iterator[Any]:
        rng = np.random.default_rng(self.seed)
        producer = (
            self._producer.factory()
            if isinstance(self._producer, _StatefulProducer)
            else self._producer
        )
        while True:
            if self.probability_of_empty and rng.random() < self.probability_of_empty:
                yield None
            else:
                yield producer(rng)

    def draw(self, rng: np.random.Generator) -> Any:
        """One value using an external rng — honors probability_of_empty.
        For composing generators (RandomMap/RandomList sources). Stateful
        producers (unique_ids) keep ONE instance across draws so state
        advances rather than resetting per element."""
        if self.probability_of_empty and rng.random() < self.probability_of_empty:
            return None
        if isinstance(self._producer, _StatefulProducer):
            cached = getattr(self, "_draw_producer", None)
            if cached is None:
                cached = self._producer.factory()
                self._draw_producer = cached
            return cached(rng)
        return self._producer(rng)

    def limit(self, n: int) -> list:
        it = self.stream()
        return [next(it) for _ in range(n)]

    def to_column(self, n: int) -> Column:
        return column_from_values(self.ftype, self.limit(n))


# ------------------------------------------------------------------- numerics
class RandomReal:
    """RandomReal.scala:85-157 — distributions over Real subtypes."""

    @staticmethod
    def uniform(
        min_value: float = 0.0, max_value: float = 1.0,
        ftype: type = T.Real, seed: int = 42,
    ) -> RandomGenerator:
        return RandomGenerator(
            ftype, lambda r: float(r.uniform(min_value, max_value)), seed=seed
        )

    @staticmethod
    def normal(
        mean: float = 0.0, sigma: float = 1.0,
        ftype: type = T.Real, seed: int = 42,
    ) -> RandomGenerator:
        return RandomGenerator(
            ftype, lambda r: float(r.normal(mean, sigma)), seed=seed
        )

    @staticmethod
    def poisson(mean: float = 0.0, ftype: type = T.Real, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: float(r.poisson(mean)), seed=seed
        )

    @staticmethod
    def exponential(mean: float = 1.0, ftype: type = T.Real, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: float(r.exponential(mean)), seed=seed
        )

    @staticmethod
    def gamma(shape: float = 1.0, scale: float = 1.0, ftype: type = T.Real, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: float(r.gamma(shape, scale)), seed=seed
        )

    @staticmethod
    def log_normal(mean: float = 0.0, sigma: float = 1.0, ftype: type = T.Real, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: float(r.lognormal(mean, sigma)), seed=seed
        )

    @staticmethod
    def weibull(shape: float = 1.0, scale: float = 1.0, ftype: type = T.Real, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: float(scale * r.weibull(shape)), seed=seed
        )


class RandomIntegral:
    """RandomIntegral.scala."""

    @staticmethod
    def integrals(low: int = 0, high: int = 100, ftype: type = T.Integral, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: int(r.integers(low, high)), seed=seed
        )

    @staticmethod
    def dates(
        start_ms: int = 1_300_000_000_000, step_ms: int = 86_400_000, seed: int = 42
    ):
        """Random dates within ~1000 steps after start."""
        return RandomGenerator(
            T.Date,
            lambda r: int(start_ms + r.integers(0, 1000) * step_ms),
            seed=seed,
        )

    @staticmethod
    def datetimes(start_ms: int = 1_300_000_000_000, seed: int = 42):
        return RandomGenerator(
            T.DateTime,
            lambda r: int(start_ms + r.integers(0, 1_000_000_000)),
            seed=seed,
        )


class RandomBinary:
    """RandomBinary.scala: Bernoulli(p)."""

    @staticmethod
    def of(probability_of_success: float = 0.5, seed: int = 42) -> RandomGenerator:
        return RandomGenerator(
            T.Binary,
            lambda r: bool(r.random() < probability_of_success),
            seed=seed,
        )


# ----------------------------------------------------------------------- text
_COUNTRIES = (
    "Afghanistan Albania Algeria Argentina Australia Austria Belgium Brazil "
    "Canada Chile China Colombia Denmark Egypt Finland France Germany Greece "
    "India Indonesia Ireland Israel Italy Japan Kenya Mexico Netherlands "
    "Nigeria Norway Pakistan Peru Poland Portugal Romania Russia Spain "
    "Sweden Switzerland Thailand Turkey Ukraine Uruguay Venezuela Vietnam"
).split()
_STATES = (
    "Alabama Alaska Arizona Arkansas California Colorado Connecticut Delaware "
    "Florida Georgia Hawaii Idaho Illinois Indiana Iowa Kansas Kentucky "
    "Louisiana Maine Maryland Massachusetts Michigan Minnesota Mississippi "
    "Missouri Montana Nebraska Nevada Ohio Oklahoma Oregon Pennsylvania "
    "Tennessee Texas Utah Vermont Virginia Washington Wisconsin Wyoming"
).split()
_CITIES = (
    "Sacramento SanFrancisco SanJose LosAngeles SanDiego Fresno Oakland "
    "Bakersfield Anaheim Stockton Riverside Irvine Fremont Berkeley"
).split()
_STREETS = (
    "FirstStreet SecondStreet MarketStreet AlmadenBoulevard SantaClaraStreet "
    "TheAlameda LincolnAvenue MeridianAvenue CamdenAvenue BlossomHillRoad"
).split()


def _rand_string(rng: np.random.Generator, min_len: int, max_len: int) -> str:
    n = int(rng.integers(min_len, max_len + 1))
    letters = np.array(list(string.ascii_lowercase))
    return "".join(rng.choice(letters, n))


class RandomText:
    """RandomText.scala — typed text streams."""

    @staticmethod
    def strings(min_len: int = 1, max_len: int = 20, ftype: type = T.Text, seed: int = 42):
        return RandomGenerator(
            ftype, lambda r: _rand_string(r, min_len, max_len), seed=seed
        )

    @staticmethod
    def text_areas(min_len: int = 1, max_len: int = 80, seed: int = 42):
        return RandomText.strings(min_len, max_len, T.TextArea, seed)

    @staticmethod
    def from_domain(
        domain: Sequence[str],
        distribution: Sequence[float] = (),
        ftype: type = T.Text,
        seed: int = 42,
    ):
        """textFromDomain / pickLists / comboBoxes with optional weights."""
        domain = list(domain)
        p = np.asarray(distribution, dtype=np.float64) if distribution else None
        if p is not None:
            p = p / p.sum()

        def producer(r: np.random.Generator) -> str:
            return str(r.choice(domain, p=p))

        return RandomGenerator(ftype, producer, seed=seed)

    @staticmethod
    def pick_lists(domain: Sequence[str], distribution: Sequence[float] = (), seed: int = 42):
        return RandomText.from_domain(domain, distribution, T.PickList, seed)

    @staticmethod
    def combo_boxes(domain: Sequence[str], distribution: Sequence[float] = (), seed: int = 42):
        return RandomText.from_domain(domain, distribution, T.ComboBox, seed)

    @staticmethod
    def countries(seed: int = 42):
        return RandomText.from_domain(_COUNTRIES, ftype=T.Country, seed=seed)

    @staticmethod
    def states(seed: int = 42):
        return RandomText.from_domain(_STATES, ftype=T.State, seed=seed)

    @staticmethod
    def cities(seed: int = 42):
        return RandomText.from_domain(_CITIES, ftype=T.City, seed=seed)

    @staticmethod
    def streets(seed: int = 42):
        return RandomText.from_domain(_STREETS, ftype=T.Street, seed=seed)

    @staticmethod
    def emails(domain: str = "example.com", seed: int = 42):
        return RandomGenerator(
            T.Email,
            lambda r: f"{_rand_string(r, 3, 10)}@{domain}",
            seed=seed,
        )

    @staticmethod
    def urls(seed: int = 42):
        return RandomGenerator(
            T.URL,
            lambda r: f"https://www.{_rand_string(r, 3, 10)}.com/{_rand_string(r, 1, 8)}",
            seed=seed,
        )

    @staticmethod
    def phones(seed: int = 42):
        """Valid-shaped US phones (RandomText.phones)."""
        return RandomGenerator(
            T.Phone,
            lambda r: f"+1{r.integers(200, 999)}{r.integers(200, 999)}{r.integers(1000, 9999)}",
            seed=seed,
        )

    @staticmethod
    def phones_with_errors(probability_of_error: float = 0.2, seed: int = 42):
        def producer(r: np.random.Generator) -> str:
            if r.random() < probability_of_error:
                return str(r.integers(0, 999))  # too short to be valid
            return f"+1{r.integers(200, 999)}{r.integers(200, 999)}{r.integers(1000, 9999)}"

        return RandomGenerator(T.Phone, producer, seed=seed)

    @staticmethod
    def postal_codes(seed: int = 42):
        return RandomGenerator(
            T.PostalCode, lambda r: f"{r.integers(10000, 99999)}", seed=seed
        )

    @staticmethod
    def ids(seed: int = 42):
        return RandomGenerator(
            T.ID, lambda r: _rand_string(r, 8, 12), seed=seed
        )

    @staticmethod
    def unique_ids(seed: int = 42):
        def factory() -> Callable:
            counter = {"i": 0}

            def producer(r: np.random.Generator) -> str:
                counter["i"] += 1
                return f"id_{counter['i']:08d}"

            return producer

        return RandomGenerator(T.ID, _StatefulProducer(factory), seed=seed)

    @staticmethod
    def base64(min_len: int = 4, max_len: int = 32, seed: int = 42):
        def producer(r: np.random.Generator) -> str:
            n = int(r.integers(min_len, max_len + 1))
            return base64.b64encode(bytes(r.integers(0, 256, n).tolist())).decode()

        return RandomGenerator(T.Base64, producer, seed=seed)


# ---------------------------------------------------------- collections, maps
class RandomList:
    """RandomList.scala."""

    @staticmethod
    def of_texts(
        source: RandomGenerator | None = None,
        min_len: int = 0,
        max_len: int = 5,
        seed: int = 42,
    ):
        src = source or RandomText.strings(seed=seed)

        def producer(r: np.random.Generator) -> list:
            n = int(r.integers(min_len, max_len + 1))
            drawn = (src.draw(r) for _ in range(n))
            return [v for v in drawn if v is not None]

        return RandomGenerator(T.TextList, producer, seed=seed)

    @staticmethod
    def of_dates(min_len: int = 0, max_len: int = 5, seed: int = 42):
        def producer(r: np.random.Generator) -> list:
            n = int(r.integers(min_len, max_len + 1))
            return [
                int(1_300_000_000_000 + r.integers(0, 1_000_000_000))
                for _ in range(n)
            ]

        return RandomGenerator(T.DateList, producer, seed=seed)

    @staticmethod
    def of_geolocations(seed: int = 42):
        def producer(r: np.random.Generator) -> list:
            return [
                float(r.uniform(-90, 90)),
                float(r.uniform(-180, 180)),
                float(r.integers(1, 10)),
            ]

        return RandomGenerator(T.Geolocation, producer, seed=seed)


class RandomSet:
    """RandomSet.scala: MultiPickList streams."""

    @staticmethod
    def of(domain: Sequence[str], min_size: int = 0, max_size: int = 3, seed: int = 42):
        domain = list(domain)

        def producer(r: np.random.Generator) -> frozenset:
            n = int(r.integers(min_size, min(max_size, len(domain)) + 1))
            return frozenset(
                str(v) for v in r.choice(domain, size=n, replace=False)
            )

        return RandomGenerator(T.MultiPickList, producer, seed=seed)


class RandomMap:
    """RandomMap.scala: map streams built from a scalar generator."""

    @staticmethod
    def of(
        source: RandomGenerator,
        map_type: type,
        keys: Sequence[str] = ("k0", "k1", "k2"),
        min_size: int = 0,
        seed: int = 42,
    ):
        keys = list(keys)

        def producer(r: np.random.Generator) -> dict:
            n = int(r.integers(min_size, len(keys) + 1))
            chosen = r.choice(len(keys), size=n, replace=False)
            # a None draw (source probability_of_empty) leaves the key absent
            drawn = {keys[i]: source.draw(r) for i in sorted(chosen)}
            return {k: v for k, v in drawn.items() if v is not None}

        return RandomGenerator(map_type, producer, seed=seed)


class RandomVector:
    """RandomVector.scala: dense vectors from a scalar distribution."""

    @staticmethod
    def dense(dim: int, mean: float = 0.0, sigma: float = 1.0, seed: int = 42):
        def producer(r: np.random.Generator):
            return r.normal(mean, sigma, dim).astype(np.float32)

        return RandomGenerator(T.OPVector, producer, seed=seed)


# -------------------------------------------------------------- fault testkit
def fault_plan(seed: int = 42) -> "Any":
    """A fresh resilience ``FaultPlan`` — the deterministic fault-injection
    harness (raise on the Nth fit, crash after a layer, NaN a stage output,
    tear a file; serving side: malform incoming rows, fail a scoring
    stage, tear a training profile, shift a feature's observed stream,
    fail streaming chunk reads; distributed side: kill a simulated host
    after a layer or mid-collective, straggle a collective, drop
    heartbeats, corrupt a checkpoint shard). Install it over a block with
    ``install_faults``::

        plan = testkit.fault_plan().crash_after_layer(1)
        with testkit.install_faults(plan):
            workflow.train(checkpoint_dir=d)   # dies after layer 1

        plan = (testkit.fault_plan()
                .malform_row("age", rows=(2,))         # quarantine row 2
                .fail_stage_transform("pred", times=3)  # trip the breaker
                .shift_feature("age", offset=50.0))     # drifted stream
        with testkit.install_faults(plan):
            fn = score_function(model)
            fn.batch(rows)

        plan = (testkit.fault_plan()
                .fail_host(1, after_layer=2)            # degraded-mesh path
                .straggle_collective("pxtx", delay=120.0))
        with testkit.install_faults(plan):
            workflow.train(checkpoint_dir=d)   # fails over, completes
    """
    from .resilience.faults import FaultPlan

    return FaultPlan(seed=seed)


def install_faults(plan: "Any"):
    """Context manager installing a FaultPlan process-globally (see
    resilience.faults.installed)."""
    from .resilience.faults import installed

    return installed(plan)


def drifted(generator: RandomGenerator, offset: float) -> RandomGenerator:
    """A shifted copy of a numeric generator — the covariate-shifted serve
    stream for drift-sentinel tests (same seed, same draw sequence, every
    value offset by ``offset``)."""
    inner = generator._producer
    if isinstance(inner, _StatefulProducer):
        raise TypeError("drifted() supports stateless numeric generators")

    def producer(r: np.random.Generator):
        return float(inner(r)) + offset

    return RandomGenerator(
        generator.ftype, producer,
        generator.probability_of_empty, generator.seed,
    )


# ----------------------------------------------------------------- RandomData
def random_dataset(
    generators: dict[str, RandomGenerator], n: int, seed: int | None = None
) -> Dataset:
    """RandomData.scala: assemble a typed Dataset from named generators.
    Per-column seeds are derived from the dataset seed so columns are
    independent but the whole dataset is reproducible."""
    cols = {}
    for i, (name, gen) in enumerate(generators.items()):
        g = gen if seed is None else gen.with_seed(seed + 1000 * i)
        cols[name] = g.to_column(n)
    return Dataset.of(cols)
