"""ASCII table rendering (reference: utils/.../table/Table.scala:156)."""
from __future__ import annotations

from typing import Sequence


def render_table(headers: Sequence[str], rows: Sequence[Sequence[str]]) -> str:
    cols = [[str(h)] + [str(r[i]) for r in rows] for i, h in enumerate(headers)]
    widths = [max(len(v) for v in col) for col in cols]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"

    def fmt(cells: Sequence[str]) -> str:
        return "|" + "|".join(
            f" {str(c):<{w}} " for c, w in zip(cells, widths)
        ) + "|"

    out = [sep, fmt(headers), sep]
    out += [fmt([str(c) for c in r]) for r in rows]
    out.append(sep)
    return "\n".join(out)
