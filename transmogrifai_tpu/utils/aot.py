"""Disk-backed AOT program cache (jax.export).

Fresh-process wall-clock on the tunneled chip is dominated by program
ACQUISITION, not execution (BASELINE.md round 2: the 25-round XGB chunk
traces+lowers in ~4 s, loads from the persistent compile cache in ~0.6 s,
and executes in ~1 ms). The persistent XLA compile cache already removes
recompilation; this layer removes the per-process TRACING by serializing
exported StableHLO programs to disk and rehydrating them with
``jax.export.deserialize`` (~0 s) — the subsequent jit-of-call compile
hits the persistent compile cache.

Usage: ``aot_call("name", jit_fn, args, statics)`` — transparently falls
back to a direct ``jit_fn(*args, **statics)`` call on ANY failure (new
shapes still work, blobs self-invalidate via a source-version salt).
Opt out with TPTPU_AOT=0.
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Any, Callable

log = logging.getLogger(__name__)

_LOCK = threading.Lock()
_MEM: dict = {}
_PENDING: set = set()
_FAILED: set = set()
_THREADS: list = []
_SALT: str | None = None
_REGISTERED = False


import time as _time

_START = _time.monotonic()


def _drain_exports() -> None:
    """Give in-flight background exports a chance to land before the
    process exits — daemon threads are otherwise killed mid-trace and the
    blob never materializes (each short-lived bench process would only
    bank one or two programs). The wait is scaled to process lifetime so a
    quick scoring CLI run never hangs ~60 s at exit: a process that ran
    for t seconds waits at most min(60, max(5, 2t))."""
    import time

    elapsed = time.monotonic() - _START
    budget = min(60.0, max(5.0, 2.0 * elapsed))
    deadline = time.monotonic() + budget
    for th in list(_THREADS):
        th.join(timeout=max(0.0, deadline - time.monotonic()))
    alive = [th for th in _THREADS if th.is_alive()]
    if alive:
        log.info("abandoning %d unfinished AOT exports at exit", len(alive))


import atexit  # noqa: E402

atexit.register(_drain_exports)


def _enabled() -> bool:
    return os.environ.get("TPTPU_AOT", "1") != "0"


def _cache_dir() -> str:
    base = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache", "exports",
    )
    os.makedirs(base, exist_ok=True)
    return base


def _version_salt() -> str:
    """Hash of the source files whose tracing the cache skips — a code
    change invalidates every blob."""
    global _SALT
    if _SALT is None:
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in (
            "models/trees.py", "models/hist_pallas.py", "models/solvers.py",
            "models/gbdt.py",
        ):
            try:
                with open(os.path.join(pkg, rel), "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(rel.encode())
        _SALT = h.hexdigest()[:16]
    return _SALT


def _register_serializations() -> None:
    global _REGISTERED
    if _REGISTERED:
        return
    from jax import export

    from ..models.solvers import GLMParams
    from ..models.trees import Tree

    for cls, sname in (
        (Tree, "transmogrifai_tpu.Tree"),
        (GLMParams, "transmogrifai_tpu.GLMParams"),
    ):
        try:
            export.register_namedtuple_serialization(
                cls, serialized_name=sname
            )
        except ValueError:
            pass  # already registered
    _REGISTERED = True


def _key(name: str, args: tuple, statics: dict) -> str:
    import jax

    # device count + per-leaf shardings are part of program identity: a
    # blob exported single-device must not shadow a mesh-sharded variant
    # (and vice versa) on the same backend/shapes
    parts = [name, _version_salt(), jax.default_backend(),
             f"ndev={len(jax.devices())}"]
    parts.append(str(jax.tree_util.tree_structure(args)))
    for a in jax.tree_util.tree_leaves(args):
        parts.append(f"{getattr(a, 'shape', ())}:{getattr(a, 'dtype', type(a).__name__)}")
        sharding = getattr(a, "sharding", None)
        if sharding is not None:
            parts.append(str(sharding))
    for k in sorted(statics):
        parts.append(f"{k}={statics[k]}")
    return hashlib.sha256("|".join(map(str, parts)).encode()).hexdigest()[:24]


def aot_call(
    name: str, jit_fn: Callable, args: tuple, statics: dict
) -> Any:
    """``jit_fn(*args, **statics)`` through the export cache."""
    if not _enabled():
        return jit_fn(*args, **statics)
    try:
        import jax
        from jax import export

        _register_serializations()
        key = _key(name, args, statics)
        with _LOCK:
            call = _MEM.get(key)
        if call is not None:
            return call(*args)
        path = os.path.join(_cache_dir(), key + ".jaxexport")
        if os.path.exists(path):
            try:
                with open(path, "rb") as fh:
                    exp = export.deserialize(fh.read())
                call = jax.jit(exp.call)
                out = call(*args)
                with _LOCK:
                    _MEM[key] = call
                return out
            except Exception as e:
                # corrupt/stale blob: remove it so a future first-use
                # re-exports instead of permanently disabling the cache
                log.info("AOT blob %s unusable (%s); removing", key, e)
                try:
                    os.remove(path)
                except OSError:
                    pass
        # first use of this program version: run directly, then export in
        # the background so FUTURE processes skip the trace (the export
        # itself re-traces, which we don't want on the critical path).
        # _PENDING dedupes concurrent validator threads; _FAILED is the
        # negative cache (a program export cannot spontaneously start
        # working, so don't re-trace it per call); the tmp suffix is
        # unique per thread so racing writers can't interleave one file.
        out = jit_fn(*args, **statics)
        with _LOCK:
            if key not in _MEM:
                # same-process repeats should reuse jit_fn's warm cache
                # instead of preferring the blob once it lands mid-process
                # (deserialize + recompile would ADD latency here)
                _MEM[key] = lambda *a: jit_fn(*a, **statics)
            if key in _PENDING or key in _FAILED:
                return out
            _PENDING.add(key)

        def _export():
            try:
                exp = export.export(
                    jax.jit(lambda *a: jit_fn(*a, **statics))
                )(*args)
                blob = exp.serialize()
                tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
                with open(tmp, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
            except Exception as e:  # never break the fit for the cache
                log.info("AOT export of %s failed: %s", name, e)
                with _LOCK:
                    _FAILED.add(key)
            finally:
                with _LOCK:
                    _PENDING.discard(key)

        th = threading.Thread(target=_export, daemon=True)
        with _LOCK:
            _THREADS.append(th)
        th.start()
        return out
    except Exception as e:
        log.info("AOT cache bypassed for %s: %s", name, e)
        return jit_fn(*args, **statics)
