"""Disk-backed AOT program cache (serialized executables + jax.export).

Fresh-process wall-clock on the tunneled chip is dominated by program
ACQUISITION, not execution (BASELINE.md round 2/3: a 25-round boost chunk
executes in ~9 ms but costs seconds to trace/compile/load per process; the
axon backend routes compiles through a remote helper, so even a cached
compile is ~0.3-0.8 s and a fresh one is tens of seconds).

Round 3 layers, fastest first:
  1. in-memory table (``_MEM``) — same-process repeats are free;
  2. serialized EXECUTABLE cache (``jax.experimental.serialize_executable``)
     — a fresh process skips trace AND compile AND compile-cache load:
     measured ~1.3 s for a 46 MB boost-chunk executable vs ~2.6 s for the
     round-2 StableHLO path and ~20-40 s for a cold compile. ``prewarm()``
     loads every banked executable for the current (backend, device-count)
     on a thread pool so the model-selector phase finds them in ``_MEM``;
  3. transparent fallback to a direct ``jit_fn(*args, **statics)`` call on
     ANY failure (new shapes still work; blobs self-invalidate via a
     source-version salt in the key).

Opt out with TPTPU_AOT=0.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
import time as _time
from typing import Any, Callable

log = logging.getLogger(__name__)

_LOCK = threading.Lock()
_MEM: dict = {}
_PENDING: set = set()
_FAILED: set = set()
_THREADS: list = []
_SALT: str | None = None

_START = _time.monotonic()


def _drain_exports() -> None:
    """Give in-flight background executable saves a chance to land before
    the process exits — daemon threads are otherwise killed mid-compile and
    the blob never materializes. The wait is scaled to process lifetime (a
    process that ran t seconds waits at most min(600, max(5, 2t))): quick
    scoring CLI runs exit within seconds, while long bench/training runs
    may sit out a background compile that takes minutes — capping those at
    60 s starved the bank forever (the same key re-missed every run)."""
    elapsed = _time.monotonic() - _START
    # long-lived processes (bench/training runs) may be draining a save
    # whose background compile is minutes — capping those at 60 s starves
    # the bank forever (the same key misses every run); quick CLI runs
    # stay bounded by twice their own lifetime
    budget = min(600.0, max(5.0, 2.0 * elapsed))
    deadline = _time.monotonic() + budget
    for th in list(_THREADS):
        th.join(timeout=max(0.0, deadline - _time.monotonic()))
    alive = [th for th in _THREADS if th.is_alive()]
    if alive:
        log.info("abandoning %d unfinished AOT saves at exit", len(alive))


import atexit  # noqa: E402

atexit.register(_drain_exports)


def _enabled() -> bool:
    return os.environ.get("TPTPU_AOT", "1") != "0"


def _exec_dir() -> str:
    import jax

    base = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache", "execs",
        f"{jax.default_backend()}-{len(jax.devices())}",
    )
    os.makedirs(base, exist_ok=True)
    return base


def _version_salt() -> str:
    """Hash of the source files whose tracing the cache skips — a code
    change invalidates every blob."""
    global _SALT
    if _SALT is None:
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # every file that DEFINES an aot_call-routed jit_fn must be listed,
        # or editing it serves stale banked executables of the old code
        for rel in (
            "models/trees.py", "models/hist_pallas.py", "models/solvers.py",
            "models/gbdt.py", "ops/embeddings.py",
        ):
            try:
                with open(os.path.join(pkg, rel), "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(rel.encode())
        # trace-time env knobs are program identity too: a blob exported
        # under one knob value must not be served to a process expecting
        # another (TPTPU_HIST additionally rides the explicit statics)
        for knob in ("TPTPU_HIST", "TPTPU_HIST_COMB", "TPTPU_GEMM_MCAP",
                     "TPTPU_BOOST_CHUNK"):
            h.update(f"{knob}={os.environ.get(knob, '')}".encode())
        _SALT = h.hexdigest()[:16]
    return _SALT


def _key(name: str, args: tuple, statics: dict) -> str:
    import jax

    # device count + per-leaf shardings are part of program identity: a
    # blob exported single-device must not shadow a mesh-sharded variant
    # (and vice versa) on the same backend/shapes
    parts = [name, _version_salt(), jax.default_backend(),
             f"ndev={len(jax.devices())}"]
    parts.append(str(jax.tree_util.tree_structure(args)))
    for a in jax.tree_util.tree_leaves(args):
        parts.append(f"{getattr(a, 'shape', ())}:{getattr(a, 'dtype', type(a).__name__)}")
        sharding = getattr(a, "sharding", None)
        if sharding is not None:
            parts.append(str(sharding))
    for k in sorted(statics):
        parts.append(f"{k}={statics[k]}")
    return hashlib.sha256("|".join(map(str, parts)).encode()).hexdigest()[:24]


def _load_exec(path: str):
    """pickle → deserialize_and_load → callable; raises on a corrupt or
    truncated blob (callers delete-and-recompile)."""
    from jax.experimental import serialize_executable as SE

    t0 = _time.monotonic()
    with open(path, "rb") as fh:
        blob = pickle.loads(fh.read())
    if not isinstance(blob, tuple) or len(blob) != 3:
        # pickle decoded but the payload is not ours — a torn write that
        # happened to truncate on a valid pickle boundary
        raise ValueError(f"malformed executable blob (got {type(blob).__name__})")
    payload, in_tree, out_tree = blob
    compiled = SE.deserialize_and_load(payload, in_tree, out_tree)
    log.info(
        "AOT load %s (%.1f MB) in %.2f s", os.path.basename(path),
        os.path.getsize(path) / 1e6, _time.monotonic() - t0,
    )
    try:
        os.utime(path)  # recency marker for pruning
    except OSError:
        pass
    return lambda *a: compiled(*a)


def _acquire_banked(path: str, name: str, key: str):
    """Lazy (non-prewarm) acquire of a banked executable, guarded the same
    way ``prewarm`` guards its loads: a corrupt/truncated ``.jaxexec`` is
    deleted so the caller recompiles, instead of crashing the sweep thread
    that happened to touch it first. Returns a callable or None."""
    if not os.path.exists(path):
        return None
    try:
        return _load_exec(path)
    except Exception as e:
        log.info("AOT executable %s unusable (%s); removing", key, e)
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def prewarm(max_workers: int = 8, max_bytes: int = 32_000_000) -> int:
    """Load every CURRENT-version banked executable for this
    backend/device-count into ``_MEM`` on a thread pool. Call early (e.g.
    right after backend init) so acquisition overlaps the data/feature
    phases; returns the number of programs loaded. Files from other source
    versions can never hit (the key embeds the salt), so they are deleted
    on sight — without this the bank grows by a full program set per source
    edit and prewarm ships gigabytes of dead executables."""
    if not _enabled():
        return 0
    try:
        d = _exec_dir()
    except Exception:
        return 0
    salt = _version_salt()
    paths = []
    for fn in os.listdir(d):
        if not fn.endswith(".jaxexec"):
            continue
        p = os.path.join(d, fn)
        if not fn.startswith(salt + "-"):
            try:
                os.remove(p)
            except OSError:
                pass
            continue
        try:
            if os.path.getsize(p) > max_bytes:
                # big executables ship their binary over the tunneled link
                # at load — prewarming them CONTENDS with the foreground
                # work's device traffic (measured: a ~1 GB prewarm stalls
                # the first sweep ~20 s). They load lazily instead, inside
                # whichever family thread needs them.
                continue
        except OSError:
            continue
        paths.append(p)
    if not paths:
        return 0
    from concurrent.futures import ThreadPoolExecutor

    loaded = [0]

    def _one(p):
        key = os.path.basename(p)[len(salt) + 1: -len(".jaxexec")]
        with _LOCK:
            if key in _MEM:
                return
        try:
            call = _load_exec(p)
        except Exception as e:
            log.info("prewarm: dropping unusable executable %s (%s)", p, e)
            try:
                os.remove(p)
            except OSError:
                pass
            return
        with _LOCK:
            _MEM.setdefault(key, call)
            loaded[0] += 1

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        list(pool.map(_one, paths))
    log.info("prewarm: %d executables loaded", loaded[0])
    return loaded[0]


def aot_call(
    name: str, jit_fn: Callable, args: tuple, statics: dict
) -> Any:
    """``jit_fn(*args, **statics)`` through the executable cache."""
    if not _enabled():
        return jit_fn(*args, **statics)
    try:
        key = _key(name, args, statics)
        with _LOCK:
            call = _MEM.get(key)
        if call is not None:
            # NOTE: dispatch is async — timing this call would measure
            # enqueue latency, not execution
            log.debug("AOT hit %s (%s)", name, key)
            return call(*args)
        path = os.path.join(
            _exec_dir(), f"{_version_salt()}-{key}.jaxexec"
        )
        call = _acquire_banked(path, name, key)
        if call is not None:
            try:
                out = call(*args)
                with _LOCK:
                    _MEM[key] = call
                return out
            except Exception as e:
                # blob deserialized but the executable is broken (stale
                # runtime, torn payload): remove it so a future first-use
                # re-saves instead of permanently disabling the cache
                log.info("AOT executable %s unusable (%s); removing", key, e)
                try:
                    os.remove(path)
                except OSError:
                    pass
        # first use of this program version: run directly, then save the
        # compiled executable in the background so FUTURE processes skip
        # trace+compile. _PENDING dedupes concurrent validator threads;
        # _FAILED is the negative cache; the tmp suffix is unique per
        # thread so racing writers can't interleave one file.
        t_direct = _time.monotonic()
        out = jit_fn(*args, **statics)
        log.info(
            "AOT miss %s (%s): direct call %.2f s", name, key,
            _time.monotonic() - t_direct,
        )
        with _LOCK:
            if key not in _MEM:
                # same-process repeats reuse jit_fn's warm cache
                _MEM[key] = lambda *a: jit_fn(*a, **statics)
            if key in _PENDING or key in _FAILED:
                return out
            _PENDING.add(key)

        def _save():
            try:
                from jax.experimental import serialize_executable as SE

                t0 = _time.monotonic()
                # .lower().compile() hits the jit's persistent compile
                # cache (same computation), so this is load-cost, not a
                # recompile
                compiled = jit_fn.lower(*args, **statics).compile()
                payload, in_tree, out_tree = SE.serialize(compiled)
                blob = pickle.dumps((payload, in_tree, out_tree))
                tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
                with open(tmp, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
                log.info(
                    "AOT saved %s (%s, %.1f MB) in %.1f s", name, key,
                    len(blob) / 1e6, _time.monotonic() - t0,
                )
            except Exception as e:  # never break the fit for the cache
                log.info("AOT save of %s failed: %s", name, e)
                with _LOCK:
                    _FAILED.add(key)
            finally:
                with _LOCK:
                    _PENDING.discard(key)

        th = threading.Thread(target=_save, daemon=True)
        with _LOCK:
            _THREADS.append(th)
        th.start()
        return out
    except Exception as e:
        log.info("AOT cache bypassed for %s: %s", name, e)
        return jit_fn(*args, **statics)
