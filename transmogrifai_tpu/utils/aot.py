"""Disk-backed AOT program cache (serialized executables + jax.export).

Fresh-process wall-clock on the tunneled chip is dominated by program
ACQUISITION, not execution (BASELINE.md round 2/3: a 25-round boost chunk
executes in ~9 ms but costs seconds to trace/compile/load per process; the
axon backend routes compiles through a remote helper, so even a cached
compile is ~0.3-0.8 s and a fresh one is tens of seconds).

This module is the persistent layer of the compile plane
(``transmogrifai_tpu/compiler/``): every model family and the serving path
route their jitted entry points through ``aot_call``, and every event
(compile, hit, corruption drop, invalidation) lands in the
``compiler.stats`` ledger surfaced as ``compileStats``.

Layers, fastest first:
  1. in-memory table (``_MEM``) — same-process repeats are free;
  2. serialized EXECUTABLE cache (``jax.experimental.serialize_executable``)
     — a fresh process skips trace AND compile AND compile-cache load:
     measured ~1.3 s for a 46 MB boost-chunk executable vs ~2.6 s for the
     round-2 StableHLO path and ~20-40 s for a cold compile. ``prewarm()``
     loads banked executables for the current (backend, device-count) on a
     thread pool — optionally filtered to the program NAMES a DAG will
     actually need (``compiler.warmup`` drives this) — so the model-selector
     phase finds them in ``_MEM``;
  3. transparent fallback to a direct ``jit_fn(*args, **statics)`` call on
     ANY failure (new shapes still work; blobs self-invalidate via a
     source-version salt in the key).

Program identity = (source salt incl. jax version, backend, device count,
ambient mesh fingerprint, arg tree structure + shapes/dtypes/shardings,
static kwargs). Blob files are ``{salt}-{name}-{key}.jaxexec`` under
``.jax_cache/execs/{backend}-{ndev}`` (override the root with
``TPTPU_COMPILE_CACHE``); writes are atomic (unique tmp + ``os.replace``),
corrupt/truncated blobs are deleted and recompiled. See docs/tpu.md.

Opt out with TPTPU_AOT=0.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import re
import threading
import time as _time
from typing import Any, Callable

log = logging.getLogger(__name__)

_LOCK = threading.Lock()
_MEM: dict = {}
_PENDING: set = set()
_FAILED: set = set()
_THREADS: list = []
_SALT: str | None = None

_START = _time.monotonic()


def _stats():
    from ..compiler import stats as _s

    return _s.stats()


def _drain_exports() -> None:
    """Give in-flight background executable saves a chance to land before
    the process exits — daemon threads are otherwise killed mid-compile and
    the blob never materializes. The wait is scaled to process lifetime (a
    process that ran t seconds waits at most min(600, max(5, 2t))): quick
    scoring CLI runs exit within seconds, while long bench/training runs
    may sit out a background compile that takes minutes — capping those at
    60 s starved the bank forever (the same key re-missed every run)."""
    elapsed = _time.monotonic() - _START
    # long-lived processes (bench/training runs) may be draining a save
    # whose background compile is minutes — capping those at 60 s starves
    # the bank forever (the same key misses every run); quick CLI runs
    # stay bounded by twice their own lifetime
    budget = min(600.0, max(5.0, 2.0 * elapsed))
    deadline = _time.monotonic() + budget
    for th in list(_THREADS):
        th.join(timeout=max(0.0, deadline - _time.monotonic()))
    alive = [th for th in _THREADS if th.is_alive()]
    if alive:
        log.info("abandoning %d unfinished AOT saves at exit", len(alive))


import atexit  # noqa: E402

atexit.register(_drain_exports)


class DonatedArgsConsumed(RuntimeError):
    """A banked executable donated (deleted) some of the caller's args and
    then failed — no in-place fallback can run. Propagated past aot_call's
    transparent-fallback handler so the caller-level retry (the
    candidate-sweep RetryPolicy) re-enters with fresh buffers."""


def _enabled() -> bool:
    return os.environ.get("TPTPU_AOT", "1") != "0"


def _exec_dir() -> str:
    import jax

    root = os.environ.get("TPTPU_COMPILE_CACHE")
    if not root:
        root = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            ".jax_cache",
        )
    base = os.path.join(
        root, "execs", f"{jax.default_backend()}-{len(jax.devices())}"
    )
    os.makedirs(base, exist_ok=True)
    return base


def _version_salt() -> str:
    """Hash of the source files whose tracing the cache skips — a code
    change invalidates every blob. The jax version rides the salt too: a
    serialized executable is runtime-specific, and loading one saved under
    a different jax/XLA build is undefined behavior at best."""
    global _SALT
    if _SALT is None:
        import jax

        h = hashlib.sha256()
        h.update(b"aot-format-2")  # filename layout: salt-name-key.jaxexec
        h.update(f"jax={jax.__version__}".encode())
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # every file that DEFINES an aot_call-routed jit_fn must be listed,
        # or editing it serves stale banked executables of the old code
        for rel in (
            "models/trees.py", "models/hist_pallas.py", "models/solvers.py",
            "models/gbdt.py", "ops/embeddings.py",
        ):
            try:
                with open(os.path.join(pkg, rel), "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(rel.encode())
        # trace-time env knobs are program identity too: a blob exported
        # under one knob value must not be served to a process expecting
        # another (TPTPU_HIST additionally rides the explicit statics).
        # TPTPU_DONATE is in the list because donation is baked into the
        # serialized executable: a donating blob served to a donate-off
        # process would still delete the caller's buffers (and vice versa
        # a donate-off blob would permanently disable the optimization).
        for knob in ("TPTPU_HIST", "TPTPU_HIST_COMB", "TPTPU_GEMM_MCAP",
                     "TPTPU_BOOST_CHUNK", "TPTPU_DONATE"):
            h.update(f"{knob}={os.environ.get(knob, '')}".encode())
        _SALT = h.hexdigest()[:16]
    return _SALT


def _mesh_fp() -> str:
    """Compact ambient-execution-mesh fingerprint: a blob compiled for a
    4-device data mesh must never shadow the single-device program of the
    same shapes (and per-leaf shardings alone miss fully-replicated
    args)."""
    try:
        from ..parallel.mesh import execution_mesh

        mesh = execution_mesh()
    except Exception:
        return "none"
    if mesh is None:
        return "none"
    try:
        return ",".join(
            f"{name}{int(mesh.shape[name])}" for name in mesh.axis_names
        )
    except Exception:
        return "unknown"


def _key(name: str, args: tuple, statics: dict) -> str:
    import jax

    # device count + per-leaf shardings are part of program identity: a
    # blob exported single-device must not shadow a mesh-sharded variant
    # (and vice versa) on the same backend/shapes
    parts = [name, _version_salt(), jax.default_backend(),
             f"ndev={len(jax.devices())}", f"mesh={_mesh_fp()}"]
    parts.append(str(jax.tree_util.tree_structure(args)))
    for a in jax.tree_util.tree_leaves(args):
        parts.append(f"{getattr(a, 'shape', ())}:{getattr(a, 'dtype', type(a).__name__)}")
        sharding = getattr(a, "sharding", None)
        if sharding is not None:
            parts.append(str(sharding))
    for k in sorted(statics):
        parts.append(f"{k}={statics[k]}")
    return hashlib.sha256("|".join(map(str, parts)).encode()).hexdigest()[:24]


def _safe_name(name: str) -> str:
    """Program name as a filename segment (no dashes: the filename parser
    splits on them)."""
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


def _blob_path(name: str, key: str) -> str:
    return os.path.join(
        _exec_dir(), f"{_version_salt()}-{_safe_name(name)}-{key}.jaxexec"
    )


def _parse_blob_name(fn: str) -> tuple[str, str, str] | None:
    """(salt, name, key) from ``salt-name-key.jaxexec``; None for files in
    an unknown layout (deleted on sight, like any stale-version blob)."""
    if not fn.endswith(".jaxexec"):
        return None
    parts = fn[: -len(".jaxexec")].split("-")
    if len(parts) != 3:
        return None
    return parts[0], parts[1], parts[2]


def _load_exec(path: str):
    """pickle → deserialize_and_load → callable; raises on a corrupt or
    truncated blob (callers delete-and-recompile)."""
    from jax.experimental import serialize_executable as SE

    t0 = _time.monotonic()
    with open(path, "rb") as fh:
        blob = pickle.loads(fh.read())
    if not isinstance(blob, tuple) or len(blob) != 3:
        # pickle decoded but the payload is not ours — a torn write that
        # happened to truncate on a valid pickle boundary
        raise ValueError(f"malformed executable blob (got {type(blob).__name__})")
    payload, in_tree, out_tree = blob
    compiled = SE.deserialize_and_load(payload, in_tree, out_tree)
    log.info(
        "AOT load %s (%.1f MB) in %.2f s", os.path.basename(path),
        os.path.getsize(path) / 1e6, _time.monotonic() - t0,
    )
    try:
        os.utime(path)  # recency marker for pruning
    except OSError:
        pass
    return lambda *a: compiled(*a)


def _acquire_banked(path: str, name: str, key: str):
    """Lazy (non-prewarm) acquire of a banked executable, guarded the same
    way ``prewarm`` guards its loads: a corrupt/truncated ``.jaxexec`` is
    deleted so the caller recompiles, instead of crashing the sweep thread
    that happened to touch it first. Returns a callable or None."""
    if not os.path.exists(path):
        return None
    try:
        return _load_exec(path)
    except Exception as e:
        log.info("AOT executable %s unusable (%s); removing", key, e)
        _stats().bump("corruptBlobsDropped")
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def prewarm(
    max_workers: int = 8,
    max_bytes: int = 32_000_000,
    names: set | frozenset | None = None,
) -> int:
    """Load banked executables for this backend/device-count into ``_MEM``
    on a thread pool. Call early (e.g. right after backend init) so
    acquisition overlaps the data/feature phases; returns the number of
    programs loaded. ``names`` restricts the load to those program names
    (the DAG-aware warmup passes the families it will actually fit) —
    unlisted blobs stay on disk untouched. Files from other source
    versions can never hit (the key embeds the salt), so they are deleted
    on sight — without this the bank grows by a full program set per source
    edit and prewarm ships gigabytes of dead executables."""
    if not _enabled():
        return 0
    try:
        d = _exec_dir()
    except Exception:
        return 0
    salt = _version_salt()
    safe_names = None if names is None else {_safe_name(n) for n in names}
    paths = []
    for fn in os.listdir(d):
        if not fn.endswith(".jaxexec"):
            continue
        p = os.path.join(d, fn)
        parsed = _parse_blob_name(fn)
        if parsed is None or parsed[0] != salt:
            _stats().bump("versionInvalidations")
            try:
                os.remove(p)
            except OSError:
                pass
            continue
        _salt_seg, name_seg, _key_seg = parsed
        if safe_names is not None and name_seg not in safe_names:
            continue
        try:
            if os.path.getsize(p) > max_bytes:
                # big executables ship their binary over the tunneled link
                # at load — prewarming them CONTENDS with the foreground
                # work's device traffic (measured: a ~1 GB prewarm stalls
                # the first sweep ~20 s). They load lazily instead, inside
                # whichever family thread needs them.
                continue
        except OSError:
            continue
        paths.append(p)
    if not paths:
        return 0
    from concurrent.futures import ThreadPoolExecutor

    loaded = [0]

    def _one(p):
        key = _parse_blob_name(os.path.basename(p))[2]
        with _LOCK:
            if key in _MEM:
                return
        try:
            call = _load_exec(p)
        except Exception as e:
            log.info("prewarm: dropping unusable executable %s (%s)", p, e)
            _stats().bump("corruptBlobsDropped")
            try:
                os.remove(p)
            except OSError:
                pass
            return
        with _LOCK:
            _MEM.setdefault(key, call)
            loaded[0] += 1

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        list(pool.map(_one, paths))
    log.info("prewarm: %d executables loaded", loaded[0])
    return loaded[0]


def aot_call(
    name: str, jit_fn: Callable, args: tuple, statics: dict
) -> Any:
    """``jit_fn(*args, **statics)`` through the executable cache.

    NOTE on donation: when ``jit_fn`` was built with ``donate_argnums``
    (compiler.dispatch.donating), the banked executable donates too —
    callers must treat those args as consumed on EVERY path through here.
    """
    if not _enabled():
        return jit_fn(*args, **statics)
    try:
        key = _key(name, args, statics)
        with _LOCK:
            call = _MEM.get(key)
        if call is not None:
            # NOTE: dispatch is async — timing this call would measure
            # enqueue latency, not execution
            log.debug("AOT hit %s (%s)", name, key)
            _stats().bump("cacheHitsMemory")
            return call(*args)
        path = _blob_path(name, key)
        call = _acquire_banked(path, name, key)
        if call is not None:
            try:
                out = call(*args)
                with _LOCK:
                    _MEM[key] = call
                _stats().bump("cacheHitsDisk")
                return out
            except Exception as e:
                # blob deserialized but the executable is broken (stale
                # runtime, torn payload): remove it so a future first-use
                # re-saves instead of permanently disabling the cache
                log.info("AOT executable %s unusable (%s); removing", key, e)
                _stats().bump("corruptBlobsDropped")
                try:
                    os.remove(path)
                except OSError:
                    pass
                import jax

                if any(
                    getattr(a, "is_deleted", lambda: False)()
                    for a in jax.tree_util.tree_leaves(args)
                ):
                    # the broken executable DONATED some args before
                    # failing — the direct-call fallback below would crash
                    # on the deleted buffers with a baffling error deep in
                    # dispatch. Re-raise instead: the candidate-level
                    # RetryPolicy (selector/validators.py) re-enters the
                    # sweep with fresh buffers, and the blob is gone.
                    log.warning(
                        "AOT executable %s consumed donated args before "
                        "failing; re-raising for caller-level retry", key,
                    )
                    raise DonatedArgsConsumed(
                        f"banked executable for {name} failed after "
                        f"donating its inputs: {e}"
                    ) from e
        # first use of this program version: run directly, then save the
        # compiled executable in the background so FUTURE processes skip
        # trace+compile. _PENDING dedupes concurrent validator threads;
        # _FAILED is the negative cache; the tmp suffix is unique per
        # thread so racing writers can't interleave one file.
        t_direct = _time.monotonic()
        import warnings

        with warnings.catch_warnings():
            # donated lane params ([K] reg/elastic-net) alias the [K]
            # intercept output; the [K'] bucketed twin of a sweep whose
            # shapes DON'T line up is expected to fall back to copy —
            # jax warns per-compile, which would spam every sweep
            warnings.filterwarnings(
                "ignore", message=".*donated buffers.*"
            )
            out = jit_fn(*args, **statics)
        log.info(
            "AOT miss %s (%s): direct call %.2f s", name, key,
            _time.monotonic() - t_direct,
        )
        _stats().record_compile(name)
        with _LOCK:
            if key not in _MEM:
                # same-process repeats reuse jit_fn's warm cache
                _MEM[key] = lambda *a: jit_fn(*a, **statics)
            if key in _PENDING or key in _FAILED:
                return out
            _PENDING.add(key)

        def _save():
            try:
                if os.environ.get("TPTPU_PROGRAM_AUDIT", "0") == "1":
                    # bank-admission contract audit (analysis/program.py):
                    # a program that bakes giant constants, leaks x64,
                    # or embeds host callbacks must never persist a blob
                    # — the violating executable would be served to every
                    # future process. Runs on this background thread, so
                    # the audit costs the foreground dispatch nothing;
                    # with the env unset the gate is one dict read.
                    from ..analysis.program import audit_jit_call

                    _stats().bump("programsAudited")
                    audit_rep = audit_jit_call(name, jit_fn, args, statics)
                    # ERROR findings only (baked constants, x64 leaks,
                    # host callbacks): warnings are reported, not
                    # refused — a weak-typed auxiliary output must not
                    # negative-cache the program out of the bank
                    bad = audit_rep.errors()
                    if bad:
                        _stats().bump("programAuditRejected")
                        log.warning(
                            "program audit refused bank admission of %s: "
                            "%s", name,
                            "; ".join(f.render() for f in bad),
                        )
                        with _LOCK:
                            _FAILED.add(key)
                        return
                from jax.experimental import serialize_executable as SE

                # .lower().compile() hits the jit's persistent compile
                # cache (same computation), so this is load-cost, not a
                # recompile. Lowering only needs avals, so it is safe even
                # when the direct call above DONATED some of args.
                t0 = _time.monotonic()
                compiled = jit_fn.lower(*args, **statics).compile()
                payload, in_tree, out_tree = SE.serialize(compiled)
                blob = pickle.dumps((payload, in_tree, out_tree))
                tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
                with open(tmp, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
                log.info(
                    "AOT saved %s (%s, %.1f MB) in %.1f s", name, key,
                    len(blob) / 1e6, _time.monotonic() - t0,
                )
            except Exception as e:  # never break the fit for the cache
                log.info("AOT save of %s failed: %s", name, e)
                _stats().bump("savesFailed")
                with _LOCK:
                    _FAILED.add(key)
            finally:
                with _LOCK:
                    _PENDING.discard(key)

        th = threading.Thread(target=_save, daemon=True)
        with _LOCK:
            _THREADS.append(th)
        th.start()
        return out
    except DonatedArgsConsumed:
        # args are gone — the transparent direct-call fallback below would
        # crash on deleted buffers; let the caller-level retry recover
        raise
    except Exception as e:
        log.info("AOT cache bypassed for %s: %s", name, e)
        return jit_fn(*args, **statics)
