"""Text helpers: cleaning (reference TextUtils semantics), tokenization, and
a deterministic MurmurHash3 for feature hashing.

Reference: utils/.../text/TextUtils.scala:39 (cleanString), core/.../feature/
TextTokenizer.scala (Lucene analyzers — replaced by a locale-light regex
tokenizer with the same observable defaults: lowercase, min token length),
and HashAlgorithm.MurMur3 (OPCollectionHashingVectorizer).
"""
from __future__ import annotations

import re
import struct

_PUNCT_RE = re.compile(r"[\W_]+", flags=re.UNICODE)
_TOKEN_RE = re.compile(r"[^\s\W_]+", flags=re.UNICODE)


def clean_string(raw: str) -> str:
    """TextUtils.cleanString: lowercase, strip punctuation, capitalize each
    word, join with no separator ("hello-world!" -> "HelloWorld")."""
    words = _PUNCT_RE.sub(" ", raw.lower()).split()
    return "".join(w.capitalize() for w in words)


def tokenize(
    text: str,
    to_lowercase: bool = True,
    min_token_length: int = 1,
) -> list[str]:
    """Language-light tokenizer standing in for Lucene's analyzers
    (TextTokenizer defaults: ToLowercase=true, MinTokenLength=1)."""
    if to_lowercase:
        text = text.lower()
    return [t for t in _TOKEN_RE.findall(text) if len(t) >= min_token_length]


def murmur3_32(data: str | bytes, seed: int = 42) -> int:
    """MurmurHash3 x86 32-bit — deterministic feature hashing
    (HashAlgorithm.MurMur3 in OPCollectionHashingVectorizer.scala)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    length = len(data)
    rounded = length & ~0x3
    for i in range(0, rounded, 4):
        k = struct.unpack_from("<I", data, i)[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = length & 0x3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def hash_to_index(value: str, num_features: int, seed: int = 42) -> int:
    """Non-negative bucket index for feature hashing."""
    return murmur3_32(value, seed) % num_features
