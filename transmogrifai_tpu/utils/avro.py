"""Minimal pure-Python Avro Object Container File reader.

Replaces the reference's Avro ingestion dependency (readers/.../
CSVAutoReaders.scala, utils/.../io/avro/AvroInOut.scala) for environments
without an avro wheel. Supports the container format (magic Obj\\x01, file
metadata, sync-marked blocks; null/deflate codecs) and the datum types the
reference's record schemas use: primitives, records, enums, fixed, arrays,
maps, and unions. Schema evolution/resolution is out of scope — files are
read with their writer schema.
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, BinaryIO, Iterator

import numpy as np

_MAGIC = b"Obj\x01"


class AvroError(ValueError):
    pass


def _snappy_uncompress(data: bytes) -> bytes:
    """Minimal pure-Python raw-Snappy decompressor (no snappy wheel in the
    image; Avro's snappy codec frames each block as raw snappy + 4-byte
    big-endian CRC32 of the plaintext). Format: varint plaintext length,
    then tagged elements — 00 literal, 01/10/11 back-references."""
    # preamble: uncompressed length varint
    n = 0
    shift = 0
    i = 0
    while True:
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                length = int.from_bytes(data[i:i + extra], "little")
                i += extra
            length += 1
            out += data[i:i + length]
            i += length
            continue
        if kind == 1:  # copy with 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[i]
            i += 1
        elif kind == 2:  # copy with 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[i:i + 2], "little")
            i += 2
        else:  # copy with 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[i:i + 4], "little")
            i += 4
        if offset == 0 or offset > len(out):
            raise AvroError("corrupt snappy stream (bad offset)")
        start = len(out) - offset
        for k in range(length):  # overlapping copies are byte-sequential
            out.append(out[start + k])
    if len(out) != n:
        raise AvroError("corrupt snappy stream (length mismatch)")
    return bytes(out)


def _read_long(fh: BinaryIO, first: bytes | None = None) -> int:
    """Zig-zag varint (Avro long); ``first`` is an already-consumed byte."""
    shift = 0
    acc = 0
    while True:
        b = first if first is not None else fh.read(1)
        first = None
        if not b:
            raise AvroError("unexpected EOF in varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _read_bytes(fh: BinaryIO) -> bytes:
    n = _read_long(fh)
    data = fh.read(n)
    if len(data) != n:
        raise AvroError("unexpected EOF in bytes")
    return data


def _read_datum(fh: BinaryIO, schema: Any) -> Any:
    if isinstance(schema, str):
        kind = schema
    elif isinstance(schema, list):
        # union: long index then the selected branch
        idx = _read_long(fh)
        if not 0 <= idx < len(schema):
            raise AvroError(f"union index {idx} out of range")
        return _read_datum(fh, schema[idx])
    else:
        kind = schema["type"]

    if kind == "null":
        return None
    if kind == "boolean":
        b = fh.read(1)
        if not b:
            raise AvroError("unexpected EOF in boolean")
        return b[0] != 0
    if kind in ("int", "long"):
        return _read_long(fh)
    if kind == "float":
        return struct.unpack("<f", fh.read(4))[0]
    if kind == "double":
        return struct.unpack("<d", fh.read(8))[0]
    if kind == "bytes":
        return _read_bytes(fh)
    if kind == "string":
        return _read_bytes(fh).decode("utf-8")
    if kind == "record":
        return {
            f["name"]: _read_datum(fh, f["type"]) for f in schema["fields"]
        }
    if kind == "enum":
        idx = _read_long(fh)
        symbols = schema["symbols"]
        if not 0 <= idx < len(symbols):
            raise AvroError(f"enum index {idx} out of range")
        return symbols[idx]
    if kind == "fixed":
        size = schema["size"]
        data = fh.read(size)
        if len(data) != size:
            raise AvroError(
                f"truncated fixed: wanted {size} bytes, got {len(data)}"
            )
        return data
    if kind == "array":
        out = []
        while True:
            n = _read_long(fh)
            if n == 0:
                break
            if n < 0:  # block with byte size prefix
                n = -n
                _read_long(fh)
            for _ in range(n):
                out.append(_read_datum(fh, schema["items"]))
        return out
    if kind == "map":
        out = {}
        while True:
            n = _read_long(fh)
            if n == 0:
                break
            if n < 0:
                n = -n
                _read_long(fh)
            for _ in range(n):
                key = _read_bytes(fh).decode("utf-8")
                out[key] = _read_datum(fh, schema["values"])
        return out
    raise AvroError(f"unsupported Avro type: {kind!r}")


def read_container(fh: BinaryIO) -> Iterator[Any]:
    """Yield datums from an Avro Object Container File."""
    if fh.read(4) != _MAGIC:
        raise AvroError("not an Avro container file (bad magic)")
    meta: dict[str, bytes] = {}
    while True:
        n = _read_long(fh)
        if n == 0:
            break
        if n < 0:
            n = -n
            _read_long(fh)
        for _ in range(n):
            key = _read_bytes(fh).decode("utf-8")
            meta[key] = _read_bytes(fh)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate", "snappy"):
        raise AvroError(f"unsupported codec: {codec}")
    sync = fh.read(16)
    while True:
        head = fh.read(1)
        if not head:
            return
        count = _read_long(fh, first=head)
        size = _read_long(fh)
        data = fh.read(size)
        if len(data) != size:
            raise AvroError("unexpected EOF in block")
        if codec == "deflate":
            data = zlib.decompress(data, -15)
        elif codec == "snappy":
            plain = _snappy_uncompress(data[:-4])
            crc = int.from_bytes(data[-4:], "big")
            if zlib.crc32(plain) & 0xFFFFFFFF != crc:
                raise AvroError("snappy block CRC mismatch")
            data = plain
        block = io.BytesIO(data)
        for _ in range(count):
            yield _read_datum(block, schema)
        marker = fh.read(16)
        if marker != sync:
            raise AvroError("sync marker mismatch (corrupt block)")


def read_avro(path: str) -> list[Any]:
    with open(path, "rb") as fh:
        return list(read_container(fh))


# ---------------------------------------------------------------------------
# writer (tests + fixture generation; null codec only)
# ---------------------------------------------------------------------------
def _write_long(out: BinaryIO, v: int) -> None:
    v = (v << 1) ^ (v >> 63)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _write_bytes(out: BinaryIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


def _branch_accepts(kind: str, v: Any, strict: bool) -> bool:
    """Does a union branch of ``kind`` match the value's type? numbers.ABCs
    cover numpy scalars (np.float32 is Real, np.int64 is Integral)."""
    import numbers

    is_bool = isinstance(v, (bool, np.bool_))
    if kind == "boolean":
        return is_bool
    if kind in ("int", "long"):
        return not is_bool and isinstance(v, numbers.Integral)
    if kind in ("float", "double"):
        if is_bool:
            return False
        if isinstance(v, numbers.Real) and not isinstance(v, numbers.Integral):
            return True
        # relaxed pass: ints may encode as float/double
        return not strict and isinstance(v, numbers.Integral)
    if kind in ("string", "enum"):
        return isinstance(v, str)
    if kind in ("bytes", "fixed"):
        return isinstance(v, (bytes, bytearray))
    if kind in ("record", "map"):
        return isinstance(v, dict)
    if kind == "array":
        return isinstance(v, (list, tuple, np.ndarray))
    return not strict


def _write_datum(out: BinaryIO, schema: Any, v: Any) -> None:
    if isinstance(schema, list):
        # match the branch to the VALUE's type — picking the first
        # non-null branch mis-encodes multi-branch unions like
        # ["null","int","string"] for string values
        for i, branch in enumerate(schema):
            kind = branch if isinstance(branch, str) else branch["type"]
            if v is None and kind == "null":
                _write_long(out, i)
                return
        for strict in (True, False):
            for i, branch in enumerate(schema):
                kind = branch if isinstance(branch, str) else branch["type"]
                if v is None or kind == "null":
                    continue
                if _branch_accepts(kind, v, strict):
                    _write_long(out, i)
                    _write_datum(out, branch, v)
                    return
        raise AvroError(f"no matching union branch for {type(v).__name__}")
    kind = schema if isinstance(schema, str) else schema["type"]
    if kind == "null":
        return
    if kind == "boolean":
        out.write(b"\x01" if v else b"\x00")
    elif kind in ("int", "long"):
        _write_long(out, int(v))
    elif kind == "float":
        out.write(struct.pack("<f", float(v)))
    elif kind == "double":
        out.write(struct.pack("<d", float(v)))
    elif kind == "bytes":
        _write_bytes(out, v)
    elif kind == "string":
        _write_bytes(out, v.encode("utf-8"))
    elif kind == "record":
        for f in schema["fields"]:
            _write_datum(out, f["type"], v[f["name"]])
    elif kind == "enum":
        _write_long(out, schema["symbols"].index(v))
    elif kind == "array":
        if v:
            _write_long(out, len(v))
            for item in v:
                _write_datum(out, schema["items"], item)
        _write_long(out, 0)
    elif kind == "map":
        if v:
            _write_long(out, len(v))
            for k, item in v.items():
                _write_bytes(out, k.encode("utf-8"))
                _write_datum(out, schema["values"], item)
        _write_long(out, 0)
    else:
        raise AvroError(f"unsupported Avro type: {kind!r}")


def write_avro(path: str, schema: dict, records: list[Any]) -> None:
    """Write an Avro container file (null codec) — used by tests and the
    CSV→Avro conversion path (CSVToAvro.scala equivalent)."""
    sync = b"\x00" * 8 + b"tptpusyn"
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null",
        }
        _write_long(fh, len(meta))
        for k, v in meta.items():
            _write_bytes(fh, k.encode())
            _write_bytes(fh, v)
        _write_long(fh, 0)
        fh.write(sync)
        block = io.BytesIO()
        for r in records:
            _write_datum(block, schema, r)
        data = block.getvalue()
        _write_long(fh, len(records))
        _write_long(fh, len(data))
        fh.write(data)
        fh.write(sync)
