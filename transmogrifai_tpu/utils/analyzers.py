"""Per-language text analyzers — tokenize → lowercase → stopword filter →
stem, per language.

Reference: core/.../utils/text/LuceneTextAnalyzer.scala:1-236 wires a Lucene
analyzer per detected language under TextTokenizer and every smart-text
path; the reference ships pretrained model support for 7 languages
(models/README.md — da, de, en, es, nl, pt, sv). This module reimplements
those seven analyzers' observable behavior without the JVM:

  * en — Porter stemmer (Lucene EnglishAnalyzer: possessive strip,
    lowercase, stop filter, PorterStemFilter);
  * da / sv — Snowball Danish / Swedish stemmers (suffix stripping over the
    R1 region, per the published Snowball definitions);
  * de — German normalization (ä→a … ß→ss) + German light stemmer;
  * es / pt — Spanish / Portuguese light stemmers (plural + gender
    suffixes);
  * nl — Dutch Snowball-style suffix stripping (e/en removal with
    undoubling, heden→heid, -ing/-end in R2).

The stemmers are implementations of the published public-domain algorithms
(snowballstem.org; Savoy's light stemmers) — behavior, not code, is ported.
Stopword sets are the standard per-language lists those analyzers use.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

from .text import tokenize

# --------------------------------------------------------------------------
# stopwords (standard snowball/Lucene lists, condensed to the high-frequency
# cores those filters actually remove in practice)
# --------------------------------------------------------------------------
STOPWORDS: dict[str, frozenset[str]] = {
    # the exact Lucene/StandardAnalyzer English stop set (33 words) —
    # EnglishAnalyzer filters precisely these, nothing more
    "en": frozenset(
        """a an and are as at be but by for if in into is it no not of on or
        such that the their then there these they this to was will
        with""".split()
    ),
    "da": frozenset(
        """og i jeg det at en den til er som på de med han af for ikke der
        var mig sig men et har om vi min havde ham hun nu over da fra du ud
        sin dem os op man hans hvor eller hvad skal selv her alle vil blev
        kunne ind når være dog noget ville jo deres efter ned skulle denne
        end dette mit også under have dig anden hende mine alt meget sit sine
        vor mod disse hvis din nogle hos blive mange ad bliver hendes været
        thi jer sådan""".split()
    ),
    "de": frozenset(
        """aber alle allem allen aller alles als also am an ander andere
        anderem anderen anderer anderes auch auf aus bei bin bis bist da
        damit dann das dass dasselbe dein deine dem den denn der des dessen
        die dies diese diesem diesen dieser dieses dir doch dort du durch
        ein eine einem einen einer eines einig einige er es etwas euer für
        gegen gewesen hab habe haben hat hatte hatten hier hin hinter ich
        ihm ihn ihnen ihr ihre im in indem ins ist ja jede jedem jeden jeder
        jedes jene kann kein keine können könnte machen man manche mein
        meine mich mir mit muss musste nach nicht nichts noch nun nur ob
        oder ohne sehr sein seine sich sie sind so solche soll sollte
        sondern sonst über um und uns unser unter viel vom von vor während
        war waren warst was weg weil weiter welche wenn werde werden wie
        wieder will wir wird wirst wo wollen wollte würde würden zu zum zur
        zwar zwischen""".split()
    ),
    "es": frozenset(
        """de la que el en y a los del se las por un para con no una su al
        lo como más pero sus le ya o este sí porque esta entre cuando muy
        sin sobre también me hasta hay donde quien desde todo nos durante
        todos uno les ni contra otros ese eso ante ellos e esto mí antes
        algunos qué unos yo otro otras otra él tanto esa estos mucho
        quienes nada muchos cual poco ella estar estas algunas algo
        nosotros mi mis tú te ti tu tus ellas nosotras vosotros vosotras os
        mío mía míos mías tuyo tuya tuyos tuyas suyo suya suyos suyas
        nuestro nuestra nuestros nuestras vuestro vuestra vuestros vuestras
        esos esas es soy eres somos sois está estás estamos estáis están
        fue fui son era eras éramos eran ser""".split()
    ),
    "nl": frozenset(
        """de en van ik te dat die in een hij het niet zijn is was op aan
        met als voor had er maar om hem dan zou of wat mijn men dit zo door
        over ze zich bij ook tot je mij uit der daar haar naar heb hoe heeft
        hebben deze u want nog zal me zij nu ge geen omdat iets worden
        toch al waren veel meer doen toen moet ben zonder kan hun dus alles
        onder ja eens hier wie werd altijd doch wordt wezen kunnen ons zelf
        tegen na reeds wil kon niets uw iemand geweest andere""".split()
    ),
    "pt": frozenset(
        """de a o que e do da em um para é com não uma os no se na por mais
        as dos como mas foi ao ele das tem à seu sua ou ser quando muito há
        nos já está eu também só pelo pela até isso ela entre era depois
        sem mesmo aos ter seus quem nas me esse eles estão você tinha foram
        essa num nem suas meu às minha têm numa pelos elas havia seja qual
        será nós tenho lhe deles essas esses pelas este fosse dele tu te
        vocês vos lhes meus minhas teu tua teus tuas nosso nossa nossos
        nossas dela delas esta estes estas aquele aquela aqueles aquelas
        isto aquilo estou está estamos estão estive esteve estivemos
        estiveram era éramos eram fui foi fomos foram seja sejamos sou
        somos são""".split()
    ),
    "sv": frozenset(
        """och det att i en jag hon som han på den med var sig för så till
        är men ett om hade de av icke mig du henne då sin nu har inte hans
        honom skulle hennes där min man ej vid kunde något från ut när
        efter upp vi dem vara vad över än dig kan sina här ha mot alla
        under någon eller allt mycket sedan ju denna själv detta åt utan
        varit hur ingen mitt ni bli blev oss din dessa några deras blir
        mina samma vilken er sådan vår blivit dess inom mellan sådant
        varför varje vilka ditt vem vilket sitta sådana vart dina vars
        vårt våra ert era vilkas""".split()
    ),
    "fr": frozenset(
        """au aux avec ce ces dans de des du elle en et eux il ils je la le
        les leur lui ma mais me même mes moi mon ne nos notre nous on ou où
        par pas pour qu que qui sa se ses son sur ta te tes toi ton tu un
        une vos votre vous c d j l à m n s t y été étée étées étés étant
        suis es est sommes êtes sont serai sera seront étais était étions
        fus fut ai as avons avez ont aurai aura auront avais avait avions
        eus eut""".split()
    ),
    "it": frozenset(
        """ad al allo ai agli alla alle con col coi da dal dallo dai dagli
        dalla dalle di del dello dei degli della delle in nel nello nei
        negli nella nelle su sul sullo sui sugli sulla sulle per tra fra io
        tu lui lei noi voi loro mio mia miei mie tuo tua tuoi tue suo sua
        suoi sue nostro nostra nostri nostre vostro vostra vostri vostre
        che chi cui non come dove quale quanto quanti quanta quante questo
        questi questa queste quello quelli quella quelle si tutto tutti a e
        ed o ho hai ha abbiamo avete hanno è sono sei siamo siete era erano
        sarà sia ma se perché anche più""".split()
    ),
    "ru": frozenset(
        """и в во не что он на я с со как а то все она так его но да ты к у
        же вы за бы по ее мне было вот от меня еще нет о из ему теперь
        когда даже ну ли если уже или ни быть был него до вас нибудь вам
        сказал себя ей может они есть надо ней для мы тебя их чем была сам
        чтоб без будто чего раз тоже себе под будет тогда кто этот того
        потому этого какой ним здесь этом один почти мой тем чтобы нее
        были куда зачем всех можно при об хоть после над больше тот через
        эти нас про всего них какая много разве эту моя свою этой перед
        иногда лучше чуть том такой им более всегда конечно всю между
        это""".split()
    ),
}

_VOWELS = {
    "en": "aeiouy",
    "da": "aeiouyæåø",
    "sv": "aeiouyäåö",
    "nl": "aeiouyè",
    "de": "aeiouyäöü",
    "es": "aeiouáéíóúü",
    "pt": "aeiouáéíóúâêôãõ",
}


def _r1(word: str, vowels: str) -> int:
    """Snowball R1: position after the first non-vowel following a vowel."""
    for i in range(len(word) - 1):
        if word[i] in vowels and word[i + 1] not in vowels:
            return i + 2
    return len(word)


# --------------------------------------------------------------------------
# English — Porter stemmer (the classic 1980 algorithm, as PorterStemFilter)
# --------------------------------------------------------------------------
def _porter_is_cons(w: str, i: int) -> bool:
    c = w[i]
    if c in "aeiou":
        return False
    if c == "y":
        return i == 0 or not _porter_is_cons(w, i - 1)
    return True


def _porter_m(w: str) -> int:
    """Measure: number of VC sequences."""
    forms = []
    for i in range(len(w)):
        forms.append("c" if _porter_is_cons(w, i) else "v")
    s = "".join(forms)
    s = re.sub(r"c+", "C", s)
    s = re.sub(r"v+", "V", s)
    return s.count("VC")


def _porter_has_vowel(w: str) -> bool:
    return any(not _porter_is_cons(w, i) for i in range(len(w)))


def _porter_cvc(w: str) -> bool:
    if len(w) < 3:
        return False
    return (
        _porter_is_cons(w, len(w) - 3)
        and not _porter_is_cons(w, len(w) - 2)
        and _porter_is_cons(w, len(w) - 1)
        and w[-1] not in "wxy"
    )


def porter_stem(w: str) -> str:
    if len(w) <= 2:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]
    # step 1b
    if w.endswith("eed"):
        if _porter_m(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and _porter_has_vowel(w[:-2]):
            w = w[:-2]
            flag = True
        elif w.endswith("ing") and _porter_has_vowel(w[:-3]):
            w = w[:-3]
            flag = True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif (
                len(w) >= 2
                and w[-1] == w[-2]
                and _porter_is_cons(w, len(w) - 1)
                and w[-1] not in "lsz"
            ):
                w = w[:-1]
            elif _porter_m(w) == 1 and _porter_cvc(w):
                w += "e"
    # step 1c
    if w.endswith("y") and _porter_has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in (
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
        ("anci", "ance"), ("izer", "ize"), ("abli", "able"), ("alli", "al"),
        ("entli", "ent"), ("eli", "e"), ("ousli", "ous"), ("ization", "ize"),
        ("ation", "ate"), ("ator", "ate"), ("alism", "al"),
        ("iveness", "ive"), ("fulness", "ful"), ("ousness", "ous"),
        ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
    ):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _porter_m(stem) > 0:
                w = stem + rep
            break
    # step 3
    for suf, rep in (
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _porter_m(stem) > 0:
                w = stem + rep
            break
    # step 4
    for suf in (
        "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
    ):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _porter_m(stem) > 1:
                w = stem
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st":
            if _porter_m(w[:-3]) > 1:
                w = w[:-3]
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = _porter_m(stem)
        if m > 1 or (m == 1 and not _porter_cvc(stem)):
            w = stem
    # step 5b
    if len(w) >= 2 and w[-1] == "l" and w[-2] == "l" and _porter_m(w) > 1:
        w = w[:-1]
    return w


# --------------------------------------------------------------------------
# Danish / Swedish — Snowball stemmers (R1-bounded suffix stripping)
# --------------------------------------------------------------------------
_DA_STEP1 = sorted(
    """hed ethed ered e erede ende erende ene erne ere en heden heder heds
    ed hederne erets eret hedens erendes endes enes er ernes eres ens ers
    ets es et s""".split(),
    key=len, reverse=True,
)
_DA_S_ENDINGS = set("abcdfghjklmnoprtvyzå")


def danish_stem(w: str) -> str:
    r1 = max(_r1(w, _VOWELS["da"]), 3)
    # step 1: longest suffix in the list, delete if in R1 ("s" needs a
    # valid s-ending before it)
    for suf in _DA_STEP1:
        if w.endswith(suf) and len(w) - len(suf) >= r1:
            if suf == "s":
                if len(w) >= 2 and w[-2] in _DA_S_ENDINGS:
                    w = w[:-1]
                break
            w = w[: -len(suf)]
            break
    # step 2: gd, dt, gt, kt → drop last letter
    if len(w) >= r1 + 1 and w[-2:] in ("gd", "dt", "gt", "kt"):
        w = w[:-1]
    # step 3: igst → drop st; lig/elig/els in R1 → delete (+repeat step 2);
    # løst → løs
    if w.endswith("igst"):
        w = w[:-2]
    for suf in ("elig", "lig", "els", "ig"):
        if w.endswith(suf) and len(w) - len(suf) >= r1:
            w = w[: -len(suf)]
            if len(w) >= r1 + 1 and w[-2:] in ("gd", "dt", "gt", "kt"):
                w = w[:-1]
            break
    else:
        if w.endswith("løst"):
            w = w[:-1]
    # step 4: undouble a final double consonant in R1
    if (
        len(w) >= 2
        and len(w) - 1 >= r1
        and w[-1] == w[-2]
        and w[-1] not in _VOWELS["da"]
    ):
        w = w[:-1]
    return w


_SV_STEP1 = sorted(
    """a arna erna heterna orna ad e ade ande arne are aste en anden aren
    heten ern ar er heter or as arnas ernas ornas es ades andes ens arens
    hetens erns at andet het ast""".split(),
    key=len, reverse=True,
)
_SV_S_ENDINGS = set("bcdfghjklmnoprtvy")


def swedish_stem(w: str) -> str:
    r1 = max(_r1(w, _VOWELS["sv"]), 3)
    for suf in _SV_STEP1:
        if w.endswith(suf) and len(w) - len(suf) >= r1:
            w = w[: -len(suf)]
            break
    else:
        if w.endswith("s") and len(w) >= 2 and w[-2] in _SV_S_ENDINGS \
                and len(w) - 1 >= r1:
            w = w[:-1]
    # step 2: dd, gd, nn, dt, gt, kt, tt → drop last letter
    if len(w) - 1 >= r1 and w[-2:] in ("dd", "gd", "nn", "dt", "gt", "kt", "tt"):
        w = w[:-1]
    # step 3
    for suf, rep in (("lig", ""), ("ig", ""), ("els", ""), ("löst", "lös"),
                     ("fullt", "full")):
        if w.endswith(suf) and len(w) - len(suf) >= r1:
            w = w[: -len(suf)] + rep
            break
    return w


# --------------------------------------------------------------------------
# German — normalization + light stemmer (GermanLightStemFilter behavior)
# --------------------------------------------------------------------------
_DE_NORM = str.maketrans({"ä": "a", "ö": "o", "ü": "u"})


_DE_S_ENDINGS = set("bdfghklmnt")


def german_stem(w: str) -> str:
    w = w.replace("ß", "ss").translate(_DE_NORM)
    # step 1: case/plural endings
    if len(w) > 5 and w.endswith("ern"):
        w = w[:-3]
    elif len(w) > 4 and w[-2:] in ("em", "en", "er", "es"):
        w = w[:-2]
    elif len(w) > 3 and w[-1] == "e":
        w = w[:-1]
    elif len(w) > 3 and w[-1] == "s" and w[-2] in _DE_S_ENDINGS:
        w = w[:-1]
    # step 2: superlative/inflection remnants
    if len(w) > 5 and w.endswith("est"):
        w = w[:-3]
    elif len(w) > 4 and w.endswith("st") and w[-3] in _DE_S_ENDINGS:
        w = w[:-2]
    return w


# --------------------------------------------------------------------------
# Spanish / Portuguese — light stemmers (plural + gender endings)
# --------------------------------------------------------------------------
def spanish_stem(w: str) -> str:
    if len(w) < 5:
        return w
    for a, b in (("á", "a"), ("é", "e"), ("í", "i"), ("ó", "o"), ("ú", "u")):
        w = w.replace(a, b)
    if w.endswith(("eses", "eces")):
        return w[:-2]
    if w.endswith("ces"):
        return w[:-3] + "z"
    if w.endswith(("os", "as", "es")):
        return w[:-2]
    if w.endswith(("o", "a", "e")):
        return w[:-1]
    return w


def portuguese_stem(w: str) -> str:
    if len(w) < 4:
        return w
    if w.endswith("ões") or w.endswith("ães"):
        return w[:-3] + "ão"
    if w.endswith("res") and len(w) > 5:
        return w[:-2]
    if w.endswith(("eis",)):
        return w[:-3] + "el"
    if w.endswith(("ais",)):
        return w[:-2] + "l"
    if w.endswith(("os", "as", "es", "is")):
        return w[:-2]
    if w.endswith(("o", "a", "e")):
        return w[:-1]
    return w


# --------------------------------------------------------------------------
# Dutch — Snowball-style suffix stripping
# --------------------------------------------------------------------------
def _nl_undouble(w: str) -> str:
    if len(w) >= 2 and w[-1] == w[-2] and w[-1] in "kdt":
        return w[:-1]
    return w


def dutch_stem(w: str) -> str:
    r1 = max(_r1(w, _VOWELS["nl"]), 3)
    # step 1
    if w.endswith("heden") and len(w) - 5 >= r1:
        w = w[:-5] + "heid"
    elif w.endswith("ene") and len(w) - 3 >= r1:
        w = _nl_undouble(w[:-3])
    elif w.endswith("en") and len(w) - 2 >= r1 and not w[:-2].endswith("gem"):
        stem = w[:-2]
        if stem and stem[-1] not in _VOWELS["nl"]:
            w = _nl_undouble(stem)
    elif w.endswith("se") and len(w) - 2 >= r1:
        w = w[:-2]
    elif w.endswith("s") and len(w) - 1 >= r1 and len(w) >= 2 \
            and w[-2] not in _VOWELS["nl"] + "j":
        w = w[:-1]
    # step 2: -e in R1 after a consonant
    if w.endswith("e") and len(w) - 1 >= r1 and len(w) >= 2 \
            and w[-2] not in _VOWELS["nl"]:
        w = _nl_undouble(w[:-1])
    # step 3a: heid → delete in R2-ish, c before
    if w.endswith("heid") and len(w) - 4 >= r1 and len(w) >= 5 \
            and w[-5] != "c":
        w = w[:-4]
        if w.endswith("en") and len(w) - 2 >= r1:
            stem = w[:-2]
            if stem and stem[-1] not in _VOWELS["nl"]:
                w = _nl_undouble(stem)
    # step 3b: -ing/-end
    for suf in ("end", "ing"):
        if w.endswith(suf) and len(w) - len(suf) >= r1:
            w = _nl_undouble(w[: -len(suf)])
            break
    return w


# --------------------------------------------------------------------------
# analyzer registry
# --------------------------------------------------------------------------
_POSSESSIVE_RE = re.compile(r"['’][sS]?(?=\W|$)")


@dataclass(frozen=True)
class LanguageAnalyzer:
    language: str
    stopwords: frozenset[str]
    stem: Callable[[str], str]
    #: custom tokenizer (CJK bigrams, Thai script runs); None = standard
    tokenizer: Callable[[str, bool, int], list[str]] | None = None

    def analyze(
        self,
        text: str,
        to_lowercase: bool = True,
        min_token_length: int = 1,
        remove_stopwords: bool = True,
        stemming: bool = True,
    ) -> list[str]:
        if self.language == "en":
            # EnglishPossessiveFilter: strip trailing 's / trailing
            # apostrophe BEFORE tokenization (the regex tokenizer would
            # otherwise split "john's" into "john", "s")
            text = _POSSESSIVE_RE.sub("", text)
        if self.tokenizer is not None:
            toks = self.tokenizer(text, to_lowercase, min_token_length)
        else:
            toks = tokenize(text, to_lowercase, min_token_length)
        # the Lucene analyzers this mirrors always lowercase before their
        # stop filter and stemmer, so those steps compare/operate on the
        # casefolded token even when to_lowercase=False preserves case in
        # the emitted tokens of non-stemmed runs
        if remove_stopwords:
            toks = [t for t in toks if t.lower() not in self.stopwords]
        if stemming:
            toks = [self.stem(t.lower()) for t in toks]
        return [t for t in toks if len(t) >= min_token_length]


# --------------------------------------------------------------------------
# French / Italian / Russian — light Snowball-style suffix stripping
# (round-4 breadth: the reference's Lucene FrenchLightStemFilter /
# ItalianLightStemFilter / RussianLightStemFilter equivalents)
# --------------------------------------------------------------------------
def french_stem(w: str) -> str:
    if len(w) < 5:
        return w
    for a, b in (("à", "a"), ("â", "a"), ("è", "e"), ("é", "e"), ("ê", "e"),
                 ("î", "i"), ("ô", "o"), ("û", "u"), ("ç", "c")):
        w = w.replace(a, b)
    if w.endswith(("issements", "issement")):
        return w[:-9 if w.endswith("issements") else -8] + "i"
    for suf in ("ements", "ement"):
        if w.endswith(suf) and len(w) > len(suf) + 3:
            return w[: -len(suf)]
    for suf in ("ations", "ation"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    if w.endswith("eaux"):
        return w[:-1]          # chateaux -> chateau (plural x)
    if w.endswith("aux") and len(w) > 4:
        return w[:-3] + "al"   # journaux -> journal
    if w.endswith("eux"):
        return w[:-1]
    if w.endswith("ées"):
        return w[:-3]
    if w.endswith(("ée", "és", "er", "ez")):
        return w[:-2]
    if w.endswith("es"):
        return w[:-2]
    if w.endswith(("s", "e")):
        return w[:-1]
    return w


def italian_stem(w: str) -> str:
    if len(w) < 5:
        return w
    for a, b in (("à", "a"), ("è", "e"), ("é", "e"), ("ì", "i"), ("ò", "o"),
                 ("ù", "u")):
        w = w.replace(a, b)
    for suf in ("azioni", "azione"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    for suf in ("amenti", "amento", "imenti", "imento"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    if w.endswith(("che", "chi")):
        return w[:-2]
    if w.endswith(("ie", "ii")):
        return w[:-2] + "i"
    if w.endswith(("i", "e", "o", "a")):
        return w[:-1]
    return w


def russian_stem(w: str) -> str:
    if len(w) < 5:
        return w
    w = w.replace("ё", "е")
    # verb/participle endings first (longest match), then case endings
    for suf in ("ировать", "ованный", "ующий", "ывать", "ивать", "уется",
                "ается", "яется"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    for suf in ("иями", "ями", "ами", "ием", "ией", "иях",
                "ого", "его", "ому", "ему", "ыми", "ими"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    for suf in ("ов", "ев", "ей", "ий", "ый", "ой", "ая", "яя", "ое", "ее",
                "ие", "ые", "ом", "ем", "ам", "ым", "им", "ах", "ях", "ую",
                "юю"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    if w.endswith(("а", "я", "о", "е", "и", "ы", "у", "ю", "ь")):
        return w[:-1]
    return w


# --------------------------------------------------------------------------
# round-5 breadth toward Lucene's ~35-analyzer set: ar, cs, el, fi, hu, no,
# ro, tr (light stemmers over the published Lucene/Snowball suffix sets) +
# th (script-run segmentation) + CJK bigrams (zh/ja/ko — the Lucene
# CJKAnalyzer behavior). The langid plane already routes all of these.
# --------------------------------------------------------------------------
_AR_DIAC = re.compile("[ً-ٰٟـ]")  # harakat + tatweel


def arabic_stem(w: str) -> str:
    """Lucene ArabicNormalizer + light10-style stemmer: normalize alef/yaa
    forms, strip diacritics, strip the definite-article prefixes and the
    common suffixes."""
    w = _AR_DIAC.sub("", w)
    w = (w.replace("أ", "ا").replace("إ", "ا").replace("آ", "ا")
          .replace("ى", "ي").replace("ة", "ه"))
    for pre in ("وال", "بال", "كال", "فال", "لل", "ال"):
        if w.startswith(pre) and len(w) > len(pre) + 2:
            w = w[len(pre):]
            break
    for suf in ("ها", "ان", "ات", "ون", "ين", "يه", "يه", "ه", "ي"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    return w


def czech_stem(w: str) -> str:
    """CzechStemmer (light): longest-match case/possessive endings."""
    if len(w) < 4:
        return w
    for suf in ("atech", "ětem", "etem", "atům", "ových", "ovém", "ovým",
                "ách", "ata", "aty", "ých", "ama", "ami", "ové", "ovi",
                "ými", "ech", "ich", "ích", "ého", "ěmi", "emi", "ému",
                "ete", "eti", "iho", "ího", "ími", "imu",
                "em", "es", "ém", "ím", "ům", "at", "ám", "os", "us", "ým",
                "mi", "ou"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    if w[-1] in "eiíěuyůaoáéý" and len(w) > 3:
        return w[:-1]
    return w


_EL_ACCENTS = str.maketrans("άέήίόύώϊΐϋΰ", "αεηιουωιιυυ")


def greek_stem(w: str) -> str:
    """GreekStemmer (light): final-sigma + accent normalization, common
    nominal/verbal endings."""
    w = w.replace("ς", "σ").translate(_EL_ACCENTS)
    if len(w) < 4:
        return w
    for suf in ("ματων", "ματα", "ματοσ", "ουσα", "ουμε", "ουνε", "ησεισ",
                "εισ", "ουσ", "εων", "ων", " οσ", "οσ", "ησ", "ασ", "εσ",
                "οι", "ου", "α", "ο", "η", "ι", "ε", "υ"):
        suf = suf.strip()
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    return w


def finnish_stem(w: str) -> str:
    """FinnishLightStemFilter-style: strip the productive case endings."""
    if len(w) < 5:
        return w
    for suf in ("issa", "issä", "ista", "istä", "illa", "illä", "ilta",
                "iltä", "ille", "iksi", "tten", "ssa", "ssä", "sta", "stä",
                "lla", "llä", "lta", "ltä", "lle", "ksi", "den", "ien",
                "ina", "inä", "ia", "iä", "in", "en", "an", "än", "on"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            w = w[: -len(suf)]
            break
    if w.endswith(("t", "n")) and len(w) > 4:
        w = w[:-1]
    if w and w[-1] in "aä" and len(w) > 4:
        w = w[:-1]
    return w


def hungarian_stem(w: str) -> str:
    """HungarianLightStemFilter-style: case endings + plural/possessive."""
    if len(w) < 4:
        return w
    for suf in ("okkal", "ekkel", "akkal", "ükkel", "okból", "ekből",
                "nak", "nek", "val", "vel", "ban", "ben", "ból", "ből",
                "hoz", "hez", "höz", "tól", "től", "ról", "ről", "nál",
                "nél", " okat", "eket", "akat", "okat",
                "ra", "re", "ba", "be", "on", "en", "ön", "ok", "ek", "ak",
                "ot", "et", "at", "öt", "ig"):
        suf = suf.strip()
        if w.endswith(suf) and len(w) > len(suf) + 2:
            w = w[: -len(suf)]
            break
    if w and w[-1] in "tk" and len(w) > 3:
        w = w[:-1]
    if w and w[-1] in "aáeéoóöőuúüű" and len(w) > 3:
        w = w[:-1]
    return w


def norwegian_stem(w: str) -> str:
    """Snowball Norwegian-style suffix stripping (bokmål endings)."""
    if len(w) < 4:
        return w
    for suf in ("hetenes", "hetene", "hetens", "heten", "heter", "endes",
                "edes", "enes", "ende", "ande", "else", "este", "eren",
                "erne", "ane", "ene", "ens", "ers", "ets", "ast",
                "en", "ar", "er", "as", "es", "et", "st", "te",
                "a", "e", "s"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    return w


_RO_NORM = str.maketrans("ăâîșşțţ", "aaisstt")


def romanian_stem(w: str) -> str:
    """RomanianStemmer (light): diacritic folding + nominal endings."""
    w = w.translate(_RO_NORM)
    if len(w) < 4:
        return w
    for suf in ("urilor", "ului", "elor", "ilor", "iilor", "atie", "atii",
                "aties", "ele", "ile", "uri", "iei", "ul", "ua", "ea",
                "ii", "ie", "ei", "le", "a", "e", "i", "u"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    return w


def turkish_lower(w: str) -> str:
    """Turkish casefold: dotted/dotless i are DISTINCT letters (İ→i, I→ı);
    python lower() would fold both to 'i'."""
    return w.replace("İ", "i").replace("I", "ı").lower()


def turkish_stem(w: str) -> str:
    """TurkishLightStemmer-style: agglutinative case/plural/possessive
    suffixes, longest first."""
    w = turkish_lower(w)
    if len(w) < 4:
        return w
    for suf in ("larından", "lerinden", "larına", "lerine", "larını",
                "lerini", "ların", "lerin", "ları", "leri", "ından",
                "inden", "undan", "ünden", "lar", "ler", "ında", "inde",
                "unda", "ünde", "dan", "den", "tan", "ten", "nın", "nin",
                "nun", "nün", "ın", "in", "un", "ün", "da", "de", "ta",
                "te", "ı", "i", "u", "ü", "a", "e"):
        if w.endswith(suf) and len(w) > len(suf) + 2:
            return w[: -len(suf)]
    return w


# ---- tier 3 (round 5): the rest of the Lucene per-language analyzer set
# (LuceneTextAnalyzer.scala wires ~35; langid already routes these codes).
# Light approximations of the published Lucene stemmers, same approach as
# the tier-2 set above: longest-match suffix strips with minimum-stem
# guards.


def bulgarian_stem(w: str) -> str:
    """BulgarianStemmer (light, Nakov): definite article THEN plural —
    sequential, so 'котките' (article те + plural и) meets 'котка'
    (plural а) at the same stem."""
    if len(w) < 4:
        return w
    for suf in ("ията", "ият", "ът", "ят", "та", "то", "те"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            w = w[: -len(suf)]
            break
    for suf in ("овци", "ища", "ове", "еве", "йки", "ия", "а", "я", "о",
                "е", "и"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            w = w[: -len(suf)]
            break
    return w


def catalan_stem(w: str) -> str:
    """Catalan light stemmer (Snowball-Catalan approximation): plurals,
    verbal/derivational endings."""
    if len(w) < 4:
        return w
    for suf in ("aments", "ament", "adora", "adors", "ances", "atges",
                "esses", "etes", "eres", "ança", "ques", "osos", "oses",
                "ista", "able", "ible", "isme", "ció", "ats", "ades",
                "ers", "era", "es", "os", "a", "s"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def basque_stem(w: str) -> str:
    """Basque light stemmer (Snowball-Basque approximation): case endings
    (ergative/genitive/locative) and determiners."""
    if len(w) < 4:
        return w
    for suf in ("arekin", "etako", "etara", "aren", "ekin", "etan", "eta",
                "ari", "ak", "ek", "en", "an", "ra", "a", "k"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


_FA_NORM = str.maketrans({
    "ي": "ی", "ك": "ک", "ة": "ه", "آ": "ا", "أ": "ا", "إ": "ا",
    "ۀ": "ه", "‌": " ",  # zero-width non-joiner -> space
})


def persian_normalize(w: str) -> str:
    """PersianAnalyzer behavior: orthographic normalization, NO stemming
    (Lucene ships PersianNormalizationFilter + stopwords only)."""
    return w.translate(_FA_NORM).strip()


def galician_stem(w: str) -> str:
    """Galician light stemmer (RSLP-style plural/gender reduction)."""
    if len(w) < 4:
        return w
    if w.endswith("ns") and len(w) > 4:
        return w[:-2] + "n"
    if (w.endswith("ais") or w.endswith("eis")) and len(w) > 5:
        return w[:-2] + "l"
    for suf in ("cións", "ción", "mente", "ista", "ismo", "es", "as", "os",
                "a", "o", "s"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def hindi_stem(w: str) -> str:
    """HindiStemmer (light; Ramanathan & Rao) — the Lucene filter: strip
    the longest of the published suffix list."""
    if len(w) < 3:
        return w
    for suf in ("ियों", "ाओं", "ियां", "ताओं", "नाओं", "ियाँ", "ाएं",
                "ुओं", "ुएं", "ुआं", "ों", "ें", "ीं", "ाँ", "ां", "ता",
                "ते", "ना", "ती", "ी", "ू", "ु", "ा", "े", "ो", "ि"):
        if w.endswith(suf) and len(w) - len(suf) >= 2:
            return w[: -len(suf)]
    return w


def armenian_stem(w: str) -> str:
    """Armenian light stemmer (Snowball-Armenian approximation): plural +
    case endings."""
    if len(w) < 4:
        return w
    for suf in ("ությունների", "ություններ", "ության", "ություն",
                "ներում", "ներին", "ներով", "ները", "ների", "երին",
                "երից", "երով", "երը", "ներ", "ում", "երի", "ով", "եր",
                "ին", "ից", "ը", "ի", "ն"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def indonesian_stem(w: str) -> str:
    """IndonesianStemmer (light; Asian et al.): particle/possessive
    suffixes, derivational -kan/-an/-i, prefixes di-/ke-/se-/me*/be*/pe*/
    te*."""
    if len(w) < 4:
        return w
    for suf in ("kah", "lah", "pun", "nya", "ku", "mu"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            w = w[: -len(suf)]
            break
    for pre in ("meng", "meny", "men", "mem", "me", "peng", "peny", "pen",
                "pem", "di", "ter", "ke", "se", "ber", "be", "per", "pe"):
        if w.startswith(pre) and len(w) - len(pre) >= 3:
            w = w[len(pre):]
            break
    for suf in ("kan", "an", "i"):
        # >= 4 remaining: root words like 'makan' must not lose their
        # final syllable (the full Asian-et-al stemmer checks derivation
        # conditions; the length guard is the light equivalent)
        if w.endswith(suf) and len(w) - len(suf) >= 4:
            w = w[: -len(suf)]
            break
    return w


def irish_lower(w: str) -> str:
    """IrishLowerCaseFilter: strip prothetic n-/t- before a vowel-initial
    word ('n-athair' → 'athair', 'tAthair' → 'athair') before folding."""
    if len(w) > 2 and w[0] in "nt" and w[1] == "-":
        w = w[2:]
    elif len(w) > 1 and w[0] in "nt" and w[1] in "AEIOUÁÉÍÓÚ":
        w = w[1:]
    return w.lower()


def irish_stem(w: str) -> str:
    """Irish light stemmer (Snowball-Irish approximation): plural/case
    endings after Irish-specific lowercasing."""
    w = irish_lower(w)
    if len(w) < 4:
        return w
    for suf in ("aíocht", "eanna", "eacha", "acha", "anna", "anta",
                "íocht", "acht", "aí", "ta", "te", "e", "a"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def bengali_stem(w: str) -> str:
    """Bengali light stemmer (Lucene BengaliStemmer family): case/plural
    particles and vowel-sign endings, longest first."""
    if len(w) < 3:
        return w
    for suf in ("দেরকে", "গুলোর", "গুলির", "গুলো", "গুলি", "খানা",
                "দের", "েরা", "দিকে", "টির", "টার", "ছিল", "বেন",
                "ের", "কে", "রা", "টা", "টি", "তে", "েই", "ে", "ি",
                "া", "ী", "ো"):
        if w.endswith(suf) and len(w) - len(suf) >= 2:
            return w[: -len(suf)]
    return w


def lithuanian_stem(w: str) -> str:
    """Lithuanian light stemmer (Snowball-Lithuanian approximation): noun/
    adjective declension endings."""
    if len(w) < 4:
        return w
    for suf in ("iausias", "iausia", "uosiuose", "uose", "iams", "ams",
                "ose", "ėse", "yse", "uje", "oje", "ėje", "iai", "ius",
                "ių", "ais", "oms", "ėms", "as", "is", "ys", "us",
                "ai", "os", "ės", "ų", "ą", "ę", "į", "ė", "a", "e", "i",
                "o", "u", "s"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def ukrainian_stem(w: str) -> str:
    """Ukrainian light stemmer (the Lucene build uses a morfologik
    dictionary; this is the standard Slavic-light suffix reduction, same
    approach as the Russian light stemmer above)."""
    if len(w) < 4:
        return w
    for suf in ("ськими", "ського", "ському", "істю", "ення", "іння",
                "ість", "ами", "ями", "ових", "ого", "ому", "ими", "іми",
                "ах", "ях", "ів", "ей", "ом", "ем", "ою", "ею",
                "ий", "ій", "ії", "ія", "ію", "и", "і", "а", "я", "у",
                "ю", "о", "е", "ь"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def latvian_stem(w: str) -> str:
    """LatvianStemmer (light): noun/adjective declension endings, longest
    first."""
    if len(w) < 4:
        return w
    for suf in ("ajiem", "ajām", "ajam", "ajai", "iem", "ajā", "ais",
                "ai", "ei", "ij", "am", "ām", "ie", "as", "es", "os",
                "is", "us", "a", "e", "i", "u", "o", "s", "š"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


_CJK_RUN = re.compile(
    "[一-鿿㐀-䶿぀-ゟ゠-ヿ가-힯"
    "豈-﫿]+"
)
_THAI_RUN = re.compile("[฀-๿]+")


def _script_bigram_tokenizer(run_re):
    """Tokenizer factory: script runs become overlapping character bigrams
    (the Lucene CJKAnalyzer bigram behavior; Thai gets the same treatment —
    without an ICU/dictionary segmenter, bigrams are the standard
    segmentation-free indexing unit). Non-script spans go through the
    standard tokenizer."""
    def tok(text: str, to_lowercase: bool, min_token_length: int):
        out: list[str] = []
        pos = 0
        for m in run_re.finditer(text):
            before = text[pos:m.start()]
            if before.strip():
                out.extend(tokenize(before, to_lowercase, min_token_length))
            run = m.group(0)
            if len(run) == 1:
                out.append(run)
            else:
                out.extend(run[i:i + 2] for i in range(len(run) - 1))
            pos = m.end()
        tail = text[pos:]
        if tail.strip():
            out.extend(tokenize(tail, to_lowercase, min_token_length))
        return out

    return tok


_cjk_tokenize = _script_bigram_tokenizer(_CJK_RUN)
_thai_tokenize = _script_bigram_tokenizer(_THAI_RUN)

_APOSTROPHE_TAIL = re.compile(r"['’][^\s]*")


#: Devanagari vowel signs are combining marks (category Mn) — \W to the
#: regex engine — so the standard tokenizer would split every Hindi word
#: at its matras; keep Devanagari runs (letters + marks + virama) whole
#: the non-Devanagari alternative must EXCLUDE the Devanagari block, or a
#: digit/Latin-led token swallows the following consonant and strands its
#: matra ("5वीं" → "5व", "ीं")
_DEVANAGARI_TOKEN = re.compile(r"[ऀ-ॿ]+|[^\s\W_ऀ-ॿ]+", re.UNICODE)


def _hindi_tokenize(text: str, to_lowercase: bool, min_token_length: int):
    if to_lowercase:
        text = text.lower()
    return [
        t for t in _DEVANAGARI_TOKEN.findall(text)
        if len(t) >= min_token_length
    ]


#: Bengali script (U+0980–U+09FF) has the same combining-vowel-sign issue
#: as Devanagari — keep script runs whole
_BENGALI_TOKEN = re.compile(r"[ঀ-৿]+|[^\s\W_ঀ-৿]+", re.UNICODE)


def _bengali_tokenize(text: str, to_lowercase: bool, min_token_length: int):
    if to_lowercase:
        text = text.lower()
    return [
        t for t in _BENGALI_TOKEN.findall(text)
        if len(t) >= min_token_length
    ]


_GA_PROTHESIS = re.compile(r"\b[nt]-(?=[aeiouáéíóú])|\b[nt](?=[AEIOUÁÉÍÓÚ])")


def _irish_tokenize(text: str, to_lowercase: bool, min_token_length: int):
    """Irish prothesis (IrishLowerCaseFilter behavior) must run BEFORE
    tokenization: the word regex would split 'n-athair' at the hyphen and
    the lowercased token stream can no longer tell 'nAthair' from a word
    that begins with n."""
    text = _GA_PROTHESIS.sub("", text)
    return tokenize(text, to_lowercase, min_token_length)


def _turkish_tokenize(text: str, to_lowercase: bool, min_token_length: int):
    """Turkish pipeline order matters: ApostropheFilter (drop the
    apostrophe and everything after it — "İstanbul'daki" → "İstanbul")
    then TurkishLowerCaseFilter (İ→i, I→ı) BEFORE the standard tokenizer —
    python str.lower() turns İ into i + combining-dot, which the word
    regex then splits."""
    text = _APOSTROPHE_TAIL.sub("", text)
    if to_lowercase:
        text = turkish_lower(text)
    return tokenize(text, False, min_token_length)

STOPWORDS.update({
    "ar": frozenset(
        """في من على ان أن إلى الى عن مع هذا هذه ذلك التي الذي و او أو ثم
        لا ما لم لن هو هي هم كان كانت يكون قد كل بعض غير بين حتى اذا إذا
        كما عند لدى منذ أي اي نحن انا أنا انت هناك ولا وما وهو وهي به له
        لها فيه عليه اليوم ايضا أيضا""".split()
    ),
    "cs": frozenset(
        """a aby ale ani ano az bez bude budem budes by byl byla byli bylo
        být co což či dalsi do ho i jak jake je jeho jej jeji jejich jen
        jeste ji jine jiz jsem jses jsme jsou jste k kam kde kdo kdyz ke
        ktera ktere kteri kterou ktery ma mate me mezi mi mit muj muze my
        na nad nam napiste nas nasi ne nebo nejsou neni nez nic nove novy o
        od ode on pak po pod podle pokud pouze prave pred pres pri pro proc
        proto protoze prvni pta re s se si sve svych svym svymi ta tak take
        takze tato tedy tento teto tim timto to tohle toho tomto tomu tu
        tuto ty tyto u uz v vam vas vase ve vice vsak za zde ze""".split()
    ),
    "el": frozenset(
        """ο η το οι τα του της των τον την και κι κ ειμαι εισαι ειναι
        ειμαστε ειστε στο στον στη στην μα αλλα απο για προσ με σε ωσ παρα
        αντι κατα μετα θα να δε δεν μη μην επι ενω εαν αν τοτε που πωσ ποιοσ
        ποια ποιο ποιοι ποιεσ ποιων ποιουσ αυτοσ αυτη αυτο αυτοι αυτων
        αυτουσ αυτεσ αυτα εκεινοσ εκεινη εκεινο εκεινοι εκεινεσ εκεινα
        εκεινων εκεινουσ οπωσ ομωσ ισωσ οσο οτι""".split()
    ),
    "fi": frozenset(
        """ja ei että on oli joka jonka jossa jotka se ne hän he minä sinä
        me te tämä nämä tuo mikä mitä missä mutta kun niin vain myös jos
        sitä siitä sen ovat olen olet olemme olette ollut olla kuin vielä
        jo nyt sitten koska mukaan ilman kanssa kautta yli ali ennen
        jälkeen""".split()
    ),
    "hu": frozenset(
        """a az és egy ez az hogy nem is van volt lesz lehet csak már még
        el fel le ki be meg át ha de vagy mert mint ezt azt ezek azok en
        én te ő mi ti ők engem téged őt minket titeket őket ami aki amely
        amelyek ahol amikor miért hogyan mit mik kik ilyen olyan minden
        mindig soha most itt ott akkor úgy így nagyon több kevés sok
        kell""".split()
    ),
    "no": frozenset(
        """og i jeg det at en et den til er som på de med han av ikke der
        så var meg seg men ett har om vi min mitt ha hadde hun nå over da
        ved fra du ut sin dem oss opp man kan hans hvor eller hva skal selv
        sjøl her alle vil bli ble blitt kunne inn når være kom noen noe
        ville dere som deres kun ja etter ned skulle denne for deg si sine
        sitt mot å meget hvorfor dette disse uten hvordan ingen din ditt
        blir samme hvilken hvilke sånn inni mellom vår både bare enn fordi
        før mange også slik vært""".split()
    ),
    "ro": frozenset(
        """de la si și în un o a al ale cu pe ce care este sunt era au fost
        fi nu se sa să mai dar din ar fi prin despre după dupa pentru spre
        între intre ca că dacă daca atunci cand când unde cum cine cât cat
        acest aceasta această acestui acestei acestor el ea ei ele eu tu
        noi voi lui iar ori sau avea are am ai aveti aveți fara fără
        foarte tot toate toți toti""".split()
    ),
    "tr": frozenset(
        """ve bir bu da de için ile ben sen o biz siz onlar ama fakat ancak
        ki ne gibi daha çok en az mi mı mu mü değil her şey kendi ise veya
        ya hem sonra önce şimdi burada orada nasıl neden niçin kim hangi
        bütün bazı diğer aynı böyle şöyle öyle olarak olan oldu olur
        olduğu üzere kadar göre arasında vardı var yok idi""".split()
    ),
    "th": frozenset(
        """ที่ การ และ ใน ของ มี ได้ ให้ ไป มา เป็น ว่า จะ ไม่ กับ แต่ หรือ ก็ นี้ นั้น
        อยู่ อย่าง จาก ถึง ด้วย แล้ว ยัง ต้อง เมื่อ ความ""".split()
    ),
    "cjk": frozenset(),
    # ---- tier 3 (round 5)
    "bg": frozenset(
        """а и в на с за не се да по от е са ще това той тя то те ние вие
        аз ти ни ви го я му ѝ им ми ли но или ако като който която което
        които кой коя кое кои защото защо кога къде как там тук при до из
        над под пред след без че бил била било били съм си сме сте е беше
        бяха има няма може трябва още вече само също така тези този тази
        това му ги""".split()
    ),
    "ca": frozenset(
        """de la el els les un una uns unes i o a en amb per què que es el
        al del dels no sí és són era eren ser estar ha han he hem heu hi
        ho aquest aquesta aquests aquestes aquell aquella allò això jo tu
        ell ella nosaltres vosaltres ells elles em et es ens us li com més
        molt poc tot tots tota totes també ja encara quan on si doncs
        però sense sobre sota entre fins des com""".split()
    ),
    "eu": frozenset(
        """eta edo ez da dira zen ziren izan du dute zuen zuten bat batzuk
        hau hori hura hauek horiek haiek ni zu gu zuek bera beraiek nire
        zure gure haren baina ere oso asko gutxi guztiak dena zer nor non
        noiz nola zergatik zein baldin gero orain hemen hor han arte kontra
        gabe bezala baino ondoren aurretik artean""".split()
    ),
    "fa": frozenset(
        """و در به از که این آن را با برای است بود شد های می ها او ما شما
        آنها من تو خود هم نیز یا اما اگر تا بر هر چه چرا کجا چگونه کی
        بین روی زیر پیش پس بدون درباره مانند باید شاید هست نیست بودند
        هستند کرد کردند کند کنند شود شده دارد دارند داشت یک دو
        آیا""".split()
    ),
    "gl": frozenset(
        """de a o as os un unha uns unhas e ou en con por para que non si
        é son era eran ser estar hai ha han ao aos á ás do da dos das no
        na nos nas este esta estes estas ese esa eses esas aquel aquela eu
        ti el ela nós vós eles elas me te se nos vos lle lles como máis
        moi pouco todo todos toda todas tamén xa aínda cando onde entre
        ata desde sen sobre baixo despois antes""".split()
    ),
    "hi": frozenset(
        """का की के में है हैं को से पर और या नहीं यह वह ये वे मैं तुम आप हम
        उसका उसकी उनके इस उस इन उन एक दो था थी थे हो होता होती होते
        किया करना करता करती करते गया गयी गये हुआ हुई हुए भी तो ही अब
        जब तब कब क्यों कैसे कौन क्या जो कि अगर लेकिन फिर बहुत कुछ सब
        अपना साथ बाद पहले लिए द्वारा""".split()
    ),
    "hy": frozenset(
        """և եւ ու է են էր էին եմ ես ենք եք չի չեն չէր այս այդ այն սա դա
        նա մենք դուք նրանք ես դու իմ քո իր մեր ձեր նրանց որ ով ինչ երբ
        որտեղ ինչպես ինչու քանի թե եթե բայց կամ նաև միայն շատ քիչ բոլոր
        ամեն մեջ վրա տակ մոտ հետ առանց մասին համար ըստ դեպի մինչև
        այնտեղ այստեղ""".split()
    ),
    "id": frozenset(
        """yang dan di ke dari untuk pada dengan adalah ini itu tidak ada
        akan telah sudah belum bisa dapat harus juga atau tetapi tapi
        karena jika kalau saya aku kamu anda dia kami kita mereka nya ya
        bukan saja hanya lebih sangat semua setiap antara dalam luar atas
        bawah sebagai seperti sampai hingga ketika saat oleh bagi tentang
        maka lalu kemudian masih pernah sedang""".split()
    ),
    "ga": frozenset(
        """agus an na is ní tá bhí níl sé sí mé tú muid sibh siad a ar as
        ag do de i le go chun faoi ó roimh thar trí gan mar nach má dá cé
        cad conas cathain cá fáth seo sin siúd é í iad ach nó más bheith
        raibh beidh bhfuil dom duit dó di dúinn daoibh dóibh mo do a ár
        bhur ina sa san leis len lena ag""".split()
    ),
    "lv": frozenset(
        """un ir nav bija būs es tu viņš viņa mēs jūs viņi viņas tas tā
        šis šī tie tās kas ko kam par ar uz no pie pēc pirms bez virs zem
        starp pret līdz kā kad kur kāpēc vai bet ja tad jo arī vēl tikai
        ļoti daudz maz viss visi visas katrs savs mans tavs mūsu jūsu
        sava""".split()
    ),
    "bn": frozenset(
        """এই ও এবং যে যা কি না হয় হবে ছিল করে করা হতে থেকে জন্য সঙ্গে সাথে
        মধ্যে উপর নিচে আগে পরে কিন্তু অথবা যদি তবে তাই আমি তুমি আপনি সে
        তারা আমরা তোমরা তার তাদের আমার আমাদের এক দুই আর এটা সেটা কোন কেন
        কীভাবে কখন কোথায় কেউ কিছু সব অনেক আরও শুধু এখন তখন এখানে সেখানে
        দিয়ে নিয়ে হয়ে গিয়ে""".split()
    ),
    "lt": frozenset(
        """ir yra nėra buvo bus aš tu jis ji mes jūs jie jos tai šis ši
        tas ta kas ką kam su iš į ant po prie per nuo iki be prieš už virš
        tarp kaip kada kur kodėl ar bet jei tada nes taip pat dar tik
        labai daug mažai visas visi visos kiekvienas savo mano tavo mūsų
        jūsų apie""".split()
    ),
    "uk": frozenset(
        """і й та в у на з із зі до від за під над при про через для без
        між це цей ця ці той та те ті він вона воно вони ми ви я ти мій
        твій наш ваш свій його її їх що як коли де чому хто або але якщо
        то тому так ні не є був була було були буде бути може треба вже
        ще тільки дуже багато мало весь вся все всі кожен інший""".split()
    ),
})

_LIGHT_STEMMERS: dict[str, Callable[[str], str]] = {
    "ar": arabic_stem,
    "cs": czech_stem,
    "el": greek_stem,
    "fi": finnish_stem,
    "hu": hungarian_stem,
    "no": norwegian_stem,
    "ro": romanian_stem,
    "tr": turkish_stem,
    # tier 3
    "bg": bulgarian_stem,
    "ca": catalan_stem,
    "eu": basque_stem,
    "fa": persian_normalize,  # PersianAnalyzer: normalization, no stemming
    "gl": galician_stem,
    "hi": hindi_stem,
    "hy": armenian_stem,
    "id": indonesian_stem,
    "ga": irish_stem,
    "lv": latvian_stem,
    "bn": bengali_stem,
    "lt": lithuanian_stem,
    "uk": ukrainian_stem,
}

_STEMMERS: dict[str, Callable[[str], str]] = {
    "en": porter_stem,
    "da": danish_stem,
    "sv": swedish_stem,
    "de": german_stem,
    "es": spanish_stem,
    "pt": portuguese_stem,
    "nl": dutch_stem,
    "fr": french_stem,
    "it": italian_stem,
    "ru": russian_stem,
    **_LIGHT_STEMMERS,
}

ANALYZERS: dict[str, LanguageAnalyzer] = {
    lang: LanguageAnalyzer(lang, STOPWORDS[lang], _STEMMERS[lang])
    for lang in _STEMMERS
}
#: Turkish: apostrophe filter + Turkish casefold before tokenization
ANALYZERS["tr"] = LanguageAnalyzer(
    "tr", STOPWORDS["tr"], turkish_stem, tokenizer=_turkish_tokenize
)
#: Irish: prothetic n-/t- stripping must precede tokenization
ANALYZERS["ga"] = LanguageAnalyzer(
    "ga", STOPWORDS["ga"], irish_stem, tokenizer=_irish_tokenize
)
#: Hindi: Devanagari-run tokenizer (matras are combining marks)
ANALYZERS["hi"] = LanguageAnalyzer(
    "hi", STOPWORDS["hi"], hindi_stem, tokenizer=_hindi_tokenize
)
#: Bengali: same script-run treatment as Devanagari
ANALYZERS["bn"] = LanguageAnalyzer(
    "bn", STOPWORDS["bn"], bengali_stem, tokenizer=_bengali_tokenize
)
#: Thai: script-run bigram tokenization (no ICU segmenter), no stemming
ANALYZERS["th"] = LanguageAnalyzer(
    "th", STOPWORDS["th"], lambda t: t, tokenizer=_thai_tokenize
)
#: CJK bigrams (Lucene CJKAnalyzer behavior) — one analyzer serves zh/ja/ko
_CJK_ANALYZER = LanguageAnalyzer(
    "cjk", STOPWORDS["cjk"], lambda t: t, tokenizer=_cjk_tokenize
)
for _code in ("zh", "ja", "ko"):
    ANALYZERS[_code] = _CJK_ANALYZER

#: the "standard" analyzer (LuceneTextAnalyzer falls back to
#: StandardAnalyzer when the language has no dedicated analyzer):
#: tokenize + lowercase only
STANDARD = LanguageAnalyzer("", frozenset(), lambda t: t)


def analyzer_for(language: str | None) -> LanguageAnalyzer:
    """Analyzer for an ISO-639-1 code ('se' — the reference's Swedish model
    directory name — is accepted as an alias of 'sv'); unknown → STANDARD."""
    if not language:
        return STANDARD
    lang = language.lower()
    if lang == "se":
        lang = "sv"
    return ANALYZERS.get(lang, STANDARD)


def detect_language(text: str) -> str | None:
    """Language detection (OptimaizeLanguageDetector stand-in) — delegates
    to nlp/langid.py's ~55-language script-census + function-word voter;
    languages without a shipped analyzer fall back to STANDARD downstream."""
    from ..nlp.langid import detect

    return detect(text)


def analyze(
    text: str,
    language: str | None = None,
    auto_detect: bool = False,
    to_lowercase: bool = True,
    min_token_length: int = 1,
) -> list[str]:
    """TextTokenizer.analyze parity: pick the analyzer by explicit language
    or auto-detection, fall back to the standard analyzer."""
    lang = language
    if auto_detect and lang is None:
        lang = detect_language(text)
    return analyzer_for(lang).analyze(
        text, to_lowercase=to_lowercase, min_token_length=min_token_length
    )
