"""Statistics plane: column stats, correlation, Cramér's V.

Reference: utils/.../stats/OpStatistics.scala:1-384 (chi-sq / Cramér's V /
PMI / association-rule confidence) and SanityChecker's use of
``Statistics.colStats`` + ``Statistics.corr``.

TPU-first design: everything here is a dense-matrix reduction —
  * column stats: per-column sum / sumsq / min / max (psum-able);
  * the full correlation matrix of [X | y] is a centered XᵀX matmul
    (MXU-friendly; shard rows over the mesh, psum the partial products);
  * Cramér's V contingency tables are one-hot matmuls Gᵀ·onehot(y).
The jitted implementations live here so the SanityChecker estimator stays a
thin policy layer.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class ColumnStats:
    count: int
    mean: np.ndarray      # [D]
    variance: np.ndarray  # [D]
    min: np.ndarray       # [D]
    max: np.ndarray       # [D]


#: below this element count the numpy path wins — jit compile time dwarfs the
#: matmul for small stats problems (tests, tiny datasets); above it the jitted
#: kernel runs on the accelerator.
_DEVICE_THRESHOLD = 1 << 22


def _stats_mesh(size: int):
    """The all-device data mesh for multi-chip stats reductions, or None for
    the single-device / small-problem fast path."""
    if size < _DEVICE_THRESHOLD:
        return None
    from ..parallel.mesh import auto_mesh

    return auto_mesh()


@partial(jax.jit, static_argnames=())
def _colstats_kernel(x: jax.Array):
    n = x.shape[0]
    mean = jnp.mean(x, axis=0)
    var = jnp.sum((x - mean) ** 2, axis=0) / jnp.maximum(n - 1, 1)
    return mean, var, jnp.min(x, axis=0), jnp.max(x, axis=0)


def column_stats(x: np.ndarray) -> ColumnStats:
    """Per-column count/mean/variance/min/max (mllib colStats parity:
    sample variance, n-1 denominator). Large inputs on a multi-device mesh
    reduce via shard_map + psum (parallel.reductions.pcolumn_stats)."""
    mesh = _stats_mesh(x.size)
    if mesh is not None:
        from ..parallel.reductions import pcolumn_stats

        r = pcolumn_stats(x, mesh)
        n = float(r["count"])
        mean = r["mean"]
        var = r["m2"] / max(n - 1.0, 1.0)
        mn, mx = r["min"], r["max"]
    elif x.size < _DEVICE_THRESHOLD:
        x64 = np.asarray(x, dtype=np.float64)
        mean = x64.mean(axis=0)
        var = ((x64 - mean) ** 2).sum(axis=0) / max(x.shape[0] - 1, 1)
        mn, mx = x64.min(axis=0), x64.max(axis=0)
    else:
        mean, var, mn, mx = _colstats_kernel(jnp.asarray(x))
    return ColumnStats(
        count=int(x.shape[0]),
        mean=np.asarray(mean, dtype=np.float64),
        variance=np.asarray(var, dtype=np.float64),
        min=np.asarray(mn, dtype=np.float64),
        max=np.asarray(mx, dtype=np.float64),
    )


@jax.jit
def _corr_kernel(m: jax.Array):
    n = m.shape[0]
    mean = jnp.mean(m, axis=0)
    c = m - mean
    cov = (c.T @ c) / jnp.maximum(n - 1, 1)
    std = jnp.sqrt(jnp.diag(cov))
    denom = jnp.outer(std, std)
    return cov / jnp.where(denom == 0, 1.0, denom), std


def _corr_numpy(m: np.ndarray):
    n = m.shape[0]
    c = m - m.mean(axis=0)
    cov = (c.T @ c) / max(n - 1, 1)
    std = np.sqrt(np.diag(cov))
    denom = np.outer(std, std)
    return cov / np.where(denom == 0, 1.0, denom), std


def correlation_matrix(x: np.ndarray, y: np.ndarray | None = None) -> np.ndarray:
    """Pearson correlation matrix of [X | y] via centered XᵀX.

    Zero-variance columns yield 0 correlation (mllib returns NaN; we
    normalize to 0 and flag them via the variance rule instead).
    """
    m = np.column_stack([x, y]) if y is not None else x
    mesh = _stats_mesh(m.size)
    if mesh is not None:
        # distributed: centered gram matrix via shard_map + psum (centering
        # before the f32 matmul avoids raw-moment cancellation)
        from ..parallel.reductions import pcentered_gram

        g, _, n = pcentered_gram(m, mesh)
        cov = g / max(n - 1.0, 1.0)
        std = np.sqrt(np.maximum(np.diag(cov), 0.0))
        denom = np.outer(std, std)
        corr = cov / np.where(denom == 0, 1.0, denom)
    elif m.size < _DEVICE_THRESHOLD:
        corr, std = _corr_numpy(np.asarray(m, dtype=np.float64))
    else:
        corr, std = _corr_kernel(jnp.asarray(m, dtype=jnp.float32))
    corr = np.asarray(corr, dtype=np.float64)
    std = np.asarray(std, dtype=np.float64)
    corr[std == 0, :] = 0.0
    corr[:, std == 0] = 0.0
    np.fill_diagonal(corr, 1.0)
    return np.clip(corr, -1.0, 1.0)


def spearman_correlation_matrix(x: np.ndarray, y: np.ndarray | None = None) -> np.ndarray:
    """Spearman = Pearson on fractional ranks (CorrelationType.Spearman)."""
    m = np.column_stack([x, y]) if y is not None else x
    ranks = np.empty_like(m, dtype=np.float64)
    for j in range(m.shape[1]):
        col = m[:, j]
        order = np.argsort(col, kind="stable")
        r = np.empty(len(col), dtype=np.float64)
        r[order] = np.arange(len(col), dtype=np.float64)
        # average ties
        _, inv, counts = np.unique(col, return_inverse=True, return_counts=True)
        sums = np.zeros(len(counts))
        np.add.at(sums, inv, r)
        ranks[:, j] = sums[inv] / counts[inv]
    return correlation_matrix(ranks)


def contingency_table(group_cols: np.ndarray, label_onehot: np.ndarray) -> np.ndarray:
    """[K, C] contingency of K category-indicator columns vs C label classes —
    a single matmul Gᵀ·Y (OpStatistics.contingencyStats input)."""
    mesh = _stats_mesh(group_cols.size + label_onehot.size)
    if mesh is not None:
        from ..parallel.reductions import pcontingency

        return pcontingency(group_cols, label_onehot, mesh)
    if group_cols.size + label_onehot.size < _DEVICE_THRESHOLD:
        return np.asarray(group_cols, dtype=np.float64).T @ np.asarray(
            label_onehot, dtype=np.float64
        )
    return np.asarray(
        jnp.asarray(group_cols).T @ jnp.asarray(label_onehot), dtype=np.float64
    )


def chi_squared(contingency: np.ndarray) -> float:
    """Pearson chi-squared statistic of a contingency table."""
    total = contingency.sum()
    if total == 0:
        return 0.0
    rows = contingency.sum(axis=1, keepdims=True)
    cols = contingency.sum(axis=0, keepdims=True)
    expected = rows @ cols / total
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(expected > 0, (contingency - expected) ** 2 / expected, 0.0)
    return float(terms.sum())


def cramers_v(contingency: np.ndarray) -> float:
    """Cramér's V (OpStatistics.cramersV): sqrt(chi2 / (n * (min(r,c)-1))).
    Degenerate tables (a single row/column) give 0."""
    # drop all-zero rows/cols — categories absent from the sample
    c = contingency[contingency.sum(axis=1) > 0][:, contingency.sum(axis=0) > 0]
    if c.size == 0:
        return 0.0
    r, k = c.shape
    denom_df = min(r - 1, k - 1)
    n = c.sum()
    if denom_df <= 0 or n == 0:
        return 0.0
    return float(np.sqrt(chi_squared(c) / (n * denom_df)))


def pointwise_mutual_information(contingency: np.ndarray) -> np.ndarray:
    """PMI matrix log2(P(x,y)/(P(x)P(y))) per cell (OpStatistics PMI);
    zero cells give 0."""
    total = contingency.sum()
    if total == 0:
        return np.zeros_like(contingency)
    p = contingency / total
    px = p.sum(axis=1, keepdims=True)
    py = p.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        pmi = np.where(p > 0, np.log2(p / (px @ py)), 0.0)
    return pmi


def association_rule_confidence(contingency: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-category (max rule confidence, support): confidence = max_c
    P(label=c | category), support = category count / total
    (OpStatistics confidence/support used by maxRuleConfidence check)."""
    totals = contingency.sum(axis=1)
    n = contingency.sum()
    with np.errstate(divide="ignore", invalid="ignore"):
        conf = np.where(
            totals[:, None] > 0, contingency / totals[:, None], 0.0
        ).max(axis=1)
    support = totals / n if n else np.zeros_like(totals)
    return conf, support
