"""Utility layer (reference: utils module — UID, stats, tables, json helpers)."""
