"""UID generation (reference: utils/.../op/UID.scala:42).

The reference issues UIDs of the form ``ClassName_%012x`` from a global
counter, with a reset hook used by tests for deterministic DAG comparison.
"""
from __future__ import annotations

import itertools
import re
import threading

_counter = itertools.count(1)
_lock = threading.Lock()

_UID_RE = re.compile(r"^(.*)_([0-9a-f]{12})$")


def make_uid(cls_or_name: type | str) -> str:
    name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
    with _lock:
        n = next(_counter)
    return f"{name}_{n:012x}"


def reset(start: int = 1) -> None:
    """Reset the counter (UID.scala reset — for deterministic tests)."""
    global _counter
    with _lock:
        _counter = itertools.count(start)


def from_string(uid: str) -> tuple[str, str]:
    """Parse a UID into (stage class name, hex suffix) (UID.scala fromString)."""
    m = _UID_RE.match(uid)
    if not m:
        raise ValueError(f"Invalid UID: {uid!r}")
    return m.group(1), m.group(2)
