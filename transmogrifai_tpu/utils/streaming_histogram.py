"""Streaming histogram — fixed-size mergeable quantile sketch.

Reference: utils/src/main/java/com/salesforce/op/utils/stats/
StreamingHistogram.java:36-269 (one of the reference's two Java files),
implementing the Ben-Haim & Tom-Tov "A Streaming Parallel Decision Tree
Algorithm" (JMLR 2010) histogram: at most ``max_bins`` (centroid, count)
pairs; inserting a point adds a unit bin then merges the closest pair;
histograms merge associatively (the monoid property that lets score
distributions aggregate across shards — used for score/feature
distributions in model insights and drift monitoring).
"""
from __future__ import annotations

import bisect


class StreamingHistogram:
    """Mergeable bounded histogram of (point, count) bins."""

    def __init__(self, max_bins: int = 100):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = max_bins
        self._points: list[float] = []
        self._counts: list[float] = []

    # ------------------------------------------------------------ building
    def update(self, value: float, count: float = 1.0) -> "StreamingHistogram":
        """Algorithm 1 (update): insert then shrink-to-capacity."""
        i = bisect.bisect_left(self._points, value)
        if i < len(self._points) and self._points[i] == value:
            self._counts[i] += count
        else:
            self._points.insert(i, float(value))
            self._counts.insert(i, float(count))
            self._shrink()
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Algorithm 2 (merge): union the bins, shrink to capacity."""
        out = StreamingHistogram(max(self.max_bins, other.max_bins))
        for p, c in sorted(
            list(zip(self._points, self._counts))
            + list(zip(other._points, other._counts))
        ):
            if out._points and out._points[-1] == p:
                out._counts[-1] += c
            else:
                out._points.append(p)
                out._counts.append(c)
        out._shrink()
        return out

    def _shrink(self) -> None:
        while len(self._points) > self.max_bins:
            # merge the closest adjacent pair (weighted centroid)
            gaps = [
                self._points[i + 1] - self._points[i]
                for i in range(len(self._points) - 1)
            ]
            i = min(range(len(gaps)), key=gaps.__getitem__)
            c = self._counts[i] + self._counts[i + 1]
            p = (
                self._points[i] * self._counts[i]
                + self._points[i + 1] * self._counts[i + 1]
            ) / c
            self._points[i : i + 2] = [p]
            self._counts[i : i + 2] = [c]

    # ------------------------------------------------------------- queries
    @property
    def bins(self) -> list[tuple[float, float]]:
        return list(zip(self._points, self._counts))

    @property
    def total_count(self) -> float:
        return sum(self._counts)

    def sum_at(self, b: float) -> float:
        """Algorithm 3 (sum): estimated number of points <= b via the
        trapezoid interpolation between surrounding centroids."""
        pts, cts = self._points, self._counts
        if not pts:
            return 0.0
        if b < pts[0]:
            return 0.0
        if b >= pts[-1]:
            return self.total_count
        i = bisect.bisect_right(pts, b) - 1
        p_i, p_j = pts[i], pts[i + 1]
        m_i, m_j = cts[i], cts[i + 1]
        # fraction of the (i, i+1) trapezoid left of b
        frac = (b - p_i) / (p_j - p_i)
        m_b = m_i + (m_j - m_i) * frac
        s = (m_i + m_b) * frac / 2.0
        return sum(cts[:i]) + m_i / 2.0 + s

    def quantile(self, q: float) -> float:
        """Inverse of sum_at by bisection over the centroid span."""
        if not self._points:
            raise ValueError("empty histogram")
        if len(self._points) == 1:
            return self._points[0]
        target = q * self.total_count
        lo, hi = self._points[0], self._points[-1]
        for _ in range(60):
            mid = (lo + hi) / 2.0
            if self.sum_at(mid) < target:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2.0

    def density(self) -> list[tuple[float, float]]:
        """Normalized (point, mass) pairs."""
        total = self.total_count
        if total == 0:
            return []
        return [(p, c / total) for p, c in zip(self._points, self._counts)]

    def to_json(self) -> dict:
        return {
            "maxBins": self.max_bins,
            "points": list(self._points),
            "counts": list(self._counts),
        }

    @classmethod
    def from_json(cls, data: dict) -> "StreamingHistogram":
        h = cls(data["maxBins"])
        h._points = [float(p) for p in data["points"]]
        h._counts = [float(c) for c in data["counts"]]
        return h


def histogram_from_values(values, max_bins: int = 64) -> StreamingHistogram:
    """Bulk-build a StreamingHistogram from an array of values.

    Exact (unique values + counts) when the data has at most ``max_bins``
    distinct values; otherwise one vectorized equal-width pre-bin whose
    centroids/counts seed the sketch — O(n) numpy work instead of n Python
    ``update`` calls, which matters when Workflow.train profiles every raw
    feature of a large training set."""
    import numpy as np

    h = StreamingHistogram(max_bins)
    vals = np.asarray(values, dtype=np.float64)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0:
        return h
    uniq, counts = np.unique(vals, return_counts=True)
    if len(uniq) > max_bins:
        # equal-width pre-bin straight to capacity, mass-weighted centers —
        # one vectorized np.histogram instead of n python merges (the
        # serving drift window feeds whole batch columns through here)
        counts, edges = np.histogram(vals, bins=max_bins)
        sums, _ = np.histogram(vals, bins=edges, weights=vals)
        keep = counts > 0
        counts = counts[keep]
        uniq = sums[keep] / counts  # centroid of each bin's actual mass
    h._points = [float(p) for p in uniq]
    h._counts = [float(c) for c in counts]
    return h
