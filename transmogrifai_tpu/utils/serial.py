"""Callable serialization for stage persistence.

The reference serializes stage lambdas by class name (Scala lambdas are
classes, features/.../OpPipelineStageReaderWriter.scala). The Python
equivalent: pickle module-level callables to base64. Lambdas/closures are
rejected AT SAVE TIME with a clear error, matching the reference's
checkSerializable gate (OpWorkflow.scala:280-287) — failing at load time
would strand a saved model.
"""
from __future__ import annotations

import base64
import pickle
from typing import Any, Callable


def encode_callable(fn: Callable | None, owner: str, param: str) -> str | None:
    """Pickle a callable param to base64; None passes through."""
    if fn is None:
        return None
    try:
        blob = pickle.dumps(fn)
        pickle.loads(blob)  # round-trip check (catches unimportable defs)
    except Exception as e:
        raise ValueError(
            f"{owner}: param '{param}' is not serializable ({e}). Use a "
            "module-level function instead of a lambda/closure so the saved "
            "workflow can be loaded."
        ) from None
    return base64.b64encode(blob).decode("ascii")


def decode_callable(value: Any) -> Any:
    """Inverse of encode_callable; non-string values pass through."""
    if isinstance(value, str):
        return pickle.loads(base64.b64decode(value.encode("ascii")))
    return value
