"""Structured trace spans — low-overhead, thread-safe, Perfetto-ready.

``span("train/layer", index=3)`` is a context manager that times a region
and records it three ways:

* a **Chrome trace event** in a bounded in-process buffer (complete
  ``"ph": "X"`` events; Perfetto nests same-thread spans by time
  containment, so the exported JSON shows layer → fit/transform,
  fold → candidate, batch → stage hierarchies with no parent bookkeeping
  in the hot path);
* an **exponential-bucket duration histogram** per span name in the
  metrics registry (``tptpu_span_seconds{span="..."}``) — true
  p50/p95/p99 per stage family;
* for root ``serve/*`` spans, a compact trace in the bounded **serving
  ring buffer** (:func:`recent_serve_traces`).

The clock is injectable (:func:`set_clock`) so the telemetry suite runs on
fake time — the same seam convention the resilience components use
(TPL004). Disabling (:func:`set_enabled` or ``TPTPU_TELEMETRY=0``) makes
``span`` a near-no-op; the <2% train+serve overhead guard in
``tests/test_telemetry.py`` pins the enabled cost.

The serving hot path records through :func:`record_serve_batch` (one call
per scored batch with pre-aggregated per-family seconds) instead of one
span per stage, so single-row scoring pays a handful of clock reads, not
dozens of span objects; per-stage detail spans engage above
``TPTPU_TRACE_STAGE_ROWS`` rows (default 16).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable

from . import metrics as _metrics

__all__ = [
    "span",
    "record_span",
    "record_serve_batch",
    "clock",
    "set_clock",
    "get_clock",
    "enabled",
    "set_enabled",
    "stage_detail",
    "set_detail_suppressed",
    "snapshot_events",
    "recent_serve_traces",
    "configure_buffers",
    "reset_for_tests",
]


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


_LOCK = threading.Lock()
_TLS = threading.local()

#: injectable monotonic clock (rebindable plain name — no lock needed)
_CLOCK: Callable[[], float] = time.monotonic

#: mutable module state crossed by worker/warmup threads — every write
#: below holds ``_LOCK`` (TPL001)
_STATE: dict[str, Any] = {
    "enabled": os.environ.get("TPTPU_TELEMETRY", "1") != "0",
    # raised by the serving load shedder (tier >= 1): per-stage detail
    # spans are the cheapest thing to drop under overload
    "detail_suppressed": False,
}
_EVENTS: deque = deque(maxlen=_env_int("TPTPU_TRACE_BUFFER", 65536))
_SERVE_RING: deque = deque(maxlen=_env_int("TPTPU_SERVE_TRACE_RING", 64))
_TIDS: dict[int, int] = {}

#: per-batch row floor below which scoring skips per-stage detail spans
_DETAIL_MIN_ROWS = _env_int("TPTPU_TRACE_STAGE_ROWS", 16)

_CHILD_CAP = 256  # children kept per span in the serving-ring trace tree


def clock() -> float:
    return _CLOCK()


def set_clock(fn: Callable[[], float] | None = None) -> None:
    """Swap the monotonic clock (None restores ``time.monotonic``)."""
    global _CLOCK
    _CLOCK = fn if fn is not None else time.monotonic


def get_clock() -> Callable[[], float]:
    """The currently installed clock callable (for save/restore swaps)."""
    return _CLOCK


def enabled() -> bool:
    return _STATE["enabled"]


def set_enabled(on: bool) -> None:
    with _LOCK:
        _STATE["enabled"] = bool(on)


def stage_detail(rows: int) -> bool:
    """True when scoring should emit per-stage detail spans for a batch of
    ``rows`` (large enough that span cost is noise, and the load shedder
    has not suppressed detail)."""
    return (
        _STATE["enabled"]
        and not _STATE["detail_suppressed"]
        and rows >= _DETAIL_MIN_ROWS
    )


def set_detail_suppressed(on: bool) -> None:
    """Shed/restore per-stage detail spans (serving shed tier 1 — the
    first, cheapest degradation under overload). A stale read in a scoring
    thread mid-transition costs one extra/missing detail span, never
    correctness, so the read side stays lock-free."""
    with _LOCK:
        _STATE["detail_suppressed"] = bool(on)


def _tid() -> int:
    t = threading.get_ident()
    got = _TIDS.get(t)
    if got is None:
        with _LOCK:
            got = _TIDS.setdefault(t, len(_TIDS) + 1)
    return got


def _observe(name: str, dur: float) -> None:
    reg = _metrics.REGISTRY
    reg.histogram("tptpu_span_seconds", labels={"span": name}).observe(dur)
    reg.counter("tptpu_spans_recorded_total").inc()


def _record(
    name: str,
    start: float,
    dur: float,
    attrs: dict | None,
    parent: "span | None",
    children: list | None,
    root_trace: bool,
) -> None:
    rec: dict[str, Any] = {
        "name": name, "ts": start, "dur": dur, "tid": _tid(),
    }
    if attrs:
        rec["args"] = dict(attrs)
    with _LOCK:
        _EVENTS.append(rec)
    _observe(name, dur)
    if parent is not None:
        kids = parent.children
        if kids is None:
            kids = parent.children = []
        if len(kids) < _CHILD_CAP:
            child: dict[str, Any] = {
                "name": name, "durMs": round(dur * 1e3, 3),
            }
            if children:
                child["children"] = children
            kids.append(child)
    elif root_trace and name.startswith("serve/"):
        trace = {
            "name": name,
            "durMs": round(dur * 1e3, 3),
            "attrs": dict(attrs) if attrs else {},
            "children": children or [],
        }
        with _LOCK:
            _SERVE_RING.append(trace)


class span:
    """``with span("cv/fold", fold=2): ...`` — times the block and records
    it (see module docstring). Near-free when telemetry is disabled."""

    __slots__ = ("name", "attrs", "children", "_t0")

    def __init__(self, name: str, **attrs: Any):
        self.name = name
        self.attrs = attrs
        self.children: list | None = None
        self._t0 = -1.0

    def __enter__(self) -> "span":
        if not _STATE["enabled"]:
            return self
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append(self)
        self._t0 = _CLOCK()
        return self

    def __exit__(self, *exc) -> bool:
        if self._t0 < 0.0:  # entered disabled
            return False
        dur = _CLOCK() - self._t0
        stack = getattr(_TLS, "stack", None)
        parent = None
        if stack and stack[-1] is self:
            stack.pop()
            parent = stack[-1] if stack else None
        _record(
            self.name, self._t0, dur, self.attrs, parent, self.children,
            root_trace=parent is None,
        )
        return False


def record_span(name: str, start: float, dur: float, **attrs: Any) -> None:
    """Record an already-measured interval (the scoring loop aggregates
    per-stage timings with raw clock reads, then emits spans in bulk).
    Chrome nesting still works — Perfetto nests by time containment."""
    if not _STATE["enabled"]:
        return
    _record(name, start, dur, attrs, None, None, root_trace=False)


def record_serve_batch(
    entry: str, rows: int, started: float, stage_seconds: dict[str, float]
) -> None:
    """One scored batch: total + per-stage-family latency histograms
    (``tptpu_serve_seconds{stage=...}``), a ``serve/batch`` trace span,
    throughput counters, and a compact trace in the serving ring."""
    if not _STATE["enabled"]:
        return
    total = _CLOCK() - started
    reg = _metrics.REGISTRY
    reg.histogram("tptpu_serve_seconds", labels={"stage": "total"}).observe(
        total
    )
    for fam, secs in stage_seconds.items():
        reg.histogram("tptpu_serve_seconds", labels={"stage": fam}).observe(
            secs
        )
    reg.counter("tptpu_serve_batches_total").inc()
    reg.counter("tptpu_serve_rows_total").inc(rows)
    rec = {
        "name": "serve/batch", "ts": started, "dur": total, "tid": _tid(),
        "args": {"rows": rows, "entry": entry},
    }
    trace = {
        "name": "serve/batch",
        "entry": entry,
        "rows": rows,
        "durMs": round(total * 1e3, 3),
        "stagesMs": {
            fam: round(secs * 1e3, 3) for fam, secs in stage_seconds.items()
        },
    }
    with _LOCK:
        _EVENTS.append(rec)
        _SERVE_RING.append(trace)


# ------------------------------------------------------------------ readers
def snapshot_events() -> list[dict]:
    """Copy of the buffered span records (seconds-domain ts/dur)."""
    with _LOCK:
        return list(_EVENTS)


def recent_serve_traces() -> list[dict]:
    """The bounded ring of recent serving traces, oldest first."""
    with _LOCK:
        return list(_SERVE_RING)


def configure_buffers(
    trace_buffer: int | None = None, serve_ring: int | None = None
) -> None:
    """Re-bound the in-process buffers (tests; production uses the
    ``TPTPU_TRACE_BUFFER`` / ``TPTPU_SERVE_TRACE_RING`` env knobs).
    Existing contents are kept up to the new bound."""
    global _EVENTS, _SERVE_RING
    with _LOCK:
        if trace_buffer is not None:
            _EVENTS = deque(_EVENTS, maxlen=max(1, trace_buffer))
        if serve_ring is not None:
            _SERVE_RING = deque(_SERVE_RING, maxlen=max(1, serve_ring))


def buffer_bounds() -> tuple[int, int]:
    return (_EVENTS.maxlen or 0, _SERVE_RING.maxlen or 0)


def reset_for_tests() -> None:
    """Clear buffers and the tid map; leaves enabled-state and clock."""
    with _LOCK:
        _EVENTS.clear()
        _SERVE_RING.clear()
        _TIDS.clear()
