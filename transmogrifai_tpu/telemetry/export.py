"""Export surfaces: Prometheus text exposition, Chrome trace JSON, the
span-derived phase breakdown, and the consolidated summary line.

``render_prometheus()`` walks the registry's own metrics (span/serve
latency histograms, throughput counters) plus every registered ledger
source (compileStats, featurizeStats, the resilience/distributed
counters, live serving counters) and renders the standard text
exposition — scrapeable as-is by a Prometheus agent, printable via
``python -m transmogrifai_tpu metrics``.

``export_chrome_trace()`` converts the bounded span buffer to the Chrome
trace-event format (complete ``"ph": "X"`` events, microsecond
timestamps); the file opens directly in Perfetto / ``chrome://tracing``
with layer → stage, fold → candidate, and batch → stage nesting.

``phase_breakdown()`` attributes buffered span time to the bench phases
(ingest / featurize / compile / fit / eval). The mapping uses the
leaf span names only, so nested spans are not double-counted; warmup
runs on a background thread, so ``compile`` seconds can overlap the
other phases (attribution, not a wall-clock decomposition).
"""
from __future__ import annotations

import json
import re
from typing import Any

from . import events as _events
from . import metrics as _metrics
from . import spans as _spans

__all__ = [
    "render_prometheus",
    "export_chrome_trace",
    "phase_breakdown",
    "serve_latency_summary",
    "serving_snapshot",
    "metrics_snapshot",
    "summary_line",
]


def _ensure_default_sources() -> None:
    """Importing the ledger modules registers them as sources — lazily, so
    a fresh CLI process exposes the full catalogue (at zero) without this
    module importing them at package-import time."""
    from ..compiler import stats as _cstats  # noqa: F401
    from ..featurize import stats as _fstats  # noqa: F401
    from ..insights import ledger as _attr  # noqa: F401
    from ..local import scoring as _scoring  # noqa: F401
    from ..resilience import distributed as _dist  # noqa: F401
    from . import runlog as _runlog  # noqa: F401


_SNAKE_RE = re.compile(r"(?<=[a-z0-9])([A-Z])")


def _snake(key: str) -> str:
    return _SNAKE_RE.sub(r"_\1", key).lower()


def _escape(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _fmt(v: float) -> str:
    if isinstance(v, int):
        return str(v)
    return format(v, ".10g")


def _render_source(src: str, mapping: dict, lines: list[str]) -> None:
    """Flatten one ledger snapshot: numeric leaves become gauges named
    ``tptpu_{src}_{snake(key)}``; ``{name: num}`` maps become labeled
    samples; ``{name: {field: num}}`` maps one labeled sample per numeric
    field. Lists / strings / None are skipped (not counters)."""
    for key in sorted(mapping):
        val = mapping[key]
        base = f"tptpu_{src}_{_snake(key)}"
        if _num(val):
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {_fmt(val)}")
        elif isinstance(val, dict):
            samples: list[str] = []
            for name in sorted(val):
                inner = val[name]
                lbl = _labels_str({"name": name})
                if _num(inner):
                    samples.append(f"{base}{lbl} {_fmt(inner)}")
                elif isinstance(inner, dict):
                    for field in sorted(inner):
                        v2 = inner[field]
                        if _num(v2):
                            samples.append(
                                f"{base}_{_snake(field)}{lbl} {_fmt(v2)}"
                            )
            if samples:
                lines.append(f"# TYPE {base} gauge")
                lines.extend(samples)


def render_prometheus(
    registry: _metrics.MetricsRegistry | None = None,
    default_sources: bool = True,
) -> str:
    """Prometheus text exposition of the whole telemetry plane (see
    module docstring). Deterministically ordered, trailing newline."""
    if registry is None:
        registry = _metrics.REGISTRY
        if default_sources:
            _ensure_default_sources()
    lines: list[str] = []
    with registry.lock:
        snap_counters = dict(registry._counters)
        snap_gauges = dict(registry._gauges)
        histograms = list(registry._histograms.values())
    # sources MUST run after the lock releases: the lock is re-entrant, so
    # calling source_snapshots() inside the block silently runs the source
    # callables with the registry lock held — an ABBA deadlock against any
    # thread holding its subsystem lock while touching a gauge/counter
    # (e.g. ScoringService.submit -> queue gauge vs. the service source ->
    # ScoringService.stats).
    sources = registry.source_snapshots()
    for name in sorted(snap_counters):
        c = snap_counters[name]
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(c.value)}")
    for name in sorted(snap_gauges):
        g = snap_gauges[name]
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(g.value)}")
    by_name: dict[str, list] = {}
    for h in histograms:
        by_name.setdefault(h.name, []).append(h)
    for name in sorted(by_name):
        lines.append(f"# TYPE {name} histogram")
        for h in sorted(
            by_name[name], key=lambda h: tuple(sorted(h.labels.items()))
        ):
            cum, count, total = h.bucket_counts()
            for bound, c in zip(h.bounds, cum):
                lbl = _labels_str({**h.labels, "le": format(bound, ".6g")})
                lines.append(f"{name}_bucket{lbl} {c}")
            lbl = _labels_str({**h.labels, "le": "+Inf"})
            lines.append(f"{name}_bucket{lbl} {cum[-1]}")
            plain = _labels_str(h.labels)
            lines.append(f"{name}_sum{plain} {_fmt(float(total))}")
            lines.append(f"{name}_count{plain} {count}")
    for src in sorted(sources):
        _render_source(src, sources[src], lines)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- chrome trace
def export_chrome_trace(path: str | None = None) -> dict[str, Any]:
    """The buffered spans as a Chrome trace-event document; written to
    ``path`` when given. Open in Perfetto (ui.perfetto.dev) or
    chrome://tracing."""
    events = []
    for rec in _spans.snapshot_events():
        ev: dict[str, Any] = {
            "name": rec["name"],
            "cat": rec["name"].split("/", 1)[0],
            "ph": "X",
            "pid": 1,
            "tid": rec["tid"],
            "ts": round(rec["ts"] * 1e6, 3),
            "dur": round(rec["dur"] * 1e6, 3),
        }
        if rec.get("args"):
            ev["args"] = rec["args"]
        events.append(ev)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, default=str)
    return doc


# ------------------------------------------------------------ phase breakdown
#: leaf span name (prefix) -> bench phase; nested parents (train/layer,
#: cv/fold, selector sweeps) are deliberately absent so time is counted once
_PHASE_PREFIXES = (
    ("train/ingest", "ingest"),
    ("train/transform", "featurize"),
    ("compile/", "compile"),
    ("train/fit", "fit"),
    ("train/eval", "eval"),
    # the explainability plane: train-time baseline sweeps + serve-time
    # explain=k sweeps both attribute to one "explain" phase
    ("train/attribution", "explain"),
    ("serve/explain", "explain"),
)


def phase_breakdown() -> dict[str, float]:
    """Span-derived seconds per bench phase (see module docstring)."""
    out = {phase: 0.0 for _, phase in _PHASE_PREFIXES}
    for rec in _spans.snapshot_events():
        name = rec["name"]
        for prefix, phase in _PHASE_PREFIXES:
            if name.startswith(prefix):
                out[phase] += rec["dur"]
                break
    return {phase: round(secs, 3) for phase, secs in out.items()}


# ------------------------------------------------------------------ summaries
def serve_latency_summary() -> dict[str, dict[str, Any]]:
    """Per-stage-family serving latency: count + p50/p95/p99 milliseconds
    from the ``tptpu_serve_seconds`` histograms."""
    out: dict[str, dict[str, Any]] = {}
    for h in _metrics.REGISTRY.histograms_named("tptpu_serve_seconds"):
        snap = h.snapshot()
        out[h.labels.get("stage", "total")] = {
            "count": snap["count"],
            "p50Ms": None if snap["p50"] is None else round(snap["p50"] * 1e3, 3),
            "p95Ms": None if snap["p95"] is None else round(snap["p95"] * 1e3, 3),
            "p99Ms": None if snap["p99"] is None else round(snap["p99"] * 1e3, 3),
        }
    return out


def serving_snapshot() -> dict[str, Any]:
    """The ``score_fn.metadata()["telemetry"]`` payload."""
    reg = _metrics.REGISTRY
    return {
        "serveLatencyMs": serve_latency_summary(),
        "spansRecorded": reg.counter("tptpu_spans_recorded_total").value,
        "serveBatches": reg.counter("tptpu_serve_batches_total").value,
        "serveRows": reg.counter("tptpu_serve_rows_total").value,
        "eventsEmitted": _events.count(),
        "recentTraces": len(_spans.recent_serve_traces()),
    }


def metrics_snapshot() -> dict[str, Any]:
    """JSON snapshot of the registry + sources (the CLI ``--json`` view)."""
    _ensure_default_sources()
    return _metrics.REGISTRY.snapshot_all()


def summary_line() -> str | None:
    """One consolidated line for ``summary_pretty()`` — None when the
    process recorded nothing."""
    reg = _metrics.REGISTRY
    spans_n = reg.counter("tptpu_spans_recorded_total").value
    events_n = _events.count()
    if not spans_n and not events_n:
        return None
    names = len(reg.histograms_named("tptpu_span_seconds"))
    line = (
        f"Telemetry: {spans_n} span(s) across {names} name(s), "
        f"{events_n} event(s)"
    )
    total = serve_latency_summary().get("total")
    if total and total["count"]:
        line += (
            f"; serve p50/p95/p99 {total['p50Ms']}/{total['p95Ms']}/"
            f"{total['p99Ms']} ms over {total['count']} batch(es)"
        )
    return line + " — python -m transmogrifai_tpu metrics"
