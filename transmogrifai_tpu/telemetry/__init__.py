"""Unified telemetry plane — trace spans, one metrics registry over the
process ledgers, Prometheus-style export, and the serving-latency
histogram pipeline.

The observability substrate under ROADMAP item 1's standing scoring
service: the reference ships run-level introspection (ModelInsights,
per-stage summaries — SURVEY §1 L3); this plane is the live counterpart.

* :mod:`telemetry.spans` — ``span("train/layer", index=3)`` structured
  trace spans (thread-safe, injectable clock, bounded buffers), a ring of
  recent serving traces, Chrome-trace export viewable in Perfetto.
* :mod:`telemetry.metrics` — counters / gauges / exponential-bucket
  histograms, plus the shared snapshot/delta core the compileStats,
  featurizeStats, and resilience ledgers sit on (one lock ⇒ consistent
  cross-ledger snapshots).
* :mod:`telemetry.events` — the structured JSONL event log (failovers,
  breaker transitions, drift alerts, checkpoint saves, warmup
  completions) with monotonic sequence numbers.
* :mod:`telemetry.export` — ``render_prometheus()``, chrome trace export,
  the span-derived bench phase breakdown, and the ``summary_pretty()``
  line.
* :mod:`telemetry.runlog` — the training-run flight recorder: one
  schema-versioned ``RunReport`` per ``Workflow.train()`` (per-phase /
  layer / fold timings, runtime host↔device transfer census, device-
  memory high-water, live progress/ETA) plus the cross-run
  ``diff_runs`` / ``RegressionSentinel`` regression verdicts.

CLI: ``python -m transmogrifai_tpu metrics`` / ``... trace`` /
``... runs``. Docs: docs/observability.md (span taxonomy + metric
catalogue + the run ledger).
"""
from __future__ import annotations

from . import events  # noqa: F401
from . import runlog  # noqa: F401
from .export import (  # noqa: F401
    export_chrome_trace,
    metrics_snapshot,
    phase_breakdown,
    render_prometheus,
    serve_latency_summary,
    serving_snapshot,
    summary_line,
)
from .metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    LedgerCore,
    MetricsRegistry,
    exponential_buckets,
    snapshot_lock,
)
from .spans import (  # noqa: F401
    clock,
    enabled,
    record_serve_batch,
    record_span,
    recent_serve_traces,
    reset_for_tests,
    set_clock,
    set_enabled,
    span,
)

emit = events.emit
