"""Training-run flight recorder — one durable, comparable record per
``Workflow.train()``.

The reference's whole L3 plane (ModelInsights, training summaries) exists
so a training run leaves evidence of what happened and why; serving got
that in PR 7 (telemetry) and PR 9 (attributions), but a train run still
evaporated into the span buffer. This module closes that gap:

* :class:`RunStats` — a process-wide :class:`~.metrics.LedgerCore` ledger
  (the ``run`` Prometheus source) counting the **runtime** host↔device
  transfer census: uploads recorded at the ``compiler/dispatch.py``
  ``prefetch_f32``/``device_f32`` seam, downloads at the
  ``local/scoring.py`` render points — count + bytes + seconds, the live
  counterpart of the static TPX census in ``analysis/plan_audit.py``
  (:func:`reconcile_transfer_census` squares the two);
* :class:`RunRecorder` — installed by ``Workflow.train()`` for the run's
  duration; captures per-phase seconds with compileStats/featurizeStats
  deltas, per-layer and per-fold/candidate timings with rows/s, sweep
  lane occupancy/pad waste (``compiler/stats.record_sweep``), device-
  memory high-water gauges polled at phase/layer boundaries
  (``device.memory_stats()`` + ``jax.live_arrays()``; graceful zero on
  CPU), and a seconds-per-layer EWMA feeding a live ETA surfaced through
  the optional ``train(progress=callback)`` hook;
* the **RunReport** artifact — a schema-versioned JSON document in the
  unified bench-report envelope (``bench.py validate_bench_report``
  accepts it), written as ``RUN_*.json`` into ``train(run_dir=...)`` /
  ``$TPTPU_RUN_DIR`` and landed in the model manifest,
  ``summary_json()["run"]``, and a "Run report:" ``summary_pretty`` line;
* :func:`diff_runs` / :class:`RegressionSentinel` — cross-run comparison
  flagging per-phase slowdowns (TPR001), compile-count blowups (TPR002),
  transfer-bytes growth (TPR003), and quality drops (TPR004) beyond
  tolerances, emitting a ``run_regression`` event. ``train(run_dir=...)``
  diffs each new run against the directory's latest automatically.

CLI: ``python -m transmogrifai_tpu runs [--last | --diff A B]``.
Docs: docs/observability.md "The run ledger".

Everything here is observability: recorder failures are contained (a
broken poll must never fail a train), and the <2% train-overhead guard in
``tests/test_runlog.py`` pins the enabled cost.

Known attribution limits (process-scoped, by design for now): the
transfer census is a DELTA over one process-global ledger, so scoring
traffic served concurrently with a ``train()`` lands in that run's
census; likewise :func:`active_recorder` resolves to the innermost
installed recorder process-wide, so two trains running concurrently in
one process attribute each other's fold/candidate pulses. Both need
context propagation (a recorder carried through the candidate pool and
the dispatch seam) to tighten — out of scope here; single-train
processes (every current caller: tests, bench, the runner) are exact.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Iterator

from . import events as _tevents
from . import metrics as _tm
from . import spans as _tspans

log = logging.getLogger(__name__)

__all__ = [
    "RUN_SCHEMA_VERSION",
    "EtaEstimator",
    "RegressionSentinel",
    "RunRecorder",
    "RunStats",
    "RunTolerances",
    "active_recorder",
    "diff_runs",
    "latest_run_report",
    "list_run_reports",
    "load_run_report",
    "poll_device_memory",
    "reconcile_transfer_census",
    "record_download",
    "record_upload",
    "recording",
    "save_run_report",
    "stats",
    "validate_run_report",
]

#: artifact schema version (the unified bench envelope's schema_version
#: rides along; this one versions the nested ``run`` payload)
RUN_SCHEMA_VERSION = 1
RUN_FILE_PREFIX = "RUN_"

_COUNTER_KEYS = (
    "h2dTransfers",     # host->device uploads through the dispatch seam
    "h2dBytes",         # bytes those uploads moved
    "d2hTransfers",     # device->host downloads at the scoring render seam
    "d2hBytes",         # bytes those downloads moved
    "runsRecorded",     # finalized RunReports this process
    "layersTimed",      # DAG-layer boundary pulses
    "foldsTimed",       # CV-fold boundary pulses
    "candidatesTimed",  # candidate-sweep timings (selector + workflow CV)
    "summaryDegraded",  # summary_pretty sections that failed and degraded
    "runRegressions",   # findings emitted by diff_runs/RegressionSentinel
)


class RunStats(_tm.LedgerCore):
    """Thread-safe counters; upload/download seconds ride along as
    floats. Shares the registry's re-entrant lock with the other ledgers,
    so a ``telemetry.snapshot_lock()`` read is consistent across all."""

    def __init__(self) -> None:
        super().__init__(_COUNTER_KEYS)
        self._h2d_s = 0.0
        self._d2h_s = 0.0

    # ------------------------------------------------------------ recording
    def record_upload(self, nbytes: int, seconds: float = 0.0) -> None:
        with self._lock:
            self._counts["h2dTransfers"] += 1
            self._counts["h2dBytes"] += int(nbytes)
            self._h2d_s += seconds

    def record_download(self, nbytes: int, seconds: float = 0.0) -> None:
        with self._lock:
            self._counts["d2hTransfers"] += 1
            self._counts["d2hBytes"] += int(nbytes)
            self._d2h_s += seconds

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counts)
            out["h2dSeconds"] = round(self._h2d_s, 4)
            out["d2hSeconds"] = round(self._d2h_s, 4)
        return out

    def reset(self) -> None:
        with self._lock:
            self._reset_counts()
            self._h2d_s = 0.0
            self._d2h_s = 0.0


_STATS = RunStats()
_tm.REGISTRY.register_source("run", _STATS.snapshot)


def stats() -> RunStats:
    return _STATS


def snapshot() -> dict:
    return _STATS.snapshot()


def delta(before: dict) -> dict:
    """Per-run view: current snapshot minus an earlier ``snapshot()``."""
    now = _STATS.snapshot()
    out: dict = _tm.counter_delta(now, before, _COUNTER_KEYS)
    for k in ("h2dSeconds", "d2hSeconds"):
        out[k] = _tm.float_delta(now, before, k, ndigits=4)
    return out


def record_upload(nbytes: int, seconds: float = 0.0) -> None:
    """One host→device upload through the dispatch seam (prefetch_f32 /
    device_f32's fresh-upload path)."""
    _STATS.record_upload(nbytes, seconds)


def record_download(nbytes: int, seconds: float = 0.0) -> None:
    """One device→host download at a scoring render point."""
    _STATS.record_download(nbytes, seconds)


# --------------------------------------------------------------- device memory
def poll_device_memory() -> dict[str, Any]:
    """Point-in-time device-memory gauges: allocator stats summed across
    local devices (``device.memory_stats()`` — None on CPU, hence the
    explicit zeros) plus the total bytes of live jax arrays
    (``jax.live_arrays()``, which works on every backend). Never raises —
    a broken poll reports zeros."""
    out: dict[str, Any] = {
        "backend": "unknown",
        "deviceBytesInUse": 0,
        "devicePeakBytes": 0,
        "liveArrayBytes": 0,
    }
    try:
        import jax

        devices = jax.local_devices()
        if devices:
            out["backend"] = devices[0].platform
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                in_use = int(ms.get("bytes_in_use", 0))
                out["deviceBytesInUse"] += in_use
                out["devicePeakBytes"] += int(
                    ms.get("peak_bytes_in_use", in_use)
                )
        try:
            out["liveArrayBytes"] = int(
                sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
            )
        except Exception:
            pass
    except Exception as e:  # observability must never break a train
        log.debug("device memory poll failed: %s", e)
    return out


def poll_host_rss() -> int:
    """Current host resident-set size in bytes (the out-of-core ingest's
    bounded-memory evidence rides this gauge per chunk). Reads
    ``/proc/self/status`` VmRSS; falls back to ``resource.getrusage``
    (peak, in KiB on Linux) where /proc is unavailable. Never raises —
    a broken poll reports 0."""
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except Exception:
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception as e:
        log.debug("host rss poll failed: %s", e)
        return 0


# ------------------------------------------------------------------------ ETA
class EtaEstimator:
    """Seconds-per-unit EWMA → remaining-time estimate. With a constant
    true per-unit cost the estimate converges monotonically (each update
    shrinks the error by ``1 - alpha``)."""

    def __init__(self, alpha: float = 0.4):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._per: float | None = None
        self.updates = 0

    def update(self, seconds: float) -> None:
        self.updates += 1
        if self._per is None:
            self._per = float(seconds)
        else:
            self._per = self.alpha * float(seconds) + (1 - self.alpha) * self._per

    @property
    def seconds_per_unit(self) -> float | None:
        return self._per

    def eta(self, remaining: int | None) -> float | None:
        """Estimated seconds to finish ``remaining`` more units (None
        before the first update or without a known total)."""
        if self._per is None or remaining is None:
            return None
        return max(0.0, self._per * remaining)


# -------------------------------------------------------------- the recorder
class RunRecorder:
    """Flight recorder for one ``Workflow.train()`` call.

    The workflow installs it via :func:`recording`; ``workflow/fit.py``,
    ``workflow/cv.py`` and ``selector/validators.py`` pulse layer/fold/
    candidate boundaries through :func:`active_recorder`. All pulse
    methods are thread-safe (candidate sweeps run on a pool) and
    exception-contained — a recorder bug degrades the report, never the
    train. The clock is the injectable telemetry clock
    (``telemetry.spans.set_clock``) unless one is passed explicitly."""

    def __init__(
        self,
        clock: Callable[[], float] | None = None,
        progress: Callable[[dict], None] | None = None,
        run_id: str | None = None,
        eta_alpha: float = 0.4,
    ):
        self._clock = clock
        self.progress = progress
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.started_unix = time.time()
        self._lock = threading.Lock()
        self._t0: float | None = None
        self._wall: float | None = None
        self.phases: dict[str, dict[str, Any]] = {}
        self.layers: list[dict[str, Any]] = []
        self.folds: list[dict[str, Any]] = []
        self.candidates: list[dict[str, Any]] = []
        self.eta = EtaEstimator(alpha=eta_alpha)
        self.quality: dict[str, Any] | None = None
        self.train_rows: int | None = None
        self._layer_t0: dict[int, tuple[float, float]] = {}
        self._fold_t0: dict[int, tuple[float, float]] = {}
        #: cumulative SIMULATED seconds injected by slow_stage chaos
        #: (resilience/faults.py) — they ride the observed phase/layer
        #: durations exactly like the serving path's breaker-elapsed
        #: convention, so chaos drives the regression sentinel with no
        #: real sleeps
        self._sim_total = 0.0
        self._mem_polls = 0
        self._mem_high: dict[str, Any] = {
            "backend": "unknown",
            "deviceBytesInUse": 0,
            "devicePeakBytes": 0,
            "liveArrayBytes": 0,
            "hostRssBytes": 0,
        }
        #: per-ingest-chunk memory samples (out-of-core fit) — bounded:
        #: past _CHUNK_SERIES_CAP the series decimates by doubling the
        #: sampling stride, so a million-chunk ingest still reports a
        #: few hundred points
        self._chunk_mem: list[dict[str, Any]] = []
        self._chunk_stride = 1
        self.stream: dict[str, Any] | None = None
        self._run_before: dict | None = None
        self._compile_before: dict | None = None
        self._featurize_before: dict | None = None
        self._progress_warned = False

    # ---------------------------------------------------------------- clock
    def _now(self) -> float:
        return self._clock() if self._clock is not None else _tspans.clock()

    def elapsed(self) -> float:
        base = 0.0 if self._t0 is None else self._now() - self._t0
        return base + self._sim_total

    def add_simulated(self, seconds: float) -> None:
        """Fold slow-stage chaos seconds into the in-flight phase/layer
        timings (``FaultPlan.slow_stage`` — simulated, no real sleep)."""
        with self._lock:
            self._sim_total += float(seconds)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RunRecorder":
        from ..compiler import stats as _cstats
        from ..featurize import stats as _fstats

        self._t0 = self._now()
        self._run_before = _STATS.snapshot()
        self._compile_before = _cstats.snapshot()
        self._featurize_before = _fstats.snapshot()
        self.poll_memory()
        return self

    def poll_memory(self) -> dict[str, Any] | None:
        """Fold one device-memory + host-RSS poll into the run's
        high-water marks; returns the point-in-time sample."""
        try:
            now = poll_device_memory()
            now["hostRssBytes"] = poll_host_rss()
            with self._lock:
                self._mem_polls += 1
                if now["backend"] != "unknown":
                    self._mem_high["backend"] = now["backend"]
                for k in (
                    "deviceBytesInUse", "devicePeakBytes",
                    "liveArrayBytes", "hostRssBytes",
                ):
                    self._mem_high[k] = max(self._mem_high[k], now[k])
            return now
        except Exception as e:
            log.debug("run recorder memory poll failed: %s", e)
            return None

    _CHUNK_SERIES_CAP = 512

    def poll_chunk_memory(self, chunk_index: int) -> None:
        """One memory sample per ingest CHUNK (not just per phase/layer):
        the per-chunk series is the flatness evidence for the out-of-core
        fit — high-water must not grow with chunk count. Bounded: when
        the series hits the cap it decimates (keep every 2nd point,
        double the stride), so memory for the memory log stays O(cap)."""
        try:
            with self._lock:
                stride = self._chunk_stride
            if chunk_index % stride:
                return
            now = self.poll_memory()
            if now is None:
                return
            with self._lock:
                self._chunk_mem.append({
                    "chunk": int(chunk_index),
                    "deviceBytesInUse": now["deviceBytesInUse"],
                    "liveArrayBytes": now["liveArrayBytes"],
                    "hostRssBytes": now["hostRssBytes"],
                })
                if len(self._chunk_mem) >= self._CHUNK_SERIES_CAP:
                    self._chunk_mem = self._chunk_mem[::2]
                    self._chunk_stride *= 2
        except Exception as e:
            log.debug("run recorder chunk memory poll failed: %s", e)

    def set_stream_summary(self, summary: dict[str, Any]) -> None:
        """Attach the out-of-core ingest summary (workflow/stream.py) —
        chunk/quarantine/window accounting, minus the bulky fitStats."""
        try:
            self.stream = {
                k: v for k, v in summary.items() if k != "fitStats"
            }
        except Exception as e:
            log.debug("run recorder stream summary failed: %s", e)

    def _emit_progress(self, event: dict[str, Any]) -> None:
        if self.progress is None:
            return
        try:
            self.progress(event)
        except Exception as e:  # a user callback must never break training
            if not self._progress_warned:
                self._progress_warned = True
                log.warning("train progress callback failed: %s", e)

    # --------------------------------------------------------------- phases
    @contextlib.contextmanager
    def phase(self, name: str, rows: int | None = None) -> Iterator[None]:
        """Bracket one train phase: seconds + the compileStats /
        featurizeStats deltas attributable to it, a memory poll at the
        boundary, and a progress pulse."""
        from ..compiler import stats as _cstats
        from ..featurize import stats as _fstats

        t0 = self._now()
        sim0 = self._sim_total
        cb = _cstats.snapshot()
        fb = _fstats.snapshot()
        try:
            yield
        finally:
            try:
                secs = self._now() - t0 + (self._sim_total - sim0)
                cd = _cstats.delta(cb)
                fd = _fstats.delta(fb)
                cell: dict[str, Any] = {
                    "seconds": round(secs, 4),
                    "rows": rows,
                    "rowsPerSec": (
                        round(rows / secs) if rows and secs > 0 else None
                    ),
                    "compile": {
                        "programsCompiled": cd["programsCompiled"],
                        "cacheHits": cd["cacheHitsMemory"] + cd["cacheHitsDisk"],
                        "dedupHits": cd["dedupHits"],
                    },
                    "featurize": {
                        "rowsFeaturized": fd["rowsFeaturized"],
                        "stagesExecuted": fd["stagesExecuted"],
                        "poolTasks": fd["poolTasks"],
                    },
                }
                with self._lock:
                    prev = self.phases.get(name)
                    if prev is None:
                        self.phases[name] = cell
                    else:  # a re-entered phase (failover loop) accumulates
                        prev["seconds"] = round(prev["seconds"] + secs, 4)
                        if rows is not None:
                            prev["rows"] = rows
                        # throughput must track the ACCUMULATED seconds —
                        # a stale first-entry rows/s would overstate a
                        # failover-re-entered phase by the retry count
                        prev["rowsPerSec"] = (
                            round(prev["rows"] / prev["seconds"])
                            if prev.get("rows") and prev["seconds"] > 0
                            else None
                        )
                        for fam in ("compile", "featurize"):
                            for k, v in cell[fam].items():
                                prev[fam][k] += v
                self.poll_memory()
                self._emit_progress({
                    "event": "phase",
                    "phase": name,
                    "seconds": round(secs, 4),
                    "elapsed": round(self.elapsed(), 4),
                })
            except Exception as e:
                log.debug("run recorder phase(%s) failed: %s", name, e)

    def set_phase_rows(self, name: str, rows: int) -> None:
        with self._lock:
            cell = self.phases.get(name)
            if cell is not None:
                cell["rows"] = rows
                secs = cell["seconds"]
                cell["rowsPerSec"] = round(rows / secs) if secs > 0 else None

    # --------------------------------------------------------------- layers
    def on_layer_start(self, index: int, total: int | None = None) -> None:
        try:
            with self._lock:
                self._layer_t0[index] = (self._now(), self._sim_total)
        except Exception as e:
            log.debug("run recorder layer_start failed: %s", e)

    def on_layer_end(
        self,
        index: int,
        total: int | None = None,
        stages: int | None = None,
        rows: int | None = None,
    ) -> None:
        try:
            now = self._now()
            with self._lock:
                mark = self._layer_t0.pop(index, None)
                sim_now = self._sim_total
            secs = (
                0.0 if mark is None
                else now - mark[0] + (sim_now - mark[1])
            )
            self.eta.update(secs)
            remaining = None if total is None else max(0, total - index - 1)
            eta_s = self.eta.eta(remaining)
            with self._lock:
                self.layers.append({
                    "index": index,
                    "seconds": round(secs, 4),
                    "stages": stages,
                    "rows": rows,
                    "rowsPerSec": (
                        round(rows / secs) if rows and secs > 0 else None
                    ),
                })
            _STATS.bump("layersTimed")
            self.poll_memory()
            self._emit_progress({
                "event": "layer",
                "index": index,
                "total": total,
                "seconds": round(secs, 4),
                "secondsPerLayer": self.eta.seconds_per_unit,
                "etaSeconds": None if eta_s is None else round(eta_s, 4),
                "elapsed": round(self.elapsed(), 4),
            })
        except Exception as e:
            log.debug("run recorder layer_end failed: %s", e)

    # ---------------------------------------------------------------- folds
    def on_fold_start(self, fold: int, total: int | None = None) -> None:
        try:
            with self._lock:
                self._fold_t0[fold] = (self._now(), self._sim_total)
        except Exception as e:
            log.debug("run recorder fold_start failed: %s", e)

    def on_fold_end(
        self,
        fold: int,
        total: int | None = None,
        rows: int | None = None,
        sweep: dict | None = None,
    ) -> None:
        try:
            now = self._now()
            with self._lock:
                mark = self._fold_t0.pop(fold, None)
                sim_now = self._sim_total
            secs = (
                0.0 if mark is None
                else now - mark[0] + (sim_now - mark[1])
            )
            record = {
                "fold": fold,
                "seconds": round(secs, 4),
                "rows": rows,
                "rowsPerSec": (
                    round(rows / secs) if rows and secs > 0 else None
                ),
            }
            if sweep is not None:
                # fold-scoped lane occupancy / pad waste: the caller hands
                # the compileStats delta across its fold (workflow/cv.py),
                # so each fold record carries its own sweep accounting
                record["sweep"] = _sweep_summary(sweep)
            with self._lock:
                self.folds.append(record)
            _STATS.bump("foldsTimed")
            self._emit_progress({
                "event": "fold",
                "fold": fold,
                "total": total,
                "seconds": round(secs, 4),
                "elapsed": round(self.elapsed(), 4),
            })
        except Exception as e:
            log.debug("run recorder fold_end failed: %s", e)

    def on_candidate(
        self,
        model: str,
        points: int,
        seconds: float,
        rows: int | None = None,
        fold: int | None = None,
        error: str | None = None,
    ) -> None:
        """One candidate family's sweep (the selector's internal validator
        batches folds into one program; workflow CV pulses per fold)."""
        try:
            with self._lock:
                self.candidates.append({
                    "model": model,
                    "points": points,
                    "fold": fold,
                    "seconds": round(seconds, 4),
                    "rows": rows,
                    "rowsPerSec": (
                        round(rows / seconds) if rows and seconds > 0 else None
                    ),
                    "error": error,
                })
            _STATS.bump("candidatesTimed")
        except Exception as e:
            log.debug("run recorder candidate pulse failed: %s", e)

    # ------------------------------------------------------------- finalize
    def record_quality(self, metrics: dict[str, Any] | None) -> None:
        if metrics:
            self.quality = {
                k: v for k, v in metrics.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }

    def finalize(self, train_rows: int | None = None) -> dict[str, Any]:
        """Freeze the run into its schema-versioned report (the unified
        bench envelope with the nested ``run`` payload)."""
        from ..compiler import stats as _cstats
        from ..featurize import stats as _fstats

        if train_rows is not None:
            self.train_rows = train_rows
        self._wall = self.elapsed()
        self.poll_memory()
        run_delta = delta(self._run_before or {})
        compile_delta = _cstats.delta(self._compile_before or {})
        featurize_delta = _fstats.delta(self._featurize_before or {})
        _STATS.bump("runsRecorded")
        return build_report(
            self, run_delta, compile_delta, featurize_delta
        )


def _sweep_summary(compile_delta: dict) -> dict[str, Any]:
    """Sweep lane occupancy/pad-waste from the compileStats delta:
    ``record_sweep`` counts lanes-1 dedup hits per batched sweep and the
    inert pad lanes bucketing added, so occupancy ≈ useful lanes over
    dispatched lanes (approximate — unbucketed sweeps contribute no pad
    accounting)."""
    dedup = compile_delta.get("dedupHits", 0)
    pads = compile_delta.get("laneBucketPads", 0)
    sweeps = compile_delta.get("bucketedSweeps", 0)
    useful = dedup + sweeps  # lanes-1 per sweep + one lane-0 per padded sweep
    total = useful + pads
    return {
        "dedupHits": dedup,
        "laneBucketPads": pads,
        "bucketedSweeps": sweeps,
        "laneOccupancy": _tm.ratio(useful, total),
        "padWasteRatio": _tm.ratio(pads, total),
    }


def build_report(
    rec: RunRecorder,
    run_delta: dict,
    compile_delta: dict,
    featurize_delta: dict,
) -> dict[str, Any]:
    wall = rec._wall if rec._wall is not None else rec.elapsed()
    census = {
        "hostToDevice": {
            "count": run_delta["h2dTransfers"],
            "bytes": run_delta["h2dBytes"],
            "seconds": run_delta["h2dSeconds"],
        },
        "deviceToHost": {
            "count": run_delta["d2hTransfers"],
            "bytes": run_delta["d2hBytes"],
            "seconds": run_delta["d2hSeconds"],
        },
    }
    mem = dict(rec._mem_high)
    mem["polls"] = rec._mem_polls
    mem["highWaterBytes"] = max(
        mem["deviceBytesInUse"], mem["devicePeakBytes"]
    )
    if rec._chunk_mem:
        mem["chunkSeries"] = list(rec._chunk_mem)
        mem["chunkSeriesStride"] = rec._chunk_stride
    metrics: dict[str, Any] = {
        "wall_s": round(wall, 4),
        "train_rows": rec.train_rows,
        "layers": len(rec.layers),
        "folds": len(rec.folds),
        "candidates": len(rec.candidates),
        "programs_compiled": compile_delta.get("programsCompiled", 0),
        "compile_cache_hits": (
            compile_delta.get("cacheHitsMemory", 0)
            + compile_delta.get("cacheHitsDisk", 0)
        ),
        "sweep_dedup_lanes": compile_delta.get("dedupHits", 0),
        "sweep_pad_lanes": compile_delta.get("laneBucketPads", 0),
        "rows_featurized": featurize_delta.get("rowsFeaturized", 0),
        "h2d_transfers": census["hostToDevice"]["count"],
        "h2d_bytes": census["hostToDevice"]["bytes"],
        "d2h_transfers": census["deviceToHost"]["count"],
        "d2h_bytes": census["deviceToHost"]["bytes"],
        "device_high_water_bytes": mem["highWaterBytes"],
        "live_array_high_water_bytes": mem["liveArrayBytes"],
        "host_rss_high_water_bytes": mem["hostRssBytes"],
    }
    if rec.stream is not None:
        metrics["stream_chunks_folded"] = rec.stream.get("chunksFolded", 0)
        metrics["stream_chunks_quarantined"] = rec.stream.get(
            "quarantinedTotal", 0
        )
        metrics["stream_rows_seen"] = rec.stream.get("rowsSeen", 0)
    for name, cell in rec.phases.items():
        metrics[f"phase_{name}_s"] = cell["seconds"]
    if rec.quality:
        for k, v in rec.quality.items():
            metrics[f"quality_{k}"] = v
    return {
        # the unified bench-report envelope (bench.validate_bench_report
        # accepts this shape as-is)
        "schema_version": 1,
        "metric": "train_run_wallclock",
        "value": round(wall, 4),
        "unit": "s",
        "seed": None,
        "median_of": None,
        "metrics": metrics,
        "run": {
            "schemaVersion": RUN_SCHEMA_VERSION,
            "runId": rec.run_id,
            "startedUnix": round(rec.started_unix, 3),
            "wallSeconds": round(wall, 4),
            "trainRows": rec.train_rows,
            "phases": rec.phases,
            "layers": rec.layers,
            "folds": rec.folds,
            "candidates": rec.candidates,
            "eta": {
                "secondsPerLayer": rec.eta.seconds_per_unit,
                "updates": rec.eta.updates,
            },
            "compileStats": compile_delta,
            "featurizeStats": featurize_delta,
            "sweeps": _sweep_summary(compile_delta),
            "transferCensus": census,
            "deviceMemory": mem,
            "quality": rec.quality,
            # out-of-core ingest accounting — only when train streamed
            # (additive: validate_run_report checks it when present)
            **({"stream": rec.stream} if rec.stream is not None else {}),
        },
    }


# ------------------------------------------------------- active-recorder seam
_ACTIVE: list[RunRecorder] = []
_ACTIVE_LOCK = threading.Lock()


def active_recorder() -> RunRecorder | None:
    """The innermost installed recorder (None outside a recorded train)."""
    with _ACTIVE_LOCK:
        return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def recording(rec: RunRecorder) -> Iterator[RunRecorder]:
    """Install ``rec`` as the active recorder for the block (re-entrant:
    a nested train — the CV label-DAG refits — pulses the innermost)."""
    with _ACTIVE_LOCK:
        _ACTIVE.append(rec)
    try:
        yield rec
    finally:
        with _ACTIVE_LOCK:
            if rec in _ACTIVE:
                _ACTIVE.remove(rec)


# ---------------------------------------------------------------- persistence
def run_filename(report: dict[str, Any]) -> str:
    started = report.get("run", {}).get("startedUnix") or time.time()
    # millisecond-resolution stamp: two same-second runs must still sort
    # chronologically by NAME (list_run_reports / prev / last / the
    # auto-diff baseline all lean on that ordering)
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(started))
    # truncate, never round: rounding a >=.9995 fraction would wrap to
    # 000 without carrying the second, sorting BEFORE earlier runs
    millis = min(999, int((started % 1.0) * 1000))
    run_id = report.get("run", {}).get("runId", "unknown")
    return f"{RUN_FILE_PREFIX}{stamp}{millis:03d}_{run_id}.json"


def save_run_report(report: dict[str, Any], run_dir: str) -> str:
    """Write one ``RUN_*.json`` artifact (filename recorded inside the
    report, so the diff surfaces can name their baseline); returns the
    path. The write is atomic (temp + rename), so a killed writer — or a
    concurrent ``runs`` CLI / ``latest_run_report`` reader — never
    observes a truncated document."""
    os.makedirs(run_dir, exist_ok=True)
    name = run_filename(report)
    report.setdefault("run", {})["file"] = name
    path = os.path.join(run_dir, name)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(report, fh, default=str)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_run_report(path: str) -> dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    problems = validate_run_report(doc)
    if problems:
        raise ValueError(f"{path}: not a valid run report: {problems}")
    return doc


def list_run_reports(run_dir: str) -> list[str]:
    """Paths of the directory's run artifacts, oldest first (the
    ``RUN_<utcstamp>_<id>.json`` names sort chronologically)."""
    if not os.path.isdir(run_dir):
        return []
    names = sorted(
        n for n in os.listdir(run_dir)
        if n.startswith(RUN_FILE_PREFIX) and n.endswith(".json")
    )
    return [os.path.join(run_dir, n) for n in names]


def latest_run_report(run_dir: str) -> dict[str, Any] | None:
    """The newest loadable run report in ``run_dir`` (skips unparseable
    files rather than failing the caller's train)."""
    for path in reversed(list_run_reports(run_dir)):
        try:
            return load_run_report(path)
        except Exception as e:
            log.warning("skipping unreadable run report %s: %s", path, e)
    return None


def validate_run_report(doc: Any) -> list[str]:
    """Problems with a run report (empty list = valid). Checks both the
    unified bench envelope and the nested ``run`` payload this module
    owns."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"not a JSON object: {type(doc).__name__}"]
    if doc.get("schema_version") != 1:
        problems.append(f"bad schema_version {doc.get('schema_version')!r}")
    if doc.get("metric") != "train_run_wallclock":
        problems.append(f"bad metric {doc.get('metric')!r}")
    if not isinstance(doc.get("metrics"), dict):
        problems.append("missing 'metrics' map")
    run = doc.get("run")
    if not isinstance(run, dict):
        return problems + ["missing 'run' payload"]
    if run.get("schemaVersion") != RUN_SCHEMA_VERSION:
        problems.append(f"bad run.schemaVersion {run.get('schemaVersion')!r}")
    for key, types in (
        ("runId", str), ("wallSeconds", (int, float)), ("phases", dict),
        ("layers", list), ("transferCensus", dict), ("deviceMemory", dict),
        ("compileStats", dict), ("featurizeStats", dict),
    ):
        if not isinstance(run.get(key), types):
            problems.append(f"run.{key} missing or invalid")
    census = run.get("transferCensus")
    if isinstance(census, dict):
        for side in ("hostToDevice", "deviceToHost"):
            cell = census.get(side)
            if not isinstance(cell, dict) or not all(
                isinstance(cell.get(k), (int, float))
                for k in ("count", "bytes", "seconds")
            ):
                problems.append(f"run.transferCensus.{side} invalid")
    # out-of-core ingest block: additive, validated WHEN PRESENT
    stream = run.get("stream")
    if stream is not None:
        if not isinstance(stream, dict):
            problems.append("run.stream not a map")
        else:
            for key in ("chunksFolded", "rowsSeen", "quarantinedTotal"):
                if not isinstance(stream.get(key), int):
                    problems.append(f"run.stream.{key} missing or invalid")
    return problems


# ------------------------------------------------------- census reconciliation
def reconcile_transfer_census(
    runtime: dict[str, Any],
    static_census: dict[str, Any],
    rows: int | None = None,
    batches: int | None = None,
    check_uploads: bool = False,
    program_counts: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Square the RUNTIME census (a :func:`delta` of the run ledger, or a
    report's ``transferCensus``) against the STATIC per-row prediction
    from ``analysis/plan_audit.py``. For a device-dispatching batch the
    static census predicts one h2d + one d2h per predictor stage per
    batch and ``downBytesPerRow`` download bytes per row; ``consistent``
    is True when the observed counts/bytes line up with that prediction.

    ``check_uploads=True`` additionally pins the upload COUNT to the
    static prediction (``hostToDeviceTransfers × batches``) — the fused
    scoring graph's "uploads only at ingest" acceptance check. Steady
    state only: the fused program's one-time model-constant upload and
    the staged path's opportunistic prefetches make the first batch after
    bring-up legitimately chattier.

    ``program_counts`` (from ``analysis.program.program_transfer_counts``)
    is the THIRD census leg: per-batch crossings derived from the compiled
    programs themselves (one argument upload + one result download per
    dispatched program). When given, the three legs must agree —
    program == static per batch, and runtime == program × batches;
    disagreement surfaces as TPJ006 through
    ``analysis.program.reconcile_program_census``."""
    if "hostToDevice" in runtime:  # a report census
        rt_d2h = runtime["deviceToHost"]["count"]
        rt_d2h_bytes = runtime["deviceToHost"]["bytes"]
        rt_h2d = runtime["hostToDevice"]["count"]
        rt_h2d_bytes = runtime["hostToDevice"]["bytes"]
    else:  # a ledger delta
        rt_d2h = runtime["d2hTransfers"]
        rt_d2h_bytes = runtime["d2hBytes"]
        rt_h2d = runtime["h2dTransfers"]
        rt_h2d_bytes = runtime["h2dBytes"]
    st_d2h = static_census.get("deviceToHostTransfers", 0)
    st_down_per_row = static_census.get("downBytesPerRow", 0.0)
    out: dict[str, Any] = {
        "runtimeH2dTransfers": rt_h2d,
        "runtimeH2dBytes": rt_h2d_bytes,
        "runtimeD2hTransfers": rt_d2h,
        "runtimeD2hBytes": rt_d2h_bytes,
        "staticH2dPerBatch": static_census.get("hostToDeviceTransfers", 0),
        "staticD2hPerBatch": st_d2h,
        "staticDownBytesPerRow": st_down_per_row,
    }
    checks: list[bool] = []
    if batches is not None:
        out["expectedD2hTransfers"] = st_d2h * batches
        checks.append(rt_d2h == st_d2h * batches)
    if rows is not None and st_down_per_row:
        out["expectedD2hBytes"] = round(st_down_per_row * rows)
        checks.append(rt_d2h_bytes == round(st_down_per_row * rows))
    if check_uploads and batches is not None:
        st_h2d = static_census.get("hostToDeviceTransfers", 0)
        out["expectedH2dTransfers"] = st_h2d * batches
        checks.append(rt_h2d == st_h2d * batches)
    if program_counts is not None:
        pg_h2d = int(program_counts.get("hostToDevicePerBatch", 0))
        pg_d2h = int(program_counts.get("deviceToHostPerBatch", 0))
        out["programH2dPerBatch"] = pg_h2d
        out["programD2hPerBatch"] = pg_d2h
        prog_checks = [
            pg_h2d == out["staticH2dPerBatch"],
            pg_d2h == out["staticD2hPerBatch"],
        ]
        if batches is not None:
            prog_checks.append(rt_d2h == pg_d2h * batches)
            if check_uploads:
                prog_checks.append(rt_h2d == pg_h2d * batches)
        out["programConsistent"] = all(prog_checks)
        checks.extend(prog_checks)
    out["consistent"] = bool(checks) and all(checks)
    return out


# --------------------------------------------------------------- run diffing
@dataclasses.dataclass
class RunTolerances:
    """Regression thresholds for :func:`diff_runs`. Ratios compare
    current/baseline; the absolute floors keep noise on tiny runs (a
    40 ms ingest doubling to 80 ms) from crying wolf."""

    phase_slowdown_ratio: float = 1.5
    phase_min_seconds: float = 0.25
    compile_blowup_ratio: float = 1.5
    compile_blowup_abs: int = 2
    transfer_growth_ratio: float = 1.5
    transfer_min_bytes: int = 1 << 20
    quality_drop: float = 0.02


#: quality-metric names (substring match) where LOWER is better — a drop
#: in these is an improvement, a rise a regression
_LOWER_IS_BETTER = ("rmse", "mse", "mae", "loss", "error", "brier")


def _quality_regressed(name: str, base: float, cur: float, tol: float) -> bool:
    lower_better = any(s in name.lower() for s in _LOWER_IS_BETTER)
    return (cur - base > tol) if lower_better else (base - cur > tol)


def _census_bytes(run: dict[str, Any]) -> int:
    c = run.get("transferCensus") or {}
    return int(
        (c.get("hostToDevice") or {}).get("bytes", 0)
        + (c.get("deviceToHost") or {}).get("bytes", 0)
    )


def diff_runs(
    baseline: dict[str, Any] | str,
    current: dict[str, Any] | str,
    tolerances: RunTolerances | None = None,
    emit_events: bool = True,
):
    """Compare two run reports; returns an
    :class:`~transmogrifai_tpu.analysis.Report` whose findings are the
    TPR-coded regressions (per-phase slowdown TPR001, compile-count
    blowup TPR002, transfer-bytes growth TPR003, quality drop TPR004 —
    all WARNING severity: nothing is refused, the verdict is evidence).
    Each regression bumps the run ledger and, with ``emit_events``, lands
    one ``run_regression`` event in the structured log."""
    from ..analysis.findings import Report, Severity

    tol = tolerances or RunTolerances()
    base_doc = load_run_report(baseline) if isinstance(baseline, str) else baseline
    cur_doc = load_run_report(current) if isinstance(current, str) else current
    base = base_doc.get("run") or {}
    cur = cur_doc.get("run") or {}
    report = Report()

    # ---- TPR001: per-phase slowdowns
    base_phases = base.get("phases") or {}
    for name, cell in (cur.get("phases") or {}).items():
        b = base_phases.get(name)
        if b is None:
            continue
        bs, cs = float(b.get("seconds", 0.0)), float(cell.get("seconds", 0.0))
        # a zero-cost baseline phase growing real seconds is a slowdown
        # too (also the injectable-clock regime, where clean timings are
        # exactly zero and only simulated chaos seconds register)
        if cs > tol.phase_min_seconds and (
            bs <= 0.0 or cs > bs * tol.phase_slowdown_ratio
        ):
            ratio_s = f"{cs / bs:.2f}x" if bs > 0 else "from zero"
            report.add(
                "TPR001",
                f"phase '{name}' slowed {ratio_s} between runs "
                f"({bs:.3f}s -> {cs:.3f}s, tolerance "
                f"{tol.phase_slowdown_ratio:.2f}x)",
                subject=name,
                severity=Severity.WARNING,
                baselineSeconds=bs,
                currentSeconds=cs,
            )

    # ---- TPR002: compile-count blowups
    bc = int((base.get("compileStats") or {}).get("programsCompiled", 0))
    cc = int((cur.get("compileStats") or {}).get("programsCompiled", 0))
    if cc > max(bc * tol.compile_blowup_ratio, bc + tol.compile_blowup_abs):
        report.add(
            "TPR002",
            f"programs compiled blew up {bc} -> {cc} between runs — a "
            "cache/bucketing regression (every extra compile is seconds "
            "on the tunneled chip)",
            subject="programsCompiled",
            severity=Severity.WARNING,
            baseline=bc,
            current=cc,
        )

    # ---- TPR003: transfer-bytes growth
    bb, cb = _census_bytes(base), _census_bytes(cur)
    if cb > tol.transfer_min_bytes and cb > max(
        bb * tol.transfer_growth_ratio, bb + tol.transfer_min_bytes
    ):
        report.add(
            "TPR003",
            f"host<->device transfer volume grew {bb} -> {cb} bytes "
            "between runs — a new boundary crossing in the hot path",
            subject="transferCensus",
            severity=Severity.WARNING,
            baselineBytes=bb,
            currentBytes=cb,
        )

    # ---- TPR004: quality drops
    base_q = base.get("quality") or {}
    for name, cv in (cur.get("quality") or {}).items():
        bv = base_q.get(name)
        if not isinstance(bv, (int, float)) or not isinstance(cv, (int, float)):
            continue
        if _quality_regressed(name, float(bv), float(cv), tol.quality_drop):
            report.add(
                "TPR004",
                f"quality metric '{name}' regressed {bv} -> {cv} "
                f"(tolerance {tol.quality_drop})",
                subject=name,
                severity=Severity.WARNING,
                baseline=float(bv),
                current=float(cv),
            )

    report.data["runDiff"] = {
        "baselineRunId": base.get("runId"),
        "currentRunId": cur.get("runId"),
        "baselineWallSeconds": base.get("wallSeconds"),
        "currentWallSeconds": cur.get("wallSeconds"),
        "regressions": len(report),
    }
    if report.findings:
        _STATS.bump("runRegressions", len(report.findings))
        if emit_events:
            _tevents.emit(
                "run_regression",
                baselineRunId=base.get("runId"),
                currentRunId=cur.get("runId"),
                codes=sorted({f.code for f in report.findings}),
                findings=len(report.findings),
            )
    return report


class RegressionSentinel:
    """Standing cross-run regression check: pin a baseline report (dict
    or path) and :meth:`check` each new run against it."""

    def __init__(
        self,
        baseline: dict[str, Any] | str,
        tolerances: RunTolerances | None = None,
    ):
        self.baseline = (
            load_run_report(baseline) if isinstance(baseline, str) else baseline
        )
        self.tolerances = tolerances or RunTolerances()

    def check(self, current: dict[str, Any] | str):
        """Diff ``current`` against the pinned baseline; returns the
        findings Report (``.ok`` is True — regressions are warnings — so
        callers gate on ``len(report)``)."""
        return diff_runs(self.baseline, current, self.tolerances)
