"""Structured JSONL event log — the ops-facing record of rare, important
state changes.

Every :func:`emit` produces one JSON-able record with a process-unique,
strictly monotonic ``seq`` (assigned under the log lock, so the JSONL
ordering is the ordering even when emitters race across threads), the
injectable-monotonic timestamp, a wall-clock timestamp for correlation
with external logs, and the emitter's fields.

Wired event kinds (see docs/observability.md for the catalogue):

* ``failover`` / ``straggler`` — resilience/distributed.py
* ``breaker_transition`` — resilience/sentinel.py circuit breakers
* ``drift_alert`` / ``drift_cleared`` — resilience/sentinel.py drift
  sentinel (one ``drift_alert`` per episode; the paired
  ``drift_cleared`` fires when that feature's window returns under
  threshold, so "still drifting" and "recovered on its own" are
  distinguishable downstream)
* ``checkpoint_save`` — resilience/checkpoint.py layer saves
* ``warmup_complete`` — compiler/warmup.py background bank loads
* ``replica_lost`` / ``hedge_fired`` — serving/fleet.py fleet plane
* ``canary_rollback`` / ``canary_promoted`` — serving/registry.py
* ``retrain_triggered`` / ``retrain_gated`` / ``retrain_promoted`` /
  ``retrain_rolled_back`` — resilience/retrain.py continuous-retraining
  control loop (trigger quorum met; refreshed model refused by the
  run-ledger gate before canary; canary promoted; canary rolled back)

The log is a bounded in-memory deque (``TPTPU_EVENT_BUFFER``, default
4096) exportable as JSONL (:func:`to_jsonl` / :func:`write`); set
``TPTPU_EVENT_LOG=/path/file.jsonl`` to also append each record to disk
as it is emitted.

In-process consumers can :func:`subscribe` a callback; subscribers are
invoked AFTER the log lock is released (an event subscriber may take
its own leaf lock, but no lock-graph edge ever leaves the events lock).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any

from . import spans as _spans
from .spans import _env_int

__all__ = [
    "emit",
    "recent",
    "count",
    "to_jsonl",
    "write",
    "subscribe",
    "unsubscribe",
    "reset_for_tests",
]

_LOCK = threading.Lock()
_BUFFER: deque = deque(maxlen=_env_int("TPTPU_EVENT_BUFFER", 4096))
_STATE: dict[str, int] = {"seq": 0}
# registered under _LOCK, SNAPSHOT under _LOCK, but always INVOKED after
# the lock is released — a subscriber that takes its own lock therefore
# never creates an edge out of the events lock
_SUBSCRIBERS: list = []


def emit(kind: str, **fields: Any) -> dict[str, Any]:
    """Append one event; returns the record (with its assigned seq).

    Honors the telemetry disable switch: when ``spans.enabled()`` is
    False the record is built and returned (seq 0) but neither buffered
    nor appended to ``TPTPU_EVENT_LOG``."""
    rec: dict[str, Any] = {
        "seq": 0,
        "ts": round(_spans.clock(), 6),
        "unix": round(time.time(), 3),
        "kind": kind,
    }
    rec.update(fields)
    if not _spans.enabled():
        return rec
    path = os.environ.get("TPTPU_EVENT_LOG")
    with _LOCK:
        _STATE["seq"] += 1
        rec["seq"] = _STATE["seq"]
        _BUFFER.append(rec)
        if path:
            # inside the lock so on-disk ordering matches seq ordering;
            # events are rare (failovers, breaker trips), so the open
            # cost is irrelevant
            try:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, default=str) + "\n")
            except OSError:
                pass  # a full disk must not take scoring down
        subs = list(_SUBSCRIBERS)
    for fn in subs:
        try:
            fn(rec)
        except Exception:
            pass  # a broken subscriber must not take the emitter down
    return rec


def subscribe(fn) -> None:
    """Register ``fn(record)`` to be called for every emitted event.

    Callbacks run on the emitting thread, after the log lock is
    released, in registration order; exceptions are swallowed. Keep
    subscribers cheap — record-and-return, decide later."""
    with _LOCK:
        if fn not in _SUBSCRIBERS:
            _SUBSCRIBERS.append(fn)


def unsubscribe(fn) -> None:
    with _LOCK:
        try:
            _SUBSCRIBERS.remove(fn)
        except ValueError:
            pass


def recent(n: int | None = None) -> list[dict[str, Any]]:
    with _LOCK:
        out = list(_BUFFER)
    return out if n is None else out[-n:]


def count() -> int:
    """Total events emitted this process (monotonic, survives buffer
    eviction)."""
    return _STATE["seq"]


def to_jsonl() -> str:
    return "\n".join(json.dumps(r, default=str) for r in recent())


def write(path: str) -> int:
    """Dump the buffered events as JSONL; returns the record count."""
    recs = recent()
    with open(path, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r, default=str) + "\n")
    return len(recs)


def reset_for_tests() -> None:
    with _LOCK:
        _BUFFER.clear()
        _STATE["seq"] = 0
        _SUBSCRIBERS.clear()
