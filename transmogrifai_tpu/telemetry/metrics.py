"""Unified metrics registry — counters, gauges, exponential-bucket
histograms, and the shared snapshot/delta core under the process ledgers.

Before this module existed the repo carried three disconnected ad-hoc
ledgers (``compiler.stats`` compileStats, ``featurize.stats``
featurizeStats, the resilience/distributed failover counters), each with
its own hand-rolled lock + snapshot + ``delta()``. They keep their public
APIs, but now:

* each ledger subclasses :class:`LedgerCore`, which owns the counter dict
  and shares ONE process-wide re-entrant lock (``REGISTRY.lock``) — so a
  snapshot taken under :func:`snapshot_lock` is a consistent point-in-time
  view ACROSS ledgers (no torn cross-ledger counts under concurrent
  scoring);
* the duplicated per-key delta arithmetic lives here once
  (:func:`counter_delta` / :func:`float_delta` / :func:`named_delta` /
  :func:`ratio`);
* each ledger registers its ``snapshot`` as a registry *source*, which is
  how ``telemetry.render_prometheus()`` exposes every counter without the
  ledgers knowing anything about exposition formats.

The registry also owns the new first-class metrics: span-duration and
serve-path latency histograms (exponential buckets, interpolated
p50/p95/p99) recorded by ``telemetry.spans``.

Everything here is stdlib-only and thread-safe; the module is on the
TPL001 thread-crossed-subsystem list, so module-global mutations hold a
lock.
"""
from __future__ import annotations

import bisect
import threading
from typing import Any, Callable, Iterable, Sequence

from ..analysis import schedule as _schedule

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LedgerCore",
    "MetricsRegistry",
    "REGISTRY",
    "counter_delta",
    "exponential_buckets",
    "float_delta",
    "named_delta",
    "ratio",
    "snapshot_lock",
]


def exponential_buckets(
    start: float, factor: float, count: int
) -> tuple[float, ...]:
    """``count`` ascending upper bounds: start, start*factor, ... — the
    Prometheus-style exponential bucket ladder."""
    if start <= 0 or factor <= 1.0 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out = []
    b = float(start)
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


#: default latency ladder: 10 µs ... ~429 s at ≤30% relative resolution
DEFAULT_BUCKETS = exponential_buckets(1e-5, 1.3, 68)


class Counter:
    """Monotonic counter (the registry lock serializes writers)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "", lock=None):
        self.name = name
        self.help = help
        # every in-package counter is registry-built and shares the
        # registry lock (what the tpc alias declares); a STANDALONE
        # construction gets its own traced node, so if one ever starts
        # ordering against real locks the schedule reconciler sees it
        if lock is None:
            lock = _schedule.make_lock("telemetry/metrics.py:Counter._lock")
        self._lock = lock  # tp: lock(telemetry/metrics.py:MetricsRegistry.lock)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Point-in-time value."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "", lock=None):
        self.name = name
        self.help = help
        # registry-built gauges share the registry lock; see Counter
        if lock is None:
            lock = _schedule.make_lock("telemetry/metrics.py:Gauge._lock")
        self._lock = lock  # tp: lock(telemetry/metrics.py:MetricsRegistry.lock)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Exponential-bucket histogram with interpolated quantiles.

    ``observe`` is O(log buckets) (bisect over precomputed bounds);
    ``quantile`` interpolates linearly inside the target bucket, so the
    estimate's relative error is bounded by the bucket growth factor."""

    __slots__ = (
        "name", "help", "labels", "bounds", "_counts", "_sum", "_count",
        "_lock",
    )

    def __init__(
        self,
        name: str,
        bounds: Sequence[float] = DEFAULT_BUCKETS,
        labels: dict[str, str] | None = None,
        help: str = "",
        lock=None,
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        # registry-built histograms share the registry lock; see Counter
        if lock is None:
            lock = _schedule.make_lock("telemetry/metrics.py:Histogram._lock")
        self._lock = lock  # tp: lock(telemetry/metrics.py:MetricsRegistry.lock)

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _quantile_from(
        self, counts: Sequence[int], total: int, q: float
    ) -> float | None:
        if total == 0:
            return None
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c and cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                # the overflow bucket has no upper bound: report its floor
                hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                return lo + (hi - lo) * ((target - cum) / c)
            cum += c
        return self.bounds[-1]

    def quantile(self, q: float) -> float | None:
        """Interpolated quantile estimate (None when empty)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return self._quantile_from(counts, total, q)

    def snapshot(self) -> dict[str, Any]:
        # one locked read feeds count, sum, AND all three quantiles, so a
        # concurrent observe() can never tear count vs quantiles
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
        return {
            "count": count,
            "sum": round(total, 6),
            "p50": self._quantile_from(counts, count, 0.50),
            "p95": self._quantile_from(counts, count, 0.95),
            "p99": self._quantile_from(counts, count, 0.99),
        }

    def bucket_counts(self) -> tuple[list[int], int, float]:
        """(cumulative bucket counts incl. +Inf, count, sum) — the
        Prometheus exposition shape."""
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return cum, count, total


class MetricsRegistry:
    """One process-wide home for metrics and ledger sources.

    ``lock`` is re-entrant and shared with every :class:`LedgerCore`, so
    ``with registry.lock:`` brackets a consistent multi-ledger snapshot."""

    def __init__(self) -> None:
        # the instrumented-lock seam (analysis/schedule.py): the literal
        # name is the static analyzer's canonical key for this lock, so
        # the dynamic lock-order graph reconciles against the static one
        self.lock = _schedule.make_lock(
            "telemetry/metrics.py:MetricsRegistry.lock", threading.RLock
        )
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[tuple, Histogram] = {}
        self._sources: dict[str, Callable[[], dict]] = {}

    # ------------------------------------------------------------- metrics
    def counter(self, name: str, help: str = "") -> Counter:
        with self.lock:
            got = self._counters.get(name)
            if got is None:
                got = self._counters[name] = Counter(name, help, self.lock)
            return got

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self.lock:
            got = self._gauges.get(name)
            if got is None:
                got = self._gauges[name] = Gauge(name, help, self.lock)
            return got

    def histogram(
        self,
        name: str,
        labels: dict[str, str] | None = None,
        bounds: Sequence[float] = DEFAULT_BUCKETS,
        help: str = "",
    ) -> Histogram:
        key = (name, tuple(sorted((labels or {}).items())))
        with self.lock:
            got = self._histograms.get(key)
            if got is None:
                got = self._histograms[key] = Histogram(
                    name, bounds, labels, help, self.lock
                )
            return got

    def histograms_named(self, name: str) -> list[Histogram]:
        with self.lock:
            return [h for (n, _), h in self._histograms.items() if n == name]

    # ------------------------------------------------------------- sources
    def register_source(self, name: str, fn: Callable[[], dict]) -> None:
        """A source is a zero-arg callable returning a JSON-able counter
        mapping (a ledger snapshot). Re-registering a name replaces it."""
        with self.lock:
            self._sources[name] = fn

    def source_snapshots(self) -> dict[str, dict]:
        """Snapshot every registered source. The source callables run
        OUTSIDE the registry lock: sources reach into their subsystem's
        own locks (the standing service's, the load shedder's), and those
        subsystems take the registry lock on their hot paths (gauges,
        counters) — invoking sources under the registry lock is an ABBA
        deadlock with any concurrent submit/update. Each ledger source
        still snapshots consistently under its own lock; only
        cross-source simultaneity is (harmlessly) approximate."""
        with self.lock:
            items = list(self._sources.items())
        out: dict[str, dict] = {}
        for name, fn in items:
            try:
                out[name] = fn()
            except Exception:  # a dead source must not kill exposition
                out[name] = {}
        return out

    # ------------------------------------------------------------ snapshot
    def snapshot_all(self) -> dict[str, Any]:
        """JSON-able view of everything the registry knows. Metrics are
        read under the shared lock (one consistent point in time); source
        snapshots run after it, outside the lock (see
        :meth:`source_snapshots` — each source is internally consistent
        under its own lock)."""
        with self.lock:
            out: dict[str, Any] = {
                "counters": {
                    n: c.value for n, c in sorted(self._counters.items())
                },
                "gauges": {
                    n: g.value for n, g in sorted(self._gauges.items())
                },
                "histograms": [
                    {"name": h.name, "labels": dict(h.labels), **h.snapshot()}
                    for _, h in sorted(self._histograms.items())
                ],
            }
        out["sources"] = self.source_snapshots()
        return out

    def reset_metrics_for_tests(self) -> None:
        """Drop counters/gauges/histograms (sources stay registered)."""
        with self.lock:
            self._counters = {}
            self._gauges = {}
            self._histograms = {}


REGISTRY = MetricsRegistry()


def snapshot_lock():
    """The shared re-entrant snapshot lock: ``with snapshot_lock():``
    brackets a consistent point-in-time read across every registered
    ledger (their recorders serialize on the same lock)."""
    return REGISTRY.lock


# ---------------------------------------------------------------- ledger core
class LedgerCore:
    """Shared base of the process-wide counter ledgers.

    Owns the counter dict + the registry's shared lock; subclasses keep
    their recording helpers and their snapshot shapes (which are pinned by
    existing tests), but the snapshot/delta arithmetic lives in the
    module-level helpers below instead of three hand-rolled copies."""

    def __init__(
        self, counter_keys: Iterable[str], registry: MetricsRegistry | None = None
    ) -> None:
        reg = registry if registry is not None else REGISTRY
        self._lock = reg.lock  # tp: lock(telemetry/metrics.py:MetricsRegistry.lock)
        self._keys = tuple(counter_keys)
        self._counts: dict[str, int] = {k: 0 for k in self._keys}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n

    def _reset_counts(self) -> None:  # tp: guarded(telemetry/metrics.py:MetricsRegistry.lock)
        """Caller holds ``self._lock``."""
        self._counts = {k: 0 for k in self._keys}


# ------------------------------------------------------------- delta helpers
def counter_delta(
    now: dict, before: dict, keys: Iterable[str]
) -> dict[str, int]:
    """Per-key integer difference — the shared core of every ledger
    ``delta()``."""
    return {k: now[k] - before.get(k, 0) for k in keys}


def float_delta(
    now: dict, before: dict, key: str, ndigits: int = 3
) -> float:
    return round(now[key] - before.get(key, 0.0), ndigits)


def named_delta(now: dict, before: dict) -> dict:
    """Difference of two ``{name: count}`` maps, dropping zero entries."""
    return {
        name: n - before.get(name, 0)
        for name, n in now.items()
        if n - before.get(name, 0)
    }


def ratio(num: float, denom: float, ndigits: int = 4) -> float | None:
    """Rounded ``num/denom``; None for an empty denominator (the ledgers'
    rate convention — 'no acquisitions yet' must not read as 0%)."""
    return round(num / denom, ndigits) if denom else None
