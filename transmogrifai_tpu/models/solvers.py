"""Pure-JAX training solvers for generalized linear models.

Replaces Spark MLlib's L-BFGS/OWL-QN/WLS native-BLAS path (SURVEY.md §2.5
item 2) with XLA-native solvers designed for the TPU execution model:

  * fixed iteration counts + ``lax.scan`` -> one compiled graph, static
    shapes, no host round-trips per iteration;
  * every solver is ``vmap``-able over its hyperparameters, so a model
    selector's param grid trains as ONE batched XLA computation instead of a
    driver thread pool (OpValidator.scala:363-367 -> vmap axis);
  * row masks (not dynamic slicing) express CV folds / resampling, keeping
    one compiled shape across folds.

Losses follow Spark semantics: mean log-loss / squared error over unmasked
rows + lambda * (alpha*||w||_1 + (1-alpha)/2*||w||_2^2), intercept
unregularized, features standardized internally (standardization=true
default) with coefficients mapped back to the original scale.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class GLMParams(NamedTuple):
    weights: jax.Array    # [D] or [D, C]
    intercept: jax.Array  # scalar or [C]


def _effectively_constant(
    std: jax.Array, scale: jax.Array, rel_tol: float = 1e-5
) -> jax.Array:
    """Columns whose std is ~float-noise relative to their magnitude.

    An exact `std > 0` check misses fold-constant columns: a column stuck
    at c within the mask computes var ≈ (c·eps)² > 0 through float
    cancellation, and dividing by that phantom std amplifies weights into
    garbage. ``rel_tol`` calibrates to the variance formula's error: the
    two-pass centered sum cancels to ~eps·c (1e-5 covers it); the ONE-PASS
    s2/n − mean² form accumulates ~sqrt(N)·eps·c² of noise, i.e. phantom
    std up to ~2e-3·c on ~1k-row folds, and needs ~3e-3 (columns with a
    genuine coefficient of variation below 0.3% are treated as constant —
    a documented trade for not materializing per-lane centered copies)."""
    return std <= jnp.maximum(rel_tol * scale, 1e-12)


def _standardize(x: jax.Array, row_mask: jax.Array):
    n = jnp.maximum(row_mask.sum(), 1.0)
    mean = (x * row_mask[:, None]).sum(0) / n
    var = ((x - mean) ** 2 * row_mask[:, None]).sum(0) / n
    std = jnp.sqrt(var)
    const = _effectively_constant(std, jnp.sqrt(var + mean**2))
    safe = jnp.where(const, 1.0, std)
    xs = jnp.where(row_mask[:, None], (x - mean) / safe, 0.0)
    # zero the constant columns entirely: (x - mean) there is pure noise
    xs = jnp.where(const[None, :], 0.0, xs)
    return xs, mean, safe


def _soft_threshold(w: jax.Array, t: jax.Array) -> jax.Array:
    return jnp.sign(w) * jnp.maximum(jnp.abs(w) - t, 0.0)


def _fista(grad_fn, prox_fn, w0, step, num_iters):
    """Accelerated proximal gradient with fixed iterations (lax.scan)."""

    def body(carry, _):
        w_prev, z, t = carry
        g = grad_fn(z)
        w_next = prox_fn(z - step * g, step)
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_next = w_next + ((t - 1.0) / t_next) * (w_next - w_prev)
        return (w_next, z_next, t_next), None

    (w, _, _), _ = jax.lax.scan(body, (w0, w0, jnp.array(1.0)), None, length=num_iters)
    return w


@partial(
    jax.jit,
    static_argnames=("num_iters", "fit_intercept", "standardization"),
)
def fit_logistic_binary(
    x: jax.Array,          # [N, D]
    y: jax.Array,          # [N] in {0, 1}
    row_mask: jax.Array,   # [N] bool/float — masked rows contribute nothing
    reg_param: jax.Array,  # lambda
    elastic_net: jax.Array,  # alpha in [0, 1]
    num_iters: int = 200,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Binary logistic regression (OpLogisticRegression parity —
    core/.../classification/OpLogisticRegression.scala wraps Spark LR)."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    if standardization:
        xs, mean, std = _standardize(x, row_mask)
        if not fit_intercept:
            # Spark parity: without an intercept, standardization SCALES
            # but does not center — centering would bake an implicit
            # intercept (mean·w) into training that predict never applies
            mean = jnp.zeros(x.shape[1], dtype=x.dtype)
            xs = jnp.where(row_mask[:, None] > 0, x / std, 0.0)
    else:
        xs = jnp.where(row_mask[:, None] > 0, x, 0.0)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        std = jnp.ones(x.shape[1], dtype=x.dtype)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def grad(params):
        w, b = params[:-1], params[-1]
        logits = xs @ w + jnp.where(fit_intercept, b, 0.0)
        p = jax.nn.sigmoid(logits)
        r = (p - y) * row_mask
        gw = xs.T @ r / n + l2 * w
        gb = jnp.where(fit_intercept, r.sum() / n, 0.0)
        return jnp.concatenate([gw, gb[None]])

    def prox(params, step):
        w = _soft_threshold(params[:-1], step * l1)
        return jnp.concatenate([w, params[-1:]])

    # Lipschitz bound for standardized logistic loss: tr(XᵀX)/(4n) + l2
    col = (xs * xs).sum(0) / n
    lip = 0.25 * col.sum() + l2
    step = 1.0 / jnp.maximum(lip, 1e-6)

    params0 = jnp.zeros(x.shape[1] + 1, dtype=x.dtype)
    params = _fista(grad, prox, params0, step, num_iters)
    w_std, b_std = params[:-1], params[-1]
    w = w_std / std
    b = b_std - (w_std * mean / std).sum()
    return GLMParams(weights=w, intercept=jnp.where(fit_intercept, b, 0.0))


@partial(
    jax.jit,
    static_argnames=("num_iters", "fit_intercept", "standardization"),
)
def fit_logistic_binary_batched(
    x: jax.Array,           # [N, D] SHARED feature matrix
    y: jax.Array,           # [N]
    row_masks: jax.Array,   # [K, N] per-fit masks (folds × grid)
    reg_params: jax.Array,  # [K]
    elastic_nets: jax.Array,  # [K]
    num_iters: int = 200,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """K binary logistic fits sharing ONE feature matrix.

    The round-1 sweep vmapped fit_logistic_binary, which materializes K
    per-lane standardized COPIES of x ([K, N, D] — 3 GB for the Titanic
    sweep) and turns every FISTA iteration into a memory-bound pass over
    them. Here lanes batch as GEMM columns on the shared x (two MXU
    matmuls per iteration: logits = x @ (W/std)ᵀ and gradients = r @ x),
    with per-lane standardization applied IMPLICITLY:
        xsᵀr = (xᵀ(r·m) − mean·Σ(r·m)) / std
    Identical math to the vmapped path, reassociated — weights agree to
    float tolerance. Returns GLMParams with weights [K, D], intercept [K].
    """
    k_fits, _ = row_masks.shape
    rm = row_masks.astype(x.dtype)
    n = jnp.maximum(rm.sum(axis=1), 1.0)                 # [K]
    # shifted-data moments: center on the GLOBAL column means first so the
    # one-pass per-lane variance s2c/n - mean_c² operates on small values —
    # the raw one-pass form catastrophically cancels in f32 for large-mean
    # columns (mean² ~4e6 has float spacing ~0.5). Without standardization
    # the model must NOT center (iterates match the sequential raw-x path),
    # so gshift/mean_c stay zero and s2 is the raw second moment.
    if standardization:
        gshift = x.mean(axis=0)                          # [D]
    else:
        gshift = jnp.zeros(x.shape[1], dtype=x.dtype)
    xc = x - gshift[None, :]
    s1 = rm @ xc                                         # [K, D]
    s2 = rm @ (xc * xc)                                  # [K, D]
    mean_raw = s1 / n[:, None]
    var = jnp.maximum(s2 / n[:, None] - mean_raw**2, 0.0)
    std = jnp.sqrt(var)
    # see _effectively_constant: fold-constant columns carry phantom
    # cancellation variance; their std must not be divided by. The wider
    # 3e-3 tolerance matches the ONE-PASS formula's error bound (e.g. a
    # rare one-hot absent from one fold: xc ≡ −p in-mask, var = p²−p²
    # cancellation noise ~2e-3·p escapes a 1e-5 gate)
    const = _effectively_constant(std, jnp.sqrt(s2 / n[:, None]), rel_tol=3e-3)
    if standardization:
        safe = jnp.where(const, 1.0, std)
        if fit_intercept:
            mean_c = mean_raw
        else:
            # no intercept → scale only, never center (Spark parity; a
            # centered fit would differ from predict by mean·w). Gradients
            # must then see RAW x, so undo the moment shift.
            mean_c = jnp.zeros_like(mean_raw)
            xc = x
    else:
        mean_c = jnp.zeros_like(mean_raw)
        safe = jnp.ones_like(std)
        xc = x
    l1 = (reg_params * elastic_nets)[:, None]            # [K, 1]
    l2 = (reg_params * (1.0 - elastic_nets))[:, None]

    def grads(params):
        w_std, b = params[:, :-1], params[:, -1]
        ws = w_std / safe                                # [K, D]
        logits = (xc @ ws.T).T - (mean_c * ws).sum(axis=1)[:, None]
        logits = logits + jnp.where(fit_intercept, b[:, None], 0.0)
        p = jax.nn.sigmoid(logits)
        r = (p - y[None, :]) * rm                        # [K, N]
        xr = r @ xc                                      # [K, D]
        rsum = r.sum(axis=1)[:, None]
        gw = (xr - mean_c * rsum) / safe / n[:, None] + l2 * w_std
        if standardization:
            # constant columns are pure cancellation noise: pin their
            # weights at 0 (matches _standardize zeroing those columns)
            gw = jnp.where(const, 0.0, gw)
        gb = jnp.where(fit_intercept, rsum[:, 0] / n, 0.0)
        return jnp.concatenate([gw, gb[:, None]], axis=1)

    # tr(XsᵀXs)/n per lane: centered standardized columns have unit
    # variance (0 for constant columns) → count of non-constant columns.
    # Scaled-but-NOT-centered columns (fit_intercept=False) have second
    # moment (var + mean²)/std² ≥ 1; without standardization it is the raw
    # masked second moment per column.
    if standardization and fit_intercept:
        col_sum = (~const).sum(axis=1).astype(x.dtype)
    elif standardization:
        raw_second = var + (gshift[None, :] + mean_raw) ** 2
        col_sum = jnp.where(const, 0.0, raw_second / safe**2).sum(axis=1)
    else:
        col_sum = (s2 / n[:, None]).sum(axis=1)
    lip = 0.25 * col_sum + l2[:, 0]
    step = (1.0 / jnp.maximum(lip, 1e-6))[:, None]       # [K, 1]

    params0 = jnp.zeros((k_fits, x.shape[1] + 1), dtype=x.dtype)

    def body(carry, _):
        w_prev, z, t = carry
        g = grads(z)
        moved = z - step * g
        w_next = jnp.concatenate(
            [_soft_threshold(moved[:, :-1], step * l1), moved[:, -1:]],
            axis=1,
        )
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_next = w_next + ((t - 1.0) / t_next) * (w_next - w_prev)
        return (w_next, z_next, t_next), None

    (params, _, _), _ = jax.lax.scan(
        body, (params0, params0, jnp.array(1.0)), None, length=num_iters
    )
    w_std, b_std = params[:, :-1], params[:, -1]
    w = w_std / safe
    mean_total = gshift[None, :] + mean_c
    b = b_std - (w_std * mean_total / safe).sum(axis=1)
    return GLMParams(
        weights=w,
        intercept=jnp.where(fit_intercept, b, jnp.zeros_like(b)),
    )


@partial(
    jax.jit,
    static_argnames=(
        "num_classes", "num_iters", "fit_intercept", "standardization"
    ),
)
def fit_logistic_multinomial(
    x: jax.Array,
    y: jax.Array,          # [N] int class ids
    row_mask: jax.Array,
    reg_param: jax.Array,
    elastic_net: jax.Array,
    num_classes: int,
    num_iters: int = 200,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Softmax regression (Spark multinomial logistic parity)."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    if standardization:
        xs, mean, std = _standardize(x, row_mask)
    else:
        xs = jnp.where(row_mask[:, None] > 0, x, 0.0)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        std = jnp.ones(x.shape[1], dtype=x.dtype)
    y1h = jax.nn.one_hot(y.astype(jnp.int32), num_classes, dtype=x.dtype)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)
    d = x.shape[1]

    def unpack(params):
        return params[: d * num_classes].reshape(d, num_classes), params[d * num_classes:]

    def grad(params):
        w, b = unpack(params)
        logits = xs @ w + jnp.where(fit_intercept, b, 0.0)
        p = jax.nn.softmax(logits, axis=-1)
        r = (p - y1h) * row_mask[:, None]
        gw = xs.T @ r / n + l2 * w
        gb = jnp.where(fit_intercept, r.sum(0) / n, jnp.zeros_like(b))
        return jnp.concatenate([gw.reshape(-1), gb])

    def prox(params, step):
        w, b = unpack(params)
        return jnp.concatenate([_soft_threshold(w, step * l1).reshape(-1), b])

    col = (xs * xs).sum(0) / n
    lip = 0.5 * col.sum() + l2
    step = 1.0 / jnp.maximum(lip, 1e-6)
    params0 = jnp.zeros(d * num_classes + num_classes, dtype=x.dtype)
    params = _fista(grad, prox, params0, step, num_iters)
    w_std, b_std = unpack(params)
    w = w_std / std[:, None]
    b = b_std - (w_std * (mean / std)[:, None]).sum(0)
    return GLMParams(weights=w, intercept=b if fit_intercept else jnp.zeros_like(b))


@partial(jax.jit, static_argnames=("num_iters", "fit_intercept", "standardization"))
def fit_linear_svc(
    x: jax.Array,
    y: jax.Array,          # [N] in {0, 1}
    row_mask: jax.Array,
    reg_param: jax.Array,
    num_iters: int = 400,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Linear SVM via Huberized hinge + L2 (OpLinearSVC parity —
    core/.../classification/OpLinearSVC.scala wraps Spark LinearSVC, which is
    hinge/OWL-QN). The hinge is smoothed on a width-``delta`` band so FISTA
    has a true Lipschitz constant and converges at the accelerated rate; as
    delta -> 0 this recovers the exact hinge objective."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    if standardization:
        xs, mean, std = _standardize(x, row_mask)
    else:
        xs = jnp.where(row_mask[:, None] > 0, x, 0.0)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        std = jnp.ones(x.shape[1], dtype=x.dtype)
    s = 2.0 * y - 1.0  # {-1, +1}
    delta = jnp.asarray(0.1, dtype=x.dtype)

    def grad(params):
        w, b = params[:-1], params[-1]
        margin = s * (xs @ w + jnp.where(fit_intercept, b, 0.0))
        # dL/dmargin for Huberized hinge: -1 below the band, linear inside
        slope = -jnp.clip((1.0 - margin) / delta, 0.0, 1.0)
        r = slope * s * row_mask
        gw = (xs * r[:, None]).sum(0) / n + reg_param * w
        gb = jnp.where(fit_intercept, r.sum() / n, 0.0)
        return jnp.concatenate([gw, gb[None]])

    def prox(params, _step):
        return params

    col = (xs * xs).sum(0) / n
    lip = (col.sum() + 1.0) / delta + reg_param
    step = 1.0 / jnp.maximum(lip, 1e-6)
    params0 = jnp.zeros(x.shape[1] + 1, dtype=x.dtype)
    params = _fista(grad, prox, params0, step, num_iters)
    w_std, b_std = params[:-1], params[-1]
    w = w_std / std
    b = b_std - (w_std * mean / std).sum()
    return GLMParams(weights=w, intercept=jnp.where(fit_intercept, b, 0.0))


# GLM family/link codes (static ints so the IRLS graph stays compiled once
# per (family, link) pair — Spark GeneralizedLinearRegression.scala parity)
GLM_FAMILIES = {"gaussian": 0, "binomial": 1, "poisson": 2, "gamma": 3}
GLM_LINKS = {"identity": 0, "log": 1, "logit": 2, "inverse": 3, "sqrt": 4}
GLM_DEFAULT_LINK = {
    "gaussian": "identity", "binomial": "logit", "poisson": "log",
    "gamma": "inverse",
}


@partial(jax.jit, static_argnames=("family", "link", "num_iters", "fit_intercept"))
def fit_glm_irls(
    x: jax.Array,
    y: jax.Array,
    row_mask: jax.Array,
    reg_param: jax.Array,  # L2 only, like Spark GLM
    family: int = 0,
    link: int = 0,
    num_iters: int = 25,
    fit_intercept: bool = True,
) -> GLMParams:
    """Iteratively reweighted least squares for generalized linear models
    (OpGeneralizedLinearRegression parity — Spark GLR's IRLS, maxIter=25).
    One `lax.scan` of normal-equation solves; D is small in tabular AutoML so
    the [D+1, D+1] solve per iteration is cheap on the MXU."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    d = x.shape[1]
    ones = jnp.ones((x.shape[0], 1), dtype=x.dtype)
    xa = jnp.concatenate([x, ones], axis=1) if fit_intercept else x
    da = xa.shape[1]
    eps = jnp.asarray(1e-7, dtype=x.dtype)

    def linkinv(eta):
        return jax.lax.switch(
            link,
            [
                lambda e: e,                       # identity
                lambda e: jnp.exp(e),              # log
                lambda e: jax.nn.sigmoid(e),       # logit
                lambda e: 1.0 / jnp.where(jnp.abs(e) > eps, e, eps),  # inverse
                lambda e: e * e,                   # sqrt
            ],
            eta,
        )

    def dmu_deta(eta, mu):
        return jax.lax.switch(
            link,
            [
                lambda: jnp.ones_like(eta),
                lambda: mu,
                lambda: mu * (1.0 - mu),
                lambda: -mu * mu,
                lambda: 2.0 * jnp.sqrt(jnp.maximum(mu, eps)),
            ],
        )

    def variance(mu):
        return jax.lax.switch(
            family,
            [
                lambda m: jnp.ones_like(m),        # gaussian
                lambda m: m * (1.0 - m),           # binomial
                lambda m: m,                       # poisson
                lambda m: m * m,                   # gamma
            ],
            mu,
        )

    def init_eta():
        # family-aware starting point on the linear scale
        mu0 = jax.lax.switch(
            family,
            [
                lambda: y,
                lambda: (y + 0.5) / 2.0,
                lambda: jnp.maximum(y, 0.0) + 0.1,
                lambda: jnp.maximum(y, eps),
            ],
        )
        return jax.lax.switch(
            link,
            [
                lambda m: m,
                lambda m: jnp.log(jnp.maximum(m, eps)),
                lambda m: jnp.log(jnp.maximum(m, eps) / jnp.maximum(1.0 - m, eps)),
                lambda m: 1.0 / jnp.maximum(m, eps),
                lambda m: jnp.sqrt(jnp.maximum(m, 0.0)),
            ],
            mu0,
        )

    def body(beta, _):
        eta = xa @ beta
        mu = linkinv(eta)
        dmu = dmu_deta(eta, mu)
        dmu = jnp.where(jnp.abs(dmu) > eps, dmu, eps)
        var = jnp.maximum(variance(mu), eps)
        z = eta + (y - mu) / dmu
        w = row_mask * dmu * dmu / var
        xtwx = (xa * w[:, None]).T @ xa / n
        xtwz = (xa * w[:, None]).T @ z / n
        reg = reg_param * jnp.eye(da, dtype=x.dtype)
        if fit_intercept:  # intercept unregularized
            reg = reg.at[da - 1, da - 1].set(0.0)
        beta_next = jnp.linalg.solve(xtwx + reg + eps * jnp.eye(da, dtype=x.dtype), xtwz)
        return beta_next, None

    eta0 = init_eta()
    w0 = row_mask
    xtwx0 = (xa * w0[:, None]).T @ xa / n
    xtwz0 = (xa * w0[:, None]).T @ eta0 / n
    beta0 = jnp.linalg.solve(
        xtwx0 + (reg_param + eps) * jnp.eye(da, dtype=x.dtype), xtwz0
    )
    beta, _ = jax.lax.scan(body, beta0, None, length=num_iters)
    if fit_intercept:
        return GLMParams(weights=beta[:-1], intercept=beta[-1])
    return GLMParams(weights=beta, intercept=jnp.zeros((), dtype=x.dtype))


@partial(jax.jit, static_argnames=("num_iters", "fit_intercept"))
def fit_linear(
    x: jax.Array,
    y: jax.Array,
    row_mask: jax.Array,
    reg_param: jax.Array,
    elastic_net: jax.Array,
    num_iters: int = 200,
    fit_intercept: bool = True,
) -> GLMParams:
    """Linear regression with elastic net (OpLinearRegression parity; Spark
    WLS/normal-equation semantics for alpha=0 via converged FISTA)."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    xs, mean, std = _standardize(x, row_mask)
    ym = (y * row_mask).sum() / n
    yc = jnp.where(row_mask > 0, y - ym, 0.0)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def grad(w):
        r = (xs @ w - yc) * row_mask
        return xs.T @ r / n + l2 * w

    def prox(w, step):
        return _soft_threshold(w, step * l1)

    col = (xs * xs).sum(0) / n
    lip = col.sum() + l2
    step = 1.0 / jnp.maximum(lip, 1e-6)
    w0 = jnp.zeros(x.shape[1], dtype=x.dtype)
    w_std = _fista(grad, prox, w0, step, num_iters)
    w = w_std / std
    b = ym - (w_std * mean / std).sum()
    return GLMParams(weights=w, intercept=jnp.where(fit_intercept, b, 0.0))
