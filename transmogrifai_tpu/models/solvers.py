"""Pure-JAX training solvers for generalized linear models.

Replaces Spark MLlib's L-BFGS/OWL-QN/WLS native-BLAS path (SURVEY.md §2.5
item 2) with XLA-native solvers designed for the TPU execution model:

  * fixed iteration counts + ``lax.scan`` -> one compiled graph, static
    shapes, no host round-trips per iteration;
  * every solver is ``vmap``-able over its hyperparameters, so a model
    selector's param grid trains as ONE batched XLA computation instead of a
    driver thread pool (OpValidator.scala:363-367 -> vmap axis);
  * row masks (not dynamic slicing) express CV folds / resampling, keeping
    one compiled shape across folds.

Losses follow Spark semantics: mean log-loss / squared error over unmasked
rows + lambda * (alpha*||w||_1 + (1-alpha)/2*||w||_2^2), intercept
unregularized, features standardized internally (standardization=true
default) with coefficients mapped back to the original scale.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class GLMParams(NamedTuple):
    weights: jax.Array    # [D] or [D, C]
    intercept: jax.Array  # scalar or [C]


def _effectively_constant(
    std: jax.Array, scale: jax.Array, rel_tol: float = 1e-5
) -> jax.Array:
    """Columns whose std is ~float-noise relative to their magnitude.

    An exact `std > 0` check misses fold-constant columns: a column stuck
    at c within the mask computes var ≈ (c·eps)² > 0 through float
    cancellation, and dividing by that phantom std amplifies weights into
    garbage. ``rel_tol`` calibrates to the two-pass centered sum's error
    (~eps·c; 1e-5 covers it). The batched logistic solver instead detects
    constants exactly via masked min/max — order-invariant, so sharded and
    single-device runs agree bit-for-bit."""
    return std <= jnp.maximum(rel_tol * scale, 1e-12)


def _masked_minmax(x: jax.Array, rm: jax.Array):
    """Per-(lane, column) masked min/max: ``([K, D] min, [K, D] max)`` for
    x [N, D] under masks rm [K, N].

    The one-shot broadcast form (``jnp.where(rm[:, :, None] > 0, x[None],
    ±big)`` reduced over axis 1) materializes O(K·N·D) temporaries — ~100 MB
    per reduction at Titanic sweep shapes, and the allocation scales with
    the grid. ``lax.map`` scans the K mask lanes instead, so peak extra
    memory is one [N, D] buffer regardless of K. min/max are exact under
    ANY association, so the result is bit-identical to the broadcast form
    (and invariant across shardings — the property the constant-column
    gate relies on)."""
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)

    def one(mask_row):  # [N] -> ([D], [D])
        mb = mask_row[:, None] > 0
        return (
            jnp.min(jnp.where(mb, x, big), axis=0),
            jnp.max(jnp.where(mb, x, -big), axis=0),
        )

    return jax.lax.map(one, rm)


def _standardize(x: jax.Array, row_mask: jax.Array):
    n = jnp.maximum(row_mask.sum(), 1.0)
    mean = (x * row_mask[:, None]).sum(0) / n
    var = ((x - mean) ** 2 * row_mask[:, None]).sum(0) / n
    std = jnp.sqrt(var)
    const = _effectively_constant(std, jnp.sqrt(var + mean**2))
    safe = jnp.where(const, 1.0, std)
    xs = jnp.where(row_mask[:, None], (x - mean) / safe, 0.0)
    # zero the constant columns entirely: (x - mean) there is pure noise
    xs = jnp.where(const[None, :], 0.0, xs)
    return xs, mean, safe, const


def _scale_only(x: jax.Array, row_mask: jax.Array, std, const):
    """Scale-without-centering variant for fit_intercept=False (Spark
    parity: centering would bake an implicit mean·w offset into training
    that predict never applies). Constant columns stay zeroed — otherwise
    they would absorb a pseudo-intercept the caller asked not to fit."""
    xs = jnp.where(row_mask[:, None] > 0, x / std, 0.0)
    return jnp.where(const[None, :], 0.0, xs)


def _soft_threshold(w: jax.Array, t: jax.Array) -> jax.Array:
    return jnp.sign(w) * jnp.maximum(jnp.abs(w) - t, 0.0)


def _fista(grad_fn, prox_fn, w0, step, num_iters):
    """Accelerated proximal gradient with fixed iterations (lax.scan)."""

    def body(carry, _):
        w_prev, z, t = carry
        g = grad_fn(z)
        w_next = prox_fn(z - step * g, step)
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z_next = w_next + ((t - 1.0) / t_next) * (w_next - w_prev)
        return (w_next, z_next, t_next), None

    (w, _, _), _ = jax.lax.scan(body, (w0, w0, jnp.array(1.0)), None, length=num_iters)
    return w


@partial(jax.jit, static_argnames=("num_iters", "fit_intercept"))
def fit_linear_batched(
    x: jax.Array,            # [N, D] SHARED feature matrix
    y: jax.Array,            # [N]
    row_masks: jax.Array,    # [K, N] per-fit masks (folds x grid)
    reg_params: jax.Array,   # [K]
    elastic_nets: jax.Array,  # [K]
    num_iters: int = 200,
    fit_intercept: bool = True,
) -> GLMParams:
    """K elastic-net linear regressions sharing ONE feature matrix.

    The regression selector's LinearRegression family previously fit
    sequentially — folds x grid separate fit_linear dispatches, ~0.75 s of
    the warm Boston wall (each dispatch a tunnel round trip for
    microseconds of FLOPs). Lanes batch as GEMM columns exactly like
    fit_logistic_binary_batched: per iteration one [N, K] forward GEMM +
    one [K, D] gradient GEMM on the shared x, with per-lane
    standardization applied implicitly (Xs_k' r = (xc' (r·m) −
    mean_k·Σ(r·m)) / std_k, xc globally shifted so one-pass lane moments
    don't cancel in f32). Per-lane semantics mirror fit_linear: same FISTA,
    same effectively-constant column rule, same no-intercept
    scale-without-centering parity. Returns weights [K, D], intercept [K].
    """
    rm = row_masks.astype(x.dtype)
    n = jnp.maximum(rm.sum(axis=1), 1.0)                    # [K]
    gshift = x.mean(axis=0)
    xc = x - gshift[None, :]
    s1 = rm @ xc                                            # [K, D]
    s2 = rm @ (xc * xc)
    mean_shift = s1 / n[:, None]
    var = jnp.maximum(s2 / n[:, None] - mean_shift**2, 0.0)
    std = jnp.sqrt(var)
    mean_true = mean_shift + gshift[None, :]
    # fold-constant detection must be EXACT (masked min/max, like
    # fit_logistic_binary_batched): an all-zero-in-mask column has
    # mean_true ~ 0, so the std-relative-to-scale test degenerates
    # (scale == std) and the phantom one-pass std would pass through —
    # the column then absorbs a garbage weight that corrupts held-out
    # predictions wherever the column is nonzero outside the mask
    xmin, xmax = _masked_minmax(x, rm)                      # [K, D] each
    const = (xmax <= xmin) | _effectively_constant(
        std, jnp.sqrt(var + mean_true**2)
    )
    safe = jnp.where(const, 1.0, std)
    if not fit_intercept:
        # Spark parity: scale only, never center x OR y (see fit_linear)
        mean_shift = jnp.zeros_like(mean_shift)
        xc = x
        ym = jnp.zeros_like(n)
    else:
        ym = (rm @ y) / n                                   # [K]
    yc = jnp.where(rm > 0, y[None, :] - ym[:, None], 0.0)   # [K, N]
    l1 = (reg_params * elastic_nets)[:, None]
    l2 = (reg_params * (1.0 - elastic_nets))[:, None]

    def grad(w_std):
        # w_std [K, D] in standardized space; const columns pinned at 0
        v = jnp.where(const, 0.0, w_std / safe)             # [K, D]
        logits = xc @ v.T - (mean_shift * v).sum(axis=1)[None, :]  # [N, K]
        r = (logits.T - yc) * rm                            # [K, N]
        g_raw = r @ xc - mean_shift * r.sum(axis=1)[:, None]
        g = jnp.where(const, 0.0, g_raw / safe) / n[:, None]
        return g + l2 * w_std

    def prox(w, step):
        return _soft_threshold(w, step * l1)

    # per-lane standardized column second moments: 1 for centered columns,
    # (var + mean^2)/std^2 for the scale-only no-intercept path (a
    # large-mean column there has norm >> 1 — assuming 1 diverges)
    if fit_intercept:
        col2 = jnp.where(const, 0.0, 1.0)
    else:
        col2 = jnp.where(const, 0.0, (var + mean_true**2) / (safe * safe))
    lip = col2.sum(axis=1)[:, None] + l2                     # [K, 1]
    step = 1.0 / jnp.maximum(lip, 1e-6)
    w0 = jnp.zeros((rm.shape[0], x.shape[1]), dtype=x.dtype)
    w_std = _fista(grad, prox, w0, step, num_iters)
    w = jnp.where(const, 0.0, w_std / safe)
    b = ym - (w_std * jnp.where(const, 0.0, mean_true / safe)).sum(axis=1)
    if not fit_intercept:
        b = jnp.zeros_like(b)
    return GLMParams(weights=w, intercept=b)


# --------------------------------------------------------------------------
# Batched L-BFGS / OWL-QN (MLlib LogisticRegression's actual algorithm —
# SURVEY.md §2.5 item 2). First-order FISTA does not converge on
# ill-conditioned one-hot matrices (Titanic 891×957, κ≈2e4) inside any
# reasonable fixed budget; the quasi-Newton direction does. TPU-shaped:
#   * K independent fits (folds × grid) advance in lockstep as rows of one
#     [K, P] parameter matrix — every GEMM stays MXU-sized;
#   * the line search evaluates ALL step candidates with ONE batched GEMM
#     ([T·K] lanes) instead of a data-dependent backtracking loop;
#   * fixed iteration count under `lax.scan` (static shapes, AOT-exportable);
#     converged lanes freeze in place so extra iterations are no-ops.
# OWL-QN (Andrew & Gao 2007) handles per-lane L1 via the pseudo-gradient +
# orthant projection; lanes with l1=0 degrade exactly to plain L-BFGS.
# --------------------------------------------------------------------------

_LBFGS_M = 8           # history pairs (MLlib/breeze default m=10; 8 aligns)
_LS_STEPS = (1.0, 0.5, 0.25, 0.1, 0.03, 0.01, 0.003)  # Armijo candidates
_LS_C1 = 1e-4


def _lbfgs_owlqn(
    value_grad,        # W [K, P] -> (F [K], g_smooth [K, P]); F includes l1
    candidates_value,  # Wc [T, K, P] -> F [T, K]
    p0,                # [K, P] initial params
    l1_mat,            # [K, P] per-component l1 strength (0 on intercepts)
    gamma0,            # [K] initial inverse-Hessian scale (≈ 1/Lipschitz)
    num_iters: int,
    gtol: float = 1e-7,
):
    """Returns argmin params [K, P]. All control flow is branchless so the
    whole optimizer is one scanned XLA program, vmap- and GSPMD-friendly."""
    k_fits, p_dim = p0.shape
    m = _LBFGS_M
    ts = jnp.asarray(_LS_STEPS, dtype=p0.dtype)

    def pseudo_grad(w, g):
        # ∂(f + l1·|w|): sign(w)-side derivative away from 0; at 0 the
        # steepest one-sided descent direction (0 inside the [-l1, l1] band)
        gp = g + l1_mat
        gm = g - l1_mat
        at0 = jnp.where(gm > 0, gm, jnp.where(gp < 0, gp, 0.0))
        return jnp.where(w > 0, gp, jnp.where(w < 0, gm, at0))

    def two_loop(pg, S, Y, rho, gamma):
        q = pg
        alphas = []
        for i in range(m - 1, -1, -1):
            a = rho[i] * (S[i] * q).sum(-1)          # [K]
            q = q - a[:, None] * Y[i]
            alphas.append(a)
        r = gamma[:, None] * q
        for i in range(m):
            a = alphas[m - 1 - i]
            b = rho[i] * (Y[i] * r).sum(-1)
            r = r + S[i] * (a - b)[:, None]
        return -r

    def body(carry, _):
        w, f_cur, g, S, Y, rho, gamma = carry
        pg = pseudo_grad(w, g)
        d = two_loop(pg, S, Y, rho, gamma)
        # OWL-QN: constrain d to a descent direction of the pseudo-gradient
        # on l1-active components (l1=0 lanes pass through untouched)
        d = jnp.where((l1_mat > 0) & (d * pg >= 0), 0.0, d)
        xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
        cand = w[None] + ts[:, None, None] * d[None]          # [T, K, P]
        cand = jnp.where((l1_mat > 0) & (cand * xi < 0), 0.0, cand)
        f_cand = candidates_value(cand)                       # [T, K]
        pgd = ((cand - w[None]) * pg[None]).sum(-1)           # [T, K]
        accept = f_cand <= f_cur[None] + _LS_C1 * pgd
        first_ok = jnp.argmax(accept, axis=0)                 # largest t ok
        fallback = jnp.argmin(f_cand, axis=0)
        idx = jnp.where(accept.any(axis=0), first_ok, fallback)
        sel = jax.nn.one_hot(idx, len(_LS_STEPS), dtype=w.dtype, axis=0)
        w_sel = (cand * sel[:, :, None]).sum(0)
        f_sel = (f_cand * sel).sum(0)
        conv = jnp.abs(pg).max(-1) <= gtol * jnp.maximum(1.0, jnp.abs(f_cur))
        move = (f_sel < f_cur) & ~conv
        w_next = jnp.where(move[:, None], w_sel, w)
        f_next_sel, g_next = value_grad(w_next)
        f_next = jnp.where(move, f_next_sel, f_cur)
        s = w_next - w
        yv = g_next - g
        sy = (s * yv).sum(-1)
        # relative curvature gate (breeze-style): tiny-positive f32 sy
        # garbage would otherwise produce huge rho and garbage directions
        s_nrm = jnp.sqrt((s * s).sum(-1))
        y_nrm = jnp.sqrt((yv * yv).sum(-1))
        valid = move & (sy > 1e-8 * s_nrm * y_nrm + 1e-20)
        # line-search failure away from convergence means the quasi-Newton
        # direction went bad (stale/ill-conditioned history): RESET to
        # steepest descent with the 1/Lipschitz scale. Without this the
        # carry never changes and the lane deadlocks at a non-converged
        # point (every later iteration rebuilds the same rejected step).
        fail = ~move & ~conv
        s = jnp.where(valid[:, None], s, 0.0)
        yv = jnp.where(valid[:, None], yv, 0.0)
        rho_new = jnp.where(valid, 1.0 / jnp.where(valid, sy, 1.0), 0.0)
        vslot = valid[None, :, None]
        S_next = jnp.where(vslot, jnp.concatenate([S[1:], s[None]]), S)
        Y_next = jnp.where(vslot, jnp.concatenate([Y[1:], yv[None]]), Y)
        rho_next = jnp.where(
            valid[None, :], jnp.concatenate([rho[1:], rho_new[None]]), rho
        )
        S_next = jnp.where(fail[None, :, None], 0.0, S_next)
        Y_next = jnp.where(fail[None, :, None], 0.0, Y_next)
        rho_next = jnp.where(fail[None, :], 0.0, rho_next)
        gamma_next = jnp.where(
            valid, sy / jnp.maximum((yv * yv).sum(-1), 1e-20), gamma
        )
        gamma_next = jnp.where(fail, gamma00, gamma_next)
        return (w_next, f_next, g_next, S_next, Y_next, rho_next, gamma_next), None

    f0, g0 = value_grad(p0)
    gamma00 = gamma0.astype(p0.dtype)
    S0 = jnp.zeros((m, k_fits, p_dim), dtype=p0.dtype)
    Y0 = jnp.zeros((m, k_fits, p_dim), dtype=p0.dtype)
    rho0 = jnp.zeros((m, k_fits), dtype=p0.dtype)
    carry0 = (p0, f0, g0, S0, Y0, rho0, gamma0.astype(p0.dtype))
    (w, *_), _ = jax.lax.scan(body, carry0, None, length=num_iters)
    return w


@partial(
    jax.jit,
    static_argnames=("num_iters", "fit_intercept", "standardization"),
)
def fit_logistic_binary(
    x: jax.Array,          # [N, D]
    y: jax.Array,          # [N] in {0, 1}
    row_mask: jax.Array,   # [N] bool/float — masked rows contribute nothing
    reg_param: jax.Array,  # lambda
    elastic_net: jax.Array,  # alpha in [0, 1]
    num_iters: int = 100,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Binary logistic regression via L-BFGS/OWL-QN (OpLogisticRegression
    parity — core/.../classification/OpLogisticRegression.scala wraps Spark
    LR, whose optimizer is breeze L-BFGS, or OWL-QN when elasticNet > 0).

    Delegates to the K=1 lane of ``fit_logistic_binary_batched`` so the
    sweep and the winner's refit run IDENTICAL math (same standardization
    moments, same constant-column gate, same optimizer trajectory)."""
    out = fit_logistic_binary_batched(
        x,
        y,
        row_mask[None, :],
        jnp.asarray(reg_param, dtype=x.dtype)[None],
        jnp.asarray(elastic_net, dtype=x.dtype)[None],
        num_iters=num_iters,
        fit_intercept=fit_intercept,
        standardization=standardization,
    )
    return GLMParams(weights=out.weights[0], intercept=out.intercept[0])


@partial(
    jax.jit,
    static_argnames=("num_iters", "fit_intercept", "standardization"),
)
def fit_logistic_binary_batched(
    x: jax.Array,           # [N, D] SHARED feature matrix
    y: jax.Array,           # [N]
    row_masks: jax.Array,   # [K, N] per-fit masks (folds × grid)
    reg_params: jax.Array,  # [K]
    elastic_nets: jax.Array,  # [K]
    num_iters: int = 100,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """K binary logistic L-BFGS/OWL-QN fits sharing ONE feature matrix.

    The round-1 sweep vmapped the sequential solver, which materializes K
    per-lane standardized COPIES of x ([K, N, D] — 3 GB for the Titanic
    sweep) and turns every iteration into a memory-bound pass over them.
    Here lanes batch as GEMM columns on the shared x (per iteration: one
    [T·K]-lane line-search GEMM + one gradient GEMM pair), with per-lane
    standardization applied IMPLICITLY:
        xsᵀr = (xᵀ(r·m) − mean·Σ(r·m)) / std
    Round 2 ran FISTA here, which provably did not converge on Titanic's
    κ≈2e4 one-hot matrix within maxIter·4 iterations (fold metrics drifted
    ±0.3 AuPR under float reassociation); the quasi-Newton direction
    reaches gradient-norm convergence in tens of iterations, matching
    MLlib's optimizer family. Returns weights [K, D], intercept [K].
    """
    k_fits, _ = row_masks.shape
    rm = row_masks.astype(x.dtype)
    n = jnp.maximum(rm.sum(axis=1), 1.0)                 # [K]
    # shifted-data moments: center on the GLOBAL column means first so the
    # one-pass per-lane variance s2c/n - mean_c² operates on small values —
    # the raw one-pass form catastrophically cancels in f32 for large-mean
    # columns (mean² ~4e6 has float spacing ~0.5). Without standardization
    # the model must NOT center (iterates match the sequential raw-x path),
    # so gshift/mean_c stay zero and s2 is the raw second moment.
    if standardization:
        gshift = x.mean(axis=0)                          # [D]
    else:
        gshift = jnp.zeros(x.shape[1], dtype=x.dtype)
    xc = x - gshift[None, :]
    s1 = rm @ xc                                         # [K, D]
    s2 = rm @ (xc * xc)                                  # [K, D]
    mean_raw = s1 / n[:, None]
    var = jnp.maximum(s2 / n[:, None] - mean_raw**2, 0.0)
    std = jnp.sqrt(var)
    # Fold-constant detection must be EXACT and reduction-order-invariant:
    # a variance threshold computed from one-pass moments sits in f32
    # cancellation noise, so a mesh-sharded run and a single-device run
    # can flip a borderline column in opposite directions — one path pins
    # the weight at 0, the other divides by the phantom std and amplifies
    # it to O(10) (observed on Titanic fold masks). Masked min/max are
    # exact under ANY association, so both paths agree bit-for-bit
    # (_masked_minmax scans lanes instead of broadcasting [K, N, D]).
    xmin, xmax = _masked_minmax(x, rm)                      # [K, D] each
    const = xmax <= xmin
    # near-constant (but not exactly constant) columns still carry one-pass
    # cancellation noise in std; clamp to the noise floor instead of gating
    # — a continuous guard cannot flip discretely between shardings
    noise_floor = 2e-3 * jnp.sqrt(s2 / n[:, None]) + 1e-12
    if standardization:
        safe = jnp.where(const, 1.0, jnp.maximum(std, noise_floor))
        if fit_intercept:
            mean_c = mean_raw
        else:
            # no intercept → scale only, never center (Spark parity; a
            # centered fit would differ from predict by mean·w). Gradients
            # must then see RAW x, so undo the moment shift.
            mean_c = jnp.zeros_like(mean_raw)
            xc = x
    else:
        mean_c = jnp.zeros_like(mean_raw)
        safe = jnp.ones_like(std)
        xc = x
    l1 = (reg_params * elastic_nets)[:, None]            # [K, 1]
    l2 = (reg_params * (1.0 - elastic_nets))[:, None]
    d_cols = x.shape[1]

    def _loss_terms(logits, w_std):
        # logits [..., K, N], w_std [..., K, D] -> total objective [..., K]
        ll = jax.nn.softplus(logits) - y * logits
        f = (ll * rm).sum(-1) / n
        f = f + 0.5 * l2[:, 0] * (w_std * w_std).sum(-1)
        return f + l1[:, 0] * jnp.abs(w_std).sum(-1)

    def _logits_of(ws, b):
        # ws [..., K, D] (already scaled by 1/safe) -> logits [..., K, N]
        lead = ws.shape[:-1]
        lin = (xc @ ws.reshape(-1, d_cols).T).T.reshape(*lead, -1)
        out = lin - (mean_c * ws).sum(-1)[..., None]
        if fit_intercept:
            out = out + b[..., None]
        return out

    def candidates_value(cand):                          # [T, K, P]
        w_std, b = cand[..., :-1], cand[..., -1]
        return _loss_terms(_logits_of(w_std / safe, b), w_std)

    def value_grad(params):                              # [K, P]
        w_std, b = params[:, :-1], params[:, -1]
        ws = w_std / safe
        logits = _logits_of(ws, b)
        f_total = _loss_terms(logits, w_std)
        p = jax.nn.sigmoid(logits)
        r = (p - y[None, :]) * rm                        # [K, N]
        xr = r @ xc                                      # [K, D]
        rsum = r.sum(axis=1)[:, None]
        gw = (xr - mean_c * rsum) / safe / n[:, None] + l2 * w_std
        if standardization:
            # constant columns are pure cancellation noise: pin their
            # weights at 0 (matches _standardize zeroing those columns)
            gw = jnp.where(const, 0.0, gw)
        gb = jnp.where(fit_intercept, rsum[:, 0] / n, 0.0)
        return f_total, jnp.concatenate([gw, gb[:, None]], axis=1)

    # tr(XsᵀXs)/n per lane: centered standardized columns have unit
    # variance (0 for constant columns) → count of non-constant columns.
    # Scaled-but-NOT-centered columns (fit_intercept=False) have second
    # moment (var + mean²)/std² ≥ 1; without standardization it is the raw
    # masked second moment per column.
    if standardization and fit_intercept:
        col_sum = (~const).sum(axis=1).astype(x.dtype)
    elif standardization:
        raw_second = var + (gshift[None, :] + mean_raw) ** 2
        col_sum = jnp.where(const, 0.0, raw_second / safe**2).sum(axis=1)
    else:
        col_sum = (s2 / n[:, None]).sum(axis=1)
    lip = 0.25 * col_sum + l2[:, 0]
    gamma0 = 1.0 / jnp.maximum(lip, 1e-6)                # [K]

    # l1 applies to weight components only, never the intercept slot
    l1_mat = jnp.concatenate(
        [jnp.broadcast_to(l1, (k_fits, d_cols)),
         jnp.zeros((k_fits, 1), dtype=x.dtype)], axis=1,
    )
    params0 = jnp.zeros((k_fits, d_cols + 1), dtype=x.dtype)
    params = _lbfgs_owlqn(
        value_grad, candidates_value, params0, l1_mat, gamma0, num_iters
    )
    w_std, b_std = params[:, :-1], params[:, -1]
    w = w_std / safe
    mean_total = gshift[None, :] + mean_c
    b = b_std - (w_std * mean_total / safe).sum(axis=1)
    return GLMParams(
        weights=w,
        intercept=jnp.where(fit_intercept, b, jnp.zeros_like(b)),
    )


@partial(
    jax.jit,
    static_argnames=(
        "num_classes", "num_iters", "fit_intercept", "standardization"
    ),
)
def fit_logistic_multinomial(
    x: jax.Array,
    y: jax.Array,          # [N] int class ids
    row_mask: jax.Array,
    reg_param: jax.Array,
    elastic_net: jax.Array,
    num_classes: int,
    num_iters: int = 200,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Softmax regression (Spark multinomial logistic parity)."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    if standardization:
        xs, mean, std, const = _standardize(x, row_mask)
        if not fit_intercept:
            mean = jnp.zeros(x.shape[1], dtype=x.dtype)
            xs = _scale_only(x, row_mask, std, const)
    else:
        xs = jnp.where(row_mask[:, None] > 0, x, 0.0)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        std = jnp.ones(x.shape[1], dtype=x.dtype)
    y1h = jax.nn.one_hot(y.astype(jnp.int32), num_classes, dtype=x.dtype)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)
    d = x.shape[1]

    def unpack(params):
        return params[: d * num_classes].reshape(d, num_classes), params[d * num_classes:]

    def grad(params):
        w, b = unpack(params)
        logits = xs @ w + jnp.where(fit_intercept, b, 0.0)
        p = jax.nn.softmax(logits, axis=-1)
        r = (p - y1h) * row_mask[:, None]
        gw = xs.T @ r / n + l2 * w
        gb = jnp.where(fit_intercept, r.sum(0) / n, jnp.zeros_like(b))
        return jnp.concatenate([gw.reshape(-1), gb])

    def prox(params, step):
        w, b = unpack(params)
        return jnp.concatenate([_soft_threshold(w, step * l1).reshape(-1), b])

    col = (xs * xs).sum(0) / n
    lip = 0.5 * col.sum() + l2
    step = 1.0 / jnp.maximum(lip, 1e-6)
    params0 = jnp.zeros(d * num_classes + num_classes, dtype=x.dtype)
    params = _fista(grad, prox, params0, step, num_iters)
    w_std, b_std = unpack(params)
    w = w_std / std[:, None]
    b = b_std - (w_std * (mean / std)[:, None]).sum(0)
    return GLMParams(weights=w, intercept=b if fit_intercept else jnp.zeros_like(b))


@partial(jax.jit, static_argnames=("num_iters", "fit_intercept", "standardization"))
def fit_linear_svc(
    x: jax.Array,
    y: jax.Array,          # [N] in {0, 1}
    row_mask: jax.Array,
    reg_param: jax.Array,
    num_iters: int = 400,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> GLMParams:
    """Linear SVM via Huberized hinge + L2 (OpLinearSVC parity —
    core/.../classification/OpLinearSVC.scala wraps Spark LinearSVC, which is
    hinge/OWL-QN). The hinge is smoothed on a width-``delta`` band so FISTA
    has a true Lipschitz constant and converges at the accelerated rate; as
    delta -> 0 this recovers the exact hinge objective."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    if standardization:
        xs, mean, std, const = _standardize(x, row_mask)
        if not fit_intercept:
            mean = jnp.zeros(x.shape[1], dtype=x.dtype)
            xs = _scale_only(x, row_mask, std, const)
    else:
        xs = jnp.where(row_mask[:, None] > 0, x, 0.0)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        std = jnp.ones(x.shape[1], dtype=x.dtype)
    s = 2.0 * y - 1.0  # {-1, +1}
    delta = jnp.asarray(0.1, dtype=x.dtype)

    def grad(params):
        w, b = params[:-1], params[-1]
        margin = s * (xs @ w + jnp.where(fit_intercept, b, 0.0))
        # dL/dmargin for Huberized hinge: -1 below the band, linear inside
        slope = -jnp.clip((1.0 - margin) / delta, 0.0, 1.0)
        r = slope * s * row_mask
        gw = (xs * r[:, None]).sum(0) / n + reg_param * w
        gb = jnp.where(fit_intercept, r.sum() / n, 0.0)
        return jnp.concatenate([gw, gb[None]])

    def prox(params, _step):
        return params

    col = (xs * xs).sum(0) / n
    lip = (col.sum() + 1.0) / delta + reg_param
    step = 1.0 / jnp.maximum(lip, 1e-6)
    params0 = jnp.zeros(x.shape[1] + 1, dtype=x.dtype)
    params = _fista(grad, prox, params0, step, num_iters)
    w_std, b_std = params[:-1], params[-1]
    w = w_std / std
    b = b_std - (w_std * mean / std).sum()
    return GLMParams(weights=w, intercept=jnp.where(fit_intercept, b, 0.0))


# GLM family/link codes (static ints so the IRLS graph stays compiled once
# per (family, link) pair — Spark GeneralizedLinearRegression.scala parity)
GLM_FAMILIES = {"gaussian": 0, "binomial": 1, "poisson": 2, "gamma": 3}
GLM_LINKS = {"identity": 0, "log": 1, "logit": 2, "inverse": 3, "sqrt": 4}
GLM_DEFAULT_LINK = {
    "gaussian": "identity", "binomial": "logit", "poisson": "log",
    "gamma": "inverse",
}


@partial(jax.jit, static_argnames=("family", "link", "num_iters", "fit_intercept"))
def fit_glm_irls(
    x: jax.Array,
    y: jax.Array,
    row_mask: jax.Array,
    reg_param: jax.Array,  # L2 only, like Spark GLM
    family: int = 0,
    link: int = 0,
    num_iters: int = 25,
    fit_intercept: bool = True,
) -> GLMParams:
    """Iteratively reweighted least squares for generalized linear models
    (OpGeneralizedLinearRegression parity — Spark GLR's IRLS, maxIter=25).
    One `lax.scan` of normal-equation solves; D is small in tabular AutoML so
    the [D+1, D+1] solve per iteration is cheap on the MXU."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    d = x.shape[1]
    ones = jnp.ones((x.shape[0], 1), dtype=x.dtype)
    xa = jnp.concatenate([x, ones], axis=1) if fit_intercept else x
    da = xa.shape[1]
    eps = jnp.asarray(1e-7, dtype=x.dtype)

    def linkinv(eta):
        return jax.lax.switch(
            link,
            [
                lambda e: e,                       # identity
                lambda e: jnp.exp(e),              # log
                lambda e: jax.nn.sigmoid(e),       # logit
                lambda e: 1.0 / jnp.where(jnp.abs(e) > eps, e, eps),  # inverse
                lambda e: e * e,                   # sqrt
            ],
            eta,
        )

    def dmu_deta(eta, mu):
        return jax.lax.switch(
            link,
            [
                lambda: jnp.ones_like(eta),
                lambda: mu,
                lambda: mu * (1.0 - mu),
                lambda: -mu * mu,
                lambda: 2.0 * jnp.sqrt(jnp.maximum(mu, eps)),
            ],
        )

    def variance(mu):
        return jax.lax.switch(
            family,
            [
                lambda m: jnp.ones_like(m),        # gaussian
                lambda m: m * (1.0 - m),           # binomial
                lambda m: m,                       # poisson
                lambda m: m * m,                   # gamma
            ],
            mu,
        )

    def init_eta():
        # family-aware starting point on the linear scale
        mu0 = jax.lax.switch(
            family,
            [
                lambda: y,
                lambda: (y + 0.5) / 2.0,
                lambda: jnp.maximum(y, 0.0) + 0.1,
                lambda: jnp.maximum(y, eps),
            ],
        )
        return jax.lax.switch(
            link,
            [
                lambda m: m,
                lambda m: jnp.log(jnp.maximum(m, eps)),
                lambda m: jnp.log(jnp.maximum(m, eps) / jnp.maximum(1.0 - m, eps)),
                lambda m: 1.0 / jnp.maximum(m, eps),
                lambda m: jnp.sqrt(jnp.maximum(m, 0.0)),
            ],
            mu0,
        )

    def body(beta, _):
        eta = xa @ beta
        mu = linkinv(eta)
        dmu = dmu_deta(eta, mu)
        dmu = jnp.where(jnp.abs(dmu) > eps, dmu, eps)
        var = jnp.maximum(variance(mu), eps)
        z = eta + (y - mu) / dmu
        w = row_mask * dmu * dmu / var
        xtwx = (xa * w[:, None]).T @ xa / n
        xtwz = (xa * w[:, None]).T @ z / n
        reg = reg_param * jnp.eye(da, dtype=x.dtype)
        if fit_intercept:  # intercept unregularized
            reg = reg.at[da - 1, da - 1].set(0.0)
        beta_next = jnp.linalg.solve(xtwx + reg + eps * jnp.eye(da, dtype=x.dtype), xtwz)
        return beta_next, None

    eta0 = init_eta()
    w0 = row_mask
    xtwx0 = (xa * w0[:, None]).T @ xa / n
    xtwz0 = (xa * w0[:, None]).T @ eta0 / n
    beta0 = jnp.linalg.solve(
        xtwx0 + (reg_param + eps) * jnp.eye(da, dtype=x.dtype), xtwz0
    )
    beta, _ = jax.lax.scan(body, beta0, None, length=num_iters)
    if fit_intercept:
        return GLMParams(weights=beta[:-1], intercept=beta[-1])
    return GLMParams(weights=beta, intercept=jnp.zeros((), dtype=x.dtype))


@partial(jax.jit, static_argnames=("num_iters", "fit_intercept"))
def fit_linear(
    x: jax.Array,
    y: jax.Array,
    row_mask: jax.Array,
    reg_param: jax.Array,
    elastic_net: jax.Array,
    num_iters: int = 200,
    fit_intercept: bool = True,
) -> GLMParams:
    """Linear regression with elastic net (OpLinearRegression parity; Spark
    WLS/normal-equation semantics for alpha=0 via converged FISTA)."""
    row_mask = row_mask.astype(x.dtype)
    n = jnp.maximum(row_mask.sum(), 1.0)
    xs, mean, std, const = _standardize(x, row_mask)
    if not fit_intercept:
        # Spark parity: scale only, never center x OR y — a centered fit
        # bakes an implicit intercept into training that predict never
        # applies (same fix as the logistic/SVC no-intercept paths)
        mean = jnp.zeros(x.shape[1], dtype=x.dtype)
        xs = _scale_only(x, row_mask, std, const)
        ym = jnp.zeros((), dtype=x.dtype)
    else:
        ym = (y * row_mask).sum() / n
    yc = jnp.where(row_mask > 0, y - ym, 0.0)
    l1 = reg_param * elastic_net
    l2 = reg_param * (1.0 - elastic_net)

    def grad(w):
        r = (xs @ w - yc) * row_mask
        return xs.T @ r / n + l2 * w

    def prox(w, step):
        return _soft_threshold(w, step * l1)

    col = (xs * xs).sum(0) / n
    lip = col.sum() + l2
    step = 1.0 / jnp.maximum(lip, 1e-6)
    w0 = jnp.zeros(x.shape[1], dtype=x.dtype)
    w_std = _fista(grad, prox, w0, step, num_iters)
    w = w_std / std
    b = ym - (w_std * mean / std).sum()
    return GLMParams(weights=w, intercept=jnp.where(fit_intercept, b, 0.0))


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py, TPJ0xx)
# --------------------------------------------------------------------------
def program_trace_specs():
    """Representative trace shapes for the banked GLM sweep programs.

    The bucketed axis is the LANE count K (``compiler.bucketing``): the
    default buckets cross the pow2(<=64) / 32-multiple boundary so the
    TPJ005 fingerprint check proves every bucket compiles the same
    program family. Small N/D and tiny iteration counts keep the whole
    trace in milliseconds — jaxpr structure does not depend on them."""
    import jax

    def _glm_args(k: int):
        f32 = "float32"
        return (
            jax.ShapeDtypeStruct((16, 3), f32),   # x
            jax.ShapeDtypeStruct((16,), f32),     # y
            jax.ShapeDtypeStruct((k, 16), f32),   # row_masks
            jax.ShapeDtypeStruct((k,), f32),      # reg_params
            jax.ShapeDtypeStruct((k,), f32),      # elastic_nets
        )

    # donation contract of the lane sweep (mirrored by the sharded twins
    # in parallel/sweep.py): the per-lane hyperparam vectors [K] alias
    # into the output intercept [K] — TPJ003 lowers this donating twin
    # and requires the aliasing to land in the StableHLO
    return [
        dict(
            name="linear_batched",
            fn=fit_linear_batched,
            build=lambda k: (
                _glm_args(k), dict(num_iters=2, fit_intercept=True)
            ),
            buckets=(8, 64, 96),
            bucket_axis="lanes",
            donate_argnums=(3, 4),
            base_fn=getattr(fit_linear_batched, "__wrapped__", None),
            static_argnames=("num_iters", "fit_intercept"),
        ),
        dict(
            name="logistic_binary_batched",
            fn=fit_logistic_binary_batched,
            build=lambda k: (
                _glm_args(k),
                dict(num_iters=2, fit_intercept=True, standardization=True),
            ),
            buckets=(8, 64, 96),
            bucket_axis="lanes",
            donate_argnums=(3, 4),
            base_fn=getattr(
                fit_logistic_binary_batched, "__wrapped__", None
            ),
            static_argnames=(
                "num_iters", "fit_intercept", "standardization"
            ),
        ),
    ]
