"""Model catalog (reference: core/.../stages/impl/{classification,regression})."""
from .base import PredictorEstimator, PredictorModel  # noqa: F401
from .logistic import LogisticRegression  # noqa: F401
from .linear import LinearRegression  # noqa: F401
