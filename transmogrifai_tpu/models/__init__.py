"""Model catalog (reference: core/.../stages/impl/{classification,regression})."""
from .base import PredictorEstimator, PredictorModel  # noqa: F401
from .logistic import LogisticRegression  # noqa: F401
from .linear import LinearRegression  # noqa: F401
from .glm import GeneralizedLinearRegression  # noqa: F401
from .mlp import MLPClassifier  # noqa: F401
from .naive_bayes import NaiveBayes  # noqa: F401
from .svc import LinearSVC  # noqa: F401
from .isotonic import IsotonicRegressionCalibrator  # noqa: F401
from .gbdt import (  # noqa: F401
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    GBTClassifier,
    GBTRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    XGBoostClassifier,
    XGBoostRegressor,
)
