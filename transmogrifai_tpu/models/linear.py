"""Linear regression with elastic net.

Reference: core/.../stages/impl/regression/OpLinearRegression.scala (wraps
Spark LinearRegression / WLS). XLA-native solver in models/solvers.py.
"""
from __future__ import annotations

import numpy as np

from .base import PredictorEstimator, PredictorModel
from .solvers import fit_linear


class LinearRegressionModel(PredictorModel):
    def __init__(self, weights: np.ndarray, intercept: float, uid: str | None = None):
        super().__init__("linreg", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = float(intercept)

    def get_arrays(self):
        return {"weights": self.weights, "intercept": np.float64(self.intercept)}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], float(arrays["intercept"]))

    def predict_arrays(self, x: np.ndarray):
        pred = x @ self.weights + self.intercept
        return pred, None, None


class LinearRegression(PredictorEstimator):
    model_type = "OpLinearRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 100,
        fit_intercept: bool = True,
        uid: str | None = None,
    ):
        super().__init__("linreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "elastic_net_param": self.elastic_net_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
        }

    def fit_arrays(self, x, y, row_mask):
        params = fit_linear(
            x,
            y,
            row_mask,
            float(self.reg_param),
            float(self.elastic_net_param),
            num_iters=max(self.max_iter * 4, 200),
            fit_intercept=self.fit_intercept,
        )
        return LinearRegressionModel(
            np.asarray(params.weights), float(params.intercept)
        )
