"""Linear regression with elastic net.

Reference: core/.../stages/impl/regression/OpLinearRegression.scala (wraps
Spark LinearRegression / WLS). XLA-native solver in models/solvers.py.
"""
from __future__ import annotations

import numpy as np

from .base import PredictorEstimator, PredictorModel
from .solvers import fit_linear


class LinearRegressionModel(PredictorModel):
    def __init__(self, weights: np.ndarray, intercept: float, uid: str | None = None):
        super().__init__("linreg", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = float(intercept)

    def get_arrays(self):
        return {"weights": self.weights, "intercept": np.float64(self.intercept)}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], float(arrays["intercept"]))

    def predict_arrays(self, x: np.ndarray):
        return self.predictions_from_core(x @ self.weights + self.intercept)

    def predictions_from_core(self, core: np.ndarray):
        return np.asarray(core, dtype=np.float64), None, None

    def fused_predict_spec(self):
        from ..compiler.fused import PredictorPlan

        params = {
            "w": np.asarray(self.weights, dtype=np.float32),
            "b": np.float32(self.intercept),
        }

        def core(plane, p):
            return plane @ p["w"] + p["b"]

        return PredictorPlan(
            stage=self, in_dim=int(self.weights.shape[0]), params=params,
            core=core, epilogue=self.predictions_from_core,
            descriptor="linreg",
        )


class LinearRegression(PredictorEstimator):
    model_type = "OpLinearRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 100,
        fit_intercept: bool = True,
        uid: str | None = None,
    ):
        super().__init__("linreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "elastic_net_param": self.elastic_net_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
        }

    def fit_arrays(self, x, y, row_mask):
        params = fit_linear(
            x,
            y,
            row_mask,
            float(self.reg_param),
            float(self.elastic_net_param),
            num_iters=max(self.max_iter * 4, 200),
            fit_intercept=self.fit_intercept,
        )
        return LinearRegressionModel(
            np.asarray(params.weights), float(params.intercept)
        )

    _KNOWN_KEYS = frozenset(
        ("reg_param", "elastic_net_param", "fit_intercept", "max_iter")
    )

    #: GLM lanes pad onto shape buckets and shard over the mesh's model
    #: axis; the pipelined fold schedule (workflow/cv.py) overlaps tree
    #: fits with these dispatches
    lane_family = "glm"

    def sweep_dispatch_masks(self, x, y, masks, grid_points):
        """Dispatch the folds × grid sweep, return a collector closure.

        Same-(fit_intercept, max_iter) groups batch (fold-mask, reg,
        elastic-net) triples onto the fit axis of fit_linear_batched;
        points with unknown params fall back to sequential fits (inside
        the collector). Under an active execution mesh the lanes route
        through the pjit'd SweepLayout path (parallel/fit.py) — explicit
        PartitionSpecs, donated fold buffers; otherwise lane counts pad
        onto shape buckets (compiler.bucketing) so near-miss sweeps share
        one banked executable. Device work is async after dispatch —
        calling the closure materializes the models, so tree-family fits
        can run in between (the pipelined lane schedule in
        workflow/cv.py)."""
        from ..compiler import bucketing, dispatch
        from ..parallel.mesh import execution_mesh
        from ..utils.aot import aot_call
        from .base import group_grid_by_statics
        from .solvers import fit_linear_batched

        masks = [np.asarray(m, dtype=np.float32) for m in masks]
        n_masks = len(masks)
        groups, sequential = group_grid_by_statics(
            grid_points, self._KNOWN_KEYS,
            lambda p: (
                bool(p.get("fit_intercept", self.fit_intercept)),
                int(p.get("max_iter", self.max_iter)),
            ),
        )
        import jax.numpy as jnp

        mesh = execution_mesh()
        stacked_groups: list[tuple[list[int], int, object]] = []
        for (fit_intercept, max_iter), idxs in groups.items():
            pts = [grid_points[i] for i in idxs] * n_masks
            regs = np.asarray(
                [p.get("reg_param", self.reg_param) for p in pts],
                dtype=np.float32,
            )
            ens = np.asarray(
                [p.get("elastic_net_param", self.elastic_net_param)
                 for p in pts],
                dtype=np.float32,
            )
            rm = np.repeat(np.stack(masks), len(idxs), axis=0)  # mask-major
            statics = dict(
                num_iters=max(max_iter * 4, 200),
                fit_intercept=fit_intercept,
            )
            if mesh is not None:
                from ..parallel.fit import sweep_parallel_fit

                k = rm.shape[0]
                stacked = sweep_parallel_fit(
                    fit_linear_batched, "sweep_linear_sharded", mesh,
                    x, y, rm, regs, ens, **statics,
                )
            else:
                k, (rm, regs, ens) = bucketing.bucket_sweep_lanes(
                    rm, regs, ens
                )
                fit_fn = dispatch.donating(
                    "linear_batched", fit_linear_batched,
                    donate_argnums=(3, 4),
                    static_argnames=("num_iters", "fit_intercept"),
                )
                stacked = aot_call(
                    "linear_batched", fit_fn,
                    (
                        dispatch.device_f32(x),
                        jnp.asarray(y, dtype=jnp.float32),
                        jnp.asarray(rm), jnp.asarray(regs),
                        jnp.asarray(ens),
                    ),
                    statics,
                )
            stacked_groups.append((idxs, k, stacked))

        def collect() -> list[list]:
            models: list[list] = [
                [None] * len(grid_points) for _ in masks
            ]
            for idxs, k, stacked in stacked_groups:
                w = np.asarray(stacked.weights)[:k]
                b = np.asarray(stacked.intercept)[:k]
                for mi in range(n_masks):
                    for j, i in enumerate(idxs):
                        models[mi][i] = LinearRegressionModel(
                            w[mi * len(idxs) + j], b[mi * len(idxs) + j]
                        )
            for i in sequential:
                est = self.with_params(**grid_points[i])
                for mi, m in enumerate(masks):
                    models[mi][i] = est.fit_arrays(x, y, m)
            return models

        return collect

    def fit_arrays_batched_masks(self, x, y, masks, grid_points):
        """Folds x grid in as few programs as the grid's static params
        allow (validators._sweep_family hook) — dispatch + immediate
        collect of :meth:`sweep_dispatch_masks`."""
        return self.sweep_dispatch_masks(x, y, masks, grid_points)()

    def fit_arrays_batched(self, x, y, row_mask, grid_points):
        """One mask, many grid points (workflow/cv.py's per-fold hook —
        linear previously fit sequentially there)."""
        return self.fit_arrays_batched_masks(
            x, y, [row_mask], grid_points
        )[0]
