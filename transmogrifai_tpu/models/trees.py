"""Histogram-based decision-tree machinery — the XLA-native replacement for
libxgboost (JNI/C++) and Spark MLlib's JVM tree ensembles (SURVEY.md §2.5
item 1, the largest native-parity item).

Design (TPU-first, static shapes throughout — SURVEY.md §7 hard-part 1):
  * features are quantile-binned once into int32 codes [N, F] (host-side
    thresholds, in-graph binning);
  * a tree grows LEVEL-WISE to a fixed ``max_depth``: level d has exactly
    2^d node slots; nodes that stop splitting carry split_feat = -1 and
    route every row left, so shapes never depend on data;
  * per-level histograms hist[node, feature, bin] of (grad, hess) are ONE
    scatter-add over flattened keys — the XLA analog of XGBoost's C++
    histogram build, and the reduction is a psum when rows are sharded
    over the mesh 'data' axis;
  * split gain is the XGBoost second-order formula
    0.5*(GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)) − γ, with
    min_child_weight / min_info_gain masks; with h ≡ 1 and λ=0 this is
    exactly CART variance reduction, so the same learner serves
    RandomForest/GBT (Spark semantics) and XGBoost;
  * whole forests train under ``vmap`` over bootstrap/feature masks; boosting
    runs as ``lax.scan`` over rounds.

Leaf values are -G/(H+λ) (Newton step). For plain mean-target trees (random
forest leaves) pass g = -target, h = 1: the leaf value becomes mean(target).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Tree(NamedTuple):
    """Dense perfect-binary-tree arrays. Level d uses slots [0, 2^d)."""

    split_feat: jax.Array  # [depth, 2^depth] int32, -1 = leaf (route left)
    split_bin: jax.Array   # [depth, 2^depth] int32, go right when bin > split_bin
    leaf_value: jax.Array  # [2^depth] float32


def quantile_thresholds(x: np.ndarray, max_bins: int = 32) -> np.ndarray:
    """Per-feature quantile bin edges [F, max_bins-1] (XGBoost 'hist' sketch
    equivalent; computed host-side once per dataset)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    thr = np.nanquantile(np.asarray(x, dtype=np.float64), qs, axis=0).T
    # make strictly non-decreasing; duplicate edges simply yield empty bins
    return np.ascontiguousarray(thr, dtype=np.float32)


def bin_data(x: jax.Array, thresholds: jax.Array) -> jax.Array:
    """int32 bin codes [N, F]: number of thresholds strictly below x."""
    return (x[:, :, None] > thresholds[None, :, :]).sum(axis=2).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=("max_depth", "num_bins", "hist_impl", "parallel_fits"),
)
def grow_tree(
    binned: jax.Array,     # [N, F] int32 codes in [0, num_bins)
    grad: jax.Array,       # [N] float32
    hess: jax.Array,       # [N] float32
    row_mask: jax.Array,   # [N] float32
    feat_mask: jax.Array,  # [F] float32 (0 disables a feature — RF colsample)
    max_depth: int,
    num_bins: int,
    reg_lambda: float | jax.Array = 1.0,
    gamma: float | jax.Array = 0.0,
    min_child_weight: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    hist_impl: str | None = None,
    parallel_fits: int = 1,
) -> Tree:
    from .hist_pallas import (
        build_histogram_pallas,
        build_histogram_scatter,
        default_impl,
    )

    n, f = binned.shape
    b = num_bins
    max_nodes = 1 << max_depth
    g = grad * row_mask
    h = hess * row_mask
    impl = hist_impl or default_impl()
    if parallel_fits > 1 and impl == "pallas":
        # vmapping the Mosaic custom call over batched grid fits crashes the
        # TPU worker (kernel fault); batched sweeps take the scatter path
        impl = "scatter"

    # ---- node chunking: bound per-level histogram memory (the Spark
    # maxMemoryInMB node-group equivalent). One shared fixed-size level body
    # runs under lax.fori_loop (unrolling per-level sizes was measured
    # SLOWER on TPU — less fusion, more distinct program regions). Forests
    # lax.map trees sequentially, so ONE tree owns the budget — but batched
    # grid fits vmap `parallel_fits` whole fits concurrently, so the caller
    # must declare that factor and the per-fit budget shrinks accordingly.
    budget_elems = max((1 << 25) // max(parallel_fits, 1), 1 << 20)
    chunk_cap = max(1, budget_elems // max(f * b, 1))
    while chunk_cap & (chunk_cap - 1):  # round down to a power of two
        chunk_cap &= chunk_cap - 1
    chunk_cap = min(chunk_cap, max_nodes)
    if impl == "pallas":
        # Mosaic keeps the kernel's full [f_pad, M, b_pad]×2 output resident
        # in scoped VMEM (plus the [row_tile, M] node one-hot), so M must
        # scale inversely with the feature count to stay under ~16 MB;
        # outputs are double-buffered: 2 bufs × 2 outs × f_pad·M·b_pad·4B
        f_pad = (f + 7) // 8 * 8
        b_pad = (b + 127) // 128 * 128  # kernel pads bins to lane width
        m_cap = max(8, (1 << 19) // (f_pad * b_pad))
        while m_cap & (m_cap - 1):
            m_cap &= m_cap - 1
        chunk_cap = min(chunk_cap, m_cap)

    def chunk_stats(node, c0, chunk_nodes):
        """Best (gain, feat, bin) for node slots [c0, c0 + chunk_nodes)."""
        active = (node >= c0) & (node < c0 + chunk_nodes)
        local = jnp.where(active, node - c0, -1)  # -1 = dead for this chunk
        if impl == "pallas":
            # MXU one-hot kernel (hist_pallas.py) — dead rows carry node -1
            hist = build_histogram_pallas(binned, local, g, h, chunk_nodes, b)
        else:
            hist = build_histogram_scatter(binned, local, g, h, chunk_nodes, b)
        hg, hh = hist[..., 0], hist[..., 1]

        gl = jnp.cumsum(hg, axis=2)[:, :, :-1]  # left = bins <= t
        hl = jnp.cumsum(hh, axis=2)[:, :, :-1]
        gt = hg.sum(axis=2, keepdims=True)
        ht = hh.sum(axis=2, keepdims=True)
        gr = gt - gl
        hr = ht - hl
        parent = (gt**2) / (ht + reg_lambda)
        gain = 0.5 * (
            gl**2 / (hl + reg_lambda) + gr**2 / (hr + reg_lambda) - parent
        ) - gamma
        valid = (
            (hl >= min_child_weight)
            & (hr >= min_child_weight)
            & (feat_mask[None, :, None] > 0)
        )
        gain = jnp.where(valid, gain, -jnp.inf)

        flat_gain = gain.reshape(chunk_nodes, -1)
        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
        best_feat = (best // (b - 1)).astype(jnp.int32)
        best_bin = (best % (b - 1)).astype(jnp.int32)
        do_split = best_gain > jnp.maximum(min_info_gain, 0.0)
        return (
            jnp.where(do_split, best_feat, -1),
            jnp.where(do_split, best_bin, 0),
        )

    chunk_nodes = chunk_cap
    num_chunks = max_nodes // chunk_nodes

    def level(d, carry):
        # one compiled level body reused for every depth (lax.fori_loop);
        # chunks wholly beyond the level's live node range are skipped
        node, feats, bins = carry
        n_nodes = jnp.left_shift(jnp.int32(1), d)

        def chunk_body(ci, fb):
            feats_d, bins_d = fb
            c0 = ci * chunk_nodes

            def run(_):
                cf, cb = chunk_stats(node, c0, chunk_nodes)
                return (
                    jax.lax.dynamic_update_slice(feats_d, cf, (c0,)),
                    jax.lax.dynamic_update_slice(bins_d, cb, (c0,)),
                )

            return jax.lax.cond(c0 < n_nodes, run, lambda _: (feats_d, bins_d), None)

        feats_d0 = jnp.full(max_nodes, -1, dtype=jnp.int32)
        bins_d0 = jnp.zeros(max_nodes, dtype=jnp.int32)
        feats_d, bins_d = jax.lax.fori_loop(
            0, num_chunks, chunk_body, (feats_d0, bins_d0)
        )
        feats = feats.at[d].set(feats_d)
        bins = bins.at[d].set(bins_d)

        # ---- route rows to children
        row_feat = feats_d[node]             # [N]
        row_thr = bins_d[node]
        code = jnp.take_along_axis(
            binned, jnp.maximum(row_feat, 0)[:, None], axis=1
        )[:, 0]
        go_right = (row_feat >= 0) & (code > row_thr)
        node = node * 2 + go_right.astype(jnp.int32)
        return node, feats, bins

    node0 = jnp.zeros(n, dtype=jnp.int32)
    feats0 = jnp.full((max_depth, max_nodes), -1, dtype=jnp.int32)
    bins0 = jnp.zeros((max_depth, max_nodes), dtype=jnp.int32)
    node, feats, bins = jax.lax.fori_loop(
        0, max_depth, level, (node0, feats0, bins0)
    )

    # ---- leaf values: Newton step -G/(H+λ) per final node
    leaf_g = jnp.zeros(max_nodes, dtype=jnp.float32).at[node].add(g)
    leaf_h = jnp.zeros(max_nodes, dtype=jnp.float32).at[node].add(h)
    leaf_value = -leaf_g / (leaf_h + reg_lambda)
    return Tree(split_feat=feats, split_bin=bins, leaf_value=leaf_value)


def predict_tree(binned: jax.Array, tree: Tree) -> jax.Array:
    """Leaf value per row — a static unrolled depth loop of gathers."""
    n = binned.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    depth = tree.split_feat.shape[0]
    for d in range(depth):
        feat = tree.split_feat[d][node]
        thr = tree.split_bin[d][node]
        code = jnp.take_along_axis(
            binned, jnp.maximum(feat, 0)[:, None], axis=1
        )[:, 0]
        go_right = (feat >= 0) & (code > thr)
        node = node * 2 + go_right.astype(jnp.int32)
    return tree.leaf_value[node]


# --------------------------------------------------------------------------
# forests (bagged, vmapped) and boosting (scanned)
# --------------------------------------------------------------------------
@partial(
    jax.jit,
    static_argnames=("max_depth", "num_bins", "num_trees", "bootstrap", "parallel_fits"),
)
def fit_forest(
    binned: jax.Array,
    target: jax.Array,      # [N] regression target (or one-vs-rest indicator)
    row_mask: jax.Array,    # [N]
    num_trees: int,
    max_depth: int,
    num_bins: int,
    subsample_rate: float | jax.Array = 1.0,
    colsample_rate: float | jax.Array = 1.0,
    min_instances: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    seed: int | jax.Array = 42,
    bootstrap: bool = True,
    parallel_fits: int = 1,
) -> Tree:
    """Random forest of mean-target trees: bootstrap row weights + feature
    subsampling, all trees trained in one vmap (Spark RandomForest parity:
    variance impurity == gain formula with h=1, λ=0)."""
    n, f = binned.shape
    key = jax.random.PRNGKey(seed)
    tkeys = jax.random.split(key, num_trees)

    def one_tree(tkey):
        k1, k2 = jax.random.split(tkey)
        if bootstrap:
            # bootstrap: Poisson(rate) counts ≈ sampling with replacement
            counts = jax.random.poisson(k1, subsample_rate, (n,)).astype(jnp.float32)
        else:
            counts = jnp.ones(n, dtype=jnp.float32)
        rmask = row_mask * counts
        fmask = (
            jax.random.uniform(k2, (f,)) < colsample_rate
        ).astype(jnp.float32)
        # ensure at least one feature stays on
        fmask = jnp.where(fmask.sum() == 0, jnp.ones(f), fmask)
        return grow_tree(
            binned,
            -target,  # g = -target, h = 1 -> leaf = mean(target)
            jnp.ones(n, dtype=jnp.float32),
            rmask,
            fmask,
            max_depth=max_depth,
            num_bins=num_bins,
            reg_lambda=0.0,
            gamma=0.0,
            min_child_weight=min_instances,
            min_info_gain=min_info_gain,
            parallel_fits=parallel_fits,
        )

    # sequential lax.map keeps peak memory at ONE tree's histograms (a deep
    # forest vmap would multiply the [max_nodes, F, B] buffers by num_trees);
    # each tree's histogram build already saturates the chip.
    return jax.lax.map(one_tree, tkeys)  # stacked Tree arrays [T, ...]


def predict_forest(binned: jax.Array, trees: Tree) -> jax.Array:
    """Mean leaf value across the stacked forest -> [N]."""
    preds = jax.vmap(lambda t: predict_tree(binned, t))(trees)  # [T, N]
    return preds.mean(axis=0)


@partial(
    jax.jit,
    static_argnames=("max_depth", "num_bins", "num_rounds", "objective", "parallel_fits"),
)
def fit_boosted(
    binned: jax.Array,
    y: jax.Array,          # [N] labels (0/1 binary, float regression)
    row_mask: jax.Array,
    num_rounds: int,
    max_depth: int,
    num_bins: int,
    eta: float | jax.Array = 0.3,
    reg_lambda: float | jax.Array = 1.0,
    gamma: float | jax.Array = 0.0,
    min_child_weight: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    base_score: float | jax.Array = 0.0,
    objective: str = "binary:logistic",
    parallel_fits: int = 1,
) -> tuple[Tree, jax.Array]:
    """Gradient boosting (XGBoost/Spark-GBT parity): lax.scan over rounds,
    second-order gradients, shrinkage eta. Returns stacked trees [R, ...]
    and the training margin."""
    n, f = binned.shape
    feat_mask = jnp.ones(f, dtype=jnp.float32)

    def grads(margin):
        if objective == "binary:logistic":
            p = jax.nn.sigmoid(margin)
            return p - y, p * (1.0 - p)
        # reg:squarederror
        return margin - y, jnp.ones_like(margin)

    def round_step(margin, _):
        g, h = grads(margin)
        tree = grow_tree(
            binned, g, h, row_mask, feat_mask,
            max_depth=max_depth, num_bins=num_bins,
            reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, min_info_gain=min_info_gain,
            parallel_fits=parallel_fits,
        )
        margin = margin + eta * predict_tree(binned, tree)
        return margin, tree

    margin0 = jnp.full(n, base_score, dtype=jnp.float32)
    margin, trees = jax.lax.scan(round_step, margin0, None, length=num_rounds)
    return trees, margin


def predict_boosted(
    binned: jax.Array,
    trees: Tree,
    eta: float,
    base_score: float = 0.0,
) -> jax.Array:
    preds = jax.vmap(lambda t: predict_tree(binned, t))(trees)  # [R, N]
    return base_score + eta * preds.sum(axis=0)
