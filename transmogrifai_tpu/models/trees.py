"""Histogram-based decision-tree machinery — the XLA-native replacement for
libxgboost (JNI/C++) and Spark MLlib's JVM tree ensembles (SURVEY.md §2.5
item 1, the largest native-parity item).

Design (TPU-first, static shapes throughout — SURVEY.md §7 hard-part 1):
  * features are quantile-binned once into int32 codes [N, F] (host-side
    thresholds, in-graph binning);
  * a tree grows LEVEL-WISE to a fixed ``max_depth``: level d has exactly
    2^d node slots; nodes that stop splitting carry split_feat = -1 and
    route every row left, so shapes never depend on data;
  * per-level histograms hist[node, feature, bin] of (grad, hess) are ONE
    scatter-add over flattened keys — the XLA analog of XGBoost's C++
    histogram build, and the reduction is a psum when rows are sharded
    over the mesh 'data' axis;
  * split gain is the XGBoost second-order formula
    0.5*(GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)) − γ, with
    min_child_weight / min_info_gain masks; with h ≡ 1 and λ=0 this is
    exactly CART variance reduction, so the same learner serves
    RandomForest/GBT (Spark semantics) and XGBoost;
  * whole forests train under ``vmap`` over bootstrap/feature masks; boosting
    runs as ``lax.scan`` over rounds.

Leaf values are -G/(H+λ) (Newton step). For plain mean-target trees (random
forest leaves) pass g = -target, h = 1: the leaf value becomes mean(target).
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Tree(NamedTuple):
    """Dense perfect-binary-tree arrays. Level d uses slots [0, 2^d)."""

    split_feat: jax.Array  # [depth, 2^depth] int32, -1 = leaf (route left)
    split_bin: jax.Array   # [depth, 2^depth] int32, go right when bin > split_bin
    leaf_value: jax.Array  # [2^depth] float32


def quantile_thresholds(x: np.ndarray, max_bins: int = 32) -> np.ndarray:
    """Per-feature quantile bin edges [F, max_bins-1] (XGBoost 'hist' sketch
    equivalent; computed host-side once per dataset). NaN-free input takes
    the plain-quantile path (np.nanquantile walks a per-column masked slow
    path — ~45× slower on a 891×957 matrix)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    xd = np.asarray(x, dtype=np.float64)
    qf = np.quantile if not np.isnan(xd).any() else np.nanquantile
    thr = qf(xd, qs, axis=0).T
    # make strictly non-decreasing; duplicate edges simply yield empty bins
    return np.ascontiguousarray(thr, dtype=np.float32)


def bin_data(x: jax.Array, thresholds: jax.Array) -> jax.Array:
    """int32 bin codes [N, F]: number of thresholds strictly below x.

    Accumulated one threshold column at a time: the broadcast form
    materializes an [N, F, B-1] temporary — 15.5 GB at 1M×500×32, the OOM
    cliff for wide scale runs — while the scan keeps peak memory at one
    [N, F] int32."""
    def step(acc, thr_col):  # thr_col [F]
        return acc + (x > thr_col[None, :]).astype(jnp.int32), None

    acc0 = jnp.zeros(x.shape, dtype=jnp.int32)
    codes, _ = jax.lax.scan(step, acc0, jnp.swapaxes(thresholds, 0, 1))
    return codes


# --------------------------------------------------------------------------
# small-table primitives — TPU scatters serialize per index and per-element
# gathers from small tables lower to slow dynamic-gathers; the one-hot
# compare/select forms are plain VPU reductions that XLA fuses (measured at
# [1M] rows, 64-entry tables, in-program: gather 3.3 ms vs 2.4; per-row
# feature select 14 ms vs ~2; occupancy scatter 10.2 ms vs 2.3).
# --------------------------------------------------------------------------
_ONEHOT_MAX_WIDTH = 512
# beyond the always-on width, the fused compare/select form is still the
# winner as long as the TOTAL lane-op count (index count × table width)
# stays around a millisecond of VPU time — deep AutoML trees at sub-4k row
# counts sit far under this (24 lanes × 891 rows × 4096 node ids ≈ 87M),
# while the 1M-row scale paths fall back to scatter/gather exactly as
# before (measured: the flagship depth-12 RF program 2.05 → 1.72 s and the
# 200-round XGB sweep 1.64 → 1.12 s from this alone).
_ONEHOT_OPS_BUDGET = 1 << 28


def _use_onehot(n_idx: int, width: int) -> bool:
    return width <= _ONEHOT_MAX_WIDTH or n_idx * width <= _ONEHOT_OPS_BUDGET


def _small_table_lookup(table: jax.Array, idx: jax.Array) -> jax.Array:
    """out[k, r] = table[k, idx[k, r]] — one-hot select for small tables,
    take_along_axis beyond the fused-form ops budget. idx must be in
    [0, M)."""
    m = table.shape[-1]
    if not _use_onehot(idx.size, m):
        return jnp.take_along_axis(table, idx, axis=-1)
    iot = jnp.arange(m, dtype=jnp.int32)
    zero = jnp.zeros((), dtype=table.dtype)
    return jnp.where(
        idx[..., None] == iot, table[..., None, :], zero
    ).sum(-1)


def _row_feature_select(binned: jax.Array, feat: jax.Array) -> jax.Array:
    """code[..., r] = binned[r, max(feat[..., r], 0)] — the per-row
    feature gather of tree routing, as a one-hot select over the feature
    axis (one fused pass over binned)."""
    f = binned.shape[1]
    if not _use_onehot(feat.size, f):
        def one(rf):
            return jnp.take_along_axis(
                binned, jnp.maximum(rf, 0)[:, None], axis=1
            )[:, 0]

        return one(feat) if feat.ndim == 1 else jax.vmap(one)(feat)
    iot = jnp.arange(f, dtype=jnp.int32)
    sel = jnp.maximum(feat, 0)[..., None] == iot
    return jnp.where(sel, binned, 0).sum(-1)


def _occupancy(idx: jax.Array, size: int) -> jax.Array:
    """count of idx == m per m in [0, size) for idx [K, N] (out-of-range
    ids drop out) — compare-reduce while fused-form ops fit the budget,
    scatter-add beyond."""
    if not _use_onehot(idx.size, size):
        return jax.vmap(
            lambda nd: jnp.zeros(size + 1, jnp.int32).at[
                jnp.minimum(nd, size)
            ].add(1)
        )(idx)[:, :size]
    iot = jnp.arange(size, dtype=jnp.int32)
    return (idx[..., None] == iot).astype(jnp.int32).sum(axis=-2)


def _segment_sum_small(values: jax.Array, idx: jax.Array, size: int) -> jax.Array:
    """out[k, m] = Σ_r values[k, r]·1[idx[k, r] == m] — one fused
    compare/select reduction for small segment counts."""
    if not _use_onehot(idx.size, size):
        return jax.vmap(
            lambda nd, v: jnp.zeros(size + 1, values.dtype).at[
                jnp.minimum(nd, size)
            ].add(v)
        )(idx, values)[:, :size]
    iot = jnp.arange(size, dtype=jnp.int32)
    return jnp.where(
        idx[..., None] == iot, values[..., None], 0.0
    ).sum(axis=-2)


def grow_tree(
    binned: jax.Array,     # [N, F] int32 codes in [0, num_bins)
    grad: jax.Array,       # [N] float32
    hess: jax.Array,       # [N] float32
    row_mask: jax.Array,   # [N] float32
    feat_mask: jax.Array,  # [F] float32 (0 disables a feature — RF colsample)
    max_depth: int,
    num_bins: int,
    reg_lambda: float | jax.Array = 1.0,
    gamma: float | jax.Array = 0.0,
    min_child_weight: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    hist_impl: str | None = None,
    parallel_fits: int = 1,  # kept for API compat; K now rides the kernel grid
    feature_groups=None,
) -> Tree:
    """Single-fit tree growth — the K=1 case of grow_tree_batched."""
    tree = grow_tree_batched(
        binned, grad[None, :], hess[None, :], row_mask[None, :],
        feat_mask[None, :],
        max_depth=max_depth, num_bins=num_bins,
        reg_lambda=reg_lambda, gamma=gamma,
        min_child_weight=min_child_weight, min_info_gain=min_info_gain,
        hist_impl=hist_impl, feature_groups=feature_groups,
    )
    return jax.tree.map(lambda a: a[0], tree)


@partial(
    jax.jit,
    static_argnames=("max_depth", "num_bins", "hist_impl", "lowp"),
)
def grow_tree_batched(
    binned: jax.Array,     # [N, F] int32 codes, SHARED across fits
    grad: jax.Array,       # [K, N] float32
    hess: jax.Array,       # [K, N] float32
    row_mask: jax.Array,   # [K, N] float32
    feat_mask: jax.Array,  # [K, F] float32
    max_depth: int,
    num_bins: int,
    reg_lambda: jax.Array | float = 1.0,       # scalar or [K]
    gamma: jax.Array | float = 0.0,
    min_child_weight: jax.Array | float = 1.0,
    min_info_gain: jax.Array | float = 0.0,
    hist_impl: str | None = None,
    lowp: bool = False,
    feature_groups=None,
) -> Tree:
    """Grow K trees at once — one per batched fit (hyperparameter grid point
    × CV fold). The fit axis is a kernel GRID dimension of the histogram
    build (hist_pallas.build_histogram_pallas_batched), NOT a vmap over the
    custom call (which crashes this TPU runtime), so the entire candidate
    sweep's tree growth runs as one compiled program. Returned Tree arrays
    carry a leading K axis."""
    return _grow_tree_impl(
        binned, grad, hess, row_mask, feat_mask,
        max_depth=max_depth, num_bins=num_bins,
        reg_lambda=reg_lambda, gamma=gamma,
        min_child_weight=min_child_weight, min_info_gain=min_info_gain,
        hist_impl=hist_impl, lowp=lowp, feature_groups=feature_groups,
    )[0]


def _grow_tree_impl(
    binned: jax.Array,     # [N_local, F] int32 codes, SHARED across fits
    grad: jax.Array,       # [K, N_local] float32
    hess: jax.Array,       # [K, N_local] float32
    row_mask: jax.Array,   # [K, N_local] float32
    feat_mask: jax.Array,  # [K, F] float32
    max_depth: int,
    num_bins: int,
    reg_lambda: jax.Array | float = 1.0,
    gamma: jax.Array | float = 0.0,
    min_child_weight: jax.Array | float = 1.0,
    min_info_gain: jax.Array | float = 0.0,
    hist_impl: str | None = None,
    lowp: bool = False,
    axis_name: str | None = None,
    axis_size: int = 1,
    feature_groups: tuple[jax.Array, jax.Array] | None = None,
    max_depth_v: jax.Array | None = None,
) -> Tree:
    """Tree-growth body shared by the single-device jit wrapper and the
    shard_map'd path. ``max_depth_v`` ([K] int32, optional) caps each
    LANE's depth at runtime: levels >= a lane's cap emit no splits, so one
    compiled program at the grid's max depth serves every depth point of a
    hyperparameter sweep (3 RF depth groups -> one program: acquisition,
    not execution, is the flagship's wall-clock). With ``axis_name`` set, the function runs per-shard
    inside shard_map: rows are the LOCAL shard, each level's histogram is
    psum'd over the mesh axis before the split search, node compaction uses
    a psum'd global occupancy mask, and leaf sums are psum'd — the direct
    ICI replacement for XGBoost's Rabit allreduce of per-worker histograms
    (reference OpXGBoostClassifier.scala:101, SURVEY §2.6 row 5). Split
    decisions consume the same reduced histogram either way, so sharded and
    single-device growth produce the same tree.

    ``feature_groups`` = (narrow_idx, wide_idx): original-feature index
    arrays partitioning the columns into ≤2-bin features (one-hot /
    indicator columns — the vast majority of a transmogrified matrix) and
    genuinely multi-bin ones. Split-search cost scales with features×bins,
    so searching 900 binary columns at num_bins=32 wastes ~16× the bin-axis
    work; the narrow group runs the same kernels at b=2 instead. Per-feature
    gains are bin-cumsum along each feature's own row, so grouped growth
    finds the SAME splits as ungrouped (tie-break by original feature id
    preserved across the group merge)."""
    from .hist_pallas import (
        FUSED_SPLIT_MAX_ROWS,
        build_best_split_pallas,
        build_histogram_pallas_batched,
        build_histogram_pallas_binloop,
        build_histogram_scatter_batched,
        default_impl,
    )

    k_fits, n = grad.shape
    f = binned.shape[1]
    b = num_bins
    max_nodes = 1 << max_depth
    g = grad * row_mask
    h = hess * row_mask
    impl = hist_impl or default_impl()

    if feature_groups is not None:
        narrow_idx, wide_idx = feature_groups
        # (binned columns, per-fit feature mask, bin count, orig ids).
        # Narrow features hold exactly two values {0, t} in code space
        # (duplicate quantile edges put the '1' value at code t = #zeros);
        # recoding (code > 0) compresses them to b=2 while the stored split
        # bin 0 routes identically in ORIGINAL code space (code > 0 ⇔
        # value is the upper one) — predict needs no remapping. Index
        # arrays may be traced (per-tree colsample subsets); shapes are
        # static, values aren't. Empty groups simply drop out.
        groups = []
        if narrow_idx.shape[0]:
            groups.append(
                (
                    (binned[:, narrow_idx] > 0).astype(jnp.int32),
                    feat_mask[:, narrow_idx], 2, narrow_idx,
                )
            )
        if wide_idx.shape[0]:
            groups.append(
                (binned[:, wide_idx], feat_mask[:, wide_idx], b, wide_idx)
            )
        if not groups:
            groups = [(binned, feat_mask, b, None)]
    else:
        groups = [(binned, feat_mask, b, None)]

    def vec(v):
        arr = jnp.asarray(v, dtype=jnp.float32).reshape(-1)
        return arr  # shape (1,) broadcasts over K; shape (K,) is per-fit

    lam = vec(reg_lambda)[:, None, None, None]
    gam = vec(gamma)[:, None, None, None]
    mcw = vec(min_child_weight)[:, None, None, None]
    mig = vec(min_info_gain)[:, None]

    # ---- node compaction: at any level at most min(2^depth, N) node slots
    # are LIVE (every live slot holds ≥1 row), so histograms are built over
    # a compact slot space of ``cap`` ids instead of the full 2^d range —
    # depth-12 growth on 1k rows costs the same as depth-10 (the dominant
    # win for the deep ends of the reference's maxDepth {3,6,12} grids).
    # When sharded, the live bound is the GLOBAL row count.
    n_global = n * axis_size
    cap = max_nodes
    if cap > n_global:
        cap = 1
        while cap < n_global:
            cap <<= 1
        cap = min(cap, max_nodes)

    # histogram impl policy: "pallas" is AUTO — at AutoML-tabular row counts
    # (≤4k) the one-hot GEMM histogram beats the kernels outright (per-level
    # work is two MXU matmuls that fuse into the program; the pallas grid
    # and the fused-split kernel carry per-pass costs that dominate at
    # small N), while large N keeps the Mosaic kernels. "gemm"/"scatter"
    # force their paths. The GEMM path also serves the sharded body: it is
    # plain jnp, and the psum below reduces its per-shard histograms.
    use_gemm = (impl == "gemm") or (impl == "pallas" and n <= 4096)

    # one-hot bin codes are loop-invariant across the level scan (and the
    # tree scan above it) — precompute ONCE per group so the GEMM
    # histogram's per-level work is the node one-hot + two einsums. XLA's
    # loop-invariant code motion is not reliable through scan+cond+fori
    # nesting, and the [N, Fg·Bg] temporary is small at GEMM row counts.
    if use_gemm:
        dt1h = jnp.bfloat16 if lowp else jnp.float32
        groups = [
            (gb_, gm, bb, gi,
             jax.nn.one_hot(gb_, bb, dtype=dt1h).reshape(gb_.shape[0], -1))
            for gb_, gm, bb, gi in groups
        ]
    else:
        groups = [(gb_, gm, bb, gi, None) for gb_, gm, bb, gi in groups]

    # fused split search: gains + arg-best computed inside the kernel while
    # histograms are VMEM-resident — nothing [M, F, B]-sized touches HBM.
    # Only possible when every row fits one VMEM tile and the bins fit the
    # kernel's 128-lane packing. The sharded path needs the raw histogram
    # for the cross-shard psum, so it always takes the two-step path.
    use_fused = (
        not use_gemm
        and impl == "pallas"
        and axis_name is None
        and n <= FUSED_SPLIT_MAX_ROWS
        and b <= 128
    )

    # per-chunk histogram memory scales with K — shrink the node chunk so
    # [K, chunk, F, B, 2] stays inside the HBM budget (the Spark
    # maxMemoryInMB node-group equivalent). With feature groups the total
    # histogram width is Σ_g f_g·b_g, and VMEM kernel caps take the min
    # over groups.
    hist_width = sum(gb.shape[1] * bb for gb, _, bb, _, _ in groups)
    budget_elems = max((1 << 25) // k_fits, 1 << 20)
    chunk_cap = max(1, budget_elems // max(hist_width, 1))
    while chunk_cap & (chunk_cap - 1):
        chunk_cap &= chunk_cap - 1
    chunk_cap = min(chunk_cap, cap)
    if use_fused:
        # the [T, M] one-hot temporaries are the only big VMEM tenants;
        # M=512 at T=896 was measured to overflow scoped VMEM on v5e —
        # 256 is the validated ceiling
        n_pad = (n + 127) // 128 * 128
        m_cap = max(8, min(256, (1 << 18) // max(n_pad, 128)))
        while m_cap & (m_cap - 1):
            m_cap &= m_cap - 1
        chunk_cap = min(cap, m_cap)
    elif use_gemm:
        # the [K, N, M] weighted node-one-hot temporaries bound the chunk;
        # the 128 ceiling keeps deep levels multi-chunk so the occupancy
        # skip can drop the (mostly dead) tail of the slot range instead of
        # paying one [K·cap, N] GEMM per level
        import os as _os

        _ceil = int(_os.environ.get("TPTPU_GEMM_MCAP", "128"))
        m_cap = max(8, min(_ceil, (1 << 24) // max(k_fits * n, 1)))
        while m_cap & (m_cap - 1):
            m_cap &= m_cap - 1
        chunk_cap = min(chunk_cap, m_cap)
    elif impl == "pallas":
        # VMEM per grid step: the [FEAT_TILE, M, b_pad]×2 output block (the
        # feature axis is gridded — f does not multiply in) plus the [T, M]
        # one-hot temporaries (the kernel shrinks its row tile as M grows)
        b_pad = (b + 127) // 128 * 128
        m_cap = max(8, min(256, (1 << 19) // (8 * b_pad)))
        while m_cap & (m_cap - 1):
            m_cap &= m_cap - 1
        chunk_cap = min(chunk_cap, m_cap)

    lam_k = jnp.broadcast_to(vec(reg_lambda), (k_fits,))
    gam_k = jnp.broadcast_to(vec(gamma), (k_fits,))
    mcw_k = jnp.broadcast_to(vec(min_child_weight), (k_fits,))

    def build_histogram_gemm(gbinned, loc, chunk_nodes, gb, codes1h):
        """[K, M, Fg, Bg, 2] histogram as TWO one-hot GEMMs — the MXU-native
        formulation for small row counts. The pallas kernel's grid economics
        only win at large N; at AutoML-tabular sizes (≤4k rows) the whole
        per-level histogram is a [K·M, N] @ [N, Fg·Bg] matmul pair that XLA
        fuses into the surrounding program (measured: the depth-12 RF group
        fell from ~25 s of kernel passes to GEMM noise). ``codes1h``
        [N, Fg·Bg] is precomputed outside the level scan (loop-invariant)."""
        fg = gbinned.shape[1]
        dt = jnp.bfloat16 if lowp else jnp.float32
        node1h = jax.nn.one_hot(loc, chunk_nodes, dtype=jnp.float32)  # [K,N,M]
        gw = (node1h * g[:, :, None]).astype(dt)
        hw = (node1h * h[:, :, None]).astype(dt)
        hg = jnp.einsum(
            "knm,nw->kmw", gw, codes1h, preferred_element_type=jnp.float32
        )
        hh = jnp.einsum(
            "knm,nw->kmw", hw, codes1h, preferred_element_type=jnp.float32
        )
        return jnp.stack([hg, hh], axis=-1).reshape(
            loc.shape[0], chunk_nodes, fg, gb, 2
        )

    def group_stats(gbinned, gmask, gb, gidx, codes1h, loc, chunk_nodes):
        """(gain, orig feat, bin) of the best split per compact slot for
        ONE feature group."""
        if use_fused:
            bg, bf, bb = build_best_split_pallas(
                gbinned, loc, g, h, gmask,
                lam_k, gam_k, mcw_k,
                num_nodes=chunk_nodes, num_bins=gb, lowp=lowp,
            )
            if gidx is not None:
                bf = gidx[jnp.maximum(bf, 0)].astype(jnp.int32)
            return bg, bf, bb
        if use_gemm:
            hist = build_histogram_gemm(gbinned, loc, chunk_nodes, gb, codes1h)
        elif impl == "pallas":
            # bin-loop kernel for narrow bin counts: one whole-block
            # compare per bin instead of the select-chain lane assembly —
            # 381 -> 141 ms per build at 1M×500×32, bit-identical
            # histograms (see _hist_binloop_kernel). Its cost is linear in
            # num_bins, so wide-bin fits (e.g. 256-bin sketches) keep the
            # lane-packed kernel (measured 2.2x better there).
            if gb <= 64:
                hist = build_histogram_pallas_binloop(
                    gbinned, loc, g, h, chunk_nodes, gb, lowp=lowp
                )
            else:
                hist = build_histogram_pallas_batched(
                    gbinned, loc, g, h, chunk_nodes, gb, lowp=lowp
                )
        else:
            hist = build_histogram_scatter_batched(
                gbinned, loc, g, h, chunk_nodes, gb
            )
        if axis_name is not None:
            # the Rabit-allreduce moment: per-shard partial histograms
            # reduce over ICI; everything after sees the global histogram
            hist = jax.lax.psum(hist, axis_name)
        hg, hh = hist[..., 0], hist[..., 1]  # [K, M, Fg, Bg]

        gl = jnp.cumsum(hg, axis=3)[..., :-1]
        hl = jnp.cumsum(hh, axis=3)[..., :-1]
        gt = hg.sum(axis=3, keepdims=True)
        ht = hh.sum(axis=3, keepdims=True)
        gr = gt - gl
        hr = ht - hl
        parent = (gt**2) / (ht + lam)
        gain = 0.5 * (gl**2 / (hl + lam) + gr**2 / (hr + lam) - parent) - gam
        valid = (
            (hl >= mcw)
            & (hr >= mcw)
            & (gmask[:, None, :, None] > 0)
        )
        gain = jnp.where(valid, gain, -jnp.inf)

        flat_gain = gain.reshape(gain.shape[0], chunk_nodes, -1)
        best = jnp.argmax(flat_gain, axis=2)
        best_gain = jnp.take_along_axis(flat_gain, best[..., None], axis=2)[..., 0]
        best_feat = (best // (gb - 1)).astype(jnp.int32)
        best_bin = (best % (gb - 1)).astype(jnp.int32)
        if gidx is not None:
            best_feat = gidx[best_feat].astype(jnp.int32)
        return best_gain, best_feat, best_bin

    def chunk_stats(local, c0, chunk_nodes):
        """Best (feat, bin) per compact slot in [c0, c0 + chunk_nodes),
        merged across feature groups (tie-break: lowest original feature
        id — matches the single-group argmax order)."""
        active = (local >= c0) & (local < c0 + chunk_nodes)
        loc = jnp.where(active, local - c0, -1)  # [K, N]
        bg, bf, bb = None, None, None
        for gbinned, gmask, grp_b, gidx, codes1h in groups:
            gg, gf, gbin = group_stats(
                gbinned, gmask, grp_b, gidx, codes1h, loc, chunk_nodes
            )
            if bg is None:
                bg, bf, bb = gg, gf, gbin
            else:
                take = (gg > bg) | ((gg == bg) & (gf < bf))
                bg = jnp.where(take, gg, bg)
                bf = jnp.where(take, gf, bf)
                bb = jnp.where(take, gbin, bb)
        do_split = bg > jnp.maximum(mig, 0.0)
        return (
            jnp.where(do_split, bf, -1),
            jnp.where(do_split, bb, 0),
        )  # each [K, chunk]

    sentinel = jnp.int32(max_nodes)  # out-of-range → dropped by scatters


    if max_depth == 0:
        # root-only tree (legal Spark maxDepth=0): no splits, leaf = all rows
        leaf_g0 = (g).sum(axis=1, keepdims=True)
        leaf_h0 = (h).sum(axis=1, keepdims=True)
        if axis_name is not None:
            leaf_g0 = jax.lax.psum(leaf_g0, axis_name)
            leaf_h0 = jax.lax.psum(leaf_h0, axis_name)
        return Tree(
            split_feat=jnp.full((k_fits, 0, 1), -1, dtype=jnp.int32),
            split_bin=jnp.zeros((k_fits, 0, 1), dtype=jnp.int32),
            leaf_value=-leaf_g0 / (leaf_h0 + vec(reg_lambda)[:, None]),
        ), jnp.zeros((k_fits, n), dtype=jnp.int32)

    # ---- lax.scan over levels with ONE shared body. Program bytes are the
    # binding constraint on the tunneled chip (serialized executables ship
    # over the link every fresh process — BASELINE.md round 3), and an
    # unrolled level loop multiplies the compiled body by max_depth. Every
    # level therefore uses the SAME static slot layout: `cap` compact slots
    # in `num_chunks` fixed chunks, with node compaction numbering live
    # slots densely from 0 so the per-chunk occupancy cond skips the
    # provably-empty tail (level 0 has one live node → one chunk runs).
    # Shallow levels pay a full-width chunk where the unrolled loop paid
    # 2^d slots; that is kernel-grid noise next to shipping a 10× bigger
    # executable.
    n_nodes = cap
    chunk_nodes = min(chunk_cap, n_nodes)
    num_chunks = (n_nodes + chunk_nodes - 1) // chunk_nodes

    def compact_local(hist_node):
        """Dense live-slot numbering via occupancy + cumsum rank. Slot =
        number of live node ids BELOW this row's id — identical numbering
        to sorted-unique compaction, but built from one scatter-add and a
        cumsum instead of sort + searchsorted (each searchsorted lowers to
        a ~log2(N)-step binary-search while loop of gather fusions, and
        three of them per level measured ~75% of deep forest exec). When
        sharded, every shard agrees on the numbering because the occupancy
        psums first. Returns ((live, rank), slot): live/rank are
        [K, max_nodes] masks/prefix-ranks used to densify per-slot results
        back into global node-id space gather-side."""
        occ = _occupancy(hist_node, max_nodes)
        if axis_name is not None:
            occ = jax.lax.psum(occ, axis_name)
        live = occ > 0
        live_i = live.astype(jnp.int32)
        rank = jnp.cumsum(live_i, axis=1) - live_i  # exclusive prefix
        slot = _small_table_lookup(
            rank, jnp.minimum(hist_node, max_nodes - 1)
        )
        slot = jnp.where(hist_node >= max_nodes, sentinel, slot).astype(
            jnp.int32
        )
        return (live, rank), slot

    def level_body(carry, level_idx):
        # rows whose node failed to split are DEAD for histogram purposes:
        # a non-split node's child holds the same rows, hence the same
        # histogram and the same failed gain test (the hereditary no-split
        # argument). Excluding them shrinks the live-slot frontier so the
        # occupancy skip drops the dead bulk of deep levels; `node` keeps
        # the full routing chain (dead rows continue left) so leaf
        # assignment is unchanged.
        node, active, alive = carry
        hist_node = jnp.where(active, node, sentinel)
        (live, rank), local = compact_local(hist_node)
        # dead rows out of every histogram / occupancy check, regardless
        # of which slot the sentinel landed on after compaction
        local = jnp.where(active, local, sentinel)

        def live_level():
            def chunk_body(ci, fb):
                feats_a, bins_a = fb
                c0 = ci * chunk_nodes
                if axis_name is None:
                    occupied = (
                        (local >= c0) & (local < c0 + chunk_nodes)
                    ).any()
                    cf, cb = jax.lax.cond(
                        occupied,
                        lambda: chunk_stats(local, c0, chunk_nodes),
                        lambda: (
                            jnp.full(
                                (k_fits, chunk_nodes), -1, dtype=jnp.int32
                            ),
                            jnp.zeros(
                                (k_fits, chunk_nodes), dtype=jnp.int32
                            ),
                        ),
                    )
                else:
                    # the sharded path always computes — its psums can't
                    # sit under a data-dependent cond
                    cf, cb = chunk_stats(local, c0, chunk_nodes)
                return (
                    jax.lax.dynamic_update_slice(feats_a, cf, (0, c0)),
                    jax.lax.dynamic_update_slice(bins_a, cb, (0, c0)),
                )

            feats_a0 = jnp.full(
                (k_fits, num_chunks * chunk_nodes), -1, dtype=jnp.int32
            )
            bins_a0 = jnp.zeros(
                (k_fits, num_chunks * chunk_nodes), dtype=jnp.int32
            )
            feats_a, bins_a = jax.lax.fori_loop(
                0, num_chunks, chunk_body, (feats_a0, bins_a0)
            )
            return feats_a[:, :n_nodes], bins_a[:, :n_nodes]

        # ---- early level exit: no-split is hereditary, so once a level
        # produces zero splits every deeper level is all-leaves — skip the
        # histogram work under a cond. The sharded path always computes
        # (replicated-predicate collectives under shard_map are not worth
        # the coupling).
        if axis_name is not None:
            feats_c, bins_c = live_level()
        else:
            feats_c, bins_c = jax.lax.cond(
                alive,
                live_level,
                lambda: (
                    jnp.full((k_fits, n_nodes), -1, dtype=jnp.int32),
                    jnp.zeros((k_fits, n_nodes), dtype=jnp.int32),
                ),
            )
        if max_depth_v is not None:
            # per-lane depth cap: a lane past its depth emits no splits
            # (identical trees to a program compiled at that lane's depth —
            # dead levels route left and add nothing)
            lane_live = (level_idx < max_depth_v)[:, None]
            feats_c = jnp.where(lane_live, feats_c, -1)
            bins_c = jnp.where(lane_live, bins_c, 0)
        alive = (feats_c >= 0).any()

        # write per-slot decisions into the GLOBAL node-slot tree arrays —
        # gather-side via the compaction rank (live id → its dense slot):
        # scatters serialize per index on TPU and searchsorted lowers to
        # binary-search while loops; both measured to dominate deep levels.
        # (A one-shot post-scan densify over all levels measured ~35%
        # SLOWER than these per-level gathers — the [depth, K, max_nodes]
        # batched gather schedules worse than the level-sized ones.)
        rank_c = jnp.minimum(rank, n_nodes - 1)
        # one-hot select, NOT take_along_axis: the [K, max_nodes] gather
        # from [K, cap] lowered to a serializing custom-fusion gather
        # measured at ~1 ms per level — 1.2 s of the 1.7 s depth-12 RF
        # program (trace: tools/trace_rf12.py)
        feats_d = jnp.where(live, _small_table_lookup(feats_c, rank_c), -1)
        bins_d = jnp.where(live, _small_table_lookup(bins_c, rank_c), 0)

        # ---- route rows to children (gather via compact slots — cheaper)
        slot = jnp.clip(local, 0, n_nodes - 1)
        row_feat = _small_table_lookup(feats_c, slot)  # [K, N]
        row_thr = _small_table_lookup(bins_c, slot)
        code = _row_feature_select(binned, row_feat)
        go_right = active & (row_feat >= 0) & (code > row_thr)
        node = node * 2 + go_right.astype(jnp.int32)
        active = active & (row_feat >= 0)
        return (node, active, alive), (feats_d, bins_d)

    (node, active, _), (feats_s, bins_s) = jax.lax.scan(
        level_body,
        (
            jnp.zeros((k_fits, n), dtype=jnp.int32),
            jnp.ones((k_fits, n), dtype=bool),
            jnp.asarray(True),
        ),
        jnp.arange(max_depth, dtype=jnp.int32),
    )
    feats = jnp.swapaxes(feats_s, 0, 1)  # [K, depth, max_nodes]
    bins = jnp.swapaxes(bins_s, 0, 1)

    leaf_g = _segment_sum_small(g, node, max_nodes)
    leaf_h = _segment_sum_small(h, node, max_nodes)
    if axis_name is not None:
        leaf_g = jax.lax.psum(leaf_g, axis_name)
        leaf_h = jax.lax.psum(leaf_h, axis_name)
    leaf_value = -leaf_g / (leaf_h + vec(reg_lambda)[:, None])
    tree = Tree(split_feat=feats, split_bin=bins, leaf_value=leaf_value)
    # `node` is each row's final leaf slot — boosting's margin update reuses
    # it (leaf_value lookup) instead of re-traversing the tree (measured
    # ~100 ms/round of serialized gathers at 1M rows)
    return tree, node


def predict_tree(binned: jax.Array, tree: Tree) -> jax.Array:
    """Leaf value per row — lax.scan over the [depth, ...] level arrays
    (one shared gather body). An unrolled depth loop with level-sliced
    one-hot lookups was measured: warm eval 1.55 -> 1.33 s, but the
    vmapped sweep programs grew ~depth×, and re-banking/contention cost far
    more than the exec win — program bytes ship over the tunneled link, so
    the scan stays."""
    n = binned.shape[0]

    def level(node, sfsb):
        sf, sb = sfsb
        feat = _small_table_lookup(sf[None, :], node[None, :])[0]
        thr = _small_table_lookup(sb[None, :], node[None, :])[0]
        code = _row_feature_select(binned, feat)
        go_right = (feat >= 0) & (code > thr)
        return node * 2 + go_right.astype(jnp.int32), None

    node, _ = jax.lax.scan(
        level, jnp.zeros(n, dtype=jnp.int32),
        (tree.split_feat, tree.split_bin),
    )
    return _small_table_lookup(tree.leaf_value[None, :], node[None, :])[0]


# --------------------------------------------------------------------------
# forests (bagged, batched over the fit axis) and boosting (chunk-scanned)
# --------------------------------------------------------------------------
def fit_forest(
    binned: jax.Array,
    target: jax.Array,      # [N] regression target (or one-vs-rest indicator)
    row_mask: jax.Array,    # [N]
    num_trees: int,
    max_depth: int,
    num_bins: int,
    subsample_rate: float | jax.Array = 1.0,
    colsample_rate: float | jax.Array = 1.0,
    min_instances: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    seed: int = 42,
    bootstrap: bool = True,
    parallel_fits: int = 1,  # kept for API compat
    lowp: bool = False,
    feature_groups=None,
) -> Tree:
    """Random forest of mean-target trees — the K=1 case of
    fit_forest_batched (Spark RandomForest parity: variance impurity ==
    gain formula with h=1, λ=0). Returns stacked Tree arrays [T, ...].
    ``seed`` must be a concrete int (it keys host-side PRNG splits)."""
    trees = fit_forest_batched(
        binned, target, jnp.asarray(row_mask)[None, :],
        num_trees=num_trees, max_depth=max_depth, num_bins=num_bins,
        subsample_rate=subsample_rate, colsample_rate=colsample_rate,
        min_instances=min_instances, min_info_gain=min_info_gain,
        seed=int(seed), bootstrap=bootstrap, lowp=lowp,
        feature_groups=feature_groups,
    )
    return jax.tree.map(lambda a: a[0], trees)


def predict_forest(binned: jax.Array, trees: Tree) -> jax.Array:
    """Mean leaf value across the stacked forest -> [N]."""
    preds = jax.vmap(lambda t: predict_tree(binned, t))(trees)  # [T, N]
    return preds.mean(axis=0)


@jax.jit
def predict_forest_raw(x: jax.Array, thresholds: jax.Array, trees: Tree) -> jax.Array:
    """Fused bin + forest predict — ONE dispatch per call (model scoring runs
    through here; the eager op-by-op path costs a host round-trip per op,
    which dominates wall-clock on a tunneled chip)."""
    return predict_forest(bin_data(x, thresholds), trees)


@jax.jit
def predict_boosted_raw(
    x: jax.Array, thresholds: jax.Array, trees: Tree,
    eta: jax.Array, base_score: jax.Array,
) -> jax.Array:
    """Fused bin + boosted predict — one dispatch; eta/base_score are
    traced arrays so distinct hyperparameter values share the compilation."""
    binned = bin_data(x, thresholds)
    preds = jax.vmap(lambda t: predict_tree(binned, t))(trees)  # [R, N]
    return base_score + eta * preds.sum(axis=0)


# --------------------------------------------------------------------------
# host (numpy) predict path — serving-size batches
# --------------------------------------------------------------------------
# Every jax-array result touch costs a fixed sync penalty on virtualized
# hosts (~0.1 s measured on the CPU backend here), and the tunneled chip
# pays an upload per call — for serving-size batches a pure-numpy predict
# is orders of magnitude cheaper than either. Semantics mirror
# bin_data/predict_tree exactly (parity pinned in tests).


def _f32_order_keys(a: np.ndarray) -> np.ndarray:
    """Monotone uint32 image of float32 order (the radix-sort bit trick):
    strict order and ties are preserved EXACTLY, so integer binning matches
    float binning bit-for-bit. -0.0 normalizes to +0.0 first (they compare
    equal as floats but have different bit patterns); NaN maps above +inf,
    which matches the device path for NaN thresholds (x > NaN is False)."""
    f = np.ascontiguousarray(a, dtype=np.float32) + np.float32(0.0)
    b = f.view(np.uint32)
    return np.where(b >> 31 != 0, ~b, b | np.uint32(0x80000000))


def _threshold_flat_keys(thresholds: np.ndarray) -> np.ndarray:
    """Per-feature-offset int64 keys of a threshold matrix (the serving
    path calls bin_data_host per batch with FIXED model thresholds —
    callers cache this)."""
    thr = np.asarray(thresholds, dtype=np.float32)
    # canonicalize NaN thresholds to the positive-NaN bit pattern: a NaN
    # with the sign bit set would key BELOW all finite values via the ~b
    # branch, binning rows one higher than the device path (where
    # x > NaN is always False). Unreachable via quantile_thresholds but
    # this function is public API for other callers.
    thr = np.where(np.isnan(thr), np.float32(np.nan), thr)
    seg = np.arange(thr.shape[0], dtype=np.int64) << 32
    return (_f32_order_keys(thr).astype(np.int64) + seg[:, None]).ravel()


def bin_data_host(
    x: np.ndarray, thresholds: np.ndarray,
    flat_keys: np.ndarray | None = None,
) -> np.ndarray:
    """Host bin_data: ONE searchsorted over per-feature-offset integer keys
    — O(N·F·log(F·B)) with no Python per-feature loop, vs the device scan's
    O(N·F·B). Exact (integer key space, see _f32_order_keys): ties at a
    threshold bin identically to the device path. Requires per-row sorted
    thresholds (quantile_thresholds guarantees it); NaN x bins to 0.
    ``flat_keys`` (from _threshold_flat_keys) skips re-keying fixed model
    thresholds on every serving batch."""
    xs = np.asarray(x, dtype=np.float32)
    n, num_f = xs.shape
    bm1 = np.asarray(thresholds).shape[1]
    xk = _f32_order_keys(xs).astype(np.int64)
    xk[np.isnan(xs)] = 0  # device: NaN > thr is False -> bin 0
    seg = np.arange(num_f, dtype=np.int64) << 32
    if flat_keys is None:
        flat_keys = _threshold_flat_keys(thresholds)
    idx = np.searchsorted(flat_keys, (xk + seg[None, :]).ravel(), side="left")
    return (
        idx.reshape(n, num_f) - np.arange(num_f, dtype=np.int64) * bm1
    ).astype(np.int32)


class _PreparedStack:
    """Contiguous traversal arrays for a host tree stack, built once per
    model (the flagship winner is a 200-tree depth-10 stack; slicing
    ``sf[:, lvl, :]`` per call copies [200, 512] twice per level).

    ``raw`` feeds the C kernel directly; the numpy-fallback structures
    (per-level flat arrays, truncated past the deepest real split — a
    split-free level maps node -> 2*node unconditionally, folded into one
    final shift) are built LAZILY so the native path never holds a second
    copy of the split arrays."""

    __slots__ = ("raw", "r", "depth", "width", "leaf_width", "max_feat",
                 "_levels", "_tail_shift", "leaf_flat")

    def __init__(self, sf: np.ndarray, sb: np.ndarray, lv: np.ndarray):
        self.raw = (sf, sb, lv)
        self.r, self.depth, self.width = sf.shape
        # stack-shape validation happens HERE, once per model load — a
        # corrupt manifest fails at prepare time with the same IndexError
        # the traversals would raise, and the serving hot loop keeps only
        # the O(1) plane-width guard in _leaf_sum (native.tree_predict_sum
        # runs prevalidated; env TPTPU_NATIVE_VALIDATE restores the
        # per-call check)
        if lv.ndim != 2 or lv.shape[1] != (1 << self.depth):
            raise IndexError(
                f"tree stack: leaf table width {lv.shape[1:]} does not "
                f"match depth {self.depth} (expected {1 << self.depth})"
            )
        self.max_feat = int(sf.max()) if sf.size else -1
        self.leaf_width = lv.shape[1]
        self.leaf_flat = lv.ravel()  # contiguous -> view, not a copy
        self._levels = None
        self._tail_shift = 0

    @property
    def levels(self) -> tuple:
        if self._levels is None:
            sf, sb, _ = self.raw
            eff = 0
            for lvl in range(self.depth):
                if (sf[:, lvl, :] >= 0).any():
                    eff = lvl + 1
            self._levels = tuple(
                (np.ascontiguousarray(sf[:, lvl, :]).ravel(),
                 np.ascontiguousarray(sb[:, lvl, :]).ravel())
                for lvl in range(eff)
            )
            self._tail_shift = self.depth - eff
        return self._levels

    @property
    def tail_shift(self) -> int:
        self.levels  # noqa: B018 — computed together
        return self._tail_shift


def prepare_host_stack(t) -> _PreparedStack:
    return _PreparedStack(
        np.ascontiguousarray(t.split_feat, dtype=np.int32),
        np.ascontiguousarray(t.split_bin, dtype=np.int32),
        np.ascontiguousarray(t.leaf_value, dtype=np.float32),
    )


def _traverse_host(binned: np.ndarray, stack) -> np.ndarray:
    """Leaf values [R, N] for a stacked host-tree pytree (mirrors
    predict_tree's routing: split_feat < 0 routes left).

    Flat 1-D fancy gathers instead of take_along_axis: at serving sizes
    the traversal is gather-overhead-bound, and the flat form measured
    ~5x cheaper on the 891-row Titanic batch. ``stack`` is a Tree of host
    arrays or a _PreparedStack (see prepare_host_stack) that skips
    per-call level slicing."""
    ps = stack if isinstance(stack, _PreparedStack) else prepare_host_stack(stack)
    n = binned.shape[0]
    node = np.zeros((ps.r, n), dtype=np.intp)
    toff = (np.arange(ps.r, dtype=np.intp) * ps.width)[:, None]
    bflat = np.ascontiguousarray(binned).ravel()
    rowbase = np.arange(n, dtype=np.intp)[None, :] * binned.shape[1]
    for sf_l, sb_l in ps.levels:
        flat = node + toff
        feat = sf_l[flat]
        thrb = sb_l[flat]
        code = bflat[rowbase + np.maximum(feat, 0)]
        node = node * 2 + ((feat >= 0) & (code > thrb))
    if ps.tail_shift:
        node <<= ps.tail_shift
    return ps.leaf_flat[
        node + (np.arange(ps.r, dtype=np.intp) * ps.leaf_width)[:, None]
    ]


def _leaf_sum(binned: np.ndarray, stack) -> np.ndarray:
    """Per-row sum of leaf values across the stack, float32 [N] — the C
    kernel when the native library is built (about 4x the numpy traversal
    on the flagship's 200-tree depth-10 winner), numpy otherwise."""
    from .. import native

    ps = stack if isinstance(stack, _PreparedStack) else prepare_host_stack(stack)
    if ps.max_feat >= binned.shape[1]:
        raise IndexError(
            f"tree stack: split feature index {ps.max_feat} out of bounds "
            f"for {binned.shape[1]} binned feature(s)"
        )
    out = native.tree_predict_sum(binned, *ps.raw, prevalidated=True)
    if out is not None:
        return out
    return _traverse_host(binned, ps).sum(axis=0)


def host_serving_plan(
    thresholds: np.ndarray, stacks: list,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, list]:
    """Used-feature compaction for host serving batches.

    A fitted model's trees reference a small subset of the feature space
    (tens of features out of the flagship's 928), but bin_data_host bins
    every column. Returns ``(used, thr_used, flat_keys, stacks_c)`` where
    ``used`` is the sorted unique split-feature index set, ``thr_used`` /
    ``flat_keys`` are the threshold rows (and their searchsorted keys) for
    just those features, and ``stacks_c`` are the tree stacks with
    split_feat remapped into the compact space. Binning ``x[:, used]``
    against ``thr_used`` and traversing ``stacks_c`` is bit-identical to
    the full-width path (binning is columnwise-independent)."""
    feats = [
        np.asarray(t.split_feat)[np.asarray(t.split_feat) >= 0].ravel()
        for t in stacks
    ]
    used = np.unique(np.concatenate(feats + [np.zeros(1, np.int64)]))
    used = used.astype(np.int64)
    thr_used = np.ascontiguousarray(np.asarray(thresholds)[used])
    flat_keys = _threshold_flat_keys(thr_used)
    stacks_c = [
        prepare_host_stack(
            t._replace(
                split_feat=np.where(
                    np.asarray(t.split_feat) >= 0,
                    np.searchsorted(used, np.asarray(t.split_feat)),
                    np.asarray(t.split_feat),
                ).astype(np.int32)
            )
        )
        for t in stacks
    ]
    return used, thr_used, flat_keys, stacks_c


def predict_boosted_host(
    x: np.ndarray, thresholds: np.ndarray, trees: Tree,
    eta: float, base_score: float,
    binned: np.ndarray | None = None,
) -> np.ndarray:
    """Numpy twin of predict_boosted_raw; ``trees`` must hold host arrays
    (a Tree stack or a prepared one from prepare_host_stack/
    host_serving_plan). ``binned`` lets multi-stack callers bin x once
    across stacks."""
    if binned is None:
        binned = bin_data_host(x, thresholds)
    return np.float32(base_score) + np.float32(eta) * _leaf_sum(binned, trees)


def predict_forest_host(
    x: np.ndarray, thresholds: np.ndarray, trees: Tree,
    binned: np.ndarray | None = None,
) -> np.ndarray:
    """Numpy twin of predict_forest_raw; ``trees`` must hold host arrays
    (a Tree stack or a prepared one). ``binned`` lets multi-stack callers
    bin x once across stacks."""
    if binned is None:
        binned = bin_data_host(x, thresholds)
    t = trees if isinstance(trees, _PreparedStack) else prepare_host_stack(trees)
    return _leaf_sum(binned, t) / np.float32(t.r)


@jax.jit
def sweep_boosted_outputs(
    x: jax.Array, thresholds: jax.Array, trees: Tree,
    eta_v: jax.Array, base_v: jax.Array,
) -> jax.Array:
    """Margins for a WHOLE sweep stack in one dispatch: trees [K, R, ...]
    (folds × grid lanes) → [K, N]. The validator's per-model predict loop
    costs a dispatch + input upload per model over the tunneled link; here
    the full candidate sweep's validation margins are one program."""
    binned = bin_data(x, thresholds)

    def one(t, e, b):
        preds = jax.vmap(lambda tt: predict_tree(binned, tt))(t)  # [R, N]
        return b + e * preds.sum(axis=0)

    return jax.vmap(one)(trees, eta_v, base_v)


@jax.jit
def sweep_forest_outputs(
    x: jax.Array, thresholds: jax.Array, trees: Tree,
    eta_v: jax.Array, base_v: jax.Array,
) -> jax.Array:
    """Forest mean-leaf outputs for a sweep stack: trees [K, T, ...] →
    [K, N]. eta_v/base_v are accepted (and ignored) so both sweep entry
    points share a call signature."""
    binned = bin_data(x, thresholds)
    return jax.vmap(lambda t: predict_forest(binned, t))(trees)


@partial(jax.jit, static_argnames=("n", "f", "bootstrap"))
def _bag_masks(tkey, sub, col, row_mask, n, f, bootstrap):
    """Bootstrap row counts + feature masks for one tree across K fits.
    Drawn over the UNPADDED row count so the sharded path (which pads rows
    afterwards) samples bit-identically to the single-device path."""
    k_fits = row_mask.shape[0]
    k1, k2 = jax.random.split(tkey)
    if bootstrap:
        # same key for every fit, drawn per-fit (vmap): each lane's sample
        # equals the sequential fit_forest draw, so batched and sequential
        # sweeps train bit-identical forests
        counts = jax.vmap(
            lambda r: jax.random.poisson(k1, r, (n,))
        )(sub).astype(jnp.float32)
    else:
        counts = jnp.ones((k_fits, n), dtype=jnp.float32)
    rmask = row_mask * counts
    fmask = jax.vmap(
        lambda c: (jax.random.uniform(k2, (f,)) < c)
    )(col).astype(jnp.float32)
    fmask = jnp.where(
        fmask.sum(axis=1, keepdims=True) == 0, jnp.ones((1, f)), fmask
    )
    return rmask, fmask


@partial(
    jax.jit,
    static_argnames=(
        "num_trees", "max_depth", "num_bins", "bootstrap", "lowp", "hist_impl",
    ),
)
def _forest_trees_scan(
    binned, target, row_mask, seed_arr, sub, col, min_instances,
    min_info_gain,
    feature_groups=None, max_depth_v=None, subset_n=None, subset_w=None, *,
    num_trees, max_depth, num_bins, bootstrap, lowp, hist_impl=None,
) -> tuple[Tree, jax.Array]:
    """The whole bagged forest as ONE program: ``lax.scan`` over the
    per-tree PRNG keys with a single tree-growth body (the same shape as
    the boosting rounds scan, which runs 200 rounds in under a second on
    chip). This replaces both the host tree loop (a ~0.4 s dispatch per
    tree over the tunneled link) and the tree-folded K'=trees×K kernels
    (whose wide grids schedule badly and defeat the early level exit).
    Masks are drawn per tree from the same keys, so forests are
    bit-identical to the per-tree path.

    ``subset_n``/``subset_w`` ([T, n_sub] int32, optional) are per-tree
    colsample feature subsets (narrow/wide partition) sampled host-side by
    ``fit_forest_batched``: each tree's histogram work runs over only its
    ~√F sampled columns via the feature_groups gather machinery instead of
    masking gains over the full one-hot width (a ~30× FLOP cut on
    transmogrified matrices, where most columns are indicators).

    Returns (Tree arrays [K, T, ...], training outputs [K, N]) — the
    outputs are each lane's mean-leaf prediction over ALL rows, read from
    the grower's own final routing, so the CV sweep needs no separate
    eval traversal program."""
    k_fits, n = row_mask.shape
    f = binned.shape[1]
    # target: [N] shared, or [K, N] per-lane (one-vs-rest class indicators
    # ride the fit axis — the multiclass RF sweep trains every
    # class × fold × grid-point forest in this one program)
    target = jnp.asarray(target)
    if target.ndim == 1:
        gb = jnp.broadcast_to(-target[None, :], (k_fits, n))
    else:
        gb = -target
    ones = jnp.ones((k_fits, n), dtype=jnp.float32)
    mi_k = jnp.broadcast_to(
        jnp.asarray(min_instances, dtype=jnp.float32).reshape(-1), (k_fits,)
    )
    mg_k = jnp.broadcast_to(
        jnp.asarray(min_info_gain, dtype=jnp.float32).reshape(-1), (k_fits,)
    )
    # per-tree keys derived IN-PROGRAM (same threefry ops → identical draws
    # to the old eager derivation; keeps PRNGKey/split eager compiles off
    # the per-process critical path)
    tkeys = jax.random.split(
        jax.random.PRNGKey(seed_arr[0].astype(jnp.uint32)), num_trees
    )

    def body(_, xs):
        tk, sn, sw = xs
        rm_t, fm_t = _bag_masks(
            tk, sub, jnp.ones_like(col) if sn is not None else col,
            row_mask, n, f, bootstrap,
        )
        grp = (sn, sw) if sn is not None else feature_groups
        tree, node = _grow_tree_impl(
            binned, gb, ones, rm_t, fm_t,
            max_depth=max_depth, num_bins=num_bins,
            reg_lambda=0.0, gamma=0.0,
            min_child_weight=mi_k, min_info_gain=mg_k,
            hist_impl=hist_impl, lowp=lowp, feature_groups=grp,
            max_depth_v=max_depth_v,
        )
        # this tree's prediction for EVERY row from the grower's own final
        # routing (leaf lookup — no re-traversal)
        pred_t = _small_table_lookup(tree.leaf_value, node)
        return None, (tree, pred_t)

    _, (trees, preds) = jax.lax.scan(
        body, None, (tkeys, subset_n, subset_w)
    )  # [T, K, ...]
    outs = preds.mean(axis=0)  # [K, N] forest mean-leaf outputs
    return jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), trees), outs


def fit_forest_batched(
    binned: jax.Array,      # [N, F] shared
    target: jax.Array,      # [N] shared regression target / indicator
    row_mask: jax.Array,    # [K, N] per-fit row masks (folds × resamples)
    num_trees: int,
    max_depth: int,
    num_bins: int,
    subsample_rate: jax.Array | float = 1.0,   # scalar or [K]
    colsample_rate: jax.Array | float = 1.0,
    min_instances: jax.Array | float = 1.0,
    min_info_gain: jax.Array | float = 0.0,
    seed: int = 42,
    bootstrap: bool = True,
    lowp: bool = False,
    mesh=None,
    feature_groups=None,
    max_depth_v=None,     # [K] int32: per-lane depth caps (see _grow_tree_impl)
    return_outputs: bool = False,
) -> Tree:
    """K random forests batched over the fit axis, the whole bagged forest
    as ONE scan-over-trees program (_forest_trees_scan — one tree-growth
    body, no per-tree dispatches, no tree-folded wide kernels). Returns
    stacked Tree arrays [K, T, ...]; with ``return_outputs`` also the
    [K, N] training-matrix mean-leaf outputs (each lane's predictions on
    every row — the CV sweep evaluates from these instead of re-traversing).

    A static ``colsample_rate`` < 1 with ``feature_groups`` samples an
    EXACT-COUNT feature subset per tree host-side (Spark's
    featureSubsetStrategy picks an exact number of features, not a
    Bernoulli mask; subsets are proportionally stratified over the
    narrow/wide bin groups) and the histogram work gathers only those
    columns — ~30× less one-hot GEMM width at √F rates on transmogrified
    matrices.

    With ``mesh`` set, rows shard over the mesh's data axis and each level's
    histogram psums over it (grows the same trees as the unsharded path —
    see _grow_tree_impl)."""
    k_fits, n = row_mask.shape
    # ---- exact-count per-tree feature subsets (static rate only: the
    # flagship RF path passes a python float; per-lane traced rates keep
    # the dense-mask path)
    subset_n = subset_w = None
    rate = (
        float(colsample_rate)
        if isinstance(colsample_rate, (int, float)) else None
    )
    if rate is not None and rate < 1.0 and feature_groups is not None:
        narrow_idx = np.asarray(feature_groups[0])
        wide_idx = np.asarray(feature_groups[1])
        f_n, f_w = len(narrow_idx), len(wide_idx)
        f_all = f_n + f_w
        n_sub = max(1, int(round(f_all * rate)))
        if n_sub < f_all:
            n_sub_n = min(f_n, int(round(n_sub * f_n / max(f_all, 1))))
            n_sub_w = min(f_w, n_sub - n_sub_n)
            n_sub_n = min(f_n, n_sub - n_sub_w)
            rng = np.random.default_rng([int(seed), 0x5EED])
            def draw(idx, k):
                return np.stack([
                    np.sort(rng.choice(idx, size=k, replace=False))
                    for _ in range(num_trees)
                ]).astype(np.int32) if k else np.zeros(
                    (num_trees, 0), dtype=np.int32
                )
            subset_n = jnp.asarray(draw(narrow_idx, n_sub_n))
            subset_w = jnp.asarray(draw(wide_idx, n_sub_w))
            colsample_rate = 1.0  # masks are all-ones under subsets
    # host-side numpy for every small knob: a dtype-converting or
    # broadcasting jnp op here is an EAGER device program, and on the
    # axon backend even trivial eager compiles cost 0.1-0.7 s per process
    # (JAX_LOG_COMPILES evidence in BASELINE.md round 5); f32 numpy arrays
    # transfer without compiling anything, and the broadcasts/PRNG-key
    # derivation happen INSIDE the jitted program
    def _vec_np(v):
        return np.asarray(
            np.broadcast_to(np.asarray(v, dtype=np.float32).reshape(-1),
                            (k_fits,))
        )

    sub = _vec_np(subsample_rate)
    col = _vec_np(colsample_rate)
    mi = np.asarray(min_instances, dtype=np.float32)
    mg = np.asarray(min_info_gain, dtype=np.float32)
    seed_arr = np.asarray([seed], dtype=np.uint32)
    if mesh is None:
        from ..parallel.mesh import execution_mesh

        mesh = execution_mesh()
    if mesh is not None:
        if max_depth_v is not None:
            raise NotImplementedError(
                "per-lane depth caps are single-device only (the sweep path)"
            )
        if getattr(target, "ndim", 1) != 1:
            raise NotImplementedError(
                "per-lane targets are single-device only (the multiclass "
                "sweep path); shard multiclass one class at a time"
            )
        key = jax.random.PRNGKey(seed)
        tkeys = jax.random.split(key, num_trees)
        trees, outs = _fit_forest_batched_sharded(
            mesh, binned, target, row_mask, tkeys, jnp.asarray(sub),
            jnp.asarray(col), mi, mg,
            num_trees=num_trees, max_depth=max_depth, num_bins=num_bins,
            bootstrap=bootstrap, lowp=lowp, feature_groups=feature_groups,
            subset_n=subset_n, subset_w=subset_w,
        )
        return (trees, outs) if return_outputs else trees
    from ..utils.aot import aot_call

    trees, outs = aot_call(
        "forest_scan", _forest_trees_scan,
        (binned, target, row_mask, seed_arr, sub, col, mi, mg,
         feature_groups, max_depth_v, subset_n, subset_w),
        dict(num_trees=num_trees,
             max_depth=max_depth, num_bins=num_bins, bootstrap=bootstrap,
             # lowp is only sound when target values are bf16-exact
             # (classification indicators); regression keeps f32
             lowp=lowp,
             # resolved EARLY so both the jit cache and the AOT blob key
             # see the trace-time impl choice
             hist_impl=_resolved_impl()),
    )
    return (trees, outs) if return_outputs else trees


@partial(
    jax.jit,
    static_argnames=("max_depth", "num_bins", "num_rounds", "objective", "parallel_fits"),
)
def fit_boosted(
    binned: jax.Array,
    y: jax.Array,          # [N] labels (0/1 binary, float regression)
    row_mask: jax.Array,
    num_rounds: int,
    max_depth: int,
    num_bins: int,
    eta: float | jax.Array = 0.3,
    reg_lambda: float | jax.Array = 1.0,
    gamma: float | jax.Array = 0.0,
    min_child_weight: float | jax.Array = 1.0,
    min_info_gain: float | jax.Array = 0.0,
    base_score: float | jax.Array = 0.0,
    objective: str = "binary:logistic",
    parallel_fits: int = 1,
    feature_groups=None,
) -> tuple[Tree, jax.Array]:
    """Gradient boosting (XGBoost/Spark-GBT parity): lax.scan over rounds,
    second-order gradients, shrinkage eta. Returns stacked trees [R, ...]
    and the training margin."""
    n, f = binned.shape
    feat_mask = jnp.ones(f, dtype=jnp.float32)

    def grads(margin):
        if objective == "binary:logistic":
            p = jax.nn.sigmoid(margin)
            return p - y, p * (1.0 - p)
        # reg:squarederror
        return margin - y, jnp.ones_like(margin)

    def round_step(margin, _):
        g, h = grads(margin)
        tree = grow_tree(
            binned, g, h, row_mask, feat_mask,
            max_depth=max_depth, num_bins=num_bins,
            reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, min_info_gain=min_info_gain,
            parallel_fits=parallel_fits, feature_groups=feature_groups,
        )
        margin = margin + eta * predict_tree(binned, tree)
        return margin, tree

    margin0 = jnp.full(n, base_score, dtype=jnp.float32)
    margin, trees = jax.lax.scan(round_step, margin0, None, length=num_rounds)
    return trees, margin


def predict_boosted(
    binned: jax.Array,
    trees: Tree,
    eta: float,
    base_score: float = 0.0,
) -> jax.Array:
    preds = jax.vmap(lambda t: predict_tree(binned, t))(trees)  # [R, N]
    return base_score + eta * preds.sum(axis=0)


def _boost_chunk_body(
    binned, y, row_mask, margin0, eta_v, reg_lambda, gamma,
    min_child_weight, min_info_gain, feature_groups=None, *,
    num_rounds, max_depth, num_bins, objective,
    axis_name=None, axis_size=1, hist_impl=None,
) -> tuple[Tree, jax.Array]:
    """A chunk of boosting rounds for all K fits (lax.scan inside one
    program) — shared by the single-device jit and the shard_map'd path
    (axis_name set: per-level histograms psum over the mesh axis; margins,
    gradients and predictions stay row-local)."""
    k_fits, n = row_mask.shape
    f = binned.shape[1]
    feat_mask = jnp.ones((k_fits, f), dtype=jnp.float32)

    def grads(margin):  # [K, N_local]
        if objective == "binary:logistic":
            p = jax.nn.sigmoid(margin)
            return p - y[None, :], p * (1.0 - p)
        return margin - y[None, :], jnp.ones_like(margin)

    def round_step(margin, _):
        g, h = grads(margin)
        tree, leaf_slot = _grow_tree_impl(
            binned, g, h, row_mask, feat_mask,
            max_depth=max_depth, num_bins=num_bins,
            reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, min_info_gain=min_info_gain,
            axis_name=axis_name, axis_size=axis_size, hist_impl=hist_impl,
            feature_groups=feature_groups,
        )
        # margin update straight from the grower's final routing — one
        # small-table lookup instead of a full predict_tree re-traversal
        step = _small_table_lookup(tree.leaf_value, leaf_slot)  # [K, N]
        margin = margin + eta_v[:, None] * step
        return margin, tree

    margin, trees = jax.lax.scan(round_step, margin0, None, length=num_rounds)
    # [R, K, ...] -> [K, R, ...] INSIDE the program: an eager transpose
    # after the fact costs a compile-cache round-trip per shape
    trees = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), trees)
    return trees, margin  # trees [K, R, ...]


_boost_rounds_batched = partial(
    jax.jit,
    static_argnames=(
        "num_rounds", "max_depth", "num_bins", "objective",
        "axis_name", "axis_size", "hist_impl",
    ),
)(_boost_chunk_body)


def _resolved_impl() -> str:
    """The histogram impl the trace WILL use, resolved at call time so it
    participates in jit-cache and AOT-blob identity (the env knob is read
    at trace time deep inside _grow_tree_impl otherwise)."""
    from .hist_pallas import default_impl

    return default_impl()


def _boost_round_chunk(num_rounds: int) -> int:
    """Boosting rounds per compiled program — DEFAULT the whole run (one
    program). Round 3 validated a single 200-round × K-fit program on the
    real chip (25.6 s one-time compile, banked as a serialized executable;
    ~ms warm) — the round-1 worker faults that motivated 25-round chunks
    no longer reproduce, and per-process cost is per-PROGRAM. Set
    TPTPU_BOOST_CHUNK=N to restore chunking on runtimes that fault."""
    import os

    env = os.environ.get("TPTPU_BOOST_CHUNK")
    return max(1, int(env)) if env else num_rounds


def fit_boosted_batched(
    binned: jax.Array,     # [N, F] shared
    y: jax.Array,          # [N] shared labels
    row_mask: jax.Array,   # [K, N]
    num_rounds: int,
    max_depth: int,
    num_bins: int,
    eta: jax.Array | float = 0.3,          # scalar or [K]
    reg_lambda: jax.Array | float = 1.0,
    gamma: jax.Array | float = 0.0,
    min_child_weight: jax.Array | float = 1.0,
    min_info_gain: jax.Array | float = 0.0,
    base_score: jax.Array | float = 0.0,
    objective: str = "binary:logistic",
    mesh=None,
    feature_groups=None,
) -> tuple[Tree, jax.Array]:
    """K boosting runs batched over the fit axis: every round grows all K
    trees in one histogram build; rounds scan in fixed-size chunks so each
    compiled program stays modest. Returns Tree arrays [K, R, ...] and the
    training margins [K, N].

    With ``mesh`` set, rows shard over the mesh's data axis: gradients and
    margins live sharded, per-level histograms psum over ICI, and trees come
    back replicated — the Rabit-tracker topology with XLA collectives."""
    k_fits, n = row_mask.shape
    # numpy, not eager jnp: dtype-converting/broadcasting eager ops each
    # compile a device program per process (~0.1-0.7 s each on the axon
    # backend); f32 numpy transfers compile nothing
    def _np_f32(v):
        return np.asarray(v, dtype=np.float32)

    eta_v = np.asarray(
        np.broadcast_to(_np_f32(eta).reshape(-1), (k_fits,))
    )
    lam = _np_f32(reg_lambda)
    gam = _np_f32(gamma)
    mcw = _np_f32(min_child_weight)
    mig = _np_f32(min_info_gain)
    if mesh is None:
        from ..parallel.mesh import execution_mesh

        mesh = execution_mesh()
    if mesh is not None:
        return _fit_boosted_batched_sharded(
            mesh, binned, y, row_mask, jnp.asarray(eta_v), jnp.asarray(lam),
            jnp.asarray(gam), jnp.asarray(mcw), jnp.asarray(mig),
            base_score=base_score, num_rounds=num_rounds,
            max_depth=max_depth, num_bins=num_bins, objective=objective,
            feature_groups=feature_groups,
        )
    # f32 numpy broadcast (no eager compile), then ONE device transfer so
    # chunk 1 and chunks 2+ present the same leaf type to the AOT key
    # (a numpy leaf has no .sharding; mixing host/device margins would
    # key-split the identical chunk program under TPTPU_BOOST_CHUNK)
    margin = jnp.asarray(np.asarray(np.broadcast_to(
        _np_f32(base_score).reshape(-1, 1), (k_fits, n)
    )))
    from ..compiler.dispatch import donating
    from ..utils.aot import aot_call

    # donated-buffer pipelining: the [K, N] margin is a pure carry between
    # chunk programs — chunk i+1 never needs chunk i's input margin again,
    # so the executable aliases it into the output margin instead of
    # allocating a fresh buffer per chunk (TPTPU_DONATE=0 opts out)
    boost_chunk_fn = donating(
        "boost_chunk", _boost_rounds_batched, donate_argnums=(3,),
        static_argnames=(
            "num_rounds", "max_depth", "num_bins", "objective",
            "axis_name", "axis_size", "hist_impl",
        ),
    )
    chunks = []
    done = 0
    chunk_size = _boost_round_chunk(num_rounds)
    while done < num_rounds:
        rc = min(chunk_size, num_rounds - done)
        trees_c, margin = aot_call(
            "boost_chunk", boost_chunk_fn,
            (binned, y, row_mask, margin, eta_v, lam, gam, mcw, mig,
             feature_groups),
            dict(num_rounds=rc, max_depth=max_depth, num_bins=num_bins,
                 objective=objective, hist_impl=_resolved_impl()),
        )
        chunks.append(trees_c)  # each [K, rc, ...] (swap happens in-jit)
        done += rc
    if len(chunks) == 1:
        return chunks[0], margin
    # multi-chunk only off the default path: concatenate on HOST (eager
    # device concatenates cost a compile-cache round-trip per shape)
    chunks = [jax.tree.map(np.asarray, c) for c in chunks]
    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=1), *chunks), margin


# --------------------------------------------------------------------------
# mesh-sharded growth: rows shard over the data axis; per-level histograms
# psum over ICI — the XLA-collective replacement for XGBoost's Rabit
# allreduce of per-worker histograms (OpXGBoostClassifier.scala:101,
# SURVEY §2.6 row 5). The split search consumes the reduced histogram
# identically, so the sharded path grows the SAME trees as single-device.
# --------------------------------------------------------------------------
def _pad_axis(a: jax.Array, axis: int, multiple: int) -> jax.Array:
    """Zero-pad ``axis`` to a multiple (static shard shapes). Zero rows are
    inert in growth: row_mask 0 drops them from histograms and leaf sums."""
    pad = (-a.shape[axis]) % multiple
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


@lru_cache(maxsize=None)
def _sharded_grow_kernel(mesh, max_depth, num_bins, hist_impl, lowp,
                         has_groups=False):
    """jit(shard_map(grow)) for one (mesh, statics) combo, built once —
    rebuilding per call would retrace every tree. Feature-group index
    arrays (when present) are replicated: the feature axis is unsharded."""
    from ..parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    size = mesh.shape[DATA_AXIS]

    def body(binned, grad, hess, row_mask, feat_mask, lam, gam, mcw, mig,
             *grp):
        return _grow_tree_impl(
            binned, grad, hess, row_mask, feat_mask,
            max_depth=max_depth, num_bins=num_bins,
            reg_lambda=lam, gamma=gam, min_child_weight=mcw,
            min_info_gain=mig, hist_impl=hist_impl, lowp=lowp,
            axis_name=DATA_AXIS, axis_size=size,
            feature_groups=grp if grp else None,
        )[0]

    rep = P()
    sm = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None),   # binned [N, F]
            P(None, DATA_AXIS),   # grad [K, N]
            P(None, DATA_AXIS),   # hess
            P(None, DATA_AXIS),   # row_mask
            rep, rep, rep, rep, rep,
        ) + ((rep, rep) if has_groups else ()),
        out_specs=Tree(split_feat=rep, split_bin=rep, leaf_value=rep),
        check_vma=False,
    )
    return jax.jit(sm)


@lru_cache(maxsize=None)
def _sharded_forest_scan_kernel(mesh, max_depth, num_bins, hist_impl, lowp,
                                has_groups=False, has_subsets=False):
    """jit(shard_map(scan-over-trees)): the sharded counterpart of
    _forest_trees_scan. Per-tree masks are drawn OUTSIDE (global-row
    semantics) and enter sharded on the row axis; the scan carries the
    whole forest in one program, psum'ing each level's histograms. Also
    emits [K, N] training outputs (row-sharded) like the single-device
    scan."""
    from ..parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    size = mesh.shape[DATA_AXIS]

    def body_fn(binned, target, rmasks, fmasks, mi_k, mg_k, *rest):
        if has_subsets:
            subset_n, subset_w = rest[-2:]
            rest = rest[:-2]
        else:
            subset_n = subset_w = None
        grp = rest if rest else None
        k_fits = rmasks.shape[1]
        n_local = binned.shape[0]
        gb = jnp.broadcast_to(-target[None, :], (k_fits, n_local))
        ones = jnp.ones((k_fits, n_local), dtype=jnp.float32)

        def one_tree(_, xs):
            rm_t, fm_t, sn, sw = xs
            tree, node = _grow_tree_impl(
                binned, gb, ones, rm_t, fm_t,
                max_depth=max_depth, num_bins=num_bins,
                reg_lambda=0.0, gamma=0.0,
                min_child_weight=mi_k, min_info_gain=mg_k,
                hist_impl=hist_impl, lowp=lowp,
                axis_name=DATA_AXIS, axis_size=size,
                feature_groups=(sn, sw) if sn is not None else grp,
            )
            pred_t = _small_table_lookup(tree.leaf_value, node)
            return None, (tree, pred_t)

        _, (trees, preds) = jax.lax.scan(
            one_tree, None, (rmasks, fmasks, subset_n, subset_w)
        )
        outs = preds.mean(axis=0)  # [K, n_local]
        return jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), trees), outs

    rep = P()
    sm = shard_map(
        body_fn,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None),        # binned [N, F]
            P(DATA_AXIS),              # target [N]
            P(None, None, DATA_AXIS),  # rmasks [T, K, N]
            rep,                       # fmasks [T, K, F]
            rep, rep,
        ) + ((rep, rep) if has_groups else ())
          + ((rep, rep) if has_subsets else ()),
        out_specs=(
            Tree(split_feat=rep, split_bin=rep, leaf_value=rep),
            P(None, DATA_AXIS),
        ),
        check_vma=False,
    )
    return jax.jit(sm)


def _fit_forest_batched_sharded(
    mesh, binned, target, row_mask, tkeys, sub, col, mi, mg,
    num_trees, max_depth, num_bins, bootstrap, lowp, feature_groups=None,
    subset_n=None, subset_w=None,
) -> tuple[Tree, np.ndarray]:
    from ..parallel.mesh import DATA_AXIS

    size = mesh.shape[DATA_AXIS]
    k_fits, n = row_mask.shape
    f = binned.shape[1]
    binned_p = _pad_axis(jnp.asarray(binned, jnp.int32), 0, size)
    target_p = _pad_axis(jnp.asarray(target, jnp.float32), 0, size)
    rm = jnp.asarray(row_mask, jnp.float32)
    # masks drawn over the UNPADDED n — bit-identical to the single-device
    # draw — then padded with zeros; [T, K, N] rides the scan axis
    rmasks, fmasks = jax.vmap(
        lambda tk: _bag_masks(tk, sub, col, rm, n=n, f=f, bootstrap=bootstrap)
    )(tkeys)
    rmasks = _pad_axis(rmasks, 2, size)
    mi_k = jnp.broadcast_to(jnp.asarray(mi, jnp.float32).reshape(-1), (k_fits,))
    mg_k = jnp.broadcast_to(jnp.asarray(mg, jnp.float32).reshape(-1), (k_fits,))
    kern = _sharded_forest_scan_kernel(
        mesh, max_depth, num_bins, _resolved_impl(), lowp,
        has_groups=feature_groups is not None,
        has_subsets=subset_n is not None,
    )
    grp_args = tuple(feature_groups) if feature_groups is not None else ()
    if subset_n is not None:
        grp_args = grp_args + (subset_n, subset_w)
    trees, outs = kern(binned_p, target_p, rmasks, fmasks, mi_k, mg_k,
                       *grp_args)
    # pull replicated trees to HOST once (memory: xla-cpu-mesh-gotchas)
    return (
        jax.tree.map(lambda a: np.asarray(a), trees),
        np.asarray(outs)[:, :n],
    )


@lru_cache(maxsize=None)
def _sharded_boost_kernel(mesh, num_rounds, max_depth, num_bins, objective,
                          hist_impl=None, has_groups=False):
    """jit(shard_map(boost-round-chunk)): margins stay row-sharded across
    the scan; each round's histogram build psums over the data axis."""
    from ..parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    size = mesh.shape[DATA_AXIS]

    def body(binned, y, row_mask, margin0, eta_v, lam, gam, mcw, mig,
             *grp):
        return _boost_chunk_body(
            binned, y, row_mask, margin0, eta_v, lam, gam, mcw, mig,
            grp if grp else None,
            num_rounds=num_rounds, max_depth=max_depth, num_bins=num_bins,
            objective=objective, axis_name=DATA_AXIS, axis_size=size,
            hist_impl=hist_impl,
        )

    rep = P()
    sm = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None),   # binned
            P(DATA_AXIS),         # y
            P(None, DATA_AXIS),   # row_mask
            P(None, DATA_AXIS),   # margin0
            rep, rep, rep, rep, rep,
        ) + ((rep, rep) if has_groups else ()),
        out_specs=(
            Tree(split_feat=rep, split_bin=rep, leaf_value=rep),
            P(None, DATA_AXIS),
        ),
        check_vma=False,
    )
    return jax.jit(sm)


def _fit_boosted_batched_sharded(
    mesh, binned, y, row_mask, eta_v, lam, gam, mcw, mig,
    base_score, num_rounds, max_depth, num_bins, objective,
    feature_groups=None,
) -> tuple[Tree, jax.Array]:
    from ..parallel.mesh import DATA_AXIS

    size = mesh.shape[DATA_AXIS]
    k_fits, n = row_mask.shape
    binned_p = _pad_axis(jnp.asarray(binned, jnp.int32), 0, size)
    y_p = _pad_axis(jnp.asarray(y, jnp.float32), 0, size)
    rm_p = _pad_axis(jnp.asarray(row_mask, jnp.float32), 1, size)
    n_pad = binned_p.shape[0]
    margin = jnp.broadcast_to(
        jnp.asarray(base_score, dtype=jnp.float32).reshape(-1, 1),
        (k_fits, n_pad),
    ).astype(jnp.float32)
    lam = jnp.asarray(lam, jnp.float32).reshape(-1)
    gam = jnp.asarray(gam, jnp.float32).reshape(-1)
    mcw = jnp.asarray(mcw, jnp.float32).reshape(-1)
    mig = jnp.asarray(mig, jnp.float32).reshape(-1)
    chunks = []
    done = 0
    chunk_size = _boost_round_chunk(num_rounds)
    while done < num_rounds:
        rc = min(chunk_size, num_rounds - done)
        kern = _sharded_boost_kernel(mesh, rc, max_depth, num_bins, objective,
                                     _resolved_impl(),
                                     has_groups=feature_groups is not None)
        grp_args = tuple(feature_groups) if feature_groups is not None else ()
        trees_c, margin = kern(
            binned_p, y_p, rm_p, margin, eta_v, lam, gam, mcw, mig, *grp_args
        )
        # host-fetch each chunk's replicated trees — eager multi-device
        # reshapes intermittently abort the XLA:CPU async runtime (memory:
        # xla-cpu-mesh-gotchas); margin stays DEVICE-resident as the next
        # chunk's carry. Chunks are [K, rc, ...] (swap happens in-jit).
        chunks.append(jax.tree.map(lambda a: np.asarray(a), trees_c))
        done += rc
    trees = jax.tree.map(lambda *xs: np.concatenate(xs, axis=1), *chunks)
    return trees, np.asarray(margin)[:, :n]


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py, TPJ0xx)
# --------------------------------------------------------------------------
def program_trace_specs():
    """Representative trace shapes for the banked fit-time tree programs
    (the boosting-round chunk and the bagged-forest scan). The bucketed
    axis is the fit-lane count K; rounds/trees/depth stay tiny — jaxpr
    structure is independent of them (they only change scan lengths)."""
    import jax

    f32, i32 = "float32", "int32"

    def _common(k: int):
        return (
            jax.ShapeDtypeStruct((16, 3), i32),   # binned
            jax.ShapeDtypeStruct((16,), f32),     # y / target
            jax.ShapeDtypeStruct((k, 16), f32),   # row_mask
        )

    def _boost(k: int):
        binned, y, rm = _common(k)
        s = jax.ShapeDtypeStruct((), f32)
        return (
            (
                binned, y, rm,
                jax.ShapeDtypeStruct((k, 16), f32),  # margin (donated)
                jax.ShapeDtypeStruct((k,), f32),     # eta_v
                s, s, s, s,                          # lam, gam, mcw, mig
                None,                                # feature_groups
            ),
            dict(
                num_rounds=2, max_depth=2, num_bins=4,
                objective="binary:logistic", hist_impl=_resolved_impl(),
            ),
        )

    def _forest(k: int):
        binned, target, rm = _common(k)
        s = jax.ShapeDtypeStruct((), f32)
        return (
            (
                binned, target, rm,
                jax.ShapeDtypeStruct((1,), "uint32"),  # seed_arr
                jax.ShapeDtypeStruct((k,), f32),       # sub
                jax.ShapeDtypeStruct((k,), f32),       # col
                s, s,                                  # mi, mg
                None, None, None, None,
            ),
            dict(
                num_trees=2, max_depth=2, num_bins=4, bootstrap=True,
                lowp=False, hist_impl=_resolved_impl(),
            ),
        )

    return [
        dict(
            name="boost_chunk",
            fn=_boost_rounds_batched,
            base_fn=_boost_chunk_body,
            build=_boost,
            buckets=(4, 8), bucket_axis="lanes",
            donate_argnums=(3,),
            static_argnames=(
                "num_rounds", "max_depth", "num_bins", "objective",
                "axis_name", "axis_size", "hist_impl",
            ),
        ),
        dict(
            name="forest_scan",
            fn=_forest_trees_scan,
            build=_forest,
            buckets=(4, 8), bucket_axis="lanes",
        ),
    ]
