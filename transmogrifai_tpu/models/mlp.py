"""Multilayer perceptron classifier.

Reference: core/.../stages/impl/classification/OpMultilayerPerceptronClassifier.scala
(wraps Spark MLP: sigmoid hidden layers + softmax output, full-batch L-BFGS
over native BLAS). TPU-native: a jitted full-batch Adam loop (``lax.scan``)
over bf16-friendly matmuls; data-parallel scaling shards the batch over the
mesh 'data' axis and gradients reduce with psum (see parallel/).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .base import PredictorEstimator, PredictorModel


def _init_params(key, sizes: Sequence[int]):
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        fan_in, fan_out = sizes[i], sizes[i + 1]
        w = jax.random.normal(sub, (fan_in, fan_out)) * jnp.sqrt(2.0 / fan_in)
        params.append({"w": w, "b": jnp.zeros(fan_out)})
    return params


def _matmul(h, layer, compute_dtype):
    """Layer matmul; with a low-precision compute dtype the operands ride
    the MXU in bf16 while accumulation and bias stay f32 (the standard TPU
    mixed-precision recipe — params and optimizer state remain f32)."""
    if compute_dtype is None:
        return h @ layer["w"] + layer["b"]
    dot = jax.lax.dot(
        h.astype(compute_dtype),
        layer["w"].astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return dot + layer["b"]


def _forward(params, x, compute_dtype=None):
    h = x
    for layer in params[:-1]:
        # Spark MLP uses sigmoid hidden activations
        h = jax.nn.sigmoid(_matmul(h, layer, compute_dtype))
    return _matmul(h, params[-1], compute_dtype)


@partial(jax.jit, static_argnames=("sizes", "num_iters", "compute_dtype"))
def _train_mlp(x, y1h, row_mask, sizes, num_iters, step_size, seed,
               compute_dtype=None):
    cd = jnp.dtype(compute_dtype) if compute_dtype else None
    params = _init_params(jax.random.PRNGKey(seed), sizes)
    opt = optax.adam(step_size)
    opt_state = opt.init(params)
    n = jnp.maximum(row_mask.sum(), 1.0)

    def loss_fn(p):
        logits = _forward(p, x, cd)
        ll = optax.softmax_cross_entropy(logits, y1h) * row_mask
        return ll.sum() / n

    def step(carry, _):
        p, s = carry
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return (p, s), loss

    (params, _), losses = jax.lax.scan(step, (params, opt_state), None, length=num_iters)
    return params, losses


class MLPClassifierModel(PredictorModel):
    def __init__(self, params, num_classes: int, uid=None):
        super().__init__("mlp", uid=uid)
        # params stay DEVICE-resident (prediction runs there anyway);
        # downloading them eagerly cost ~1.6 s of the wide bench's fit
        # over the tunneled link — persistence pulls lazily via get_arrays
        self.params = list(params)
        self.num_classes = num_classes

    def get_arrays(self):
        out = {}
        for i, l in enumerate(self.params):
            out[f"w{i}"] = np.asarray(l["w"])
            out[f"b{i}"] = np.asarray(l["b"])
        return out

    def get_params(self):
        return {"num_classes": self.num_classes,
                "layer_sizes": [int(l["w"].shape[0]) for l in self.params]
                + [int(self.params[-1]["w"].shape[1])]}

    @classmethod
    def from_params(cls, params, arrays):
        layers = []
        i = 0
        while f"w{i}" in arrays:
            layers.append({"w": arrays[f"w{i}"], "b": arrays[f"b{i}"]})
            i += 1
        return cls(layers, params["num_classes"])

    def predict_arrays(self, x: np.ndarray):
        logits = np.asarray(_forward(self.params, jnp.asarray(x, dtype=jnp.float32)))
        logits64 = logits.astype(np.float64)
        shifted = logits64 - logits64.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        prob = e / e.sum(axis=1, keepdims=True)
        return prob.argmax(axis=1).astype(np.float64), prob, logits64


class MLPClassifier(PredictorEstimator):
    """Spark MLP defaults: maxIter=100, stepSize=0.03 (we default Adam 1e-2),
    hidden layers user-specified (Spark requires explicit layers)."""

    model_type = "OpMultilayerPerceptronClassifier"

    def __init__(
        self,
        hidden_layers: Sequence[int] = (10,),
        max_iter: int = 100,
        step_size: float = 0.01,
        seed: int = 42,
        compute_dtype: str | None = None,
        uid: str | None = None,
    ):
        super().__init__("mlp", uid=uid)
        self.hidden_layers = tuple(hidden_layers)
        self.max_iter = max_iter
        self.step_size = step_size
        self.seed = seed
        #: e.g. "bfloat16": matmuls ride the MXU in bf16 with f32
        #: accumulation; params/optimizer state stay f32 (mixed precision)
        self.compute_dtype = compute_dtype

    def get_params(self):
        return {
            "hidden_layers": list(self.hidden_layers),
            "max_iter": self.max_iter,
            "step_size": self.step_size,
            "seed": self.seed,
            "compute_dtype": self.compute_dtype,
        }

    def fit_arrays(self, x, y, row_mask):
        from ..parallel.mesh import (
            data_row_multiple,
            pad_rows,
            shard_rows_if_active,
        )

        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        sizes = (x.shape[1], *self.hidden_layers, num_classes)
        # join the row-partitioned substrate (SURVEY §2.6): rows shard over
        # the ambient mesh's data axis; GSPMD propagates the sharding
        # through the scan body and psums the gradients over ICI. Mask-0
        # padding rows are inert (loss is mask-weighted, n = mask.sum()).
        # Device-resident inputs that need no padding stay on device — a
        # host pad of the wide bench's 512 MB x would round-trip it over
        # the tunneled link (measured ~26 s of a 32 s fit).
        mult = data_row_multiple()
        if x.shape[0] % mult:
            x, _ = pad_rows(np.asarray(x, dtype=np.float32), mult)
            y, _ = pad_rows(np.asarray(y, dtype=np.float32), mult)
            row_mask, _ = pad_rows(
                np.asarray(row_mask, dtype=np.float32), mult
            )
        y1h = jax.nn.one_hot(
            jnp.asarray(y).astype(jnp.int32), num_classes, dtype=jnp.float32
        )
        params, losses = _train_mlp(
            shard_rows_if_active(jnp.asarray(x, dtype=jnp.float32)),
            y1h,
            jnp.asarray(row_mask, dtype=jnp.float32),
            sizes,
            int(self.max_iter),
            float(self.step_size),
            int(self.seed),
            compute_dtype=self.compute_dtype,
        )
        self.metadata["finalLoss"] = float(np.asarray(losses)[-1])
        return MLPClassifierModel(params, num_classes)
