"""Pallas TPU kernel: serve-side vectorized multi-tree traversal.

Serving a fitted ensemble is a traversal, not a matmul: ``predict_tree``
walks the level arrays with per-row gathers (``lax.scan`` over levels of
small-table lookups), which lowers to serialized dynamic-gathers on TPU —
fine at fit time where the histogram build dominates, but at serve time
the traversal IS the program. This kernel reformulates the whole
ensemble's traversal as level-synchronous one-hot linear algebra over the
quantized/binned plane, the same trick the fit-side histogram kernel
(``models/hist_pallas.py``) uses for its scatter:

    code[r, t, m]  = Σ_f binned[r, f] · 1[split_feat[t, l, m] = f]   (MXU)
    right[r, t, m] = 1[code > split_bin] · 1[split_feat ≥ 0]          (VPU)
    p_{l+1}[r, t, 2m + right] = p_l[r, t, m] · selector               (VPU)

i.e. per level one [R, F] x [F, Tt·2^l] matmul routes every (row, tree)
pair one level down; after ``depth`` levels the node one-hot ``p`` picks
each row's leaf in one fused multiply-reduce against the leaf table. All
arithmetic is exact (one-hots and small-int codes in f32), so predictions
are BIT-IDENTICAL to the gather traversal — parity is pinned by the
interpret-mode CPU twin in the unit tests, the same twin pattern as
``hist_pallas``.

Grid: (row tiles, tree tiles); each program touches one [row_tile, F]
code block and one tree tile's level arrays, VMEM-budgeted like the
fit-side kernels (~6 MB model, Mosaic double-buffering headroom
included). Padded rows produce garbage sliced off by the wrapper; padded
trees carry ``split_feat = -1`` and a zero leaf table so they contribute
exactly 0 to every sum.

``serve_impl()`` picks the implementation (env ``TPTPU_SERVE_TREES``
overrides; Pallas on real TPU backends, the gather scan elsewhere), and
``program_trace_specs()`` registers the kernel with the TPJ program bank
gate so admissions get bucket-stable fingerprints like every other
serving program.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _serve_kernel(codes_ref, sf_ref, sb_ref, lv_ref, out_ref, *, depth,
                  leaf_w):
    """One (row-tile, tree-tile) step: route the block's rows through the
    tile's trees level-by-level and emit per-(row, tree) leaf values."""
    import jax.lax as lax

    codes = codes_ref[...]                      # [R, F] f32 (exact ints)
    r, f = codes.shape
    tt = sf_ref.shape[0]
    noh = jnp.ones((r, tt, 1), jnp.float32)     # node one-hot, root only
    for lvl in range(depth):
        nl = 1 << lvl
        sf_l = sf_ref[:, lvl, :nl]              # [Tt, nl] int32 (-1 leaf)
        sb_l = sb_ref[:, lvl, :nl]
        # per-(tree, node) feature one-hot; sf = -1 selects nothing
        g = (
            sf_l[:, :, None]
            == lax.broadcasted_iota(jnp.int32, (tt, nl, f), 2)
        ).astype(jnp.float32)
        # routed code per (row, tree, node) — ONE MXU dot per level
        c = lax.dot_general(
            codes, g.reshape(tt * nl, f),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(r, tt, nl)
        go_right = (
            (c > sb_l[None, :, :].astype(jnp.float32))
            & (sf_l[None, :, :] >= 0)
        ).astype(jnp.float32)
        # children interleave [left0, right0, left1, right1, ...] —
        # exactly node·2 + go_right of the gather traversal
        noh = jnp.stack(
            [noh * (1.0 - go_right), noh * go_right], axis=-1
        ).reshape(r, tt, 2 * nl)
    out_ref[...] = jnp.sum(noh * lv_ref[:, :leaf_w][None, :, :], axis=-1)


@functools.partial(
    jax.jit, static_argnames=("row_tile", "tree_tile", "interpret")
)
def serve_trees_pallas(
    binned: jax.Array,      # [N, F] int32 bin codes (bin_data output)
    split_feat: jax.Array,  # [T, depth, 2^depth] int32, -1 = leaf
    split_bin: jax.Array,   # [T, depth, 2^depth] int32
    leaf_value: jax.Array,  # [T, 2^depth] f32
    row_tile: int | None = None,
    tree_tile: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Per-tree leaf value for every row -> [N, T] f32, bit-identical to
    ``vmap(predict_tree)``. Callers reduce (sum for boosting, mean for
    forests) outside — the reduction is where the families differ."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = binned.shape
    t, depth, w = split_feat.shape
    leaf_w = int(leaf_value.shape[1])
    f_pad = _round_up(max(f, 8), 8)
    w_pad = _round_up(w, 128)

    if row_tile is None:
        row_tile = 256
    if tree_tile is None:
        # big VMEM temporaries per program: the [R, Tt, 2^depth] node
        # one-hot pair, the level-max [Tt, 2^(depth-1), F] feature
        # one-hot, and the tree tile's level arrays — budget ~6 MB
        # (Mosaic double-buffers blocks; measured safe for the fit-side
        # kernels at this model)
        def vmem(tt: int) -> int:
            return (
                row_tile * f_pad * 4
                + tt * w_pad * (2 * depth + 1) * 4
                + 3 * row_tile * tt * w * 4
                + tt * max(w // 2, 1) * f_pad * 4
            )

        tree_tile = 8
        while tree_tile * 2 <= _round_up(t, 8) and vmem(tree_tile * 2) <= (
            6 << 20
        ):
            tree_tile *= 2
        while vmem(tree_tile) > (6 << 20) and row_tile > 64:
            row_tile //= 2
    n_pad = _round_up(max(n, row_tile), row_tile)
    t_pad = _round_up(max(t, tree_tile), tree_tile)

    codes_p = jnp.zeros((n_pad, f_pad), jnp.float32)
    codes_p = codes_p.at[:n, :f].set(binned.astype(jnp.float32))
    sf_p = jnp.full((t_pad, depth, w_pad), -1, jnp.int32)
    sf_p = sf_p.at[:t, :, :w].set(split_feat)
    sb_p = jnp.zeros((t_pad, depth, w_pad), jnp.int32)
    sb_p = sb_p.at[:t, :, :w].set(split_bin)
    lv_p = jnp.zeros((t_pad, w_pad), jnp.float32)
    lv_p = lv_p.at[:t, :leaf_w].set(leaf_value)

    grid = (n_pad // row_tile, t_pad // tree_tile)
    out = pl.pallas_call(
        functools.partial(_serve_kernel, depth=depth, leaf_w=leaf_w),
        out_shape=jax.ShapeDtypeStruct((n_pad, t_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (row_tile, f_pad), lambda i, j: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tree_tile, depth, w_pad), lambda i, j: (j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tree_tile, depth, w_pad), lambda i, j: (j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (tree_tile, w_pad), lambda i, j: (j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (row_tile, tree_tile), lambda i, j: (i, j),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
    )(codes_p, sf_p, sb_p, lv_p)
    return out[:n, :t]


def predict_forest_pallas(binned, trees, interpret: bool = False):
    """Mean leaf value across the stacked forest -> [N] (the
    ``predict_forest`` contract over the Pallas traversal)."""
    per_tree = serve_trees_pallas(
        binned, trees.split_feat, trees.split_bin, trees.leaf_value,
        interpret=interpret,
    )
    return per_tree.mean(axis=1)


def predict_boosted_pallas(binned, trees, eta, base_score,
                           interpret: bool = False):
    """base + eta·Σ rounds -> [N] (the ``predict_boosted`` contract)."""
    per_tree = serve_trees_pallas(
        binned, trees.split_feat, trees.split_bin, trees.leaf_value,
        interpret=interpret,
    )
    return base_score + eta * per_tree.sum(axis=1)


def serve_impl() -> str:
    """'pallas' on real TPU backends, 'gather' (the lax.scan traversal)
    elsewhere; env ``TPTPU_SERVE_TREES`` forces either. CPU callers that
    force 'pallas' run the kernel in interpret mode — the CPU twin the
    unit tests pin parity with."""
    import os

    forced = os.environ.get("TPTPU_SERVE_TREES")
    if forced:
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "gather"


def serve_interpret() -> bool:
    """Interpret-mode flag for the current backend (True off-TPU)."""
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py, TPJ0xx)
# --------------------------------------------------------------------------
def program_trace_specs():
    """The serve-side traversal kernel over a representative small
    ensemble, bucketed on the BATCH axis like the fused serving programs
    (TPJ bank gate + TPJ005 bucket-fingerprint stability)."""
    i32, f32 = "int32", "float32"
    depth, w, t, f = 3, 8, 5, 6

    def _build(n: int):
        return (
            (
                jax.ShapeDtypeStruct((n, f), i32),
                jax.ShapeDtypeStruct((t, depth, w), i32),
                jax.ShapeDtypeStruct((t, depth, w), i32),
                jax.ShapeDtypeStruct((t, w), f32),
            ),
            dict(row_tile=128, tree_tile=8, interpret=True),
        )

    return [
        dict(
            name="serve_trees",
            fn=serve_trees_pallas,
            build=_build,
            buckets=(8, 16),
            static_argnames=("row_tile", "tree_tile", "interpret"),
            scoring=True,
        ),
    ]
