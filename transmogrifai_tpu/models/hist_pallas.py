"""Pallas TPU kernel: per-node gradient histograms for tree growth.

The histogram build is THE hot op of histogram GBDT (the reference runs it
in libxgboost's C++ core, SURVEY.md §2.5 item 1). The XLA scatter-add in
models/trees.py lowers to a serialized sort/scatter on TPU; this kernel
reformulates the build as matmuls so it runs on the MXU:

    hist[m, f, b] = Σ_r 1[node_r = m] · 1[binned_{r,f} = b] · v_r
                  = (NodeOneHot · v)ᵀ @ BinOneHot_f        per feature f

i.e. for every feature an [M, T] x [T, B] matmul over row tiles T — dense
systolic-array work instead of scattered memory traffic. Grad and hess are
two value columns of the same one-hot product.

Grid: (F, N/T). The output block for feature f is revisited across row
tiles (accumulation pattern: init at j==0, add afterwards). Padded rows
carry node = -1 → their one-hot row is all zero → no contribution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


FEAT_TILE = 8  # features per program (TPU sublane granule)


def _hist_pack(num_bins: int) -> tuple[int, int]:
    """(pack, sub_lanes): features sharing one 128-lane bin axis. Lane
    sub·S + bin holds feature-sub's bin count, so one dot builds ``pack``
    features' histograms — a pack× FLOP cut over one-feature-per-dot."""
    if num_bins <= 32:
        return 4, 32
    if num_bins <= 64:
        return 2, 64
    if num_bins <= 128:
        return 1, 128
    return 1, _round_up(num_bins, 128)


def _hist_kernel(binned_ref, node_ref, g_ref, h_ref, outg_ref, outh_ref,
                 *, m_pad, b_pad, pack, sub_lanes, lowp, feat_tile,
                 comb="base"):
    """One (fit, feature-tile, row-tile) step: accumulate grad/hess
    histograms for one batched fit (separate outputs — a trailing dim of 2
    would be tile-padded to 128 and blow VMEM). Output lanes are PACKED:
    lane sub·S + bin of group q is (feature q·pack+sub, bin) — the wrapper
    unpacks with one reshape/transpose.

    Precision: the one-hots are bf16-exact; the value operand splits into
    hi/lo bf16 halves (wg == hi + lo to ~2^-17 relative) so the dots run
    single-pass at the full bf16 MXU rate with f32 accumulation instead of
    the 6-pass f32 HIGHEST schedule — measured 6-8x on the 1M-row build.
    ``lowp`` callers assert values are ALREADY bf16-exact (RF indicators)
    and skip the lo half.

    The batch (fit) axis is a GRID dimension, not a vmap: Mosaic custom
    calls crash this TPU runtime under vmap, and a grid axis reuses the same
    VMEM working set per step anyway."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    j = pl.program_id(2)

    nodes = node_ref[0, 0, :]    # [T] int32 (-1 = padded/dead row)
    g = g_ref[0, 0, :]           # [T] f32
    h = h_ref[0, 0, :]           # [T] f32
    t = nodes.shape[0]

    # stack built DIRECTLY in the [T, nvar·M] lane space — a bf16 concat of
    # M-lane pieces costs lane-shift relayouts per step; here one compare
    # against (iota mod M) plus variant-selects assembles the same operand
    nvar = 2 if lowp else 4
    iota_s = lax.broadcasted_iota(jnp.int32, (t, nvar * m_pad), 1)
    m_lane = iota_s % m_pad
    variant = iota_s // m_pad
    oh = nodes[:, None] == m_lane                         # [T, nvar·M]
    if lowp:
        val = jnp.where(variant == 0, g[:, None], h[:, None])
    else:
        g_hi = g.astype(jnp.bfloat16).astype(jnp.float32)
        g_lo = g - g_hi
        h_hi = h.astype(jnp.bfloat16).astype(jnp.float32)
        h_lo = h - h_hi
        val = jnp.where(
            variant == 0, g_hi[:, None],
            jnp.where(
                variant == 1, g_lo[:, None],
                jnp.where(variant == 2, h_hi[:, None], h_lo[:, None]),
            ),
        )
    stack = jnp.where(oh, val, 0.0).astype(jnp.bfloat16)
    iota_b = lax.broadcasted_iota(jnp.int32, (t, b_pad), 1)
    contract = (((0,), (0,)), ((), ()))  # contract the row-tile axis

    for q in range(feat_tile // pack):
        # ONE compare per group: broadcast each sub-feature's codes onto its
        # own lane segment with nested selects, then a single 128-lane
        # equality — the per-sub compare+convert+add loop was the VPU cost
        # that dominated the whole build (trace: 18.0 of 18.6 s at 1M x 500).
        # comb='const' is a timing probe (wrong results) isolating the
        # dot+stack cost from the comb construction; round-5 measured the
        # chain at 333 of 408 ms per 1M×500×32 build, which motivated the
        # bin-loop kernel below (the default for ≤64 bins).
        if comb == "const":
            comb_oh = jnp.full((t, b_pad), jnp.bfloat16(1.0))
        else:
            code_b = binned_ref[q * pack + 0, :][:, None]
            for sub in range(1, pack):
                seg = binned_ref[q * pack + sub, :][:, None] + sub * sub_lanes
                code_b = jnp.where(iota_b < sub * sub_lanes, code_b, seg)
            comb_oh = (code_b == iota_b).astype(jnp.bfloat16)
        out = lax.dot_general(
            stack, comb_oh, contract,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.DEFAULT,
        )  # [nvar·M, b_pad]
        if lowp:
            hg = out[:m_pad]
            hh = out[m_pad:]
        else:
            hg = out[:m_pad] + out[m_pad:2 * m_pad]
            hh = out[2 * m_pad:3 * m_pad] + out[3 * m_pad:]

        @pl.when(j == 0)
        def _(q=q, hg=hg, hh=hh):
            outg_ref[0, q, :, :] = hg
            outh_ref[0, q, :, :] = hh

        @pl.when(j > 0)
        def _(q=q, hg=hg, hh=hh):
            outg_ref[0, q, :, :] = outg_ref[0, q, :, :] + hg
            outh_ref[0, q, :, :] = outh_ref[0, q, :, :] + hh


def build_histogram_pallas_batched(
    binned, node, grad, hess, num_nodes, num_bins,
    row_tile=None, lowp=False, interpret=False, comb=None,
):
    """hist [K, num_nodes, F, num_bins, 2] via the MXU one-hot formulation
    (bin-axis packing + hi/lo bf16 value split — see _hist_kernel).

    K batched fits (grid points × CV folds) share one binned matrix; the fit
    axis rides the kernel grid, so the whole hyperparameter sweep's
    histograms build in one custom call.

    ``comb``: 'base' (default) or 'const' (a timing probe producing WRONG
    results — isolates dot+stack cost from comb construction). The
    TPTPU_HIST_COMB env knob is resolved HERE, outside the traced body, so
    the jit cache keys on the resolved string (an env change between calls
    can never serve a stale trace), and the knob also salts the AOT bank
    (utils/aot.py) so probe executables cannot leak across processes."""
    if comb is None:
        import os

        comb = os.environ.get("TPTPU_HIST_COMB", "base")
    return _build_histogram_pallas_batched(
        binned, node, grad, hess, num_nodes, num_bins,
        row_tile=row_tile, lowp=lowp, interpret=interpret, comb=comb,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_nodes", "num_bins", "row_tile", "lowp", "interpret", "comb",
    ),
)
def _build_histogram_pallas_batched(
    binned: jax.Array,   # [N, F] int32 codes in [0, num_bins), SHARED
    node: jax.Array,     # [K, N] int32 node slot per row per fit (-1 = dead)
    grad: jax.Array,     # [K, N] f32 (pre-masked)
    hess: jax.Array,     # [K, N] f32
    num_nodes: int,
    num_bins: int,
    row_tile: int | None = None,
    lowp: bool = False,
    interpret: bool = False,
    comb: str = "base",
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k_fits, n = node.shape
    f = binned.shape[1]
    m_pad = _round_up(max(num_nodes, 8), 8)
    pack, sub_lanes = _hist_pack(num_bins)
    b_pad = pack * sub_lanes
    nvar = 2 if lowp else 4
    if row_tile is None:
        # the kernel's big VMEM temporaries are the [T, M] node one-hot,
        # its value-weighted copies, and the [T, nvar·M] stacked operand —
        # shrink the row tile as the node axis grows so T·nvar·M stays
        # bounded; lane-align to 128 (Mosaic trailing-block constraint)
        row_tile = max(
            128, min(4096, ((1 << 20) // (nvar * m_pad)) // 128 * 128)
        )

    def vmem_bytes(ft: int) -> int:
        # binned block + two output accumulators + the stacked bf16 value
        # operand + node one-hot / weighted copies / comb one-hot
        return (
            ft * row_tile * 4
            + 2 * (ft // pack) * m_pad * b_pad * 4
            + row_tile * nvar * m_pad * 2
            + row_tile * (3 * m_pad * 4 + 2 * b_pad * 2)
        )

    # feature tile: as many features per grid step as scoped VMEM (~16 MB,
    # budget 12 MB for headroom) allows — small tiles multiply grid steps,
    # and every step rebuilds the [T, nvar·M] value stack (measured
    # 74 -> 16 ms/level at 1M x 64 going from 8-feature steps to 64)
    feat_tile = FEAT_TILE
    while (
        feat_tile * 2 <= _round_up(f, FEAT_TILE)
        and vmem_bytes(feat_tile * 2) <= (12 << 20)
    ):
        feat_tile *= 2
    while vmem_bytes(feat_tile) > (12 << 20) and row_tile > 512:
        row_tile //= 2
    n_pad = _round_up(max(n, row_tile), row_tile)
    f_pad = _round_up(f, feat_tile)
    groups = f_pad // pack

    binned_t = jnp.zeros((f_pad, n_pad), dtype=jnp.int32)
    binned_t = binned_t.at[:f, :n].set(binned.T)
    # per-fit row vectors get a singleton sublane axis [K, 1, n_pad] so the
    # (1, row_tile) trailing block dims satisfy Mosaic's tiling constraint
    node_p = jnp.full((k_fits, 1, n_pad), -1, dtype=jnp.int32).at[:, 0, :n].set(node)
    g_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(grad)
    h_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(hess)

    num_row_tiles = n_pad // row_tile
    grid = (k_fits, f_pad // feat_tile, num_row_tiles)
    groups_per_tile = feat_tile // pack

    out_g, out_h = pl.pallas_call(
        functools.partial(
            _hist_kernel, m_pad=m_pad, b_pad=b_pad, pack=pack,
            sub_lanes=sub_lanes, lowp=lowp, feat_tile=feat_tile, comb=comb,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k_fits, groups, m_pad, b_pad), jnp.float32),
            jax.ShapeDtypeStruct((k_fits, groups, m_pad, b_pad), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (feat_tile, row_tile), lambda k, i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, groups_per_tile, m_pad, b_pad),
                lambda k, i, j: (k, i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, groups_per_tile, m_pad, b_pad),
                lambda k, i, j: (k, i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        interpret=interpret,
    )(binned_t, node_p, g_p, h_p)

    # unpack lanes: [K, G, M, pack·S] -> [K, G, M, pack, S] -> [K, F, M, B]
    def unpack(a):
        a = a.reshape(k_fits, groups, m_pad, pack, sub_lanes)
        a = jnp.transpose(a, (0, 1, 3, 2, 4))
        return a.reshape(k_fits, f_pad, m_pad, sub_lanes)

    out = jnp.stack([unpack(out_g), unpack(out_h)], axis=-1)
    return jnp.transpose(out[:, :f, :num_nodes, :num_bins, :], (0, 2, 1, 3, 4))


def _hist_binloop_kernel(binned_ref, node_ref, g_ref, h_ref, outg_ref,
                         outh_ref, *, m_pad, num_bins, lowp):
    """Bin-loop histogram step: one whole-block compare per bin instead of
    the per-group select-chain assembly. The comb construction drops from
    ~5 VPU ops per one-hot element to 2 (compare + convert) — the
    select-chain was measured at 333 ms of the 408 ms level cost at
    1M×500×32 (comb='const' probe). Layout: binned block [feat_tile, T]
    (features on sublanes), stack [T, nvar·M]; per bin b the dot
    [feat_tile, T] @ [T, nvar·M] emits that bin's [feat_tile, nvar·M]
    plane, written at a static outermost index."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    j = pl.program_id(2)

    nodes = node_ref[0, 0, :]
    g = g_ref[0, 0, :]
    h = h_ref[0, 0, :]
    t = nodes.shape[0]

    nvar = 2 if lowp else 4
    iota_s = lax.broadcasted_iota(jnp.int32, (t, nvar * m_pad), 1)
    m_lane = iota_s % m_pad
    variant = iota_s // m_pad
    oh = nodes[:, None] == m_lane
    if lowp:
        val = jnp.where(variant == 0, g[:, None], h[:, None])
    else:
        g_hi = g.astype(jnp.bfloat16).astype(jnp.float32)
        g_lo = g - g_hi
        h_hi = h.astype(jnp.bfloat16).astype(jnp.float32)
        h_lo = h - h_hi
        val = jnp.where(
            variant == 0, g_hi[:, None],
            jnp.where(
                variant == 1, g_lo[:, None],
                jnp.where(variant == 2, h_hi[:, None], h_lo[:, None]),
            ),
        )
    stack = jnp.where(oh, val, 0.0).astype(jnp.bfloat16)
    codes = binned_ref[...]  # [feat_tile, T] int32
    contract = (((1,), (0,)), ((), ()))  # contract the row-tile axis

    for b in range(num_bins):
        comb = (codes == b).astype(jnp.bfloat16)  # [feat_tile, T]
        out = lax.dot_general(
            comb, stack, contract,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.DEFAULT,
        )  # [feat_tile, nvar·M]
        if lowp:
            hg = out[:, :m_pad]
            hh = out[:, m_pad:]
        else:
            hg = out[:, :m_pad] + out[:, m_pad:2 * m_pad]
            hh = out[:, 2 * m_pad:3 * m_pad] + out[:, 3 * m_pad:]

        @pl.when(j == 0)
        def _(b=b, hg=hg, hh=hh):
            outg_ref[0, b, :, :] = hg
            outh_ref[0, b, :, :] = hh

        @pl.when(j > 0)
        def _(b=b, hg=hg, hh=hh):
            outg_ref[0, b, :, :] = outg_ref[0, b, :, :] + hg
            outh_ref[0, b, :, :] = outh_ref[0, b, :, :] + hh


@functools.partial(
    jax.jit,
    static_argnames=("num_nodes", "num_bins", "row_tile", "lowp", "interpret"),
)
def build_histogram_pallas_binloop(
    binned: jax.Array,   # [N, F] int32 codes in [0, num_bins), SHARED
    node: jax.Array,     # [K, N] int32 node slot per row per fit (-1 = dead)
    grad: jax.Array,     # [K, N] f32 (pre-masked)
    hess: jax.Array,     # [K, N] f32
    num_nodes: int,
    num_bins: int,
    row_tile: int | None = None,
    lowp: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """hist [K, num_nodes, F, num_bins, 2] via the bin-loop kernel (see
    _hist_binloop_kernel). Same contract as build_histogram_pallas_batched."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k_fits, n = node.shape
    f = binned.shape[1]
    m_pad = _round_up(max(num_nodes, 8), 8)
    nvar = 2 if lowp else 4
    if row_tile is None:
        # 2048 measured best at the 1M-row scale shapes (1024: 190 ms,
        # 2048: 141 ms, 4096: 263 ms per build at 1M×500×32, M=64)
        row_tile = max(
            128, min(2048, ((1 << 20) // (nvar * m_pad)) // 128 * 128)
        )

    def vmem_bytes(ft: int) -> int:
        # binned block + 2 output accumulators + stacked operand + comb
        return (
            ft * row_tile * 4
            + 2 * num_bins * ft * m_pad * 4
            + row_tile * nvar * m_pad * 2
            + row_tile * (3 * m_pad * 4 + ft * 2)
        )

    # budget 6 MB by this model: Mosaic double-buffers grid blocks and
    # carries dot/select temporaries the model does not count (measured
    # ~2x) — 12 MB nominal blew the 16 MB scoped-vmem stack
    feat_tile = FEAT_TILE
    while (
        feat_tile * 2 <= _round_up(f, FEAT_TILE)
        and vmem_bytes(feat_tile * 2) <= (6 << 20)
    ):
        feat_tile *= 2
    while vmem_bytes(feat_tile) > (6 << 20) and row_tile > 512:
        row_tile //= 2
    n_pad = _round_up(max(n, row_tile), row_tile)
    f_pad = _round_up(f, feat_tile)

    binned_t = jnp.full((f_pad, n_pad), -1, dtype=jnp.int32)
    binned_t = binned_t.at[:f, :n].set(binned.T)
    node_p = jnp.full((k_fits, 1, n_pad), -1, dtype=jnp.int32).at[:, 0, :n].set(node)
    g_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(grad)
    h_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(hess)

    grid = (k_fits, f_pad // feat_tile, n_pad // row_tile)

    out_g, out_h = pl.pallas_call(
        functools.partial(
            _hist_binloop_kernel, m_pad=m_pad, num_bins=num_bins, lowp=lowp,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(
                (k_fits, num_bins, f_pad, m_pad), jnp.float32
            ),
            jax.ShapeDtypeStruct(
                (k_fits, num_bins, f_pad, m_pad), jnp.float32
            ),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (feat_tile, row_tile), lambda k, i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, row_tile), lambda k, i, j: (k, 0, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, num_bins, feat_tile, m_pad),
                lambda k, i, j: (k, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, num_bins, feat_tile, m_pad),
                lambda k, i, j: (k, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        interpret=interpret,
    )(binned_t, node_p, g_p, h_p)

    # [K, B, F, M] -> [K, M, F, B, 2]
    out = jnp.stack([out_g, out_h], axis=-1)
    out = jnp.transpose(out, (0, 3, 2, 1, 4))
    return out[:, :num_nodes, :f, :, :]


def build_histogram_pallas(
    binned: jax.Array,   # [N, F] int32 codes in [0, num_bins)
    node: jax.Array,     # [N] int32 node slot per row (-1 = dead)
    grad: jax.Array,     # [N] f32 (pre-masked)
    hess: jax.Array,     # [N] f32
    num_nodes: int,
    num_bins: int,
    row_tile: int | None = None,
    lowp: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """hist [num_nodes, F, num_bins, 2] — the K=1 case of the batched build."""
    return build_histogram_pallas_batched(
        binned, node[None, :], grad[None, :], hess[None, :],
        num_nodes, num_bins, row_tile=row_tile, lowp=lowp,
        interpret=interpret,
    )[0]


def build_histogram_scatter(
    binned: jax.Array,
    node: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    num_nodes: int,
    num_bins: int,
) -> jax.Array:
    """XLA scatter-add reference implementation (CPU / correctness).

    Grad and hess scatter as separate flat [N·F] vectors — a trailing
    length-2 axis would be tile-padded 64× on TPU (catastrophic under the
    forest vmap)."""
    n, f = binned.shape
    col_ids = jnp.arange(f, dtype=jnp.int32)[None, :]
    safe_node = jnp.maximum(node, 0)
    alive = (node >= 0).astype(jnp.float32)
    flat = ((safe_node[:, None] * f + col_ids) * num_bins + binned).reshape(-1)
    gv = jnp.repeat(grad * alive, f)
    hv = jnp.repeat(hess * alive, f)
    size = num_nodes * f * num_bins
    hg = jnp.zeros(size, dtype=jnp.float32).at[flat].add(gv)
    hh = jnp.zeros(size, dtype=jnp.float32).at[flat].add(hv)
    return jnp.stack(
        [hg.reshape(num_nodes, f, num_bins), hh.reshape(num_nodes, f, num_bins)],
        axis=-1,
    )


SPLIT_FEAT_TILE = 32  # features per split-kernel program step


def _split_kernel(
    binned_ref, node_ref, g_ref, h_ref, fmask_ref, lam_ref, gam_ref, mcw_ref,
    outg_ref, outf_ref, outb_ref, *, m_pad, num_bins, pack, feat_tile, lowp,
):
    """Fused best-split step for one (fit, feature-tile): histogram build
    (MXU one-hot matmuls), prefix sums (block-triangular matmul), XGBoost
    gain, and the per-tile arg-best — all while the blocks are
    VMEM-resident. Only [M] bests leave the kernel, never [M, F, B]
    histograms.

    ``pack`` features share the 128-lane bin axis (lane = sub·S + bin with
    S = 128 // pack), so one [T,M]ᵀ@[T,128] dot builds ``pack`` features'
    histograms — a ``pack``× FLOP cut over one-feature-per-dot."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    i = pl.program_id(1)

    nodes = node_ref[0, 0, :]    # [T]
    g = g_ref[0, 0, :]
    h = h_ref[0, 0, :]
    lam = lam_ref[0, 0, 0]
    gam = gam_ref[0, 0, 0]
    mcw = mcw_ref[0, 0, 0]
    mrow = fmask_ref[0, 0, 0, :]  # [feat_tile_pad] lanes (one per feature)
    t = nodes.shape[0]
    s = 128 // pack  # lanes per feature group

    # lowp: operands in bf16 with f32 MXU accumulation — callers assert the
    # values are bf16-exact (RF: g ∈ {0,±1}, h = 1), so sums stay exact up
    # to 2^24 while the dots run at the bf16 MXU rate
    op_dtype = jnp.bfloat16 if lowp else jnp.float32
    iota_m = lax.broadcasted_iota(jnp.int32, (t, m_pad), 1)
    node_oh = (nodes[:, None] == iota_m).astype(jnp.float32)
    wg = (node_oh * g[:, None]).astype(op_dtype)
    wh = (node_oh * h[:, None]).astype(op_dtype)
    iota_b = lax.broadcasted_iota(jnp.int32, (t, 128), 1)

    # block-diagonal prefix/total matrices: lane (q·S+b) aggregates lanes of
    # the SAME feature group only
    r0 = lax.broadcasted_iota(jnp.int32, (128, 128), 0)
    c0 = lax.broadcasted_iota(jnp.int32, (128, 128), 1)
    same_grp = (r0 // s) == (c0 // s)
    tri_bd = (same_grp & (r0 <= c0)).astype(jnp.float32)   # prefix within group
    ones_bd = same_grp.astype(jnp.float32)                 # total within group

    lane = lax.broadcasted_iota(jnp.int32, (m_pad, 128), 1)
    lane_bin = lane % s
    lane_sub = lane // s
    thr_ok = lane_bin < (num_bins - 1)  # valid thresholds t = 0..B-2
    contract = (((0,), (0,)), ((), ()))
    mm = (((1,), (0,)), ((), ()))

    best_gain = jnp.full((m_pad,), -jnp.inf, dtype=jnp.float32)
    best_feat = jnp.full((m_pad,), -1, dtype=jnp.int32)
    best_bin = jnp.zeros((m_pad,), dtype=jnp.int32)

    hist_precision = lax.Precision.DEFAULT if lowp else lax.Precision.HIGHEST
    for q in range(feat_tile // pack):
        # combined (sub-feature, bin) one-hot: pack features in one dot
        comb_oh = jnp.zeros((t, 128), dtype=op_dtype)
        for sub in range(pack):
            codes = binned_ref[q * pack + sub, :]
            comb_oh = comb_oh + (
                (codes[:, None] + sub * s) == iota_b
            ).astype(op_dtype)
        hg = lax.dot_general(
            wg, comb_oh, contract,
            preferred_element_type=jnp.float32,
            precision=hist_precision,
        )  # [M, 128] = pack features' histograms side by side
        hh = lax.dot_general(
            wh, comb_oh, contract,
            preferred_element_type=jnp.float32,
            precision=hist_precision,
        )
        gl = lax.dot_general(
            hg, tri_bd, mm,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )  # per-feature inclusive prefix sums
        hl = lax.dot_general(
            hh, tri_bd, mm,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        gt = lax.dot_general(
            hg, ones_bd, mm,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )  # per-feature totals broadcast across the group
        ht = lax.dot_general(
            hh, ones_bd, mm,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        gr = gt - gl
        hr = ht - hl
        gain = 0.5 * (
            gl * gl / (hl + lam) + gr * gr / (hr + lam) - gt * gt / (ht + lam)
        ) - gam
        # per-lane feature mask: feature q*pack + lane_sub of this tile
        # (static per-sub scalar selects — no gathers inside the kernel)
        mlane = jnp.zeros((m_pad, 128), dtype=jnp.float32)
        for sub in range(pack):
            mlane = jnp.where(lane_sub == sub, mrow[q * pack + sub], mlane)
        valid = thr_ok & (hl >= mcw) & (hr >= mcw) & (mlane > 0)
        gain = jnp.where(valid, gain, -jnp.inf)

        bg = jnp.max(gain, axis=1)  # [M]
        # deterministic tie-break: smallest lane at the max
        bl = jnp.min(
            jnp.where(gain >= bg[:, None], lane, 128), axis=1
        ).astype(jnp.int32)
        better = bg > best_gain
        best_gain = jnp.where(better, bg, best_gain)
        best_feat = jnp.where(
            better, i * feat_tile + q * pack + bl // s, best_feat
        ).astype(jnp.int32)
        best_bin = jnp.where(better, bl % s, best_bin).astype(jnp.int32)

    outg_ref[0, 0, :] = best_gain
    outf_ref[0, 0, :] = best_feat
    outb_ref[0, 0, :] = best_bin


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "num_bins", "lowp", "interpret")
)
def build_best_split_pallas(
    binned: jax.Array,     # [N, F] int32, SHARED
    node: jax.Array,       # [K, N] int32 compact slot per row (-1 = dead)
    grad: jax.Array,       # [K, N] f32 (pre-masked)
    hess: jax.Array,       # [K, N] f32
    feat_mask: jax.Array,  # [K, F] f32 (0 disables a feature)
    reg_lambda: jax.Array,       # [K] f32
    gamma: jax.Array,            # [K] f32
    min_child_weight: jax.Array, # [K] f32
    num_nodes: int,
    num_bins: int,
    lowp: bool = False,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(best_gain, best_feat, best_bin) each [K, num_nodes] — the fused
    split search. Requires all rows to fit one VMEM tile (N ≲ 2k); callers
    fall back to the two-phase histogram path beyond that."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k_fits, n = node.shape
    f = binned.shape[1]
    m_pad = _round_up(max(num_nodes, 8), 8)
    n_pad = _round_up(max(n, 128), 128)
    # bin-axis packing: features per 128-lane dot (4 for ≤32 bins)
    pack = 4 if num_bins <= 32 else (2 if num_bins <= 64 else 1)
    feat_tile = SPLIT_FEAT_TILE
    f_pad = _round_up(f, feat_tile)
    n_tiles = f_pad // feat_tile

    binned_t = jnp.zeros((f_pad, n_pad), dtype=jnp.int32)
    binned_t = binned_t.at[:f, :n].set(binned.T)
    node_p = jnp.full((k_fits, 1, n_pad), -1, dtype=jnp.int32).at[:, 0, :n].set(node)
    g_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(grad)
    h_p = jnp.zeros((k_fits, 1, n_pad), dtype=jnp.float32).at[:, 0, :n].set(hess)
    # per-(fit, tile) mask rows, one lane per feature of the tile
    ft_pad = _round_up(feat_tile, 128)
    fm = jnp.zeros((k_fits, n_tiles, 1, ft_pad), dtype=jnp.float32)
    fm_src = jnp.zeros((k_fits, f_pad), dtype=jnp.float32).at[:, :f].set(feat_mask)
    fm = fm.at[:, :, 0, :feat_tile].set(
        fm_src.reshape(k_fits, n_tiles, feat_tile)
    )
    scal = lambda v: jnp.asarray(v, dtype=jnp.float32).reshape(k_fits, 1, 1)  # noqa: E731

    grid = (k_fits, n_tiles)
    out_shape = jax.ShapeDtypeStruct((k_fits * n_tiles, 1, m_pad), jnp.float32)
    out_shape_i = jax.ShapeDtypeStruct((k_fits * n_tiles, 1, m_pad), jnp.int32)
    out_spec = pl.BlockSpec(
        (1, 1, m_pad), lambda k, i: (k * n_tiles + i, 0, 0),
        memory_space=pltpu.VMEM,
    )

    outg, outf, outb = pl.pallas_call(
        functools.partial(
            _split_kernel, m_pad=m_pad, num_bins=num_bins, pack=pack,
            feat_tile=feat_tile, lowp=lowp,
        ),
        out_shape=(out_shape, out_shape_i, out_shape_i),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (feat_tile, n_pad), lambda k, i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, n_pad), lambda k, i: (k, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, n_pad), lambda k, i: (k, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, n_pad), lambda k, i: (k, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, 1, ft_pad), lambda k, i: (k, i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, 1), lambda k, i: (k, 0, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                (1, 1, 1), lambda k, i: (k, 0, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                (1, 1, 1), lambda k, i: (k, 0, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=(out_spec, out_spec, out_spec),
        interpret=interpret,
    )(
        binned_t, node_p, g_p, h_p, fm,
        scal(reg_lambda), scal(gamma), scal(min_child_weight),
    )

    # reduce the per-tile bests over tiles (tiny [K, n_tiles, M] arrays)
    outg = outg.reshape(k_fits, n_tiles, m_pad)
    outf = outf.reshape(k_fits, n_tiles, m_pad)
    outb = outb.reshape(k_fits, n_tiles, m_pad)
    ti = jnp.argmax(outg, axis=1)  # [K, M]
    take = lambda a: jnp.take_along_axis(a, ti[:, None, :], axis=1)[:, 0, :]  # noqa: E731
    return (
        take(outg)[:, :num_nodes],
        take(outf)[:, :num_nodes],
        take(outb)[:, :num_nodes],
    )


#: rows must fit one VMEM tile for the fused split kernel
FUSED_SPLIT_MAX_ROWS = 2048


def build_histogram_scatter_batched(
    binned: jax.Array,   # [N, F] shared
    node: jax.Array,     # [K, N]
    grad: jax.Array,     # [K, N]
    hess: jax.Array,     # [K, N]
    num_nodes: int,
    num_bins: int,
) -> jax.Array:
    """[K, num_nodes, F, num_bins, 2] scatter-add fallback (CPU / non-TPU)."""
    return jax.vmap(
        lambda nd, g, h: build_histogram_scatter(
            binned, nd, g, h, num_nodes, num_bins
        )
    )(node, grad, hess)


def default_impl() -> str:
    """'pallas' on real TPU backends, 'scatter' elsewhere (CPU tests run the
    kernel via interpret mode in the dedicated unit tests only)."""
    import os

    forced = os.environ.get("TPTPU_HIST")
    if forced:
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "scatter"
