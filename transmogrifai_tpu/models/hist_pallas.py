"""Pallas TPU kernel: per-node gradient histograms for tree growth.

The histogram build is THE hot op of histogram GBDT (the reference runs it
in libxgboost's C++ core, SURVEY.md §2.5 item 1). The XLA scatter-add in
models/trees.py lowers to a serialized sort/scatter on TPU; this kernel
reformulates the build as matmuls so it runs on the MXU:

    hist[m, f, b] = Σ_r 1[node_r = m] · 1[binned_{r,f} = b] · v_r
                  = (NodeOneHot · v)ᵀ @ BinOneHot_f        per feature f

i.e. for every feature an [M, T] x [T, B] matmul over row tiles T — dense
systolic-array work instead of scattered memory traffic. Grad and hess are
two value columns of the same one-hot product.

Grid: (F, N/T). The output block for feature f is revisited across row
tiles (accumulation pattern: init at j==0, add afterwards). Padded rows
carry node = -1 → their one-hot row is all zero → no contribution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


FEAT_TILE = 8  # features per program (TPU sublane granule)


def _hist_kernel(binned_ref, node_ref, g_ref, h_ref, outg_ref, outh_ref,
                 *, m_pad, b_pad):
    """One (feature-tile, row-tile) step: accumulate grad/hess histograms
    [FEAT_TILE, M, B] (separate outputs — a trailing dim of 2 would be
    tile-padded to 128 and blow VMEM)."""
    import jax.lax as lax
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    nodes = node_ref[0, :]    # [T] int32 (-1 = padded/dead row)
    g = g_ref[0, :]           # [T] f32
    h = h_ref[0, :]           # [T] f32
    t = nodes.shape[0]

    iota_m = lax.broadcasted_iota(jnp.int32, (t, m_pad), 1)
    node_oh = (nodes[:, None] == iota_m).astype(jnp.float32)     # [T, M]
    # HIGHEST: the one-hots are exact in bf16 but the value operand is not —
    # split-precision passes keep the histogram sums f32-accurate
    wg = node_oh * g[:, None]
    wh = node_oh * h[:, None]
    iota_b = lax.broadcasted_iota(jnp.int32, (t, b_pad), 1)
    contract = (((0,), (0,)), ((), ()))  # contract the row-tile axis

    for k in range(FEAT_TILE):
        codes = binned_ref[k, :]  # [T] int32 for feature k of this tile
        bin_oh = (codes[:, None] == iota_b).astype(jnp.float32)  # [T, B]
        hg = lax.dot_general(
            wg, bin_oh, contract,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )  # [M, B]
        hh = lax.dot_general(
            wh, bin_oh, contract,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )

        @pl.when(j == 0)
        def _(k=k, hg=hg, hh=hh):
            outg_ref[k, :, :] = hg
            outh_ref[k, :, :] = hh

        @pl.when(j > 0)
        def _(k=k, hg=hg, hh=hh):
            outg_ref[k, :, :] = outg_ref[k, :, :] + hg
            outh_ref[k, :, :] = outh_ref[k, :, :] + hh


@functools.partial(
    jax.jit, static_argnames=("num_nodes", "num_bins", "row_tile", "interpret")
)
def build_histogram_pallas(
    binned: jax.Array,   # [N, F] int32 codes in [0, num_bins)
    node: jax.Array,     # [N] int32 node slot per row (-1 = dead)
    grad: jax.Array,     # [N] f32 (pre-masked)
    hess: jax.Array,     # [N] f32
    num_nodes: int,
    num_bins: int,
    row_tile: int = 2048,
    interpret: bool = False,
) -> jax.Array:
    """hist [num_nodes, F, num_bins, 2] via the MXU one-hot formulation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = binned.shape
    m_pad = _round_up(max(num_nodes, 8), 8)
    b_pad = _round_up(num_bins, 128)
    n_pad = _round_up(max(n, row_tile), row_tile)
    f_pad = _round_up(f, FEAT_TILE)

    binned_t = jnp.zeros((f_pad, n_pad), dtype=jnp.int32)
    binned_t = binned_t.at[:f, :n].set(binned.T)
    node_p = jnp.full((1, n_pad), -1, dtype=jnp.int32).at[0, :n].set(node)
    g_p = jnp.zeros((1, n_pad), dtype=jnp.float32).at[0, :n].set(grad)
    h_p = jnp.zeros((1, n_pad), dtype=jnp.float32).at[0, :n].set(hess)

    num_row_tiles = n_pad // row_tile
    grid = (f_pad // FEAT_TILE, num_row_tiles)

    out_g, out_h = pl.pallas_call(
        functools.partial(_hist_kernel, m_pad=m_pad, b_pad=b_pad),
        out_shape=(
            jax.ShapeDtypeStruct((f_pad, m_pad, b_pad), jnp.float32),
            jax.ShapeDtypeStruct((f_pad, m_pad, b_pad), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (FEAT_TILE, row_tile), lambda i, j: (i, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, row_tile), lambda i, j: (0, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, row_tile), lambda i, j: (0, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, row_tile), lambda i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (FEAT_TILE, m_pad, b_pad), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (FEAT_TILE, m_pad, b_pad), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        interpret=interpret,
    )(binned_t, node_p, g_p, h_p)

    # 2 × [F, M, B] -> [M, F, B, 2], unpadded
    out = jnp.stack([out_g, out_h], axis=-1)
    return jnp.transpose(out[:f, :num_nodes, :num_bins, :], (1, 0, 2, 3))


def build_histogram_scatter(
    binned: jax.Array,
    node: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    num_nodes: int,
    num_bins: int,
) -> jax.Array:
    """XLA scatter-add reference implementation (CPU / correctness).

    Grad and hess scatter as separate flat [N·F] vectors — a trailing
    length-2 axis would be tile-padded 64× on TPU (catastrophic under the
    forest vmap)."""
    n, f = binned.shape
    col_ids = jnp.arange(f, dtype=jnp.int32)[None, :]
    safe_node = jnp.maximum(node, 0)
    alive = (node >= 0).astype(jnp.float32)
    flat = ((safe_node[:, None] * f + col_ids) * num_bins + binned).reshape(-1)
    gv = jnp.repeat(grad * alive, f)
    hv = jnp.repeat(hess * alive, f)
    size = num_nodes * f * num_bins
    hg = jnp.zeros(size, dtype=jnp.float32).at[flat].add(gv)
    hh = jnp.zeros(size, dtype=jnp.float32).at[flat].add(hv)
    return jnp.stack(
        [hg.reshape(num_nodes, f, num_bins), hh.reshape(num_nodes, f, num_bins)],
        axis=-1,
    )


def default_impl() -> str:
    """'pallas' on real TPU backends, 'scatter' elsewhere (CPU tests run the
    kernel via interpret mode in the dedicated unit tests only)."""
    import os

    forced = os.environ.get("TPTPU_HIST")
    if forced:
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "scatter"
