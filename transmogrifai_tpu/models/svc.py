"""Linear SVM classifier.

Reference: core/.../stages/impl/classification/OpLinearSVC.scala wraps Spark
LinearSVC (hinge loss, L2 regularization, OWL-QN over native BLAS). Here
training is the pure XLA proximal-subgradient solver in solvers.py
(fit_linear_svc): fixed-iteration `lax.scan`, vmap-able over the reg grid.
"""
from __future__ import annotations

import numpy as np

from .base import PredictorEstimator, PredictorModel
from .solvers import fit_linear_svc


class LinearSVCModel(PredictorModel):
    def __init__(self, weights, intercept, uid=None):
        super().__init__("linearSVC", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = float(np.asarray(intercept))

    def get_arrays(self):
        return {"weights": self.weights,
                "intercept": np.asarray(self.intercept)}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], arrays["intercept"])

    def predict_arrays(self, x: np.ndarray):
        margin = x @ self.weights + self.intercept
        raw = np.stack([-margin, margin], axis=1)
        pred = (margin > 0).astype(np.float64)
        # SVC has no probability column (Spark LinearSVC emits rawPrediction
        # only); evaluators fall back to the margin ranking.
        return pred, None, raw


class LinearSVC(PredictorEstimator):
    """Spark defaults: regParam=0.0, maxIter=100, standardization=true,
    fitIntercept=true (OpLinearSVC.scala)."""

    model_type = "OpLinearSVC"

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: str | None = None):
        super().__init__("linearSVC", uid=uid)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
            "standardization": self.standardization,
        }

    def fit_arrays(self, x, y, row_mask):
        # maxIter is the Spark-semantic knob; the smoothed-hinge FISTA needs
        # ~4 steps per OWL-QN iteration for comparable convergence, so the
        # budget scales with the grid value rather than flooring it.
        params = fit_linear_svc(
            x, y, row_mask, float(self.reg_param),
            num_iters=self.max_iter * 4,
            fit_intercept=self.fit_intercept,
            standardization=self.standardization,
        )
        return LinearSVCModel(np.asarray(params.weights),
                              np.asarray(params.intercept))
