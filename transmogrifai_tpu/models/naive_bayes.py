"""Naive Bayes classifier (multinomial + bernoulli).

Reference: core/.../stages/impl/classification/OpNaiveBayes.scala wraps Spark
NaiveBayes (modelType multinomial|bernoulli, smoothing=1.0). The fit is one
matmul on the MXU: per-class feature sums are ``one_hot(y).T @ x`` — the
Spark ``treeAggregate`` becomes an XLA reduction that psums over the data
mesh axis when sharded.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator, PredictorModel


@partial(jax.jit, static_argnames=("num_classes", "bernoulli"))
def _fit_nb(x, y, row_mask, smoothing, num_classes: int, bernoulli: bool):
    row_mask = row_mask.astype(x.dtype)
    y1h = jax.nn.one_hot(y.astype(jnp.int32), num_classes, dtype=x.dtype)
    y1h = y1h * row_mask[:, None]
    class_count = y1h.sum(0)                       # [C]
    pi = jnp.log(class_count + smoothing) - jnp.log(
        class_count.sum() + smoothing * num_classes
    )
    xb = (x > 0).astype(x.dtype) if bernoulli else x
    feat_sum = y1h.T @ xb                          # [C, D]
    if bernoulli:
        theta = jnp.log(feat_sum + smoothing) - jnp.log(
            (class_count + 2.0 * smoothing)[:, None]
        )
    else:
        theta = jnp.log(feat_sum + smoothing) - jnp.log(
            (feat_sum.sum(1) + smoothing * x.shape[1])[:, None]
        )
    return pi, theta


class NaiveBayesModel(PredictorModel):
    def __init__(self, pi, theta, model_kind: str = "multinomial", uid=None):
        super().__init__("naiveBayes", uid=uid)
        self.pi = np.asarray(pi, dtype=np.float64)        # [C]
        self.theta = np.asarray(theta, dtype=np.float64)  # [C, D]
        self.model_kind = model_kind

    def get_arrays(self):
        return {"pi": self.pi, "theta": self.theta}

    def get_params(self):
        return {"model_kind": self.model_kind}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["pi"], arrays["theta"], params.get("model_kind", "multinomial"))

    def predict_arrays(self, x: np.ndarray):
        if self.model_kind == "bernoulli":
            # Spark bernoulli scoring: x must be 0/1; score = pi + x·theta +
            # (1-x)·log(1 - e^theta)
            xb = (x > 0).astype(np.float64)
            neg = np.log1p(-np.minimum(np.exp(self.theta), 1.0 - 1e-12))
            raw = self.pi + xb @ self.theta.T + (1.0 - xb) @ neg.T
        else:
            raw = self.pi + x @ self.theta.T
        shifted = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(shifted)
        prob = e / e.sum(axis=1, keepdims=True)
        pred = raw.argmax(axis=1).astype(np.float64)
        return pred, prob, raw


class NaiveBayes(PredictorEstimator):
    """Spark defaults: smoothing=1.0, modelType='multinomial'
    (OpNaiveBayes.scala). Features must be non-negative (count-like)."""

    model_type = "OpNaiveBayes"

    def __init__(self, smoothing: float = 1.0, model_kind: str = "multinomial",
                 uid: str | None = None):
        super().__init__("naiveBayes", uid=uid)
        if model_kind not in ("multinomial", "bernoulli"):
            raise ValueError(f"unknown modelType {model_kind}")
        self.smoothing = smoothing
        self.model_kind = model_kind

    def get_params(self):
        return {"smoothing": self.smoothing, "model_kind": self.model_kind}

    def fit_arrays(self, x, y, row_mask):
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        if np.any(x[np.asarray(row_mask) > 0] < 0):
            raise ValueError(
                "NaiveBayes requires non-negative feature values "
                "(Spark NaiveBayes semantics)"
            )
        pi, theta = _fit_nb(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(row_mask),
            jnp.asarray(self.smoothing, dtype=jnp.float32),
            num_classes=num_classes, bernoulli=self.model_kind == "bernoulli",
        )
        return NaiveBayesModel(pi, theta, self.model_kind)
