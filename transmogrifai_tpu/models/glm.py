"""Generalized linear regression (IRLS).

Reference: core/.../stages/impl/regression/OpGeneralizedLinearRegression.scala
wraps Spark GeneralizedLinearRegression (families gaussian/binomial/poisson/
gamma, canonical + explicit links, IRLS with maxIter=25, L2 regParam). The
IRLS loop is one compiled `lax.scan` of normal-equation solves
(solvers.fit_glm_irls).
"""
from __future__ import annotations

import numpy as np

from .base import PredictorEstimator, PredictorModel
from .solvers import GLM_DEFAULT_LINK, GLM_FAMILIES, GLM_LINKS, fit_glm_irls


def _linkinv_np(eta: np.ndarray, link: str) -> np.ndarray:
    if link == "identity":
        return eta
    if link == "log":
        return np.exp(eta)
    if link == "logit":
        return 1.0 / (1.0 + np.exp(-eta))
    if link == "inverse":
        safe = np.where(np.abs(eta) > 1e-7, eta, 1e-7)
        return 1.0 / safe
    if link == "sqrt":
        return eta * eta
    raise ValueError(f"unknown link {link}")


class GeneralizedLinearRegressionModel(PredictorModel):
    def __init__(self, weights, intercept, family: str, link: str, uid=None):
        super().__init__("glm", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = float(np.asarray(intercept))
        self.family = family
        self.link = link

    def get_arrays(self):
        return {"weights": self.weights, "intercept": np.asarray(self.intercept)}

    def get_params(self):
        return {"family": self.family, "link": self.link}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], arrays["intercept"],
                   params["family"], params["link"])

    def predict_arrays(self, x: np.ndarray):
        return self.predictions_from_core(x @ self.weights + self.intercept)

    def predictions_from_core(self, core: np.ndarray):
        """Host epilogue shared by staged predict and the fused graph:
        the link inverse over the downloaded linear predictor eta."""
        eta = np.asarray(core, dtype=np.float64)
        mu = _linkinv_np(eta, self.link)
        return mu.astype(np.float64), None, None

    def fused_predict_spec(self):
        from ..compiler.fused import PredictorPlan

        params = {
            "w": np.asarray(self.weights, dtype=np.float32),
            "b": np.float32(self.intercept),
        }

        def core(plane, p):
            return plane @ p["w"] + p["b"]

        return PredictorPlan(
            stage=self, in_dim=int(self.weights.shape[0]), params=params,
            core=core, epilogue=self.predictions_from_core,
            descriptor=f"glm:{self.family}:{self.link}",
        )


class GeneralizedLinearRegression(PredictorEstimator):
    """Spark defaults: family='gaussian', link=canonical, regParam=0,
    maxIter=25, fitIntercept=true (OpGeneralizedLinearRegression.scala)."""

    model_type = "OpGeneralizedLinearRegression"

    def __init__(self, family: str = "gaussian", link: str | None = None,
                 reg_param: float = 0.0, max_iter: int = 25,
                 fit_intercept: bool = True, uid: str | None = None):
        super().__init__("glm", uid=uid)
        if family not in GLM_FAMILIES:
            raise ValueError(f"unknown family {family}")
        link = link or GLM_DEFAULT_LINK[family]
        if link not in GLM_LINKS:
            raise ValueError(f"unknown link {link}")
        self.family = family
        self.link = link
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept

    def get_params(self):
        return {
            "family": self.family,
            "link": self.link,
            "reg_param": self.reg_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
        }

    def with_params(self, **params):
        # grid points that change the family without naming a link must get
        # the new family's canonical link, not this instance's resolved one
        if "family" in params and "link" not in params:
            params = {**params, "link": GLM_DEFAULT_LINK[params["family"]]}
        return super().with_params(**params)

    def fit_arrays(self, x, y, row_mask):
        params = fit_glm_irls(
            x, y, row_mask, float(self.reg_param),
            family=GLM_FAMILIES[self.family], link=GLM_LINKS[self.link],
            num_iters=self.max_iter, fit_intercept=self.fit_intercept,
        )
        return GeneralizedLinearRegressionModel(
            np.asarray(params.weights), np.asarray(params.intercept),
            self.family, self.link,
        )
