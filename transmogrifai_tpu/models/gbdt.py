"""Tree-ensemble model stages: XGBoost / GBT / RandomForest / DecisionTree.

Reference stages replaced (behavioral parity on the histogram learner in
models/trees.py):
  * OpXGBoostClassifier/Regressor (core/.../classification/OpXGBoostClassifier.scala
    — JNI libxgboost + Rabit allreduce): XLA boosting with second-order
    gradients; pass ``mesh=`` to the trees.fit_* entry points to shard rows
    over the mesh data axis with per-level histograms psum'd over ICI
    (trees._sharded_boost_kernel — the Rabit replacement, proven
    tree-identical in tests/test_trees_sharded.py).
  * OpGBTClassifier/Regressor (Spark GBT; defaults maxIter 20, stepSize 0.1).
  * OpRandomForestClassifier/Regressor (Spark RF; defaults numTrees 50 in
    selector grids, maxDepth 5 spark default).
  * OpDecisionTreeClassifier/Regressor: single unbagged tree.

Known divergences (documented per SURVEY.md §7 hard-part 5): multiclass
boosting is one-vs-rest rather than softmax-per-round; RF classification
impurity is variance on per-class indicators (probability trees) rather than
gini — both preserve the fitted-probability semantics used downstream.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

from .base import PredictorEstimator, PredictorModel
from . import trees as TR

import threading as _threading

# (matrix, max_bins) -> (x-ref, thresholds, binned, fgroups); see
# _TreeEstimator._binned
_BINNED_CACHE: dict = {}
_BINNED_LOCK = _threading.Lock()


def _sigmoid(m: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-m))


def _tree_from_arrays(arrays: dict, prefix: str = "") -> TR.Tree:
    return TR.Tree(
        split_feat=arrays[f"{prefix}split_feat"],
        split_bin=arrays[f"{prefix}split_bin"],
        leaf_value=arrays[f"{prefix}leaf_value"],
    )


def _class_trees_from_arrays(arrays: dict) -> list[TR.Tree]:
    out = []
    c = 0
    while f"c{c}__split_feat" in arrays:
        out.append(_tree_from_arrays(arrays, prefix=f"c{c}__"))
        c += 1
    return out


def _feature_bin_groups(x: np.ndarray):
    """(narrow_idx, wide_idx) partition of the columns: 0/1 indicator
    columns (the bulk of a transmogrified one-hot matrix) vs multi-valued
    ones. Tree growth searches the narrow group at 2 bins instead of
    max_bins — split-search cost scales with features×bins, so this is a
    ~10-16× cut on one-hot-heavy matrices with identical fitted trees
    (trees._grow_tree_impl docstring). Host-side and cheap: one vectorized
    pass over the matrix."""
    xf = np.asarray(x)
    with np.errstate(invalid="ignore"):
        binary = ((xf == 0) | (xf == 1) | ~np.isfinite(xf)).all(axis=0)
    narrow = np.nonzero(binary)[0].astype(np.int32)
    wide = np.nonzero(~binary)[0].astype(np.int32)
    if len(narrow) == 0:
        return None
    return jnp.asarray(narrow), jnp.asarray(wide)


_bin_data_jit = jax.jit(TR.bin_data)


@jax.jit
def _stack_lane(trees, lane):
    """One lane of a stacked-trees pytree, sliced ON DEVICE (lane is a
    traced scalar, so every lane of a given stack shape shares one
    program)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, lane, 0, keepdims=False),
        trees,
    )


class _LazySlice:
    """Deferred materialization of one lane of a stacked-trees fit.

    The batched sweep fits K candidates' trees as one device array that the
    sweep itself never slices — candidate metrics come from
    sweep_eval_batched on the DEVICE stack, and only the winner's model
    ever needs its tree arrays (for persistence or re-scoring). The slice
    happens on device (a ~200 MB refit-lane stack pulled to host for a
    16 MB lane was measured at ~15 s over the tunneled link); persistence
    downloads just the winner's lane when get_arrays() converts to numpy."""

    def __init__(self, stack: dict, lane: int):
        self.stack = stack
        self.lane = lane

    def get(self):
        cache = self.stack.setdefault("lane_slices", {})
        out = cache.get(self.lane)
        if out is None:
            trees = self.stack["trees"]
            if isinstance(jax.tree.leaves(trees)[0], np.ndarray):
                # host stack (multi-device mesh path pre-pulls — see
                # _batched_group_fit): plain numpy view
                out = jax.tree.map(lambda a: a[self.lane], trees)
            else:
                from ..utils.aot import aot_call

                out = aot_call(
                    "stack_lane", _stack_lane,
                    (trees, np.int32(self.lane)), {},
                )
            cache[self.lane] = out
        return out


def _resolve_trees(t):
    return t.get() if isinstance(t, _LazySlice) else t


def _host_trees(t):
    """Tree pytree as host numpy (persistence path — downloads only this
    lane when the trees live on device)."""
    return jax.tree.map(np.asarray, _resolve_trees(t))


def _aot_predict_boosted(x, thresholds, trees, eta, base_score):
    """predict_boosted_raw through the AOT executable bank. The refit winner
    rides the validation sweep (detach_from_sweep), so the standalone
    scoring program is never compiled during training — without the bank,
    the FIRST model.score() of a fresh process pays the full remote compile
    (the round-3 score_s regression: 0.024 s -> 0.742 s)."""
    from ..utils.aot import aot_call

    return aot_call(
        "predict_boosted", TR.predict_boosted_raw,
        (x, thresholds, trees, eta, base_score), {},
    )


def _aot_predict_forest(x, thresholds, trees):
    """predict_forest_raw through the AOT executable bank (see
    _aot_predict_boosted)."""
    from ..utils.aot import aot_call

    return aot_call("predict_forest", TR.predict_forest_raw,
                    (x, thresholds, trees), {})


class _BinnedModel(PredictorModel):
    """Shared state for binned-tree models; prediction goes through the
    fused jitted entry points (trees.predict_*_raw) which bin internally —
    one dispatch per scoring call.

    Tree arrays are stored as given (host numpy from batched sweeps, device
    from sequential fits) and uploaded LAZILY on first predict: the sweep
    path never calls per-model predict (see sweep_eval_batched), so eagerly
    uploading every candidate's trees would re-send the whole stacked array
    over the tunnel for nothing."""

    def __init__(self, operation_name: str, thresholds: np.ndarray, uid=None):
        super().__init__(operation_name, uid=uid)
        self.thresholds = np.asarray(thresholds, dtype=np.float32)
        self._dev_cache = None
        self._host_cache = None
        self._serve_plan = None

    def _use_host(self, x) -> bool:
        """Serving-size batches predict in numpy on the host: a jax result
        touch costs ~0.1 s fixed on virtualized hosts and an upload per call
        on the tunneled chip, so the device path only wins at scale."""
        import os

        return len(x) <= int(os.environ.get("TPTPU_HOST_PREDICT_MAX", "16384"))

    def _host(self, trees):
        if self._host_cache is None:
            if isinstance(trees, list):
                self._host_cache = [_host_trees(t) for t in trees]
            else:
                self._host_cache = _host_trees(trees)
        return self._host_cache

    def _dev(self, trees):
        if self._dev_cache is None:
            if isinstance(trees, list):
                trees = [_resolve_trees(t) for t in trees]
            else:
                trees = _resolve_trees(trees)
            self._dev_cache = jax.tree.map(jnp.asarray, trees)
        return self._dev_cache

    def _predict_stacks(self, x, trees, boosted: bool) -> np.ndarray:
        """float64 [N, k] of margins (boosted) or mean-leaf values (forest)
        — k=1 for a single stacked-tree pytree, one column per class for a
        list. The ONLY host-vs-device dispatch point for scoring."""
        many = isinstance(trees, list)
        if self._use_host(x):
            # Fixed for a fitted model, built once: the used-feature subset
            # (trees touch tens of the flagship's 928 columns), its
            # threshold keys, and feature-remapped stacks — then each batch
            # bins ONLY those columns, once across all class stacks.
            plan = getattr(self, "_serve_plan", None)
            if plan is None:
                hs0 = self._host(trees)
                plan = TR.host_serving_plan(
                    self.thresholds, hs0 if many else [hs0]
                )
                self._serve_plan = plan
                # the full-width host stacks are only needed to build the
                # plan — keeping them would double host serving memory
                self._host_cache = None
            used, thr_used, fk, hs = plan
            # xu/thr_used stay consistent with the REMAPPED stacks: if a
            # future path ever let ``binned`` default inside predict_*_host,
            # it would still bin in the compact feature space
            xu = np.asarray(x, dtype=np.float32)[:, used]
            binned = TR.bin_data_host(xu, thr_used, flat_keys=fk)
            if boosted:
                outs = [
                    TR.predict_boosted_host(
                        xu, thr_used, t, self.eta, self.base_score,
                        binned=binned,
                    )
                    for t in hs
                ]
            else:
                outs = [
                    TR.predict_forest_host(xu, thr_used, t, binned=binned)
                    for t in hs
                ]
        else:
            from ..compiler.dispatch import device_f32

            # the serving path prefetches the feature matrix while earlier
            # plan stages run; pick that transfer up here
            xj = device_f32(x)
            thr = jnp.asarray(self.thresholds)
            ds = self._dev(trees)
            ds = ds if many else [ds]
            if boosted:
                eta = jnp.float32(self.eta)
                base = jnp.float32(self.base_score)
                outs = [np.asarray(_aot_predict_boosted(xj, thr, t, eta, base))
                        for t in ds]
            else:
                outs = [np.asarray(_aot_predict_forest(xj, thr, t))
                        for t in ds]
        return np.stack(outs, axis=1).astype(np.float64)

    # ---- shared predict entry: family-specific stacks + HOST epilogue ----
    def _tree_stacks(self):
        """(trees-or-per-class-list, boosted) — the arrays
        ``_predict_stacks`` dispatches over."""
        raise NotImplementedError

    def predictions_from_core(self, core: np.ndarray):
        """(pred, prob, raw) from the [N, k] margin/mean-leaf core — the
        numpy tail shared by the staged path and the fused graph's
        downloaded core, so the two are bit-identical."""
        raise NotImplementedError

    def predict_arrays(self, x):
        trees, boosted = self._tree_stacks()
        return self.predictions_from_core(
            self._predict_stacks(x, trees, boosted=boosted)
        )

    def fused_predict_spec(self):
        """Device core for the fused scoring graph: the same
        ``predict_*_raw`` programs the staged device path banks, traced
        over the in-graph plane — tree predictions stay bit-identical."""
        from ..compiler.fused import PredictorPlan
        from .serve_pallas import (
            predict_boosted_pallas, predict_forest_pallas, serve_impl,
            serve_interpret,
        )

        trees, boosted = self._tree_stacks()
        ds = self._dev(trees)
        ds = ds if isinstance(trees, list) else [ds]
        params: dict = {
            "thr": np.asarray(self.thresholds, dtype=np.float32),
            "trees": tuple(ds),
        }
        if boosted:
            params["eta"] = np.float32(self.eta)
            params["base"] = np.float32(self.base_score)
        # implementation is resolved HERE, at spec-build time, never inside
        # the traced core — the choice is baked into the program and salts
        # the fused fingerprint (":pl") so the bank never replays a gather
        # executable for a pallas plan or vice versa
        pallas = serve_impl() == "pallas"
        interp = serve_interpret()

        def core(plane, p):
            if pallas:
                binned = TR.bin_data(plane, p["thr"])
                if boosted:
                    outs = [
                        predict_boosted_pallas(
                            binned, t, p["eta"], p["base"],
                            interpret=interp,
                        )
                        for t in p["trees"]
                    ]
                else:
                    outs = [
                        predict_forest_pallas(binned, t, interpret=interp)
                        for t in p["trees"]
                    ]
            elif boosted:
                outs = [
                    TR.predict_boosted_raw(
                        plane, p["thr"], t, p["eta"], p["base"]
                    )
                    for t in p["trees"]
                ]
            else:
                outs = [
                    TR.predict_forest_raw(plane, p["thr"], t)
                    for t in p["trees"]
                ]
            return jnp.stack(outs, axis=1)

        return PredictorPlan(
            stage=self, in_dim=int(self.thresholds.shape[0]), params=params,
            core=core, epilogue=self.predictions_from_core,
            descriptor=(
                f"{'boost' if boosted else 'forest'}:{len(ds)}"
                + (":pl" if pallas else "")
            ),
        )

    def fused_bin_thresholds(self) -> np.ndarray:
        """Per-input bin edges for the quantized fused plane: the
        quantizer emits bin-aligned uint8 codes that re-bin IDENTICALLY
        in-graph, so quantized tree predictions stay bit-identical to the
        f32 plane (``featurize/quantize.py``)."""
        return np.asarray(self.thresholds, dtype=np.float32)

    def detach_from_sweep(self):
        """Cut every reference to the stacked sweep arrays: materialize this
        model's own lane (a small independent device array) and drop the
        stack attrs, so selecting a winner does not pin the whole
        (folds+refit) × grid stack in HBM for the model's lifetime."""
        def own(t):
            # numpy lane slices are VIEWS into the host stack — copy so the
            # base array can be collected; device slices are independent
            resolved = _resolve_trees(t)

            def _own_leaf(a):
                if isinstance(a, np.ndarray):
                    return np.array(a)
                # device lane: start the host transfer NOW — the first
                # consumer is the holdout predict's host serving plan, and
                # the async copy overlaps the holdout DAG transform instead
                # of blocking np.asarray on an 8 MB tunnel download
                try:
                    a.copy_to_host_async()
                except Exception:
                    pass
                return a

            return jax.tree.map(_own_leaf, resolved)

        # predict caches built pre-detach hold lane VIEWS into the sweep
        # stack — clearing them is part of the contract
        self._dev_cache = None
        self._host_cache = None
        self._serve_plan = None
        for attr in ("trees", "trees_per_class", "forests_per_class"):
            t = getattr(self, attr, None)
            if isinstance(t, _LazySlice):
                setattr(self, attr, own(t))
            elif isinstance(t, list):
                setattr(self, attr, [own(x) for x in t])
        for attr in ("_sweep_stack", "_sweep_lane", "_sweep_lanes"):
            if hasattr(self, attr):
                delattr(self, attr)


class BoostedBinaryModel(_BinnedModel):
    def __init__(self, thresholds, trees: TR.Tree, eta: float, base_score: float, uid=None):
        super().__init__("xgbClassifier", thresholds, uid=uid)
        self.trees = trees
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        t = _host_trees(self.trees)
        return {
            "thresholds": self.thresholds,
            "split_feat": t.split_feat,
            "split_bin": t.split_bin,
            "leaf_value": t.leaf_value,
        }

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _tree_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def _tree_stacks(self):
        return self.trees, True

    def predictions_from_core(self, core):
        return self.predictions_from_sweep(
            np.asarray(core, dtype=np.float64)[:, 0]
        )

    # ---- batched sweep-eval protocol (validators._sweep_family) ----------
    sweep_mode = "boost"

    def sweep_lane_params(self):
        return float(self.eta), float(self.base_score)

    def predictions_from_sweep(self, margin):
        p1 = _sigmoid(np.asarray(margin, dtype=np.float64))
        prob = np.stack([1 - p1, p1], axis=1)
        raw = np.stack([-margin, margin], axis=1)
        return (p1 > 0.5).astype(np.float64), prob, raw


class BoostedMultiModel(_BinnedModel):
    """One-vs-rest stack of boosted binary models."""

    def __init__(self, thresholds, trees_per_class: list[TR.Tree], eta, base_score, uid=None):
        super().__init__("xgbClassifier", thresholds, uid=uid)
        self.trees_per_class = trees_per_class
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        out = {"thresholds": self.thresholds}
        for c, t in enumerate(map(_host_trees, self.trees_per_class)):
            out[f"c{c}__split_feat"] = t.split_feat
            out[f"c{c}__split_bin"] = t.split_bin
            out[f"c{c}__leaf_value"] = t.leaf_value
        return out

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _class_trees_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def _tree_stacks(self):
        return self.trees_per_class, True

    def predictions_from_core(self, core):
        margins = np.asarray(core, dtype=np.float64)
        p = _sigmoid(margins)
        prob = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        return prob.argmax(axis=1).astype(np.float64), prob, margins


class BoostedRegressionModel(_BinnedModel):
    def __init__(self, thresholds, trees, eta, base_score, uid=None):
        super().__init__("xgbRegressor", thresholds, uid=uid)
        self.trees = trees
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        t = _host_trees(self.trees)
        return {
            "thresholds": self.thresholds,
            "split_feat": t.split_feat,
            "split_bin": t.split_bin,
            "leaf_value": t.leaf_value,
        }

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _tree_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def _tree_stacks(self):
        return self.trees, True

    def predictions_from_core(self, core):
        return np.asarray(core, dtype=np.float64)[:, 0], None, None

    sweep_mode = "boost"

    def sweep_lane_params(self):
        return float(self.eta), float(self.base_score)

    @staticmethod
    def predictions_from_sweep(margin):
        return np.asarray(margin, dtype=np.float64), None, None


class ForestClassifierModel(_BinnedModel):
    """Per-class probability forests (leaf value = class fraction)."""

    def __init__(self, thresholds, forests_per_class: list[TR.Tree], uid=None):
        super().__init__("rfClassifier", thresholds, uid=uid)
        self.forests_per_class = forests_per_class

    def get_arrays(self):
        out = {"thresholds": self.thresholds}
        for c, t in enumerate(map(_host_trees, self.forests_per_class)):
            out[f"c{c}__split_feat"] = t.split_feat
            out[f"c{c}__split_bin"] = t.split_bin
            out[f"c{c}__leaf_value"] = t.leaf_value
        return out

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["thresholds"], _class_trees_from_arrays(arrays))

    def _tree_stacks(self):
        return self.forests_per_class, False

    def predictions_from_core(self, core):
        return self._probs_to_predictions(np.asarray(core, dtype=np.float64))

    @staticmethod
    def _probs_to_predictions(probs):
        probs = np.clip(probs, 0.0, 1.0)
        if probs.shape[1] == 1:  # binary trained on the positive indicator
            probs = np.concatenate([1 - probs, probs], axis=1)
        raw = probs.copy()
        prob = probs / np.maximum(probs.sum(axis=1, keepdims=True), 1e-12)
        return prob.argmax(axis=1).astype(np.float64), prob, raw

    # sweep-eval protocol: only single-forest (binary) stacks batch — the
    # one-vs-rest multiclass loop stays on the per-model path
    sweep_mode = "forest"

    def sweep_lane_params(self):
        return 1.0, 0.0

    def predictions_from_sweep(self, preds):
        if len(self.forests_per_class) != 1:
            raise ValueError("sweep path is single-forest only")
        return self._probs_to_predictions(
            np.asarray(preds, dtype=np.float64)[:, None]
        )

    def predictions_from_sweep_multi(self, rows):
        """[C, N] per-class mean-leaf outputs (one sweep lane per class) →
        (pred, prob, raw)."""
        return self._probs_to_predictions(
            np.asarray(rows, dtype=np.float64).T
        )


class ForestRegressionModel(_BinnedModel):
    def __init__(self, thresholds, trees, uid=None):
        super().__init__("rfRegressor", thresholds, uid=uid)
        self.trees = trees

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["thresholds"], _tree_from_arrays(arrays))

    def get_arrays(self):
        t = _host_trees(self.trees)
        return {
            "thresholds": self.thresholds,
            "split_feat": t.split_feat,
            "split_bin": t.split_bin,
            "leaf_value": t.leaf_value,
        }

    def _tree_stacks(self):
        return self.trees, False

    def predictions_from_core(self, core):
        return np.asarray(core, dtype=np.float64)[:, 0], None, None

    sweep_mode = "forest"

    def sweep_lane_params(self):
        return 1.0, 0.0

    @staticmethod
    def predictions_from_sweep(preds):
        return np.asarray(preds, dtype=np.float64), None, None


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------
class _TreeEstimator(PredictorEstimator):
    #: grid params that are STATIC in the jitted fit (shape-affecting);
    #: points sharing them batch into one vmapped fit
    _STATIC_GRID_KEYS: tuple = ()

    def __init__(self, operation_name: str, max_depth: int, max_bins: int, uid=None):
        super().__init__(operation_name, uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins

    def _binned(self, x: np.ndarray):
        """(thresholds, binned codes, narrow/wide feature groups).

        Cached per (matrix, max_bins) across estimators and threads: every
        family of a candidate sweep bins the SAME training matrix (XGB + 3
        RF depth groups = 4 redundant device bin_data dispatches + host
        quantile passes on the flagship otherwise). The cache keeps a
        strong reference to x, so buffer-address keys cannot alias."""
        key = (
            x.__array_interface__["data"][0] if isinstance(x, np.ndarray)
            else id(x),
            getattr(x, "shape", None), getattr(x, "strides", None),
            int(self.max_bins),
        )
        with _BINNED_LOCK:
            hit = _BINNED_CACHE.get(key)
        if hit is not None:
            return hit[1], hit[2], hit[3]
        thresholds = TR.quantile_thresholds(x, self.max_bins)
        # through the AOT executable bank: a plain bin_data call pays a
        # per-process remote compile-cache load (~0.3-0.8 s on the axon
        # backend) exactly once, on the sweep's critical path
        from ..utils.aot import aot_call

        from ..compiler.dispatch import device_f32

        # device_f32 picks up the async upload the DAG fit prefetched for
        # this matrix, when one is in flight (compiler.dispatch)
        binned = aot_call(
            "bin_data", _bin_data_jit,
            (device_f32(x), jnp.asarray(thresholds)),
            {},
        )
        fgroups = _feature_bin_groups(x)
        with _BINNED_LOCK:
            _BINNED_CACHE[key] = (x, thresholds, binned, fgroups)
            while len(_BINNED_CACHE) > 4:
                _BINNED_CACHE.pop(next(iter(_BINNED_CACHE)))
        return thresholds, binned, fgroups

    def _fit_group_masks(self, x, y, masks, group_points):
        """Fit len(masks) × len(group_points) same-static-shape models in
        ONE batched program (fit axis = histogram-kernel grid axis, see
        trees.grow_tree_batched); None → caller falls back to sequential
        fits. Overridden per family. ``masks`` is [M, N] float32."""
        return None

    def fit_arrays_batched(self, x, y, row_mask, points):
        """One mask, many grid points (back-compat validator hook)."""
        return self.fit_arrays_batched_masks(x, y, [row_mask], points)[0]

    def fit_arrays_batched_masks(self, x, y, masks, points):
        """Validator hook: the folds × grid sweep batches points that share
        static shapes into one compiled program per group — the TPU
        replacement for the reference's driver thread pool
        (OpValidator.scala:363-367). A 3-fold × 18-point RF grid becomes 3
        programs (one per max_depth) instead of 54 dispatches.

        Set TPTPU_BATCHED_FITS=0 to force sequential fits."""
        import os

        masks = [np.asarray(m, dtype=np.float32) for m in masks]
        if (
            os.environ.get("TPTPU_BATCHED_FITS") == "0"
            or not self._STATIC_GRID_KEYS
        ):
            return [
                [self.with_params(**p).fit_arrays(x, y, m) for p in points]
                for m in masks
            ]
        groups: dict[tuple, list[int]] = {}
        for i, p in enumerate(points):
            merged = {**self.get_params(), **p}
            key = tuple(merged.get(k) for k in self._STATIC_GRID_KEYS)
            groups.setdefault(key, []).append(i)
        models: list[list] = [[None] * len(points) for _ in masks]
        mask_arr = np.stack(masks)
        # deepest group first: its program is the sweep's long pole on the
        # chip, so putting it at the head of the device queue overlaps its
        # execution with the shallower groups' host phases
        def _depth_of(key_idxs):
            merged = {**self.get_params(), **points[key_idxs[1][0]]}
            return -int(merged.get("max_depth", 0) or 0)

        for _, idxs in sorted(groups.items(), key=_depth_of):
            fitted = self._fit_group_masks(
                x, y, mask_arr, [points[i] for i in idxs]
            )
            if fitted is None:
                fitted = [
                    [
                        self.with_params(**points[i]).fit_arrays(x, y, m)
                        for i in idxs
                    ]
                    for m in masks
                ]
            for mi in range(len(masks)):
                for j, i in enumerate(idxs):
                    models[mi][i] = fitted[mi][j]
        return models

    @staticmethod
    def _tree_slice(stacked_trees, i):
        return jax.tree.map(lambda a: a[i], stacked_trees)

    def sweep_eval_batched(self, models_by_fold, x, y, folds, evaluator):
        """Validator hook: validation metrics for the WHOLE folds × grid
        sweep with one device program per fitted stack. The per-model
        predict loop pays a dispatch + val-matrix upload per model over the
        tunneled link (~0.1-0.3 s each × 54 RF models); here each stack's
        [K, N] outputs come back in one download and the per-lane
        probability/metric math runs on host exactly as predict_arrays
        would. Returns [n_points][n_folds] metric values, or None when any
        model lacks the sweep protocol (caller falls back)."""
        from ..utils.aot import aot_call

        flat = [m for fold_models in models_by_fold for m in fold_models]
        if not flat or any(
            getattr(m, "_sweep_stack", None) is None
            or not hasattr(m, "predictions_from_sweep")
            for m in flat
        ):
            return None
        try:
            for m in flat:
                # multiclass stacks batch only via the per-class output
                # lanes set by _fit_group_masks_multiclass
                if (
                    getattr(m, "forests_per_class", None) is not None
                    and len(m.forests_per_class) != 1
                    and (
                        getattr(m, "_sweep_lanes", None) is None
                        or m._sweep_stack.get("outputs") is None
                    )
                ):
                    return None
            import time as _t

            _t0 = _t.perf_counter()
            xj = None
            outputs: dict[int, np.ndarray] = {}
            for m in flat:
                stack = m._sweep_stack
                sid = id(stack)
                if sid in outputs:
                    continue
                if stack.get("outputs") is not None:
                    # the fit program already computed every lane's raw
                    # outputs on the training matrix — one tiny download,
                    # no traversal program, no x upload
                    outputs[sid] = np.asarray(stack["outputs"])
                    log.debug(
                        "sweep_eval outputs reused +%.2fs",
                        _t.perf_counter() - _t0,
                    )
                    continue
                log.debug("sweep_eval stack start +%.2fs", _t.perf_counter() - _t0)
                k = stack["k"]
                eta_v = np.ones(k, dtype=np.float32)
                base_v = np.zeros(k, dtype=np.float32)
                for mm in flat:
                    if mm._sweep_stack is stack:
                        e, b = mm.sweep_lane_params()
                        eta_v[mm._sweep_lane] = e
                        base_v[mm._sweep_lane] = b
                mode = m.sweep_mode
                fn = (
                    TR.sweep_boosted_outputs
                    if mode == "boost"
                    else TR.sweep_forest_outputs
                )
                if xj is None:
                    xj = jnp.asarray(x, dtype=jnp.float32)
                out = aot_call(
                    f"sweep_{mode}_outputs", fn,
                    (
                        xj, jnp.asarray(stack["thresholds"]),
                        jax.tree.map(jnp.asarray, stack["trees"]),
                        jnp.asarray(eta_v), jnp.asarray(base_v),
                    ),
                    {},
                )
                log.debug("sweep_eval dispatched +%.2fs", _t.perf_counter() - _t0)
                outputs[sid] = np.asarray(out)  # [K, N]
                log.debug("sweep_eval downloaded +%.2fs", _t.perf_counter() - _t0)
            _t1 = _t.perf_counter()
            values: list[list[float]] = [
                [] for _ in range(len(models_by_fold[0]))
            ]
            for fi, (_train_mask, val_mask) in enumerate(folds):
                val_idx = np.nonzero(val_mask)[0]
                for gi, m in enumerate(models_by_fold[fi]):
                    lanes = getattr(m, "_sweep_lanes", None)
                    out_m = outputs[id(m._sweep_stack)]
                    if lanes is not None:
                        rows = out_m[lanes][:, val_idx]  # [C, n_val]
                        pred, prob, _ = m.predictions_from_sweep_multi(rows)
                    else:
                        pred, prob, _ = m.predictions_from_sweep(
                            out_m[m._sweep_lane][val_idx]
                        )
                    metrics = evaluator.evaluate_arrays(y[val_idx], pred, prob)
                    values[gi].append(evaluator.metric_of(metrics))
            log.debug(
                "sweep_eval: device outputs %.2fs, host metrics %.2fs",
                _t1 - _t0, _t.perf_counter() - _t1,
            )
            return values
        except Exception:
            log.warning("batched sweep-eval failed; falling back", exc_info=True)
            return None

    def _batched_group_fit(
        self, x, masks, group_points, run_batched, make_model, normalize=None
    ):
        """Shared plumbing for the masks × points batched fit: bin once,
        merge (+ normalize) params, stack the float knobs mask-major
        (fit k = mask_index * n_points + point_index), run the family's
        batched trainer, slice the [K, ...] tree pytree back out.

        ``run_batched(binned, m0, row_mask_K, knob) -> ([K, ...] tree
        pytree, [K, N] training outputs-or-None)`` where ``knob(name)``
        returns the [K] float32 array for a param;
        ``make_model(thresholds, sliced_trees, merged_params, mask_index)``.
        The training outputs (every lane's raw model output on the full
        training matrix, computed by the fit program itself) ride the stack
        so sweep_eval_batched needs no re-traversal program.
        """
        import time as _t

        _t0 = _t.perf_counter()
        base = self.with_params(**group_points[0])
        thresholds, binned, fgroups = base._binned(x)
        self._last_feature_groups = fgroups
        log.debug(
            "%s group fit: binned in %.2fs", type(self).__name__,
            _t.perf_counter() - _t0,
        )
        norm = normalize or (lambda m: m)
        merged = [norm({**self.get_params(), **p}) for p in group_points]
        n_masks, n_pts = masks.shape[0], len(merged)
        # cross-candidate dedup ledger: every (mask × point) lane of this
        # static group shares ONE compiled program. Tree lanes do NOT pad
        # onto shape buckets (compiler.bucketing): split decisions are
        # discrete, and a reassociated histogram sum under a different
        # lane count can flip a borderline split.
        from ..compiler import stats as cstats

        cstats.stats().record_sweep(lanes=n_masks * n_pts)
        row_mask_k = jnp.asarray(np.repeat(masks, n_pts, axis=0))

        def knob(name):
            # numpy (not jnp): eager dtype-converting transfers compile a
            # device program per process on the axon backend; the batched
            # trainers transfer these once inside their jitted calls
            return np.asarray(
                [float(m[name]) for m in merged] * n_masks, dtype=np.float32
            )

        trees, outputs = run_batched(binned, merged[0], row_mask_k, knob, fgroups)
        log.debug(
            "%s group fit: dispatched at %.2fs", type(self).__name__,
            _t.perf_counter() - _t0,
        )
        # the stacked trees STAY on device for sweep_eval_batched (one
        # validation program per stack); per-model tree arrays materialize
        # lazily via _LazySlice — eager host pulls cost a ~44 MB download
        # over the tunnel and eager device slicing compiles a
        # dynamic_slice/squeeze program per shape. On a multi-device mesh
        # the stack is host-pulled once up front instead: keeping
        # replicated arrays around invites the eager multi-device slicing
        # that aborts the async XLA:CPU runtime (memory:
        # xla-cpu-mesh-gotchas).
        leaves = jax.tree.leaves(trees)
        is_dev = bool(leaves) and hasattr(leaves[0], "devices")
        multi_dev = is_dev and len(leaves[0].devices()) > 1
        if multi_dev or not is_dev:
            trees = jax.tree.map(lambda a: np.asarray(a), trees)
        stack = {
            "trees": trees,
            "thresholds": thresholds,
            "k": n_masks * n_pts,
            # [K, N] raw outputs on the training matrix straight from the
            # fit program (device-resident until eval time; ~85 KB at
            # flagship shapes). sweep_eval_batched downloads it instead of
            # dispatching a traversal program + x upload per stack.
            "outputs": outputs,
        }
        models = [
            [
                make_model(
                    thresholds,
                    _LazySlice(stack, mi * n_pts + j),
                    merged[j],
                    mi,
                )
                for j in range(n_pts)
            ]
            for mi in range(n_masks)
        ]
        for mi in range(n_masks):
            for j in range(n_pts):
                m = models[mi][j]
                m._sweep_stack = stack
                m._sweep_lane = mi * n_pts + j
        return models


class XGBoostClassifier(_TreeEstimator):
    """OpXGBoostClassifier parity (XGBoost defaults: eta 0.3, maxDepth 6,
    lambda 1, numRound 100 in the reference grids)."""

    model_type = "OpXGBoostClassifier"

    def __init__(
        self,
        num_round: int = 100,
        eta: float = 0.3,
        max_depth: int = 6,
        reg_lambda: float = 1.0,
        gamma: float = 0.0,
        min_child_weight: float = 1.0,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__("xgbClassifier", max_depth, max_bins, uid=uid)
        self.num_round = num_round
        self.eta = eta
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.min_info_gain = min_info_gain

    def get_params(self):
        return {
            "num_round": self.num_round,
            "eta": self.eta,
            "max_depth": self.max_depth,
            "reg_lambda": self.reg_lambda,
            "gamma": self.gamma,
            "min_child_weight": self.min_child_weight,
            "min_info_gain": self.min_info_gain,
            "max_bins": self.max_bins,
        }

    _STATIC_GRID_KEYS = ("num_round", "max_depth", "max_bins")

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        kwargs = dict(
            num_rounds=int(self.num_round),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            eta=float(self.eta),
            reg_lambda=float(self.reg_lambda),
            gamma=float(self.gamma),
            min_child_weight=float(self.min_child_weight),
            min_info_gain=float(self.min_info_gain),
            objective="binary:logistic",
            feature_groups=fgroups,
        )
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        if num_classes == 2:
            trees, _ = TR.fit_boosted(binned, jnp.asarray(y, dtype=jnp.float32), rm, **kwargs)
            return BoostedBinaryModel(thresholds, trees, float(self.eta), 0.0)
        per_class = []
        for c in range(num_classes):
            yc = jnp.asarray((y == c).astype(np.float32))
            trees, _ = TR.fit_boosted(binned, yc, rm, **kwargs)
            per_class.append(trees)
        return BoostedMultiModel(thresholds, per_class, float(self.eta), 0.0)

    def _normalize_boost(self, merged: dict) -> dict:
        """Map this family's param names onto the boosting knobs (GBT uses
        Spark names: maxIter/stepSize/minInstancesPerNode)."""
        return merged

    def _fit_group_masks(self, x, y, masks, group_points):
        present = y[masks.max(axis=0) > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        if num_classes != 2:
            return None  # one-vs-rest loops stay sequential
        yj = np.asarray(y, dtype=np.float32)

        def run_batched(binned, m0, row_mask_k, knob, fgroups):
            trees, margin = TR.fit_boosted_batched(
                binned, yj, row_mask_k,
                num_rounds=int(m0["num_round"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                eta=knob("eta"), reg_lambda=knob("reg_lambda"),
                gamma=knob("gamma"),
                min_child_weight=knob("min_child_weight"),
                min_info_gain=knob("min_info_gain"),
                objective="binary:logistic",
                feature_groups=fgroups,
            )
            # the final margin IS each lane's raw output on every row
            return trees, margin

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: BoostedBinaryModel(th, tr, float(m["eta"]), 0.0),
            normalize=self._normalize_boost,
        )


class XGBoostRegressor(_TreeEstimator):
    model_type = "OpXGBoostRegressor"

    def __init__(
        self,
        num_round: int = 100,
        eta: float = 0.3,
        max_depth: int = 6,
        reg_lambda: float = 1.0,
        gamma: float = 0.0,
        min_child_weight: float = 1.0,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__("xgbRegressor", max_depth, max_bins, uid=uid)
        self.num_round = num_round
        self.eta = eta
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.min_info_gain = min_info_gain

    get_params = XGBoostClassifier.get_params
    _STATIC_GRID_KEYS = ("num_round", "max_depth", "max_bins")
    _normalize_boost = XGBoostClassifier._normalize_boost

    def _fit_group_masks(self, x, y, masks, group_points):
        yj = np.asarray(y, dtype=np.float32)
        # per-mask base score = mean target over that mask's rows
        sums = masks @ y.astype(np.float64)
        cnts = masks.sum(axis=1)
        base_scores = np.where(cnts > 0, sums / np.maximum(cnts, 1), 0.0)
        n_pts = len(group_points)

        def run_batched(binned, m0, row_mask_k, knob, fgroups):
            base_k = np.repeat(base_scores, n_pts).astype(np.float32)
            trees, margin = TR.fit_boosted_batched(
                binned, yj, row_mask_k,
                num_rounds=int(m0["num_round"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                eta=knob("eta"), reg_lambda=knob("reg_lambda"),
                gamma=knob("gamma"),
                min_child_weight=knob("min_child_weight"),
                min_info_gain=knob("min_info_gain"),
                base_score=base_k,
                objective="reg:squarederror",
                feature_groups=fgroups,
            )
            return trees, margin

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: BoostedRegressionModel(
                th, tr, float(m["eta"]), float(base_scores[mi])
            ),
            normalize=self._normalize_boost,
        )

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        base = float(np.mean(y[row_mask > 0])) if (row_mask > 0).any() else 0.0
        trees, _ = TR.fit_boosted(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_rounds=int(self.num_round),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            eta=float(self.eta),
            reg_lambda=float(self.reg_lambda),
            gamma=float(self.gamma),
            min_child_weight=float(self.min_child_weight),
            min_info_gain=float(self.min_info_gain),
            base_score=base,
            objective="reg:squarederror",
            feature_groups=fgroups,
        )
        return BoostedRegressionModel(thresholds, trees, float(self.eta), base)


class GBTClassifier(XGBoostClassifier):
    """OpGBTClassifier parity: Spark GBT defaults maxIter 20, stepSize 0.1,
    maxDepth 5, variance-style gain with no regularization."""

    model_type = "OpGBTClassifier"

    def __init__(
        self,
        max_iter: int = 20,
        step_size: float = 0.1,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__(
            num_round=max_iter,
            eta=step_size,
            max_depth=max_depth,
            reg_lambda=0.0,
            gamma=0.0,
            min_child_weight=float(min_instances_per_node),
            max_bins=max_bins,
            uid=uid,
        )
        self.max_iter = max_iter
        self.step_size = step_size
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain

    def get_params(self):
        return {
            "max_iter": self.max_iter,
            "step_size": self.step_size,
            "max_depth": self.max_depth,
            "min_instances_per_node": self.min_instances_per_node,
            "min_info_gain": self.min_info_gain,
            "max_bins": self.max_bins,
        }

    _STATIC_GRID_KEYS = ("max_iter", "max_depth", "max_bins")

    def fit_arrays(self, x, y, row_mask):
        # keep the boosted knobs in sync with the Spark-named params
        self.num_round = self.max_iter
        self.eta = self.step_size
        self.min_child_weight = float(self.min_instances_per_node)
        return super().fit_arrays(x, y, row_mask)

    def _normalize_boost(self, merged: dict) -> dict:
        return {
            "num_round": merged["max_iter"],
            "eta": merged["step_size"],
            "reg_lambda": 0.0,
            "gamma": 0.0,
            "min_child_weight": float(merged["min_instances_per_node"]),
            "min_info_gain": merged["min_info_gain"],
            "max_depth": merged["max_depth"],
            "max_bins": merged["max_bins"],
        }


class GBTRegressor(XGBoostRegressor):
    model_type = "OpGBTRegressor"
    _STATIC_GRID_KEYS = ("max_iter", "max_depth", "max_bins")
    _normalize_boost = GBTClassifier._normalize_boost

    def __init__(
        self,
        max_iter: int = 20,
        step_size: float = 0.1,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__(
            num_round=max_iter,
            eta=step_size,
            max_depth=max_depth,
            reg_lambda=0.0,
            gamma=0.0,
            min_child_weight=float(min_instances_per_node),
            max_bins=max_bins,
            uid=uid,
        )
        self.max_iter = max_iter
        self.step_size = step_size
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain

    get_params = GBTClassifier.get_params

    def fit_arrays(self, x, y, row_mask):
        self.num_round = self.max_iter
        self.eta = self.step_size
        self.min_child_weight = float(self.min_instances_per_node)
        return super().fit_arrays(x, y, row_mask)


class RandomForestClassifier(_TreeEstimator):
    """OpRandomForestClassifier parity (Spark defaults: numTrees 20, maxDepth
    5, featureSubsetStrategy 'auto' = sqrt for classification)."""

    model_type = "OpRandomForestClassifier"

    def __init__(
        self,
        num_trees: int = 20,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        subsampling_rate: float = 1.0,
        max_bins: int = 32,
        seed: int = 42,
        uid: str | None = None,
    ):
        super().__init__("rfClassifier", max_depth, max_bins, uid=uid)
        self.num_trees = num_trees
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.seed = seed

    def get_params(self):
        return {
            "num_trees": self.num_trees,
            "max_depth": self.max_depth,
            "min_instances_per_node": self.min_instances_per_node,
            "min_info_gain": self.min_info_gain,
            "subsampling_rate": self.subsampling_rate,
            "max_bins": self.max_bins,
            "seed": self.seed,
        }

    # max_depth STAYS static by default: collapsing the depth groups into
    # one max-depth program via max_depth_v measured SLOWER end-to-end on
    # the tunneled chip (one fat program compiles/loads worse than three
    # slim ones, and every lane pays deep-level eval). run_batched still
    # wires per-lane caps for custom groupings that mix depths.
    _STATIC_GRID_KEYS = ("num_trees", "max_depth", "max_bins", "seed")

    @staticmethod
    def _colsample(num_features: int) -> float:
        """Spark featureSubsetStrategy 'auto' = sqrt for classification."""
        return 1.0 / np.sqrt(max(num_features, 1))

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        colsample = self._colsample(x.shape[1])
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        kwargs = dict(
            num_trees=int(self.num_trees),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            subsample_rate=float(self.subsampling_rate),
            colsample_rate=float(colsample),
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain),
            seed=int(self.seed),
            lowp=True,  # one-vs-rest indicators are bf16-exact
            feature_groups=fgroups,
        )
        if num_classes == 2:
            forests = [
                TR.fit_forest(binned, jnp.asarray((y == 1).astype(np.float32)), rm, **kwargs)
            ]
        else:
            forests = [
                TR.fit_forest(binned, jnp.asarray((y == c).astype(np.float32)), rm, **kwargs)
                for c in range(num_classes)
            ]
        return ForestClassifierModel(thresholds, forests)

    def _fit_group_masks(self, x, y, masks, group_points):
        present = y[masks.max(axis=0) > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        if num_classes != 2:
            return self._fit_group_masks_multiclass(
                x, y, masks, group_points, num_classes
            )
        colsample = self._colsample(x.shape[1])
        yj = np.asarray((y == 1), dtype=np.float32)

        def run_batched(binned, m0, row_mask_k, knob, fgroups):
            # depth rides the lane axis: ONE program at the grid's max
            # depth serves every depth point (program acquisition, not
            # execution, dominates the flagship sweep)
            depth_arr = np.asarray(knob("max_depth"))
            uniform = bool((depth_arr == depth_arr[0]).all())
            return TR.fit_forest_batched(
                binned, yj, row_mask_k,
                num_trees=int(m0["num_trees"]),
                max_depth=int(depth_arr.max()),
                num_bins=int(m0["max_bins"]),
                subsample_rate=knob("subsampling_rate"),
                colsample_rate=float(colsample),
                min_instances=knob("min_instances_per_node"),
                min_info_gain=knob("min_info_gain"),
                seed=int(m0["seed"]),
                lowp=True,  # one-vs-rest indicators are bf16-exact
                feature_groups=fgroups,
                max_depth_v=(
                    None if uniform
                    else depth_arr.astype(np.int32)
                ),
                return_outputs=True,
            )

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: ForestClassifierModel(th, [tr]),
        )

    def _fit_group_masks_multiclass(self, x, y, masks, group_points,
                                    num_classes):
        """One-vs-rest multiclass sweep as ONE batched program per static
        group: lane (mask_i·n_pts + point_j)·C + c trains class c's
        indicator forest (per-lane targets — trees._forest_trees_scan).
        The sequential fallback paid masks × points × classes separate
        forest programs (the 143 s iris bench of round 5's first cut)."""
        from ..parallel.mesh import execution_mesh

        if execution_mesh() is not None:
            # per-lane targets are single-device only (trees.py raises);
            # a raise here would trip the validator's candidate isolation
            # and silently drop the whole RF family — keep the sequential
            # sharded-safe fallback instead
            return None
        thresholds, binned, fgroups = self._binned(x)
        self._last_feature_groups = fgroups
        colsample = self._colsample(x.shape[1])
        merged = [{**self.get_params(), **p} for p in group_points]
        n_masks, n_pts = masks.shape[0], len(merged)
        c = num_classes
        from ..compiler import stats as cstats

        # one program serves masks × points × classes lanes (dedup ledger)
        cstats.stats().record_sweep(lanes=n_masks * n_pts * c)
        ind = np.stack(
            [(y == cls) for cls in range(c)]
        ).astype(np.float32)                         # [C, N]
        rm = np.repeat(np.repeat(masks, n_pts, axis=0), c, axis=0)
        tg = np.tile(ind, (n_masks * n_pts, 1))      # [K·C, N]

        def knob(name):
            base = np.asarray(
                [float(m[name]) for m in merged] * n_masks, dtype=np.float32
            )
            return np.repeat(base, c)

        # max_depth is in _STATIC_GRID_KEYS, so every point of this group
        # shares one depth — no per-lane depth caps needed here
        m0 = merged[0]
        trees, outs = TR.fit_forest_batched(
            binned, tg, rm,
            num_trees=int(m0["num_trees"]),
            max_depth=int(m0["max_depth"]),
            num_bins=int(m0["max_bins"]),
            subsample_rate=knob("subsampling_rate"),
            colsample_rate=float(colsample),
            min_instances=knob("min_instances_per_node"),
            min_info_gain=knob("min_info_gain"),
            seed=int(m0["seed"]),
            lowp=True,
            feature_groups=fgroups,
            return_outputs=True,
        )
        leaves = jax.tree.leaves(trees)
        is_dev = bool(leaves) and hasattr(leaves[0], "devices")
        if (is_dev and len(leaves[0].devices()) > 1) or not is_dev:
            trees = jax.tree.map(lambda a: np.asarray(a), trees)
        stack = {"trees": trees, "thresholds": thresholds,
                 "k": n_masks * n_pts * c, "outputs": outs}
        models = [
            [
                ForestClassifierModel(
                    thresholds,
                    [
                        _LazySlice(stack, (mi * n_pts + j) * c + cls)
                        for cls in range(c)
                    ],
                )
                for j in range(n_pts)
            ]
            for mi in range(n_masks)
        ]
        # C output lanes per model: sweep_eval_batched evaluates from the
        # fit program's own per-class probabilities (the per-model predict
        # fallback materializes C device lane slices per model over the
        # tunnel — measured 143 s for the 18-point iris sweep)
        for mi in range(n_masks):
            for j in range(n_pts):
                m = models[mi][j]
                m._sweep_stack = stack
                m._sweep_lanes = [
                    (mi * n_pts + j) * c + cls for cls in range(c)
                ]
        return models


class RandomForestRegressor(_TreeEstimator):
    model_type = "OpRandomForestRegressor"

    def __init__(
        self,
        num_trees: int = 20,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        subsampling_rate: float = 1.0,
        max_bins: int = 32,
        seed: int = 42,
        uid: str | None = None,
    ):
        super().__init__("rfRegressor", max_depth, max_bins, uid=uid)
        self.num_trees = num_trees
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.seed = seed

    get_params = RandomForestClassifier.get_params
    # max_depth STAYS static by default: collapsing the depth groups into
    # one max-depth program via max_depth_v measured SLOWER end-to-end on
    # the tunneled chip (one fat program compiles/loads worse than three
    # slim ones, and every lane pays deep-level eval). run_batched still
    # wires per-lane caps for custom groupings that mix depths.
    _STATIC_GRID_KEYS = ("num_trees", "max_depth", "max_bins", "seed")

    @staticmethod
    def _colsample(num_features: int) -> float:
        """Spark featureSubsetStrategy 'auto' = onethird for regression."""
        return 1.0 / 3.0

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        colsample = self._colsample(x.shape[1])
        trees = TR.fit_forest(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_trees=int(self.num_trees),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            subsample_rate=float(self.subsampling_rate),
            colsample_rate=colsample,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain),
            seed=int(self.seed),
            feature_groups=fgroups,
        )
        return ForestRegressionModel(thresholds, trees)

    def _fit_group_masks(self, x, y, masks, group_points):
        colsample = self._colsample(x.shape[1])
        yj = np.asarray(y, dtype=np.float32)

        def run_batched(binned, m0, row_mask_k, knob, fgroups):
            depth_arr = np.asarray(knob("max_depth"))
            uniform = bool((depth_arr == depth_arr[0]).all())
            return TR.fit_forest_batched(
                binned, yj, row_mask_k,
                num_trees=int(m0["num_trees"]),
                max_depth=int(depth_arr.max()),
                num_bins=int(m0["max_bins"]),
                subsample_rate=knob("subsampling_rate"),
                colsample_rate=float(colsample),
                min_instances=knob("min_instances_per_node"),
                min_info_gain=knob("min_info_gain"),
                seed=int(m0["seed"]),
                feature_groups=fgroups,
                max_depth_v=(
                    None if uniform
                    else depth_arr.astype(np.int32)
                ),
                return_outputs=True,
            )

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: ForestRegressionModel(th, tr),
        )


class DecisionTreeClassifier(RandomForestClassifier):
    """Single unbagged tree (OpDecisionTreeClassifier parity)."""

    model_type = "OpDecisionTreeClassifier"

    def _fit_group_masks(self, x, y, masks, group_points):
        # RF's batched fit bootstraps + column-samples; a decision tree is
        # deterministic and full-feature — never inherit that path
        return None

    def __init__(self, max_depth: int = 5, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, max_bins: int = 32, uid=None):
        super().__init__(
            num_trees=1, max_depth=max_depth,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain, max_bins=max_bins, uid=uid,
        )

    def get_params(self):
        # a single tree has no forest knobs (num_trees/subsampling/seed);
        # params must mirror __init__ so the persistence round trip holds
        return {
            "max_depth": self.max_depth,
            "min_instances_per_node": self.min_instances_per_node,
            "min_info_gain": self.min_info_gain,
            "max_bins": self.max_bins,
        }

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        kwargs = dict(
            num_trees=1, max_depth=int(self.max_depth),
            num_bins=int(self.max_bins), subsample_rate=1.0, colsample_rate=1.0,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain), seed=int(self.seed),
            bootstrap=False, feature_groups=fgroups,
        )
        indicators = [1] if num_classes == 2 else list(range(num_classes))
        forests = [
            TR.fit_forest(binned, jnp.asarray((y == c).astype(np.float32)), rm, **kwargs)
            for c in indicators
        ]
        return ForestClassifierModel(thresholds, forests)


class DecisionTreeRegressor(RandomForestRegressor):
    model_type = "OpDecisionTreeRegressor"

    def _fit_group_masks(self, x, y, masks, group_points):
        return None  # see DecisionTreeClassifier — no RF randomization

    def __init__(self, max_depth: int = 5, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, max_bins: int = 32, uid=None):
        super().__init__(
            num_trees=1, max_depth=max_depth,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain, max_bins=max_bins, uid=uid,
        )

    get_params = DecisionTreeClassifier.get_params

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned, fgroups = self._binned(x)
        trees = TR.fit_forest(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_trees=1, max_depth=int(self.max_depth),
            num_bins=int(self.max_bins), subsample_rate=1.0, colsample_rate=1.0,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain), seed=int(self.seed),
            bootstrap=False, feature_groups=fgroups,
        )
        return ForestRegressionModel(thresholds, trees)


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py, TPJ0xx)
# --------------------------------------------------------------------------
def _trace_tree_stack(*lead: int):
    """Abstract Tree stack with the given leading axes (depth 2)."""
    import jax

    return TR.Tree(
        split_feat=jax.ShapeDtypeStruct((*lead, 2, 4), "int32"),
        split_bin=jax.ShapeDtypeStruct((*lead, 2, 4), "int32"),
        leaf_value=jax.ShapeDtypeStruct((*lead, 4), "float32"),
    )


def program_trace_specs():
    """Representative trace shapes for the banked serving/sweep tree
    programs. Serving programs bucket the BATCH axis (the scoring
    closure's pow2 row buckets); sweep programs bucket the LANE axis."""
    import jax

    f32, i32 = "float32", "int32"

    def _x(n: int):
        return jax.ShapeDtypeStruct((n, 3), f32)

    _thr = jax.ShapeDtypeStruct((3, 3), f32)
    _scalar = jax.ShapeDtypeStruct((), f32)

    def _predict_boosted(n: int):
        return (
            (_x(n), _thr, _trace_tree_stack(2), _scalar, _scalar), {}
        )

    def _predict_forest(n: int):
        return ((_x(n), _thr, _trace_tree_stack(2)), {})

    def _sweep(k: int):
        return (
            (
                _x(8), _thr, _trace_tree_stack(k, 2),
                jax.ShapeDtypeStruct((k,), f32),
                jax.ShapeDtypeStruct((k,), f32),
            ),
            {},
        )

    return [
        dict(
            name="bin_data",
            fn=_bin_data_jit,
            build=lambda n: ((_x(n), _thr), {}),
            buckets=(8, 16), scoring=True,
        ),
        dict(
            name="stack_lane",
            fn=_stack_lane,
            build=lambda k: (
                (
                    _trace_tree_stack(k, 2),
                    jax.ShapeDtypeStruct((), i32),
                ),
                {},
            ),
            buckets=(4, 8), bucket_axis="lanes", scoring=True,
        ),
        dict(
            name="predict_boosted",
            fn=TR.predict_boosted_raw,
            build=_predict_boosted,
            buckets=(8, 16), scoring=True,
        ),
        dict(
            name="predict_forest",
            fn=TR.predict_forest_raw,
            build=_predict_forest,
            buckets=(8, 16), scoring=True,
        ),
        dict(
            name="sweep_boost_outputs",
            fn=TR.sweep_boosted_outputs,
            build=_sweep,
            buckets=(4, 8), bucket_axis="lanes",
        ),
        dict(
            name="sweep_forest_outputs",
            fn=TR.sweep_forest_outputs,
            build=_sweep,
            buckets=(4, 8), bucket_axis="lanes",
        ),
    ]
