"""Tree-ensemble model stages: XGBoost / GBT / RandomForest / DecisionTree.

Reference stages replaced (behavioral parity on the histogram learner in
models/trees.py):
  * OpXGBoostClassifier/Regressor (core/.../classification/OpXGBoostClassifier.scala
    — JNI libxgboost + Rabit allreduce): XLA boosting with second-order
    gradients; pass ``mesh=`` to the trees.fit_* entry points to shard rows
    over the mesh data axis with per-level histograms psum'd over ICI
    (trees._sharded_boost_kernel — the Rabit replacement, proven
    tree-identical in tests/test_trees_sharded.py).
  * OpGBTClassifier/Regressor (Spark GBT; defaults maxIter 20, stepSize 0.1).
  * OpRandomForestClassifier/Regressor (Spark RF; defaults numTrees 50 in
    selector grids, maxDepth 5 spark default).
  * OpDecisionTreeClassifier/Regressor: single unbagged tree.

Known divergences (documented per SURVEY.md §7 hard-part 5): multiclass
boosting is one-vs-rest rather than softmax-per-round; RF classification
impurity is variance on per-class indicators (probability trees) rather than
gini — both preserve the fitted-probability semantics used downstream.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator, PredictorModel
from . import trees as TR


def _sigmoid(m: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-m))


def _tree_from_arrays(arrays: dict, prefix: str = "") -> TR.Tree:
    return TR.Tree(
        split_feat=arrays[f"{prefix}split_feat"],
        split_bin=arrays[f"{prefix}split_bin"],
        leaf_value=arrays[f"{prefix}leaf_value"],
    )


def _class_trees_from_arrays(arrays: dict) -> list[TR.Tree]:
    out = []
    c = 0
    while f"c{c}__split_feat" in arrays:
        out.append(_tree_from_arrays(arrays, prefix=f"c{c}__"))
        c += 1
    return out


class _BinnedModel(PredictorModel):
    """Shared state for binned-tree models; prediction goes through the
    fused jitted entry points (trees.predict_*_raw) which bin internally —
    one dispatch per scoring call."""

    def __init__(self, operation_name: str, thresholds: np.ndarray, uid=None):
        super().__init__(operation_name, uid=uid)
        self.thresholds = np.asarray(thresholds, dtype=np.float32)


class BoostedBinaryModel(_BinnedModel):
    def __init__(self, thresholds, trees: TR.Tree, eta: float, base_score: float, uid=None):
        super().__init__("xgbClassifier", thresholds, uid=uid)
        self.trees = jax.tree.map(jnp.asarray, trees)
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        return {
            "thresholds": self.thresholds,
            "split_feat": self.trees.split_feat,
            "split_bin": self.trees.split_bin,
            "leaf_value": self.trees.leaf_value,
        }

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _tree_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def predict_arrays(self, x):
        margin = np.asarray(
            TR.predict_boosted_raw(
                jnp.asarray(x, dtype=jnp.float32),
                jnp.asarray(self.thresholds), self.trees,
                jnp.float32(self.eta), jnp.float32(self.base_score),
            ),
            dtype=np.float64,
        )
        p1 = _sigmoid(margin)
        prob = np.stack([1 - p1, p1], axis=1)
        raw = np.stack([-margin, margin], axis=1)
        return (p1 > 0.5).astype(np.float64), prob, raw


class BoostedMultiModel(_BinnedModel):
    """One-vs-rest stack of boosted binary models."""

    def __init__(self, thresholds, trees_per_class: list[TR.Tree], eta, base_score, uid=None):
        super().__init__("xgbClassifier", thresholds, uid=uid)
        self.trees_per_class = [jax.tree.map(jnp.asarray, t) for t in trees_per_class]
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        out = {"thresholds": self.thresholds}
        for c, t in enumerate(self.trees_per_class):
            out[f"c{c}__split_feat"] = t.split_feat
            out[f"c{c}__split_bin"] = t.split_bin
            out[f"c{c}__leaf_value"] = t.leaf_value
        return out

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _class_trees_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def predict_arrays(self, x):
        xj = jnp.asarray(x, dtype=jnp.float32)
        thr = jnp.asarray(self.thresholds)
        eta = jnp.float32(self.eta)
        base = jnp.float32(self.base_score)
        margins = np.stack(
            [
                np.asarray(TR.predict_boosted_raw(xj, thr, t, eta, base))
                for t in self.trees_per_class
            ],
            axis=1,
        ).astype(np.float64)
        p = _sigmoid(margins)
        prob = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        return prob.argmax(axis=1).astype(np.float64), prob, margins


class BoostedRegressionModel(_BinnedModel):
    def __init__(self, thresholds, trees, eta, base_score, uid=None):
        super().__init__("xgbRegressor", thresholds, uid=uid)
        self.trees = jax.tree.map(jnp.asarray, trees)
        self.eta = eta
        self.base_score = base_score

    def get_arrays(self):
        return {
            "thresholds": self.thresholds,
            "split_feat": self.trees.split_feat,
            "split_bin": self.trees.split_bin,
            "leaf_value": self.trees.leaf_value,
        }

    def get_params(self):
        return {"eta": self.eta, "base_score": self.base_score}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["thresholds"], _tree_from_arrays(arrays),
            params["eta"], params["base_score"],
        )

    def predict_arrays(self, x):
        pred = np.asarray(
            TR.predict_boosted_raw(
                jnp.asarray(x, dtype=jnp.float32),
                jnp.asarray(self.thresholds), self.trees,
                jnp.float32(self.eta), jnp.float32(self.base_score),
            ),
            dtype=np.float64,
        )
        return pred, None, None


class ForestClassifierModel(_BinnedModel):
    """Per-class probability forests (leaf value = class fraction)."""

    def __init__(self, thresholds, forests_per_class: list[TR.Tree], uid=None):
        super().__init__("rfClassifier", thresholds, uid=uid)
        self.forests_per_class = [jax.tree.map(jnp.asarray, t) for t in forests_per_class]

    def get_arrays(self):
        out = {"thresholds": self.thresholds}
        for c, t in enumerate(self.forests_per_class):
            out[f"c{c}__split_feat"] = t.split_feat
            out[f"c{c}__split_bin"] = t.split_bin
            out[f"c{c}__leaf_value"] = t.leaf_value
        return out

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["thresholds"], _class_trees_from_arrays(arrays))

    def predict_arrays(self, x):
        xj = jnp.asarray(x, dtype=jnp.float32)
        thr = jnp.asarray(self.thresholds)
        probs = np.stack(
            [
                np.asarray(TR.predict_forest_raw(xj, thr, t))
                for t in self.forests_per_class
            ],
            axis=1,
        ).astype(np.float64)
        probs = np.clip(probs, 0.0, 1.0)
        if probs.shape[1] == 1:  # binary trained on the positive indicator
            probs = np.concatenate([1 - probs, probs], axis=1)
        raw = probs.copy()
        prob = probs / np.maximum(probs.sum(axis=1, keepdims=True), 1e-12)
        return prob.argmax(axis=1).astype(np.float64), prob, raw


class ForestRegressionModel(_BinnedModel):
    def __init__(self, thresholds, trees, uid=None):
        super().__init__("rfRegressor", thresholds, uid=uid)
        self.trees = jax.tree.map(jnp.asarray, trees)

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["thresholds"], _tree_from_arrays(arrays))

    def get_arrays(self):
        return {
            "thresholds": self.thresholds,
            "split_feat": self.trees.split_feat,
            "split_bin": self.trees.split_bin,
            "leaf_value": self.trees.leaf_value,
        }

    def predict_arrays(self, x):
        pred = np.asarray(
            TR.predict_forest_raw(
                jnp.asarray(x, dtype=jnp.float32),
                jnp.asarray(self.thresholds), self.trees,
            ),
            dtype=np.float64,
        )
        return pred, None, None


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------
class _TreeEstimator(PredictorEstimator):
    #: grid params that are STATIC in the jitted fit (shape-affecting);
    #: points sharing them batch into one vmapped fit
    _STATIC_GRID_KEYS: tuple = ()

    def __init__(self, operation_name: str, max_depth: int, max_bins: int, uid=None):
        super().__init__(operation_name, uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins

    def _binned(self, x: np.ndarray) -> tuple[np.ndarray, jax.Array]:
        thresholds = TR.quantile_thresholds(x, self.max_bins)
        return thresholds, TR.bin_data(
            jnp.asarray(x, dtype=jnp.float32), jnp.asarray(thresholds)
        )

    def _fit_group_masks(self, x, y, masks, group_points):
        """Fit len(masks) × len(group_points) same-static-shape models in
        ONE batched program (fit axis = histogram-kernel grid axis, see
        trees.grow_tree_batched); None → caller falls back to sequential
        fits. Overridden per family. ``masks`` is [M, N] float32."""
        return None

    def fit_arrays_batched(self, x, y, row_mask, points):
        """One mask, many grid points (back-compat validator hook)."""
        return self.fit_arrays_batched_masks(x, y, [row_mask], points)[0]

    def fit_arrays_batched_masks(self, x, y, masks, points):
        """Validator hook: the folds × grid sweep batches points that share
        static shapes into one compiled program per group — the TPU
        replacement for the reference's driver thread pool
        (OpValidator.scala:363-367). A 3-fold × 18-point RF grid becomes 3
        programs (one per max_depth) instead of 54 dispatches.

        Set TPTPU_BATCHED_FITS=0 to force sequential fits."""
        import os

        masks = [np.asarray(m, dtype=np.float32) for m in masks]
        if (
            os.environ.get("TPTPU_BATCHED_FITS") == "0"
            or not self._STATIC_GRID_KEYS
        ):
            return [
                [self.with_params(**p).fit_arrays(x, y, m) for p in points]
                for m in masks
            ]
        groups: dict[tuple, list[int]] = {}
        for i, p in enumerate(points):
            merged = {**self.get_params(), **p}
            key = tuple(merged.get(k) for k in self._STATIC_GRID_KEYS)
            groups.setdefault(key, []).append(i)
        models: list[list] = [[None] * len(points) for _ in masks]
        mask_arr = np.stack(masks)
        for idxs in groups.values():
            fitted = self._fit_group_masks(
                x, y, mask_arr, [points[i] for i in idxs]
            )
            if fitted is None:
                fitted = [
                    [
                        self.with_params(**points[i]).fit_arrays(x, y, m)
                        for i in idxs
                    ]
                    for m in masks
                ]
            for mi in range(len(masks)):
                for j, i in enumerate(idxs):
                    models[mi][i] = fitted[mi][j]
        return models

    @staticmethod
    def _tree_slice(stacked_trees, i):
        return jax.tree.map(lambda a: a[i], stacked_trees)

    def _batched_group_fit(
        self, x, masks, group_points, run_batched, make_model, normalize=None
    ):
        """Shared plumbing for the masks × points batched fit: bin once,
        merge (+ normalize) params, stack the float knobs mask-major
        (fit k = mask_index * n_points + point_index), run the family's
        batched trainer, slice the [K, ...] tree pytree back out.

        ``run_batched(binned, m0, row_mask_K, knob) -> [K, ...] tree pytree``
        where ``knob(name)`` returns the [K] float32 array for a param;
        ``make_model(thresholds, sliced_trees, merged_params, mask_index)``.
        """
        base = self.with_params(**group_points[0])
        thresholds, binned = base._binned(x)
        norm = normalize or (lambda m: m)
        merged = [norm({**self.get_params(), **p}) for p in group_points]
        n_masks, n_pts = masks.shape[0], len(merged)
        row_mask_k = jnp.asarray(np.repeat(masks, n_pts, axis=0))

        def knob(name):
            return jnp.asarray(
                [float(m[name]) for m in merged] * n_masks, dtype=jnp.float32
            )

        trees = run_batched(binned, merged[0], row_mask_k, knob)
        # mesh-sharded fits return trees replicated across the mesh; pull
        # them to host ONCE before the per-model slicing — slicing a
        # multi-device array eagerly dispatches a gather on every device per
        # slice (hundreds across a sweep), which both wastes dispatches and
        # stresses the async CPU runtime. Single-device (1-chip) fits stay
        # device-resident for the fused predict paths.
        leaves = jax.tree.leaves(trees)
        if leaves and len(getattr(leaves[0], "devices", lambda: [0])()) > 1:
            trees = jax.tree.map(lambda a: np.asarray(a), trees)
        return [
            [
                make_model(
                    thresholds,
                    self._tree_slice(trees, mi * n_pts + j),
                    merged[j],
                    mi,
                )
                for j in range(n_pts)
            ]
            for mi in range(n_masks)
        ]


class XGBoostClassifier(_TreeEstimator):
    """OpXGBoostClassifier parity (XGBoost defaults: eta 0.3, maxDepth 6,
    lambda 1, numRound 100 in the reference grids)."""

    model_type = "OpXGBoostClassifier"

    def __init__(
        self,
        num_round: int = 100,
        eta: float = 0.3,
        max_depth: int = 6,
        reg_lambda: float = 1.0,
        gamma: float = 0.0,
        min_child_weight: float = 1.0,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__("xgbClassifier", max_depth, max_bins, uid=uid)
        self.num_round = num_round
        self.eta = eta
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.min_info_gain = min_info_gain

    def get_params(self):
        return {
            "num_round": self.num_round,
            "eta": self.eta,
            "max_depth": self.max_depth,
            "reg_lambda": self.reg_lambda,
            "gamma": self.gamma,
            "min_child_weight": self.min_child_weight,
            "min_info_gain": self.min_info_gain,
            "max_bins": self.max_bins,
        }

    _STATIC_GRID_KEYS = ("num_round", "max_depth", "max_bins")

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        kwargs = dict(
            num_rounds=int(self.num_round),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            eta=float(self.eta),
            reg_lambda=float(self.reg_lambda),
            gamma=float(self.gamma),
            min_child_weight=float(self.min_child_weight),
            min_info_gain=float(self.min_info_gain),
            objective="binary:logistic",
        )
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        if num_classes == 2:
            trees, _ = TR.fit_boosted(binned, jnp.asarray(y, dtype=jnp.float32), rm, **kwargs)
            return BoostedBinaryModel(thresholds, trees, float(self.eta), 0.0)
        per_class = []
        for c in range(num_classes):
            yc = jnp.asarray((y == c).astype(np.float32))
            trees, _ = TR.fit_boosted(binned, yc, rm, **kwargs)
            per_class.append(trees)
        return BoostedMultiModel(thresholds, per_class, float(self.eta), 0.0)

    def _normalize_boost(self, merged: dict) -> dict:
        """Map this family's param names onto the boosting knobs (GBT uses
        Spark names: maxIter/stepSize/minInstancesPerNode)."""
        return merged

    def _fit_group_masks(self, x, y, masks, group_points):
        present = y[masks.max(axis=0) > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        if num_classes != 2:
            return None  # one-vs-rest loops stay sequential
        yj = jnp.asarray(y, dtype=jnp.float32)

        def run_batched(binned, m0, row_mask_k, knob):
            trees, _ = TR.fit_boosted_batched(
                binned, yj, row_mask_k,
                num_rounds=int(m0["num_round"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                eta=knob("eta"), reg_lambda=knob("reg_lambda"),
                gamma=knob("gamma"),
                min_child_weight=knob("min_child_weight"),
                min_info_gain=knob("min_info_gain"),
                objective="binary:logistic",
            )
            return trees

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: BoostedBinaryModel(th, tr, float(m["eta"]), 0.0),
            normalize=self._normalize_boost,
        )


class XGBoostRegressor(_TreeEstimator):
    model_type = "OpXGBoostRegressor"

    def __init__(
        self,
        num_round: int = 100,
        eta: float = 0.3,
        max_depth: int = 6,
        reg_lambda: float = 1.0,
        gamma: float = 0.0,
        min_child_weight: float = 1.0,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__("xgbRegressor", max_depth, max_bins, uid=uid)
        self.num_round = num_round
        self.eta = eta
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.min_info_gain = min_info_gain

    get_params = XGBoostClassifier.get_params
    _STATIC_GRID_KEYS = ("num_round", "max_depth", "max_bins")
    _normalize_boost = XGBoostClassifier._normalize_boost

    def _fit_group_masks(self, x, y, masks, group_points):
        yj = jnp.asarray(y, dtype=jnp.float32)
        # per-mask base score = mean target over that mask's rows
        sums = masks @ y.astype(np.float64)
        cnts = masks.sum(axis=1)
        base_scores = np.where(cnts > 0, sums / np.maximum(cnts, 1), 0.0)
        n_pts = len(group_points)

        def run_batched(binned, m0, row_mask_k, knob):
            base_k = jnp.asarray(
                np.repeat(base_scores, n_pts), dtype=jnp.float32
            )
            trees, _ = TR.fit_boosted_batched(
                binned, yj, row_mask_k,
                num_rounds=int(m0["num_round"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                eta=knob("eta"), reg_lambda=knob("reg_lambda"),
                gamma=knob("gamma"),
                min_child_weight=knob("min_child_weight"),
                min_info_gain=knob("min_info_gain"),
                base_score=base_k,
                objective="reg:squarederror",
            )
            return trees

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: BoostedRegressionModel(
                th, tr, float(m["eta"]), float(base_scores[mi])
            ),
            normalize=self._normalize_boost,
        )

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        base = float(np.mean(y[row_mask > 0])) if (row_mask > 0).any() else 0.0
        trees, _ = TR.fit_boosted(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_rounds=int(self.num_round),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            eta=float(self.eta),
            reg_lambda=float(self.reg_lambda),
            gamma=float(self.gamma),
            min_child_weight=float(self.min_child_weight),
            min_info_gain=float(self.min_info_gain),
            base_score=base,
            objective="reg:squarederror",
        )
        return BoostedRegressionModel(thresholds, trees, float(self.eta), base)


class GBTClassifier(XGBoostClassifier):
    """OpGBTClassifier parity: Spark GBT defaults maxIter 20, stepSize 0.1,
    maxDepth 5, variance-style gain with no regularization."""

    model_type = "OpGBTClassifier"

    def __init__(
        self,
        max_iter: int = 20,
        step_size: float = 0.1,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__(
            num_round=max_iter,
            eta=step_size,
            max_depth=max_depth,
            reg_lambda=0.0,
            gamma=0.0,
            min_child_weight=float(min_instances_per_node),
            max_bins=max_bins,
            uid=uid,
        )
        self.max_iter = max_iter
        self.step_size = step_size
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain

    def get_params(self):
        return {
            "max_iter": self.max_iter,
            "step_size": self.step_size,
            "max_depth": self.max_depth,
            "min_instances_per_node": self.min_instances_per_node,
            "min_info_gain": self.min_info_gain,
            "max_bins": self.max_bins,
        }

    _STATIC_GRID_KEYS = ("max_iter", "max_depth", "max_bins")

    def fit_arrays(self, x, y, row_mask):
        # keep the boosted knobs in sync with the Spark-named params
        self.num_round = self.max_iter
        self.eta = self.step_size
        self.min_child_weight = float(self.min_instances_per_node)
        return super().fit_arrays(x, y, row_mask)

    def _normalize_boost(self, merged: dict) -> dict:
        return {
            "num_round": merged["max_iter"],
            "eta": merged["step_size"],
            "reg_lambda": 0.0,
            "gamma": 0.0,
            "min_child_weight": float(merged["min_instances_per_node"]),
            "min_info_gain": merged["min_info_gain"],
            "max_depth": merged["max_depth"],
            "max_bins": merged["max_bins"],
        }


class GBTRegressor(XGBoostRegressor):
    model_type = "OpGBTRegressor"
    _STATIC_GRID_KEYS = ("max_iter", "max_depth", "max_bins")
    _normalize_boost = GBTClassifier._normalize_boost

    def __init__(
        self,
        max_iter: int = 20,
        step_size: float = 0.1,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        max_bins: int = 32,
        uid: str | None = None,
    ):
        super().__init__(
            num_round=max_iter,
            eta=step_size,
            max_depth=max_depth,
            reg_lambda=0.0,
            gamma=0.0,
            min_child_weight=float(min_instances_per_node),
            max_bins=max_bins,
            uid=uid,
        )
        self.max_iter = max_iter
        self.step_size = step_size
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain

    get_params = GBTClassifier.get_params

    def fit_arrays(self, x, y, row_mask):
        self.num_round = self.max_iter
        self.eta = self.step_size
        self.min_child_weight = float(self.min_instances_per_node)
        return super().fit_arrays(x, y, row_mask)


class RandomForestClassifier(_TreeEstimator):
    """OpRandomForestClassifier parity (Spark defaults: numTrees 20, maxDepth
    5, featureSubsetStrategy 'auto' = sqrt for classification)."""

    model_type = "OpRandomForestClassifier"

    def __init__(
        self,
        num_trees: int = 20,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        subsampling_rate: float = 1.0,
        max_bins: int = 32,
        seed: int = 42,
        uid: str | None = None,
    ):
        super().__init__("rfClassifier", max_depth, max_bins, uid=uid)
        self.num_trees = num_trees
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.seed = seed

    def get_params(self):
        return {
            "num_trees": self.num_trees,
            "max_depth": self.max_depth,
            "min_instances_per_node": self.min_instances_per_node,
            "min_info_gain": self.min_info_gain,
            "subsampling_rate": self.subsampling_rate,
            "max_bins": self.max_bins,
            "seed": self.seed,
        }

    _STATIC_GRID_KEYS = ("num_trees", "max_depth", "max_bins", "seed")

    @staticmethod
    def _colsample(num_features: int) -> float:
        """Spark featureSubsetStrategy 'auto' = sqrt for classification."""
        return 1.0 / np.sqrt(max(num_features, 1))

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        colsample = self._colsample(x.shape[1])
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        kwargs = dict(
            num_trees=int(self.num_trees),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            subsample_rate=float(self.subsampling_rate),
            colsample_rate=float(colsample),
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain),
            seed=int(self.seed),
            lowp=True,  # one-vs-rest indicators are bf16-exact
        )
        if num_classes == 2:
            forests = [
                TR.fit_forest(binned, jnp.asarray((y == 1).astype(np.float32)), rm, **kwargs)
            ]
        else:
            forests = [
                TR.fit_forest(binned, jnp.asarray((y == c).astype(np.float32)), rm, **kwargs)
                for c in range(num_classes)
            ]
        return ForestClassifierModel(thresholds, forests)

    def _fit_group_masks(self, x, y, masks, group_points):
        present = y[masks.max(axis=0) > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        if num_classes != 2:
            return None
        colsample = self._colsample(x.shape[1])
        yj = jnp.asarray((y == 1).astype(np.float32))

        def run_batched(binned, m0, row_mask_k, knob):
            return TR.fit_forest_batched(
                binned, yj, row_mask_k,
                num_trees=int(m0["num_trees"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                subsample_rate=knob("subsampling_rate"),
                colsample_rate=float(colsample),
                min_instances=knob("min_instances_per_node"),
                min_info_gain=knob("min_info_gain"),
                seed=int(m0["seed"]),
                lowp=True,  # one-vs-rest indicators are bf16-exact
            )

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: ForestClassifierModel(th, [tr]),
        )


class RandomForestRegressor(_TreeEstimator):
    model_type = "OpRandomForestRegressor"

    def __init__(
        self,
        num_trees: int = 20,
        max_depth: int = 5,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        subsampling_rate: float = 1.0,
        max_bins: int = 32,
        seed: int = 42,
        uid: str | None = None,
    ):
        super().__init__("rfRegressor", max_depth, max_bins, uid=uid)
        self.num_trees = num_trees
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.seed = seed

    get_params = RandomForestClassifier.get_params
    _STATIC_GRID_KEYS = ("num_trees", "max_depth", "max_bins", "seed")

    @staticmethod
    def _colsample(num_features: int) -> float:
        """Spark featureSubsetStrategy 'auto' = onethird for regression."""
        return 1.0 / 3.0

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        colsample = self._colsample(x.shape[1])
        trees = TR.fit_forest(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_trees=int(self.num_trees),
            max_depth=int(self.max_depth),
            num_bins=int(self.max_bins),
            subsample_rate=float(self.subsampling_rate),
            colsample_rate=colsample,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain),
            seed=int(self.seed),
        )
        return ForestRegressionModel(thresholds, trees)

    def _fit_group_masks(self, x, y, masks, group_points):
        colsample = self._colsample(x.shape[1])
        yj = jnp.asarray(y, dtype=jnp.float32)

        def run_batched(binned, m0, row_mask_k, knob):
            return TR.fit_forest_batched(
                binned, yj, row_mask_k,
                num_trees=int(m0["num_trees"]),
                max_depth=int(m0["max_depth"]),
                num_bins=int(m0["max_bins"]),
                subsample_rate=knob("subsampling_rate"),
                colsample_rate=float(colsample),
                min_instances=knob("min_instances_per_node"),
                min_info_gain=knob("min_info_gain"),
                seed=int(m0["seed"]),
            )

        return self._batched_group_fit(
            x, masks, group_points, run_batched,
            lambda th, tr, m, mi: ForestRegressionModel(th, tr),
        )


class DecisionTreeClassifier(RandomForestClassifier):
    """Single unbagged tree (OpDecisionTreeClassifier parity)."""

    model_type = "OpDecisionTreeClassifier"

    def _fit_group_masks(self, x, y, masks, group_points):
        # RF's batched fit bootstraps + column-samples; a decision tree is
        # deterministic and full-feature — never inherit that path
        return None

    def __init__(self, max_depth: int = 5, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, max_bins: int = 32, uid=None):
        super().__init__(
            num_trees=1, max_depth=max_depth,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain, max_bins=max_bins, uid=uid,
        )

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        rm = jnp.asarray(row_mask, dtype=jnp.float32)
        kwargs = dict(
            num_trees=1, max_depth=int(self.max_depth),
            num_bins=int(self.max_bins), subsample_rate=1.0, colsample_rate=1.0,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain), seed=int(self.seed),
            bootstrap=False,
        )
        indicators = [1] if num_classes == 2 else list(range(num_classes))
        forests = [
            TR.fit_forest(binned, jnp.asarray((y == c).astype(np.float32)), rm, **kwargs)
            for c in indicators
        ]
        return ForestClassifierModel(thresholds, forests)


class DecisionTreeRegressor(RandomForestRegressor):
    model_type = "OpDecisionTreeRegressor"

    def _fit_group_masks(self, x, y, masks, group_points):
        return None  # see DecisionTreeClassifier — no RF randomization

    def __init__(self, max_depth: int = 5, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, max_bins: int = 32, uid=None):
        super().__init__(
            num_trees=1, max_depth=max_depth,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain, max_bins=max_bins, uid=uid,
        )

    def fit_arrays(self, x, y, row_mask):
        thresholds, binned = self._binned(x)
        trees = TR.fit_forest(
            binned,
            jnp.asarray(y, dtype=jnp.float32),
            jnp.asarray(row_mask, dtype=jnp.float32),
            num_trees=1, max_depth=int(self.max_depth),
            num_bins=int(self.max_bins), subsample_rate=1.0, colsample_rate=1.0,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=float(self.min_info_gain), seed=int(self.seed),
            bootstrap=False,
        )
        return ForestRegressionModel(thresholds, trees)
