"""Predictor stage bases.

Reference: core/.../stages/sparkwrappers/specific/OpPredictorWrapper.scala:70 —
every model family is an Estimator[(RealNN label, OPVector features)] ->
Prediction, producing a model whose transform emits the Prediction column
(prediction + probability_* + rawPrediction_*).

TPU design: ``fit_arrays(x, y, row_mask)`` is the whole training step — a
pure jitted function of dense arrays, so fold masks and hyperparameter grids
become vmap axes in the model selector rather than driver threads.
"""
from __future__ import annotations

from typing import Any

import numpy as np

from ..dataset import Dataset
from ..stages.base import Estimator, Model
from ..types import OPVector, Prediction, RealNN
from ..types.columns import Column, NumericColumn, PredictionColumn, VectorColumn


class PredictorModel(Model):
    output_type = Prediction
    label_inputs = (0,)  # (label, features) — label slot is sanctioned

    def predict_arrays(
        self, x: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray | None, np.ndarray | None]:
        """(prediction [N], probability [N,C]|None, raw [N,C]|None)."""
        raise NotImplementedError

    def transform_columns(self, *cols: Column, num_rows: int) -> PredictionColumn:
        vec = cols[-1]
        assert isinstance(vec, VectorColumn), "predictor expects (label, features)"
        pred, prob, raw = self.predict_arrays(np.asarray(vec.values, dtype=np.float32))
        return PredictionColumn(
            Prediction,
            np.asarray(pred, dtype=np.float64),
            None if prob is None else np.asarray(prob, dtype=np.float64),
            None if raw is None else np.asarray(raw, dtype=np.float64),
        )


class PredictorEstimator(Estimator):
    """Base for model-family estimators. Subclasses implement
    ``fit_arrays(x, y, row_mask) -> PredictorModel`` and expose their
    hyperparameters as attributes + ``get_params``."""

    input_types = (RealNN, OPVector)
    output_type = Prediction
    label_inputs = (0,)  # the response is THIS stage's training target

    def extract_xy(self, dataset: Dataset) -> tuple[np.ndarray, np.ndarray]:
        label_name, vec_name = self.input_names
        label = dataset[label_name]
        vec = dataset[vec_name]
        assert isinstance(label, NumericColumn) and isinstance(vec, VectorColumn)
        return (
            np.asarray(vec.values, dtype=np.float32),
            label.values.astype(np.float32),
        )

    def fit_model(self, dataset: Dataset) -> PredictorModel:
        x, y = self.extract_xy(dataset)
        mask = np.ones(len(y), dtype=np.float32)
        return self.fit_arrays(x, y, mask)

    def fit_arrays(
        self, x: np.ndarray, y: np.ndarray, row_mask: np.ndarray
    ) -> PredictorModel:
        raise NotImplementedError

    # ---- grid support ----------------------------------------------------
    def with_params(self, **params: Any) -> "PredictorEstimator":
        """A copy of this estimator with hyperparameters overridden (used by
        the model selector's grid expansion)."""
        import copy

        c = copy.copy(self)
        from ..utils import uid as uid_util

        c.uid = uid_util.make_uid(type(self))
        c.metadata = {}
        for k, v in params.items():
            if not hasattr(c, k):
                raise AttributeError(f"{type(self).__name__} has no param {k}")
            setattr(c, k, v)
        return c


def group_grid_by_statics(points, known_keys, statics_of):
    """Group grid-point indices by their STATIC (shape-affecting) params so
    dynamic params batch as lanes of one program; points carrying unknown
    keys fall out to a sequential list. Shared by the logistic and linear
    batched-masks sweeps (the grouping logic diverging between families was
    exactly how the round-1 'statics compared against ctor defaults' bug
    hid — see LogisticRegression._static_groups history).

    ``statics_of(point) -> hashable key``; returns (groups, sequential)
    where groups maps key -> [point indices].
    """
    groups: dict[Any, list[int]] = {}
    sequential: list[int] = []
    for i, p in enumerate(points):
        if set(p) - known_keys:
            sequential.append(i)
            continue
        groups.setdefault(statics_of(p), []).append(i)
    return groups, sequential
