"""Logistic regression (binary + multinomial).

Reference: core/.../stages/impl/classification/OpLogisticRegression.scala —
wraps Spark LR (L-BFGS/OWL-QN over native BLAS). Here training is the pure
XLA solver in models/solvers.py; gradients over a sharded batch reduce with
``psum`` when the data axis is sharded over a mesh.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .base import PredictorEstimator, PredictorModel
from .solvers import (
    fit_logistic_binary,
    fit_logistic_binary_batched,
    fit_logistic_multinomial,
)


class LogisticRegressionModel(PredictorModel):
    def __init__(
        self,
        weights: np.ndarray,       # [D] binary or [D, C] multinomial
        intercept: np.ndarray,     # scalar or [C]
        num_classes: int,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = np.asarray(intercept, dtype=np.float64)
        self.num_classes = num_classes

    def get_arrays(self):
        return {"weights": self.weights, "intercept": self.intercept}

    def get_params(self):
        return {"num_classes": self.num_classes}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], arrays["intercept"], params["num_classes"])

    def predict_arrays(self, x: np.ndarray):
        return self.predictions_from_core(x @ self.weights + self.intercept)

    def predictions_from_core(self, core: np.ndarray):
        """(pred, prob, raw) from the linear core (binary margin [N] or
        multinomial logits [N, C]) — the HOST epilogue shared by the
        staged predict and the fused graph's downloaded core."""
        core = np.asarray(core, dtype=np.float64)
        if self.num_classes == 2:
            margin = core
            p1 = 1.0 / (1.0 + np.exp(-margin))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margin, margin], axis=1)
        else:
            logits = core - core.max(axis=1, keepdims=True)
            e = np.exp(logits)
            prob = e / e.sum(axis=1, keepdims=True)
            raw = logits
        pred = prob.argmax(axis=1).astype(np.float64)
        return pred, prob, raw

    def fused_predict_spec(self):
        """Device core for the fused scoring graph: ``plane @ w + b`` in
        f32 (predictions within 1e-6 of the staged f64 host matmul)."""
        from ..compiler.fused import PredictorPlan

        params = {
            "w": np.asarray(self.weights, dtype=np.float32),
            "b": np.asarray(self.intercept, dtype=np.float32),
        }

        def core(plane, p):
            return plane @ p["w"] + p["b"]

        return PredictorPlan(
            stage=self, in_dim=int(self.weights.shape[0]), params=params,
            core=core, epilogue=self.predictions_from_core,
            descriptor=f"logreg:{self.num_classes}",
        )


class LogisticRegression(PredictorEstimator):
    """Params mirror Spark LR defaults (regParam=0, elasticNetParam=0,
    maxIter=100, standardization=true, fitIntercept=true)."""

    model_type = "OpLogisticRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 100,
        fit_intercept: bool = True,
        standardization: bool = True,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "elastic_net_param": self.elastic_net_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
            "standardization": self.standardization,
        }

    @staticmethod
    def _mesh_rows(x, y, masks):
        """Pad rows to the execution-mesh multiple (mask-0 padding is inert
        in the mask-weighted solvers) and shard x over the data axis;
        identity when no mesh is active. ``masks`` pads on its LAST axis
        (handles both [N] and [K, N])."""
        from ..parallel.mesh import data_row_multiple, shard_rows_if_active

        x = np.asarray(x, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        masks = np.asarray(masks, dtype=np.float32)
        pad = (-x.shape[0]) % data_row_multiple()
        if pad:
            x = np.pad(x, ((0, pad), (0, 0)))
            y = np.pad(y, (0, pad))
            widths = [(0, 0)] * (masks.ndim - 1) + [(0, pad)]
            masks = np.pad(masks, widths)
        return shard_rows_if_active(x), y, masks

    def fit_arrays(self, x, y, row_mask):
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        x, y, row_mask = self._mesh_rows(x, y, row_mask)
        # binary runs quasi-Newton (maxIter is the Spark-semantic knob,
        # 1:1); multinomial still runs FISTA, which needs a larger budget
        iters = self.max_iter * 4
        if num_classes == 2:
            params = fit_logistic_binary(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_iters=self.max_iter,
                fit_intercept=self.fit_intercept,
                standardization=self.standardization,
            )
        else:
            params = fit_logistic_multinomial(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_classes=num_classes,
                num_iters=iters,
                fit_intercept=self.fit_intercept,
                standardization=self.standardization,
            )
        return LogisticRegressionModel(
            np.asarray(params.weights), np.asarray(params.intercept), num_classes
        )

    # ---- batched sweeps (SURVEY.md §2.6: the reference's driver thread
    # pool becomes a batch axis of one compiled program) -------------------

    _KNOWN_KEYS = frozenset(
        ("reg_param", "elastic_net_param", "fit_intercept", "max_iter",
         "standardization")
    )

    def _static_groups(self, points) -> tuple[dict, list[int]]:
        """Group point indices by their STATIC params (fit_intercept,
        max_iter, standardization) — reg/elastic-net vary freely inside a
        group and batch as GEMM lanes. (Round 1 compared statics against
        the estimator's ctor defaults, so the default grid's max_iter=50
        vs ctor 100 silently disabled batching — every default sweep ran
        24 sequential fits.)"""
        from .base import group_grid_by_statics

        return group_grid_by_statics(
            points, self._KNOWN_KEYS,
            lambda p: (
                bool(p.get("fit_intercept", self.fit_intercept)),
                int(p.get("max_iter", self.max_iter)),
                bool(p.get("standardization", self.standardization)),
            ),
        )

    def _grid_values(self, points) -> tuple[np.ndarray, np.ndarray]:
        regs = np.asarray(
            [p.get("reg_param", self.reg_param) for p in points],
            dtype=np.float32,
        )
        ens = np.asarray(
            [p.get("elastic_net_param", self.elastic_net_param) for p in points],
            dtype=np.float32,
        )
        return regs, ens

    @staticmethod
    def _num_classes(y, any_mask) -> int:
        present = y[any_mask > 0]
        return max(int(present.max()) + 1 if len(present) else 2, 2)

    #: GLM lanes pad onto shape buckets and shard over the mesh's model
    #: axis; the pipelined fold schedule (workflow/cv.py) overlaps tree
    #: fits with these dispatches
    lane_family = "glm"

    def fit_arrays_batched(self, x, y, row_mask, grid_points):
        """One mask, many grid points — same-static groups batch into one
        program each; points with unknown params fit sequentially."""
        return self.fit_arrays_batched_masks(x, y, [row_mask], grid_points)[0]

    def _batched_fit(self, xp, yp, rm, regs, ens, num_classes, statics,
                     mesh=None):
        fit_intercept, max_iter, standardization = statics
        if num_classes == 2:
            from ..compiler import bucketing, dispatch
            from ..utils.aot import aot_call

            statics_kw = dict(
                num_iters=max_iter,
                fit_intercept=fit_intercept,
                standardization=standardization,
            )
            if mesh is not None:
                # the sharded sweep: lanes over MODEL_AXIS, rows over
                # DATA_AXIS, on the explicit SweepLayout PartitionSpecs,
                # with fold-level buffer donation (parallel/fit.py)
                from ..parallel.fit import sweep_parallel_fit

                return sweep_parallel_fit(
                    fit_logistic_binary_batched,
                    "sweep_logistic_binary_sharded", mesh,
                    xp, yp, rm, regs, ens, **statics_kw,
                )
            # cross-candidate dedup: every lane of this sweep shares ONE
            # program, and the lane count pads onto a shape bucket so a
            # near-miss sweep (one more grid point, one more fold) reuses
            # the same banked executable instead of compiling its own
            k, (rm, regs, ens) = bucketing.bucket_sweep_lanes(rm, regs, ens)
            # shared-x GEMM sweep (see fit_logistic_binary_batched); the x
            # upload reuses the transfer the DAG fit prefetched, when one
            # is in flight (compiler.dispatch)
            fit_fn = dispatch.donating(
                "logistic_binary_batched", fit_logistic_binary_batched,
                donate_argnums=(3, 4),
                static_argnames=(
                    "num_iters", "fit_intercept", "standardization"
                ),
            )
            out = aot_call(
                "logistic_binary_batched", fit_fn,
                (
                    dispatch.device_f32(xp), jnp.asarray(yp),
                    jnp.asarray(rm), jnp.asarray(regs), jnp.asarray(ens),
                ),
                statics_kw,
            )
            if rm.shape[0] > k:
                from .solvers import GLMParams

                out = GLMParams(
                    weights=out.weights[:k], intercept=out.intercept[:k]
                )
            return out
        return jax.vmap(
            lambda r, e, m: fit_logistic_multinomial(
                xp, yp, m, r, e, num_classes=num_classes,
                num_iters=max_iter * 4, fit_intercept=fit_intercept,
                standardization=standardization,
            )
        )(regs, ens, rm)

    def sweep_dispatch_masks(self, x, y, masks, grid_points):
        """Dispatch the folds × grid sweep, return a collector closure.

        Each same-(fit_intercept, max_iter, standardization) group batches
        (fold-mask, reg, elastic-net) triples onto the fit axis (binary:
        shared-x GEMM FISTA); points with unknown params fall back to
        sequential fits inside the collector. Binary groups under an
        active execution mesh route through the pjit'd SweepLayout path —
        explicit per-axis PartitionSpecs, donated fold buffers. Dispatch
        is async; the closure materializes the models, so tree-family
        fits can overlap (the pipelined lane schedule in workflow/cv.py)."""
        masks = [np.asarray(m, dtype=np.float32) for m in masks]
        groups, sequential = self._static_groups(grid_points)
        num_classes = self._num_classes(y, np.max(np.stack(masks), axis=0))
        n_masks = len(masks)
        stacked_groups: list[tuple[tuple, list[int], object]] = []
        if groups:
            from ..parallel.mesh import execution_mesh

            mesh = execution_mesh() if num_classes == 2 else None
            if mesh is not None:
                # the sharded path pads + places rows itself — handing it
                # raw host arrays keeps the donated buffers private to
                # one dispatch (a shared pre-sharded x could be consumed
                # out from under the next static group)
                xp, yp, masksp = (
                    np.asarray(x, dtype=np.float32),
                    np.asarray(y, dtype=np.float32),
                    np.stack(masks),
                )
            else:
                xp, yp, masksp = self._mesh_rows(x, y, np.stack(masks))
            for statics, idxs in groups.items():
                pts = [grid_points[i] for i in idxs]
                regs, ens = self._grid_values(pts * n_masks)
                rm = np.repeat(
                    masksp, len(pts), axis=0
                )  # [K, N], mask-major to match regs/ens tiling
                stacked = self._batched_fit(
                    xp, yp, rm, regs, ens, num_classes, statics, mesh=mesh
                )
                stacked_groups.append((idxs, len(pts), stacked))

        def collect() -> list[list]:
            models: list[list] = [
                [None] * len(grid_points) for _ in masks
            ]
            for idxs, n_pts, stacked in stacked_groups:
                w = np.asarray(stacked.weights)
                b = np.asarray(stacked.intercept)
                for mi in range(n_masks):
                    for j, i in enumerate(idxs):
                        models[mi][i] = LogisticRegressionModel(
                            w[mi * n_pts + j], b[mi * n_pts + j],
                            num_classes,
                        )
            for i in sequential:
                est = self.with_params(**grid_points[i])
                for mi, m in enumerate(masks):
                    models[mi][i] = est.fit_arrays(x, y, m)
            return models

        return collect

    def fit_arrays_batched_masks(self, x, y, masks, grid_points):
        """Folds × grid in as few programs as the grid's static params
        allow — dispatch + immediate collect of
        :meth:`sweep_dispatch_masks`."""
        return self.sweep_dispatch_masks(x, y, masks, grid_points)()
