"""Logistic regression (binary + multinomial).

Reference: core/.../stages/impl/classification/OpLogisticRegression.scala —
wraps Spark LR (L-BFGS/OWL-QN over native BLAS). Here training is the pure
XLA solver in models/solvers.py; gradients over a sharded batch reduce with
``psum`` when the data axis is sharded over a mesh.
"""
from __future__ import annotations

import numpy as np

import jax

from .base import PredictorEstimator, PredictorModel
from .solvers import fit_logistic_binary, fit_logistic_multinomial


class LogisticRegressionModel(PredictorModel):
    def __init__(
        self,
        weights: np.ndarray,       # [D] binary or [D, C] multinomial
        intercept: np.ndarray,     # scalar or [C]
        num_classes: int,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = np.asarray(intercept, dtype=np.float64)
        self.num_classes = num_classes

    def get_arrays(self):
        return {"weights": self.weights, "intercept": self.intercept}

    def get_params(self):
        return {"num_classes": self.num_classes}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], arrays["intercept"], params["num_classes"])

    def predict_arrays(self, x: np.ndarray):
        if self.num_classes == 2:
            margin = x @ self.weights + self.intercept
            p1 = 1.0 / (1.0 + np.exp(-margin))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margin, margin], axis=1)
        else:
            logits = x @ self.weights + self.intercept
            logits -= logits.max(axis=1, keepdims=True)
            e = np.exp(logits)
            prob = e / e.sum(axis=1, keepdims=True)
            raw = logits
        pred = prob.argmax(axis=1).astype(np.float64)
        return pred, prob, raw


class LogisticRegression(PredictorEstimator):
    """Params mirror Spark LR defaults (regParam=0, elasticNetParam=0,
    maxIter=100, standardization=true, fitIntercept=true)."""

    model_type = "OpLogisticRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 100,
        fit_intercept: bool = True,
        standardization: bool = True,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "elastic_net_param": self.elastic_net_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
            "standardization": self.standardization,
        }

    @staticmethod
    def _mesh_rows(x, y, masks):
        """Pad rows to the execution-mesh multiple (mask-0 padding is inert
        in the mask-weighted solvers) and shard x over the data axis;
        identity when no mesh is active. ``masks`` pads on its LAST axis
        (handles both [N] and [K, N])."""
        from ..parallel.mesh import data_row_multiple, shard_rows_if_active

        x = np.asarray(x, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        masks = np.asarray(masks, dtype=np.float32)
        pad = (-x.shape[0]) % data_row_multiple()
        if pad:
            x = np.pad(x, ((0, pad), (0, 0)))
            y = np.pad(y, (0, pad))
            widths = [(0, 0)] * (masks.ndim - 1) + [(0, pad)]
            masks = np.pad(masks, widths)
        return shard_rows_if_active(x), y, masks

    def fit_arrays(self, x, y, row_mask):
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        x, y, row_mask = self._mesh_rows(x, y, row_mask)
        # FISTA needs more iterations than Newton for tight convergence;
        # scale the budget (maxIter is the Spark-semantic knob).
        iters = self.max_iter * 4
        if num_classes == 2:
            params = fit_logistic_binary(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        else:
            params = fit_logistic_multinomial(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_classes=num_classes,
                num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        return LogisticRegressionModel(
            np.asarray(params.weights), np.asarray(params.intercept), num_classes
        )

    # ---- batched sweeps (SURVEY.md §2.6: the reference's driver thread
    # pool becomes a batch axis of one compiled program) -------------------

    def _is_vmappable(self, p: dict) -> bool:
        # only reg/elastic-net vary inside the vmap; any other overridden
        # param must match this estimator's static value
        return all(
            k in ("reg_param", "elastic_net_param") or v == getattr(self, k)
            for k, v in p.items()
        )

    def _grid_values(self, points) -> tuple[np.ndarray, np.ndarray]:
        regs = np.asarray(
            [p.get("reg_param", self.reg_param) for p in points],
            dtype=np.float32,
        )
        ens = np.asarray(
            [p.get("elastic_net_param", self.elastic_net_param) for p in points],
            dtype=np.float32,
        )
        return regs, ens

    def _vmapped_fit(self, x, y, num_classes: int):
        """fit fn of (reg, elastic_net, row_mask) for the vmapped sweep;
        callers pass x already padded/sharded via _mesh_rows."""
        iters = self.max_iter * 4
        if num_classes == 2:
            return lambda r, e, m: fit_logistic_binary(
                x, y, m, r, e, num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        return lambda r, e, m: fit_logistic_multinomial(
            x, y, m, r, e, num_classes=num_classes,
            num_iters=iters, fit_intercept=self.fit_intercept,
        )

    @staticmethod
    def _num_classes(y, any_mask) -> int:
        present = y[any_mask > 0]
        return max(int(present.max()) + 1 if len(present) else 2, 2)

    def fit_arrays_batched(self, x, y, row_mask, grid_points):
        """One mask, many grid points — vmappable points train in one
        program; stragglers fall back to sequential fits."""
        vmappable = [i for i, p in enumerate(grid_points) if self._is_vmappable(p)]
        rest = [i for i in range(len(grid_points)) if i not in vmappable]
        num_classes = self._num_classes(y, row_mask)
        models: dict[int, LogisticRegressionModel] = {}
        if vmappable:
            regs, ens = self._grid_values([grid_points[i] for i in vmappable])
            xp, yp, rmp = self._mesh_rows(x, y, row_mask)
            rm = np.broadcast_to(rmp, (len(vmappable), len(yp)))
            stacked = jax.vmap(self._vmapped_fit(xp, yp, num_classes))(regs, ens, rm)
            w = np.asarray(stacked.weights)
            b = np.asarray(stacked.intercept)
            for j, i in enumerate(vmappable):
                models[i] = LogisticRegressionModel(w[j], b[j], num_classes)
        for i in rest:
            models[i] = self.with_params(**grid_points[i]).fit_arrays(x, y, row_mask)
        return [models[i] for i in range(len(grid_points))]

    def fit_arrays_batched_masks(self, x, y, masks, grid_points):
        """Folds × grid in ONE vmapped program: the fit axis carries
        (fold-mask, reg, elastic-net) triples, so the validator's whole
        sweep is a single dispatch. Non-vmappable points fall back to the
        per-fold batched path."""
        if not all(self._is_vmappable(p) for p in grid_points):
            return [
                self.fit_arrays_batched(x, y, m, grid_points) for m in masks
            ]
        num_classes = self._num_classes(y, np.max(np.stack(masks), axis=0))
        n_pts = len(grid_points)
        regs, ens = self._grid_values(list(grid_points) * len(masks))
        xp, yp, masksp = self._mesh_rows(x, y, np.stack(masks))
        rm = np.repeat(
            masksp, n_pts, axis=0
        )  # [K, N], mask-major to match regs/ens tiling
        stacked = jax.vmap(self._vmapped_fit(xp, yp, num_classes))(regs, ens, rm)
        w = np.asarray(stacked.weights)
        b = np.asarray(stacked.intercept)
        return [
            [
                LogisticRegressionModel(
                    w[mi * n_pts + j], b[mi * n_pts + j], num_classes
                )
                for j in range(n_pts)
            ]
            for mi in range(len(masks))
        ]
