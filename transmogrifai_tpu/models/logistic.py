"""Logistic regression (binary + multinomial).

Reference: core/.../stages/impl/classification/OpLogisticRegression.scala —
wraps Spark LR (L-BFGS/OWL-QN over native BLAS). Here training is the pure
XLA solver in models/solvers.py; gradients over a sharded batch reduce with
``psum`` when the data axis is sharded over a mesh.
"""
from __future__ import annotations

import numpy as np

import jax

from .base import PredictorEstimator, PredictorModel
from .solvers import fit_logistic_binary, fit_logistic_multinomial


class LogisticRegressionModel(PredictorModel):
    def __init__(
        self,
        weights: np.ndarray,       # [D] binary or [D, C] multinomial
        intercept: np.ndarray,     # scalar or [C]
        num_classes: int,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.intercept = np.asarray(intercept, dtype=np.float64)
        self.num_classes = num_classes

    def get_arrays(self):
        return {"weights": self.weights, "intercept": self.intercept}

    def get_params(self):
        return {"num_classes": self.num_classes}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["weights"], arrays["intercept"], params["num_classes"])

    def predict_arrays(self, x: np.ndarray):
        if self.num_classes == 2:
            margin = x @ self.weights + self.intercept
            p1 = 1.0 / (1.0 + np.exp(-margin))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margin, margin], axis=1)
        else:
            logits = x @ self.weights + self.intercept
            logits -= logits.max(axis=1, keepdims=True)
            e = np.exp(logits)
            prob = e / e.sum(axis=1, keepdims=True)
            raw = logits
        pred = prob.argmax(axis=1).astype(np.float64)
        return pred, prob, raw


class LogisticRegression(PredictorEstimator):
    """Params mirror Spark LR defaults (regParam=0, elasticNetParam=0,
    maxIter=100, standardization=true, fitIntercept=true)."""

    model_type = "OpLogisticRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 100,
        fit_intercept: bool = True,
        standardization: bool = True,
        uid: str | None = None,
    ):
        super().__init__("logreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def get_params(self):
        return {
            "reg_param": self.reg_param,
            "elastic_net_param": self.elastic_net_param,
            "max_iter": self.max_iter,
            "fit_intercept": self.fit_intercept,
            "standardization": self.standardization,
        }

    def fit_arrays(self, x, y, row_mask):
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        # FISTA needs more iterations than Newton for tight convergence;
        # scale the budget (maxIter is the Spark-semantic knob).
        iters = self.max_iter * 4
        if num_classes == 2:
            params = fit_logistic_binary(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        else:
            params = fit_logistic_multinomial(
                x,
                y,
                row_mask,
                float(self.reg_param),
                float(self.elastic_net_param),
                num_classes=num_classes,
                num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        return LogisticRegressionModel(
            np.asarray(params.weights), np.asarray(params.intercept), num_classes
        )

    def fit_arrays_batched(self, x, y, row_mask, grid_points):
        """Train the whole hyperparameter grid as ONE vmapped XLA computation
        (SURVEY.md §2.6: the reference's driver thread pool becomes a vmap
        axis). Grid points sharing this estimator's static params (max_iter,
        fit_intercept) vmap over (reg_param, elastic_net); stragglers fall
        back to sequential fits."""
        def _is_vmappable(p):
            # only reg/elastic-net vary inside the vmap; any other overridden
            # param must match this estimator's static value
            return all(
                k in ("reg_param", "elastic_net_param") or v == getattr(self, k)
                for k, v in p.items()
            )

        vmappable = [i for i, p in enumerate(grid_points) if _is_vmappable(p)]
        rest = [i for i in range(len(grid_points)) if i not in vmappable]
        present = y[row_mask > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        iters = self.max_iter * 4
        models: dict[int, LogisticRegressionModel] = {}
        if vmappable:
            regs = np.asarray(
                [grid_points[i].get("reg_param", self.reg_param) for i in vmappable],
                dtype=np.float32,
            )
            ens = np.asarray(
                [
                    grid_points[i].get("elastic_net_param", self.elastic_net_param)
                    for i in vmappable
                ],
                dtype=np.float32,
            )
            if num_classes == 2:
                fn = lambda r, e: fit_logistic_binary(  # noqa: E731
                    x, y, row_mask, r, e, num_iters=iters,
                    fit_intercept=self.fit_intercept,
                )
            else:
                fn = lambda r, e: fit_logistic_multinomial(  # noqa: E731
                    x, y, row_mask, r, e, num_classes=num_classes,
                    num_iters=iters, fit_intercept=self.fit_intercept,
                )
            stacked = jax.vmap(fn)(regs, ens)
            w = np.asarray(stacked.weights)
            b = np.asarray(stacked.intercept)
            for j, i in enumerate(vmappable):
                models[i] = LogisticRegressionModel(w[j], b[j], num_classes)
        for i in rest:
            models[i] = self.with_params(**grid_points[i]).fit_arrays(x, y, row_mask)
        return [models[i] for i in range(len(grid_points))]

    def fit_arrays_batched_masks(self, x, y, masks, grid_points):
        """Folds × grid in ONE vmapped program: the fit axis carries
        (fold-mask, reg, elastic-net) triples, so the validator's whole
        sweep is a single dispatch. Non-vmappable points fall back to the
        per-fold batched path."""
        import numpy as _np

        def _is_vmappable(p):
            return all(
                k in ("reg_param", "elastic_net_param") or v == getattr(self, k)
                for k, v in p.items()
            )

        if not all(_is_vmappable(p) for p in grid_points):
            return [
                self.fit_arrays_batched(x, y, m, grid_points) for m in masks
            ]
        present = y[_np.max(_np.stack(masks), axis=0) > 0]
        num_classes = max(int(present.max()) + 1 if len(present) else 2, 2)
        iters = self.max_iter * 4
        n_pts = len(grid_points)
        regs = _np.asarray(
            [
                p.get("reg_param", self.reg_param)
                for _ in masks for p in grid_points
            ],
            dtype=_np.float32,
        )
        ens = _np.asarray(
            [
                p.get("elastic_net_param", self.elastic_net_param)
                for _ in masks for p in grid_points
            ],
            dtype=_np.float32,
        )
        rm = _np.repeat(
            _np.stack(masks).astype(_np.float32), n_pts, axis=0
        )  # [K, N]
        if num_classes == 2:
            fn = lambda r, e, m: fit_logistic_binary(  # noqa: E731
                x, y, m, r, e, num_iters=iters,
                fit_intercept=self.fit_intercept,
            )
        else:
            fn = lambda r, e, m: fit_logistic_multinomial(  # noqa: E731
                x, y, m, r, e, num_classes=num_classes,
                num_iters=iters, fit_intercept=self.fit_intercept,
            )
        stacked = jax.vmap(fn)(regs, ens, rm)
        w = np.asarray(stacked.weights)
        b = np.asarray(stacked.intercept)
        return [
            [
                LogisticRegressionModel(
                    w[mi * n_pts + j], b[mi * n_pts + j], num_classes
                )
                for j in range(n_pts)
            ]
            for mi in range(len(masks))
        ]
