"""Isotonic regression calibrator.

Reference: core/.../stages/impl/regression/IsotonicRegressionCalibrator.scala
— BinaryEstimator[RealNN label, RealNN score] -> RealNN wrapping Spark
IsotonicRegression (univariate, isotonic=true by default). Fit is the
pool-adjacent-violators algorithm; prediction interpolates linearly between
learned boundaries exactly as Spark's IsotonicRegressionModel does.

PAV is inherently sequential over *distinct score values* (tiny after the
tie-collapse), so it runs host-side in numpy; scoring is vectorized
interpolation (np.interp == Spark's linear interpolation + boundary clamp).
"""
from __future__ import annotations

import numpy as np

from ..dataset import Dataset
from ..stages.base import Estimator, Model
from ..types import RealNN
from ..types.columns import Column, NumericColumn


def _pav(x: np.ndarray, y: np.ndarray, w: np.ndarray):
    """Pool-adjacent-violators on (x sorted ascending, y, weights); returns
    (boundaries, predictions) like Spark's IsotonicRegressionModel."""
    order = np.argsort(x, kind="stable")
    xs, ys, ws = x[order], y[order].astype(np.float64), w[order].astype(np.float64)
    # collapse ties on x (weighted mean) — Spark does this pre-pass
    ux, inv = np.unique(xs, return_inverse=True)
    wsum = np.bincount(inv, weights=ws)
    ysum = np.bincount(inv, weights=ys * ws)
    ym = ysum / np.maximum(wsum, 1e-300)
    # stack-based PAV; pooling mutates the stack tops in place so the whole
    # fit is O(n) even on all-distinct continuous scores
    vals: list[float] = []
    wts: list[float] = []
    lo: list[int] = []
    hi: list[int] = []
    for i in range(len(ux)):
        vals.append(float(ym[i])); wts.append(float(wsum[i])); lo.append(i); hi.append(i)
        while len(vals) > 1 and vals[-2] > vals[-1]:
            w2 = wts[-2] + wts[-1]
            v = (vals[-2] * wts[-2] + vals[-1] * wts[-1]) / w2
            h2 = hi[-1]
            vals.pop(); wts.pop(); lo.pop(); hi.pop()
            vals[-1] = v; wts[-1] = w2; hi[-1] = h2
    boundaries: list[float] = []
    predictions: list[float] = []
    for v, l, h in zip(vals, lo, hi):
        boundaries.append(float(ux[l])); predictions.append(v)
        if h != l:
            boundaries.append(float(ux[h])); predictions.append(v)
    return np.asarray(boundaries), np.asarray(predictions)


class IsotonicRegressionCalibratorModel(Model):
    output_type = RealNN

    def __init__(self, boundaries, predictions, isotonic: bool = True, uid=None):
        super().__init__("isotonicCalibrator", uid=uid)
        self.boundaries = np.asarray(boundaries, dtype=np.float64)
        self.predictions = np.asarray(predictions, dtype=np.float64)
        self.isotonic = isotonic

    def get_arrays(self):
        return {"boundaries": self.boundaries, "predictions": self.predictions}

    def get_params(self):
        return {"isotonic": self.isotonic}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(arrays["boundaries"], arrays["predictions"],
                   params.get("isotonic", True))

    def transform_columns(self, *cols: Column, num_rows: int) -> NumericColumn:
        score = cols[-1]
        assert isinstance(score, NumericColumn)
        x = score.values.astype(np.float64)
        # boundaries are stored ascending for both directions (fit reverses
        # the antitonic solution), so plain interpolation covers both.
        out = np.interp(x, self.boundaries, self.predictions)
        return NumericColumn(RealNN, out, np.ones(num_rows, dtype=bool))


class IsotonicRegressionCalibrator(Estimator):
    """BinaryEstimator[(RealNN label, RealNN score)] -> RealNN calibrated."""

    input_types = (RealNN, RealNN)
    output_type = RealNN

    def __init__(self, isotonic: bool = True, uid: str | None = None):
        super().__init__("isotonicCalibrator", uid=uid)
        self.isotonic = isotonic

    def get_params(self):
        return {"isotonic": self.isotonic}

    def fit_model(self, dataset: Dataset) -> IsotonicRegressionCalibratorModel:
        label_name, score_name = self.input_names
        label = dataset[label_name]
        score = dataset[score_name]
        assert isinstance(label, NumericColumn) and isinstance(score, NumericColumn)
        y = label.values.astype(np.float64)
        x = score.values.astype(np.float64)
        if not self.isotonic:
            x = -x
        b, p = _pav(x, y, np.ones_like(y))
        if not self.isotonic:
            b = (-b)[::-1]
            p = p[::-1]
        self.metadata["numBoundaries"] = int(len(b))
        return IsotonicRegressionCalibratorModel(b, p, self.isotonic)
