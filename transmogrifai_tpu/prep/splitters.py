"""Data splitters: train/holdout reserve, binary balancing, multiclass label
cutting.

Reference: core/.../stages/impl/tuning/{Splitter,DataSplitter,DataBalancer,
DataCutter}.scala. Defaults (Splitter.scala:176-178): reserveTestFraction 0.1,
maxTrainingSample 1e6; DataBalancer sampleFraction 0.1 (target minority
fraction); DataCutter maxLabelCategories 100, minLabelFraction 0.0.

TPU design: splitters produce row-index arrays / masks, never copies — the
fitted DAG keeps one compiled shape and folds/resamples are masks
(SURVEY.md §7 hard-part 3).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

RESERVE_TEST_FRACTION = 0.1
MAX_TRAINING_SAMPLE = 1_000_000
BALANCER_SAMPLE_FRACTION = 0.1
CUTTER_MAX_LABEL_CATEGORIES = 100
CUTTER_MIN_LABEL_FRACTION = 0.0


@dataclasses.dataclass
class SplitterSummary:
    splitter: str
    details: dict[str, Any]

    def to_json(self) -> dict[str, Any]:
        return {"splitter": self.splitter, **self.details}


class DataSplitter:
    """Train/holdout reserve + down-sampling cap (DataSplitter.scala:65-128)."""

    def __init__(
        self,
        reserve_test_fraction: float = RESERVE_TEST_FRACTION,
        max_training_sample: int = MAX_TRAINING_SAMPLE,
        seed: int = 42,
    ):
        self.reserve_test_fraction = reserve_test_fraction
        self.max_training_sample = max_training_sample
        self.seed = seed
        self.summary: SplitterSummary | None = None

    def split(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        """(train indices, holdout indices)."""
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        return np.sort(perm[n_test:]), np.sort(perm[:n_test])

    def prepare(self, y: np.ndarray) -> np.ndarray:
        """validationPrepare: row mask over the training set (down-sampling
        to max_training_sample)."""
        n = len(y)
        mask = np.ones(n, dtype=bool)
        if n > self.max_training_sample:
            rng = np.random.default_rng(self.seed)
            keep = rng.choice(n, self.max_training_sample, replace=False)
            mask = np.zeros(n, dtype=bool)
            mask[keep] = True
        self.summary = SplitterSummary(
            "DataSplitter",
            {"downSampleFraction": float(mask.mean()), "totalRows": n},
        )
        return mask

    def get_params(self) -> dict[str, Any]:
        return {
            "reserve_test_fraction": self.reserve_test_fraction,
            "max_training_sample": self.max_training_sample,
            "seed": self.seed,
        }


class DataBalancer(DataSplitter):
    """Binary balancing (DataBalancer.scala:73-340): if the positive fraction
    is below sample_fraction, down-sample negatives (and/or up-sample
    positives) toward the target minority fraction."""

    def __init__(
        self,
        sample_fraction: float = BALANCER_SAMPLE_FRACTION,
        max_training_sample: int = MAX_TRAINING_SAMPLE,
        reserve_test_fraction: float = RESERVE_TEST_FRACTION,
        seed: int = 42,
    ):
        super().__init__(reserve_test_fraction, max_training_sample, seed)
        self.sample_fraction = sample_fraction

    def prepare(self, y: np.ndarray) -> np.ndarray:
        n = len(y)
        pos = y == 1.0
        n_pos, n_neg = int(pos.sum()), int((~pos).sum())
        mask = np.ones(n, dtype=bool)
        if n_pos == 0 or n_neg == 0:
            self.summary = SplitterSummary(
                "DataBalancer",
                {"positiveFraction": n_pos / max(n, 1), "balanced": False},
            )
            return mask
        minority, majority = min(n_pos, n_neg), max(n_pos, n_neg)
        minority_is_pos = n_pos <= n_neg
        frac = minority / n
        if frac < self.sample_fraction:
            # down-sample majority so minority fraction reaches the target
            target_majority = int(minority / self.sample_fraction) - minority
            rng = np.random.default_rng(self.seed)
            maj_idx = np.nonzero(pos != minority_is_pos)[0]
            keep = rng.choice(maj_idx, min(target_majority, len(maj_idx)), replace=False)
            mask = np.zeros(n, dtype=bool)
            mask[pos == minority_is_pos] = True
            mask[keep] = True
        self.summary = SplitterSummary(
            "DataBalancer",
            {
                "positiveCount": n_pos,
                "negativeCount": n_neg,
                "desiredFraction": self.sample_fraction,
                "keptFraction": float(mask.mean()),
            },
        )
        return mask

    def get_params(self) -> dict[str, Any]:
        return {**super().get_params(), "sample_fraction": self.sample_fraction}


class DataCutter(DataSplitter):
    """Multiclass label cutting (DataCutter.scala:78-260): keep at most
    max_label_categories top labels with at least min_label_fraction mass;
    rows with dropped labels are excluded."""

    def __init__(
        self,
        max_label_categories: int = CUTTER_MAX_LABEL_CATEGORIES,
        min_label_fraction: float = CUTTER_MIN_LABEL_FRACTION,
        reserve_test_fraction: float = RESERVE_TEST_FRACTION,
        max_training_sample: int = MAX_TRAINING_SAMPLE,
        seed: int = 42,
    ):
        super().__init__(reserve_test_fraction, max_training_sample, seed)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction
        self.labels_kept: list[float] | None = None

    def prepare(self, y: np.ndarray) -> np.ndarray:
        n = len(y)
        vals, counts = np.unique(y, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        kept = [
            float(vals[i])
            for i in order[: self.max_label_categories]
            if counts[i] / n >= self.min_label_fraction
        ]
        self.labels_kept = kept
        mask = np.isin(y, kept)
        self.summary = SplitterSummary(
            "DataCutter",
            {
                "labelsKept": len(kept),
                "labelsDropped": len(vals) - len(kept),
                "keptFraction": float(mask.mean()),
            },
        )
        return mask

    def get_params(self) -> dict[str, Any]:
        return {
            **super().get_params(),
            "max_label_categories": self.max_label_categories,
            "min_label_fraction": self.min_label_fraction,
        }
