"""RawFeatureFilter — pre-training raw-feature quality / drift gate.

Reference: core/.../filters/RawFeatureFilter.scala:90-616,
FeatureDistribution.scala:58-260, Summary.scala:43,
RawFeatureFilterResults.scala:50-136.

Per raw feature, on the training data (and optionally scoring data):
  * Summary (min/max/sum/count) and a binned FeatureDistribution —
    equal-width histograms for numerics, hashed-token histograms for text;
    null counts tracked separately;
  * drop rules (defaults at RawFeatureFilter.scala):
      - fill rate < min_fill (0.001)
      - |train fill - score fill| > max_fill_difference (0.9)
      - relative fill ratio > max_fill_ratio_diff (20.0)
      - Jensen-Shannon divergence train↔score > max_js_divergence (0.9)
      - null-indicator ↔ label correlation > max_correlation (0.95)
  * emits RawFeatureFilterResults (config + per-feature metrics + exclusion
    reasons); the workflow then rewrites the DAG minus blocklisted features
    (OpWorkflow.setBlocklist :118-167).

The histogram build is a monoid reduction (order-invariant), matching the
reference's map-reduce passes.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..types.columns import (
    Column,
    ListColumn,
    MapColumn,
    NumericColumn,
    SetColumn,
    TextColumn,
)
from ..utils.text import clean_string, hash_to_index

MIN_FILL = 0.001
MAX_FILL_DIFFERENCE = 0.90
MAX_FILL_RATIO_DIFF = 20.0
MAX_JS_DIVERGENCE = 0.90
MAX_NULL_LABEL_CORR = 0.95
DEFAULT_BINS = 100
TEXT_BINS = 255


@dataclasses.dataclass
class FeatureDistribution:
    """Binned distribution + fill statistics (FeatureDistribution.scala:58)."""

    name: str
    count: int          # total rows
    nulls: int
    distribution: np.ndarray  # [bins] counts
    summary: dict[str, float]

    @property
    def fill_rate(self) -> float:
        """FeatureDistribution.fillRate (:94)."""
        return 0.0 if self.count == 0 else 1.0 - self.nulls / self.count

    def relative_fill_ratio(self, other: "FeatureDistribution") -> float:
        """:125 — max(fill)/min(fill), inf when one side is empty."""
        a, b = self.fill_rate, other.fill_rate
        lo, hi = min(a, b), max(a, b)
        if lo == 0.0:
            return float("inf") if hi > 0 else 1.0
        return hi / lo

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """:149 — JS divergence of the normalized bin histograms."""
        p = self.distribution.astype(np.float64)
        q = other.distribution.astype(np.float64)
        if p.sum() == 0 or q.sum() == 0:
            return 0.0
        p = p / p.sum()
        q = q / q.sum()
        m = 0.5 * (p + q)

        def kl(a, b):
            mask = a > 0
            return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

        return 0.5 * kl(p, m) + 0.5 * kl(q, m)


def _null_mask(col: Column) -> np.ndarray:
    if isinstance(col, NumericColumn):
        return ~col.mask
    if isinstance(col, TextColumn):
        return np.array([v is None for v in col.values], dtype=bool)
    if isinstance(col, (SetColumn, ListColumn, MapColumn)):
        return np.array([not v for v in col.values], dtype=bool)
    return np.zeros(len(col), dtype=bool)


def compute_distribution(
    name: str,
    col: Column,
    bins: int = DEFAULT_BINS,
    text_bins: int = TEXT_BINS,
    numeric_range: tuple[float, float] | None = None,
) -> FeatureDistribution:
    n = len(col)
    nulls = int(_null_mask(col).sum())
    if isinstance(col, NumericColumn):
        vals = col.values[col.mask].astype(np.float64)
        if numeric_range is None:
            lo, hi = (float(vals.min()), float(vals.max())) if len(vals) else (0.0, 1.0)
        else:
            lo, hi = numeric_range
        if hi <= lo:
            hi = lo + 1.0
        # clip into the reference range so out-of-range score-time values
        # land in the edge bins (drift must show up, not vanish)
        hist, _ = np.histogram(np.clip(vals, lo, hi), bins=bins, range=(lo, hi))
        summary = {
            "min": float(vals.min()) if len(vals) else 0.0,
            "max": float(vals.max()) if len(vals) else 0.0,
            "sum": float(vals.sum()),
            "count": float(len(vals)),
        }
        return FeatureDistribution(name, n, nulls, hist.astype(np.float64), summary)
    # text-format hashing (textBinsFormula, RawFeatureFilter.scala:588)
    hist = np.zeros(text_bins, dtype=np.float64)
    total_tokens = 0
    for v in _iter_tokens(col):
        hist[hash_to_index(v, text_bins)] += 1
        total_tokens += 1
    summary = {"count": float(n - nulls), "tokens": float(total_tokens)}
    return FeatureDistribution(name, n, nulls, hist, summary)


def _iter_tokens(col: Column):
    if isinstance(col, TextColumn):
        for v in col.values:
            if v is not None:
                yield clean_string(v)
    elif isinstance(col, (SetColumn, ListColumn)):
        for members in col.values:
            for m in members:
                yield clean_string(str(m))
    elif isinstance(col, MapColumn):
        for d in col.values:
            for k, v in d.items():
                yield clean_string(f"{k}:{v}")


@dataclasses.dataclass
class RawFeatureFilterResults:
    """Config + per-feature metrics + exclusion reasons
    (RawFeatureFilterResults.scala:50-136)."""

    config: dict[str, Any]
    feature_metrics: dict[str, dict[str, Any]]
    excluded: dict[str, list[str]]

    def to_json(self) -> dict[str, Any]:
        return {
            "rawFeatureFilterConfig": self.config,
            "rawFeatureDistributions": self.feature_metrics,
            "exclusionReasons": self.excluded,
        }


class RawFeatureFilter:
    def __init__(
        self,
        min_fill: float = MIN_FILL,
        max_fill_difference: float = MAX_FILL_DIFFERENCE,
        max_fill_ratio_diff: float = MAX_FILL_RATIO_DIFF,
        max_js_divergence: float = MAX_JS_DIVERGENCE,
        max_null_label_corr: float = MAX_NULL_LABEL_CORR,
        bins: int = DEFAULT_BINS,
        protected_features: tuple[str, ...] = (),
    ):
        self.min_fill = min_fill
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_null_label_corr = max_null_label_corr
        self.bins = bins
        self.protected_features = tuple(protected_features)
        self.results: RawFeatureFilterResults | None = None

    def compute_exclusions(
        self,
        train: Dataset,
        raw_features: list[Feature],
        score: Dataset | None = None,
        label_name: str | None = None,
    ) -> list[str]:
        """Names of raw features to blocklist (generateFilteredRaw :486)."""
        excluded: dict[str, list[str]] = {}
        metrics: dict[str, dict[str, Any]] = {}
        label = None
        label_valid = None
        if label_name is not None and label_name in train:
            lc = train[label_name]
            if isinstance(lc, NumericColumn):
                # unlabeled rows (mask False) hold an unspecified fill value —
                # restrict the null↔label correlation to labeled rows
                label = lc.values.astype(np.float64)
                label_valid = lc.mask

        for f in raw_features:
            if f.is_response or f.name in self.protected_features:
                continue
            if f.name not in train:
                continue
            col = train[f.name]
            dist = compute_distribution(f.name, col, bins=self.bins)
            reasons: list[str] = []
            if dist.fill_rate < self.min_fill:
                reasons.append(f"fillRate={dist.fill_rate:.5f}<{self.min_fill}")

            m: dict[str, Any] = {
                "fillRate": dist.fill_rate,
                "nulls": dist.nulls,
                "count": dist.count,
            }
            if score is not None and f.name in score:
                scol = score[f.name]
                rng = None
                if isinstance(col, NumericColumn):
                    rng = (dist.summary["min"], dist.summary["max"])
                sdist = compute_distribution(
                    f.name, scol, bins=self.bins, numeric_range=rng
                )
                fill_diff = abs(dist.fill_rate - sdist.fill_rate)
                fill_ratio = dist.relative_fill_ratio(sdist)
                js = dist.js_divergence(sdist)
                m.update(
                    {"scoreFillRate": sdist.fill_rate, "fillDifference": fill_diff,
                     "fillRatio": fill_ratio, "jsDivergence": js}
                )
                if fill_diff > self.max_fill_difference:
                    reasons.append(f"fillDifference={fill_diff:.3f}")
                if fill_ratio > self.max_fill_ratio_diff:
                    reasons.append(f"fillRatioDiff={fill_ratio:.2f}")
                if js > self.max_js_divergence:
                    reasons.append(f"jsDivergence={js:.3f}")

            if label is not None:
                nulls = _null_mask(col).astype(np.float64)[label_valid]
                lbl = label[label_valid]
                if len(lbl) > 1 and nulls.std() > 0 and lbl.std() > 0:
                    corr = float(np.corrcoef(nulls, lbl)[0, 1])
                    m["nullLabelCorrelation"] = corr
                    if abs(corr) > self.max_null_label_corr:
                        reasons.append(f"nullLabelCorr={corr:.3f}")

            metrics[f.name] = m
            if reasons:
                excluded[f.name] = reasons

        self.results = RawFeatureFilterResults(
            config={
                "minFill": self.min_fill,
                "maxFillDifference": self.max_fill_difference,
                "maxFillRatioDiff": self.max_fill_ratio_diff,
                "maxJSDivergence": self.max_js_divergence,
                "maxNullLabelCorr": self.max_null_label_corr,
                "bins": self.bins,
            },
            feature_metrics=metrics,
            excluded=excluded,
        )
        return list(excluded)
