"""SanityChecker — automated feature validation / leakage detection.

Reference: core/.../stages/impl/preparators/SanityChecker.scala:58-581 and
DerivedFeatureFilterUtils.scala. BinaryEstimator(label RealNN, features
OPVector) -> OPVector with bad columns removed.

Checks (thresholds mirrored from SanityChecker.scala:561-581):
  * variance < MinVariance (1e-5)                        -> drop column
  * |corr(feature, label)| > MaxCorrelation (0.95)        -> drop (leakage)
  * corr(feature, feature') > MaxFeatureCorr (0.99)       -> drop the later
  * Cramér's V (categorical group vs label) > MaxCramersV (0.95)
                                                          -> drop the group
  * association-rule max confidence > MaxRuleConfidence with support >=
    MinRequiredRuleSupport (both 1.0 = off by default)    -> drop the group
RemoveFeatureGroup (default true): a label-leakage drop removes the whole
pivot group the column belongs to (null indicator included).

TPU mapping (SURVEY.md §7 step 4): all statistics are dense reductions —
correlation is a centered XᵀX matmul over [X | y] and every Cramér's V table
is a one-hot matmul — computed in utils/stats.py (jitted).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from ..dataset import Dataset
from ..stages.base import Estimator
from ..stages.metadata import VectorMetadata
from ..types import OPVector, RealNN
from ..types.columns import Column, NumericColumn, VectorColumn
from ..utils import stats as S
from .derived_filter import FeatureRemovalModel

# SanityChecker.scala:561-581 defaults
CHECK_SAMPLE = 1.0
SAMPLE_LOWER_LIMIT = 1_000
SAMPLE_UPPER_LIMIT = 1_000_000
PROTECT_TEXT_SHARED_HASH = False  # SanityChecker.ProtectTextSharedHash
#: parent types whose shared-hash columns protect_text_shared_hash shields
#: (DerivedFeatureFilterUtils.isTextSharedHash)
_TEXT_HASH_PARENT_TYPES = frozenset(
    {"Text", "TextArea", "TextMap", "TextAreaMap"}
)
MAX_CORRELATION = 0.95
MAX_FEATURE_CORR = 0.99
MIN_CORRELATION = 0.0
MIN_VARIANCE = 1e-5
MAX_CRAMERS_V = 0.95
MAX_RULE_CONFIDENCE = 1.0
MIN_REQUIRED_RULE_SUPPORT = 1.0


@dataclasses.dataclass
class ColumnReport:
    name: str
    parent: str | None
    mean: float
    variance: float
    corr_label: float
    cramers_v: float | None
    dropped: bool
    reasons: list[str]


class SanityChecker(Estimator):
    """Estimator[(RealNN label, OPVector features)] -> OPVector."""

    input_types = (RealNN, OPVector)
    output_type = OPVector
    label_inputs = (0,)  # label-aware by design: correlation screening

    def __init__(
        self,
        max_correlation: float = MAX_CORRELATION,
        max_feature_corr: float = MAX_FEATURE_CORR,
        min_correlation: float = MIN_CORRELATION,
        min_variance: float = MIN_VARIANCE,
        max_cramers_v: float = MAX_CRAMERS_V,
        max_rule_confidence: float = MAX_RULE_CONFIDENCE,
        min_required_rule_support: float = MIN_REQUIRED_RULE_SUPPORT,
        remove_bad_features: bool = False,
        remove_feature_group: bool = True,
        protect_text_shared_hash: bool = PROTECT_TEXT_SHARED_HASH,
        correlation_type: str = "pearson",
        correlation_exclusion: str = "NoExclusion",  # or "HashedText"
        check_sample: float = CHECK_SAMPLE,
        sample_lower_limit: int = SAMPLE_LOWER_LIMIT,
        sample_upper_limit: int = SAMPLE_UPPER_LIMIT,
        sample_seed: int = 42,
        uid: str | None = None,
    ):
        super().__init__("sanityCheck", uid=uid)
        self.max_correlation = max_correlation
        self.max_feature_corr = max_feature_corr
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.remove_bad_features = remove_bad_features
        self.remove_feature_group = remove_feature_group
        self.protect_text_shared_hash = protect_text_shared_hash
        self.correlation_type = correlation_type
        self.correlation_exclusion = correlation_exclusion
        self.check_sample = check_sample
        self.sample_lower_limit = sample_lower_limit
        self.sample_upper_limit = sample_upper_limit
        self.sample_seed = sample_seed

    def _sample_fraction(self, total: int) -> float:
        """SanityChecker.fraction (SanityChecker.scala:356-361): clamp the
        requested check_sample fraction so the checked row count lands in
        [sample_lower_limit, sample_upper_limit]."""
        min_fraction = min(1.0, self.sample_lower_limit / max(total, 1))
        max_fraction = max(0.0, self.sample_upper_limit / max(total, 1))
        return max(min(self.check_sample, max_fraction), min_fraction)

    def get_params(self) -> dict[str, Any]:
        return {
            "max_correlation": self.max_correlation,
            "max_feature_corr": self.max_feature_corr,
            "min_correlation": self.min_correlation,
            "min_variance": self.min_variance,
            "max_cramers_v": self.max_cramers_v,
            "max_rule_confidence": self.max_rule_confidence,
            "min_required_rule_support": self.min_required_rule_support,
            "remove_bad_features": self.remove_bad_features,
            "remove_feature_group": self.remove_feature_group,
            "protect_text_shared_hash": self.protect_text_shared_hash,
            "correlation_type": self.correlation_type,
            "correlation_exclusion": self.correlation_exclusion,
            "check_sample": self.check_sample,
            "sample_lower_limit": self.sample_lower_limit,
            "sample_upper_limit": self.sample_upper_limit,
            "sample_seed": self.sample_seed,
        }

    # ------------------------------------------------------------------ fit
    def fit_model(self, dataset: Dataset) -> FeatureRemovalModel:
        label_name, vector_name = self.input_names
        label_col = dataset[label_name]
        vec_col = dataset[vector_name]
        assert isinstance(label_col, NumericColumn) and isinstance(vec_col, VectorColumn)

        x = np.asarray(vec_col.values, dtype=np.float64)
        y = label_col.values.astype(np.float64)
        n_total = x.shape[0]
        frac = self._sample_fraction(n_total)
        if frac < 1.0:
            # stats on a seeded row sample (SanityChecker.scala:356-361,
            # 562-564): the checker's cost is bounded by sample_upper_limit
            # rows no matter the dataset size
            rng = np.random.default_rng(self.sample_seed)
            take = rng.choice(
                n_total, size=max(1, round(frac * n_total)), replace=False
            )
            take.sort()
            x, y = x[take], y[take]
        n, d = x.shape
        meta = vec_col.metadata or VectorMetadata(vector_name, ())
        names = (
            meta.column_names() if meta.size == d else [f"col_{j}" for j in range(d)]
        )

        col_stats = S.column_stats(x)
        if self.correlation_type == "spearman":
            corr = S.spearman_correlation_matrix(x, y)
        else:
            corr = S.correlation_matrix(x, y)
        corr_label = corr[:d, d].copy()
        corr_features = corr[:d, :d].copy()

        # CorrelationExclusion.HashedText (SanityChecker.scala:428):
        # text-shared-hash columns sit out the correlation checks entirely
        if self.correlation_exclusion == "HashedText" and meta.size == d:
            excluded = np.array(
                [
                    c.parent_type in _TEXT_HASH_PARENT_TYPES
                    and c.grouping is None
                    and c.indicator_value is None
                    for c in meta.columns
                ],
                dtype=bool,
            )
            corr_label[excluded] = np.nan
            corr_features[excluded, :] = 0.0
            corr_features[:, excluded] = 0.0

        # label one-hot for categorical stats (binary or small multiclass).
        # A CONTINUOUS label gets no Cramér's V / association-rule
        # treatment at all (SanityChecker.scala categoricalLabel
        # auto-detection: the label counts as categorical only when its
        # distinct-value count is small relative to the row count;
        # BadFeatureZooTest :264/:628 pin the skip).
        classes = np.unique(y)
        label_is_categorical = len(classes) <= min(
            100, max(2, int(0.1 * len(y)))
        )
        label_onehot = (y[:, None] == classes[None, :]).astype(np.float64)

        drop_reasons: dict[int, list[str]] = {}

        def drop(j: int, reason: str) -> None:
            drop_reasons.setdefault(j, []).append(reason)

        # 1. low variance
        for j in np.nonzero(col_stats.variance < self.min_variance)[0]:
            drop(int(j), f"variance<{self.min_variance}")

        # 2. label-correlation leakage (+ too-low correlation if configured)
        for j in range(d):
            c = abs(corr_label[j])
            if c > self.max_correlation:
                drop(j, f"|corrLabel|={c:.4f}>{self.max_correlation}")
            elif c < self.min_correlation:
                drop(j, f"|corrLabel|={c:.4f}<{self.min_correlation}")

        # 3. feature-feature correlation: drop the later column of each pair
        hi = np.argwhere(np.triu(np.abs(corr_features), k=1) > self.max_feature_corr)
        for _, j in hi:
            drop(int(j), f"featureCorr>{self.max_feature_corr}")

        # 4. categorical groups: Cramér's V + association rules
        group_v: dict[tuple, float] = {}
        group_cols: dict[tuple, list[int]] = {}
        if meta.size == d and label_is_categorical:
            for key, idxs in meta.index_of_group().items():
                cats = [
                    i for i in idxs if meta.columns[i].indicator_value is not None
                ]
                if len(cats) < 1:
                    continue
                contingency = S.contingency_table(x[:, cats], label_onehot)
                v = S.cramers_v(contingency)
                group_v[key] = v
                group_cols[key] = cats
                if v > self.max_cramers_v:
                    for i in cats:
                        drop(i, f"cramersV={v:.4f}>{self.max_cramers_v}")
                conf, support = S.association_rule_confidence(contingency)
                if self.max_rule_confidence < 1.0:
                    for ci, i in enumerate(cats):
                        if (
                            conf[ci] > self.max_rule_confidence
                            and support[ci] >= self.min_required_rule_support
                        ):
                            drop(i, f"ruleConfidence={conf[ci]:.4f}")

        # 5. group-wise removal at PARENT-FEATURE granularity
        # (DerivedFeatureFilterUtils.reasonsToRemove parentExclusionReasons):
        # a leaky categorical group takes down every column of the same
        # parent feature — incl. its hashed-text block and null indicator —
        # unless the column is a text shared hash and protection is on
        # (isTextSharedHash: Text-family parent, no grouping, no indicator).
        if self.remove_feature_group and meta.size == d:

            def parent_key(c):
                base = "_".join(c.parent_names)
                if c.grouping and c.grouping != base:
                    return f"{base}_{c.grouping}"  # parentNamesWithMapKeys
                return base

            def no_keys(c):
                return "_".join(c.parent_names)

            # max |corrLabel| and max Cramér's V per parent (NaN-filtered,
            # makeColumnStatistics.maxByParent)
            parent_corr: dict[str, float] = {}
            parent_corr_nk: dict[str, float] = {}
            for j in range(d):
                c = abs(corr_label[j])
                if np.isnan(c):
                    continue
                for table, key in (
                    (parent_corr, parent_key(meta.columns[j])),
                    (parent_corr_nk, no_keys(meta.columns[j])),
                ):
                    table[key] = max(table.get(key, 0.0), float(c))
            parent_v: dict[str, float] = {}
            parent_v_nk: dict[str, float] = {}
            for key, v in group_v.items():
                if np.isnan(v):
                    continue
                for i in group_cols[key]:
                    for table, pk in (
                        (parent_v, parent_key(meta.columns[i])),
                        (parent_v_nk, no_keys(meta.columns[i])),
                    ):
                        table[pk] = max(table.get(pk, 0.0), float(v))

            def is_text_shared_hash(c) -> bool:
                return (
                    c.parent_type in _TEXT_HASH_PARENT_TYPES
                    and c.grouping is None
                    and c.indicator_value is None
                )

            for j in range(d):
                c = meta.columns[j]
                if self.protect_text_shared_hash and is_text_shared_hash(c):
                    continue
                pk, nk = parent_key(c), no_keys(c)
                pv = parent_v.get(pk, parent_v_nk.get(nk))
                if pv is not None and pv > self.max_cramers_v:
                    drop(j, f"parentCramersV={pv:.4f}>{self.max_cramers_v}")
                pc = parent_corr.get(pk, parent_corr_nk.get(nk))
                if pc is not None and pc > self.max_correlation:
                    drop(j, f"parentCorr={pc:.4f}>{self.max_correlation}")

            # rule-confidence drops still take their indicator group
            # (removedGroups in getFeaturesToDrop)
            groups = meta.index_of_group()
            for j in list(drop_reasons):
                if not any(r.startswith("ruleConfidence")
                           for r in drop_reasons[j]):
                    continue
                key = meta.columns[j].grouped_key()
                if key[1] is None:
                    continue
                for i in groups.get(key, []):
                    if i not in drop_reasons:
                        drop(i, "featureGroupRemoval")

        indices_to_keep = [j for j in range(d) if j not in drop_reasons]

        # ------------------------- summary ledger -------------------------
        reports = [
            ColumnReport(
                name=names[j],
                parent=(
                    meta.columns[j].parent_names[0]
                    if meta.size == d and meta.columns[j].parent_names
                    else None
                ),
                mean=float(col_stats.mean[j]),
                variance=float(col_stats.variance[j]),
                corr_label=float(corr_label[j]),
                cramers_v=(
                    group_v.get(meta.columns[j].grouped_key())
                    if meta.size == d
                    else None
                ),
                dropped=j in drop_reasons,
                reasons=drop_reasons.get(j, []),
            )
            for j in range(d)
        ]
        self.metadata["sanityCheckerSummary"] = {
            "numRows": n,
            "numColumns": d,
            "numDropped": len(drop_reasons),
            "columns": [dataclasses.asdict(r) for r in reports],
            "correlationType": self.correlation_type,
        }
        new_meta = meta.select(indices_to_keep) if meta.size == d else None
        return FeatureRemovalModel(
            indices_to_keep=indices_to_keep,
            remove_bad_features=self.remove_bad_features,
            new_metadata=new_meta,
            operation_name="sanityCheck",
        )
