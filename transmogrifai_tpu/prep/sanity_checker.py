"""SanityChecker — automated feature validation / leakage detection.

Reference: core/.../stages/impl/preparators/SanityChecker.scala:58-581 and
DerivedFeatureFilterUtils.scala. BinaryEstimator(label RealNN, features
OPVector) -> OPVector with bad columns removed.

Checks (thresholds mirrored from SanityChecker.scala:561-581):
  * variance < MinVariance (1e-5)                        -> drop column
  * |corr(feature, label)| > MaxCorrelation (0.95)        -> drop (leakage)
  * corr(feature, feature') > MaxFeatureCorr (0.99)       -> drop the later
  * Cramér's V (categorical group vs label) > MaxCramersV (0.95)
                                                          -> drop the group
  * association-rule max confidence > MaxRuleConfidence with support >=
    MinRequiredRuleSupport (both 1.0 = off by default)    -> drop the group
RemoveFeatureGroup (default true): a label-leakage drop removes the whole
pivot group the column belongs to (null indicator included).

TPU mapping (SURVEY.md §7 step 4): all statistics are dense reductions —
correlation is a centered XᵀX matmul over [X | y] and every Cramér's V table
is a one-hot matmul — computed in utils/stats.py (jitted).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from ..dataset import Dataset
from ..stages.base import Estimator
from ..stages.metadata import VectorMetadata
from ..types import OPVector, RealNN
from ..types.columns import Column, NumericColumn, VectorColumn
from ..utils import stats as S
from .derived_filter import FeatureRemovalModel

# SanityChecker.scala:561-581 defaults
CHECK_SAMPLE = 1.0
SAMPLE_LOWER_LIMIT = 1_000
SAMPLE_UPPER_LIMIT = 1_000_000
MAX_CORRELATION = 0.95
MAX_FEATURE_CORR = 0.99
MIN_CORRELATION = 0.0
MIN_VARIANCE = 1e-5
MAX_CRAMERS_V = 0.95
MAX_RULE_CONFIDENCE = 1.0
MIN_REQUIRED_RULE_SUPPORT = 1.0


@dataclasses.dataclass
class ColumnReport:
    name: str
    parent: str | None
    mean: float
    variance: float
    corr_label: float
    cramers_v: float | None
    dropped: bool
    reasons: list[str]


class SanityChecker(Estimator):
    """Estimator[(RealNN label, OPVector features)] -> OPVector."""

    input_types = (RealNN, OPVector)
    output_type = OPVector

    def __init__(
        self,
        max_correlation: float = MAX_CORRELATION,
        max_feature_corr: float = MAX_FEATURE_CORR,
        min_correlation: float = MIN_CORRELATION,
        min_variance: float = MIN_VARIANCE,
        max_cramers_v: float = MAX_CRAMERS_V,
        max_rule_confidence: float = MAX_RULE_CONFIDENCE,
        min_required_rule_support: float = MIN_REQUIRED_RULE_SUPPORT,
        remove_bad_features: bool = False,
        remove_feature_group: bool = True,
        correlation_type: str = "pearson",
        uid: str | None = None,
    ):
        super().__init__("sanityCheck", uid=uid)
        self.max_correlation = max_correlation
        self.max_feature_corr = max_feature_corr
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.remove_bad_features = remove_bad_features
        self.remove_feature_group = remove_feature_group
        self.correlation_type = correlation_type

    def get_params(self) -> dict[str, Any]:
        return {
            "max_correlation": self.max_correlation,
            "max_feature_corr": self.max_feature_corr,
            "min_correlation": self.min_correlation,
            "min_variance": self.min_variance,
            "max_cramers_v": self.max_cramers_v,
            "max_rule_confidence": self.max_rule_confidence,
            "min_required_rule_support": self.min_required_rule_support,
            "remove_bad_features": self.remove_bad_features,
            "remove_feature_group": self.remove_feature_group,
            "correlation_type": self.correlation_type,
        }

    # ------------------------------------------------------------------ fit
    def fit_model(self, dataset: Dataset) -> FeatureRemovalModel:
        label_name, vector_name = self.input_names
        label_col = dataset[label_name]
        vec_col = dataset[vector_name]
        assert isinstance(label_col, NumericColumn) and isinstance(vec_col, VectorColumn)

        x = np.asarray(vec_col.values, dtype=np.float64)
        y = label_col.values.astype(np.float64)
        n, d = x.shape
        meta = vec_col.metadata or VectorMetadata(vector_name, ())
        names = (
            meta.column_names() if meta.size == d else [f"col_{j}" for j in range(d)]
        )

        col_stats = S.column_stats(x)
        if self.correlation_type == "spearman":
            corr = S.spearman_correlation_matrix(x, y)
        else:
            corr = S.correlation_matrix(x, y)
        corr_label = corr[:d, d]
        corr_features = corr[:d, :d]

        # label one-hot for categorical stats (binary or small multiclass)
        classes = np.unique(y)
        label_onehot = (y[:, None] == classes[None, :]).astype(np.float64)

        drop_reasons: dict[int, list[str]] = {}

        def drop(j: int, reason: str) -> None:
            drop_reasons.setdefault(j, []).append(reason)

        # 1. low variance
        for j in np.nonzero(col_stats.variance < self.min_variance)[0]:
            drop(int(j), f"variance<{self.min_variance}")

        # 2. label-correlation leakage (+ too-low correlation if configured)
        for j in range(d):
            c = abs(corr_label[j])
            if c > self.max_correlation:
                drop(j, f"|corrLabel|={c:.4f}>{self.max_correlation}")
            elif c < self.min_correlation:
                drop(j, f"|corrLabel|={c:.4f}<{self.min_correlation}")

        # 3. feature-feature correlation: drop the later column of each pair
        hi = np.argwhere(np.triu(np.abs(corr_features), k=1) > self.max_feature_corr)
        for _, j in hi:
            drop(int(j), f"featureCorr>{self.max_feature_corr}")

        # 4. categorical groups: Cramér's V + association rules
        group_v: dict[tuple, float] = {}
        group_cols: dict[tuple, list[int]] = {}
        if meta.size == d:
            for key, idxs in meta.index_of_group().items():
                cats = [
                    i for i in idxs if meta.columns[i].indicator_value is not None
                ]
                if len(cats) < 1:
                    continue
                contingency = S.contingency_table(x[:, cats], label_onehot)
                v = S.cramers_v(contingency)
                group_v[key] = v
                group_cols[key] = cats
                if v > self.max_cramers_v:
                    for i in cats:
                        drop(i, f"cramersV={v:.4f}>{self.max_cramers_v}")
                conf, support = S.association_rule_confidence(contingency)
                if self.max_rule_confidence < 1.0:
                    for ci, i in enumerate(cats):
                        if (
                            conf[ci] > self.max_rule_confidence
                            and support[ci] >= self.min_required_rule_support
                        ):
                            drop(i, f"ruleConfidence={conf[ci]:.4f}")

        # 5. group-wise removal: leakage drops take the whole pivot group
        if self.remove_feature_group and meta.size == d:
            groups = meta.index_of_group()
            leak_reasons = ("corrLabel", "cramersV", "ruleConfidence")
            for j in list(drop_reasons):
                if not any(r.startswith(("|corrLabel|", "cramersV", "ruleConfidence"))
                           for r in drop_reasons[j]):
                    continue
                key = meta.columns[j].grouped_key()
                if key[1] is None:
                    continue
                for i in groups.get(key, []):
                    if i not in drop_reasons:
                        drop(i, "featureGroupRemoval")

        indices_to_keep = [j for j in range(d) if j not in drop_reasons]

        # ------------------------- summary ledger -------------------------
        reports = [
            ColumnReport(
                name=names[j],
                parent=(
                    meta.columns[j].parent_names[0]
                    if meta.size == d and meta.columns[j].parent_names
                    else None
                ),
                mean=float(col_stats.mean[j]),
                variance=float(col_stats.variance[j]),
                corr_label=float(corr_label[j]),
                cramers_v=(
                    group_v.get(meta.columns[j].grouped_key())
                    if meta.size == d
                    else None
                ),
                dropped=j in drop_reasons,
                reasons=drop_reasons.get(j, []),
            )
            for j in range(d)
        ]
        self.metadata["sanityCheckerSummary"] = {
            "numRows": n,
            "numColumns": d,
            "numDropped": len(drop_reasons),
            "columns": [dataclasses.asdict(r) for r in reports],
            "correlationType": self.correlation_type,
        }
        new_meta = meta.select(indices_to_keep) if meta.size == d else None
        return FeatureRemovalModel(
            indices_to_keep=indices_to_keep,
            remove_bad_features=self.remove_bad_features,
            new_metadata=new_meta,
            operation_name="sanityCheck",
        )
