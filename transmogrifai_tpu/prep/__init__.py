"""Data preparation: SanityChecker, RawFeatureFilter, splitters."""
from .sanity_checker import SanityChecker  # noqa: F401
