"""Sensitive-feature detection — flags columns that look like personal data.

Reference: utils/.../op/SensitiveFeatureInformation.scala:1-164 (records
detected-name and other sensitive columns in stage metadata; populated by
the name-detection pass inside SmartTextVectorizer when sensitive-feature
mode is on). Equivalent here: a dataset-level scan producing
``SensitiveFeatureInformation`` records that the workflow stores in the
model summary, so downstream governance can see which raw features carried
names / emails / phones / urls and act (e.g. DetectAndRemove).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Sequence

from ..dataset import Dataset
from ..features.feature import Feature
from ..ops.text_stages import _COMMON_NAMES, _EMAIL_RE
from ..types import Email, Phone, Text, URL, is_subtype
from ..nlp.name_model import is_probable_name
from ..types.columns import TextColumn
from ..utils.text import tokenize

# phone shapes: 7-15 digits with optional +/()/separators; date-like strings
# (ISO or slashed) and short plain-digit ids must NOT match
_PHONE_RE = re.compile(r"^\+?[\d\s().-]{7,17}$")
_DATE_LIKE_RE = re.compile(
    r"^\d{4}[-/.]\d{1,2}[-/.]\d{1,2}$|^\d{1,2}[-/.]\d{1,2}[-/.]\d{2,4}$"
)


def _looks_like_phone(v: str) -> bool:
    if not _PHONE_RE.match(v) or _DATE_LIKE_RE.match(v):
        return False
    digits = sum(c.isdigit() for c in v)
    if not 7 <= digits <= 15:
        return False
    # plain digit runs under 10 digits are more likely ids than phones
    if v.isdigit() and digits < 10:
        return False
    return True
_URL_RE = re.compile(r"^(https?|ftp)://", re.IGNORECASE)


@dataclasses.dataclass
class SensitiveFeatureInformation:
    """One flagged feature (SensitiveFeatureInformation.scala)."""

    name: str
    kind: str                 # Name | Email | Phone | Url
    proportion_matched: float
    action_taken: bool = False

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "proportionMatched": round(self.proportion_matched, 4),
            "actionTaken": self.action_taken,
        }


def detect_sensitive_features(
    dataset: Dataset,
    features: Sequence[Feature],
    threshold: float = 0.5,
    names: frozenset = _COMMON_NAMES,
    use_model: bool = True,
) -> list[SensitiveFeatureInformation]:
    """Scan text-family columns for person names / emails / phones / urls.
    Declared types (Email/Phone/URL features) are flagged outright; plain
    Text columns are sampled against the detectors. ``use_model`` adds the
    trained char-level name model (nlp/name_model.py) on top of the
    dictionary; pass False for dictionary-only precision."""
    name_set = frozenset(n.lower() for n in names)
    out: list[SensitiveFeatureInformation] = []
    for f in features:
        if f.name not in dataset:
            continue
        col = dataset[f.name]
        if not isinstance(col, TextColumn):
            continue
        if is_subtype(f.ftype, Email):
            out.append(SensitiveFeatureInformation(f.name, "Email", 1.0))
            continue
        if is_subtype(f.ftype, Phone):
            out.append(SensitiveFeatureInformation(f.name, "Phone", 1.0))
            continue
        if is_subtype(f.ftype, URL):
            out.append(SensitiveFeatureInformation(f.name, "Url", 1.0))
            continue
        if not is_subtype(f.ftype, Text):
            continue
        values = [v for v in col.values if v]
        if not values:
            continue
        counts = {"Name": 0, "Email": 0, "Phone": 0, "Url": 0}
        for v in values:
            if _EMAIL_RE.match(v):
                counts["Email"] += 1
            elif _URL_RE.match(v):
                counts["Url"] += 1
            elif _looks_like_phone(v):
                counts["Phone"] += 1
            else:
                toks = tokenize(v)
                if toks and any(
                    t in name_set
                    or (use_model and is_probable_name(t, threshold=0.7))
                    for t in toks
                ):
                    counts["Name"] += 1
        n = len(values)
        # report the DOMINANT kind crossing the threshold, not the first in
        # dict order — a 60%-email / 30%-name column is an Email column
        kind, c = max(counts.items(), key=lambda kv: kv[1])
        if c / n >= threshold:
            out.append(SensitiveFeatureInformation(f.name, kind, c / n))
    return out
