"""Column-removal model shared by SanityChecker (and later derived-feature
filters).

Reference: DerivedFeatureFilterUtils.removeFeatures
(core/.../preparators/DerivedFeatureFilterUtils.scala) — the fitted model is
just an index-keep mask applied to the feature vector, with metadata subset
to match (SanityChecker.scala:544-559).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from ..stages.metadata import VectorMetadata
from ..types import OPVector
from ..types.columns import Column, VectorColumn
from ..stages.base import Model


class FeatureRemovalModel(Model):
    output_type = OPVector

    @property
    def label_inputs(self) -> tuple[int, ...]:
        # fitted by SanityChecker it inherits (label, vector) wiring — the
        # label slot is a sanctioned response crossing for the pre-flight
        # leakage walk; a bare single-vector wiring has no label slot
        return (0,) if len(self.input_features) == 2 else ()

    def __init__(
        self,
        indices_to_keep: Sequence[int],
        remove_bad_features: bool,
        new_metadata: VectorMetadata | None,
        operation_name: str = "featureRemoval",
        uid: str | None = None,
    ):
        super().__init__(operation_name, uid=uid)
        self.indices_to_keep = list(indices_to_keep)
        self.remove_bad_features = remove_bad_features
        self.new_metadata = new_metadata

    def get_params(self):
        return {
            "indices_to_keep": self.indices_to_keep,
            "remove_bad_features": self.remove_bad_features,
            "new_metadata": (
                self.new_metadata.to_json() if self.new_metadata else None
            ),
        }

    def get_arrays(self):
        return {"indices_to_keep": np.asarray(self.indices_to_keep, dtype=np.int64)}

    @classmethod
    def from_params(cls, params, arrays):
        meta_json = params.get("new_metadata")
        return cls(
            indices_to_keep=[int(i) for i in arrays["indices_to_keep"]],
            remove_bad_features=params["remove_bad_features"],
            new_metadata=(
                VectorMetadata.from_json(meta_json) if meta_json else None
            ),
        )

    def fused_gather_indices(self) -> np.ndarray | None:
        """The keep-index gather for the fused scoring graph
        (compiler/fused.py): ``plane[:, idx]`` traced in-graph, or None
        when this model is a passthrough."""
        if not self.remove_bad_features:
            return None
        return np.asarray(self.indices_to_keep, dtype=np.int32)

    def transform_columns(self, *cols: Column, num_rows: int) -> VectorColumn:
        # inputs are (label, vector); the vector is always the last input
        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        if not self.remove_bad_features:
            return vec
        idx = getattr(self, "_idx_arr", None)
        if idx is None:
            # fancy indexing with a Python list re-builds the index array
            # every scoring call; indices_to_keep is fit-static (set in
            # __init__/from_params, never rebound), so cache unconditionally
            idx = self._idx_arr = np.asarray(self.indices_to_keep, dtype=np.intp)
        values = np.asarray(vec.values)[:, idx]
        meta = self.new_metadata
        if meta is None and vec.metadata is not None:
            # select() reindexes one dataclass per kept column — fit-static,
            # so cache it for repeated scoring calls
            meta = self.new_metadata = vec.metadata.select(self.indices_to_keep)
        return VectorColumn(OPVector, values, meta)
