"""Fused end-to-end on-device scoring graph (ROADMAP item 1).

The staged serving loop crosses the host↔device boundary per stage family:
vectorizers featurize on HOST into the fusion plane, the plane uploads,
the predictor dispatches, predictions download. For steady-state batches
the boundary IS the margin (serve_batch_vs_sklearn ~1.07-1.3, BENCH_r05),
so this module compiles the fitted serving plan — numeric coercion, pivot
scatter, dense-plane assembly, feature removal, and model predict — into
ONE donated, bucketed XLA dispatch:

* **ingest** stays host-side and shrinks to codecs: numeric value/mask
  arrays and the CSR text-interning kernels' code arrays
  (``ops.categorical._pivot_codes`` — string → vocab code, once per
  DISTINCT value). Those small arrays are the ONLY upload, counted as one
  host→device crossing on the runtime transfer census;
* **the fused program** rebuilds every member's block on device (impute +
  null-track, one-hot scatter from codes), concatenates the plane,
  applies the SanityChecker's keep-index gathers, and runs the model
  family's device predict — returning the predictor's CORE array (GLM
  margins/logits, tree margin stacks). The core is the only download
  (render); the host epilogue (`predictions_from_core`) is the same numpy
  code the staged path runs, so tree predictions are bit-identical and
  GLMs differ only by f32-on-device arithmetic (<= 1e-6);
* **explain lanes ride the same dispatch**: ``explain=k`` batches trace
  base core + ``[lanes × N, width]`` perturbation cores in one program
  (group column masks zero slices in-graph), so explain-enabled serving
  still crosses the boundary exactly twice per batch (ingest up, render
  down);
* **identity & banking**: programs are keyed by a structural fingerprint
  (member families, widths, predictor family) — model ARRAYS are traced
  arguments, so same-shaped models share executables — and dispatch rides
  ``utils.aot.aot_call`` (names ``fused_serve`` / ``fused_serve_explain``,
  listed in ``compiler.warmup.SCORE_PROGRAMS``), i.e. the same
  mesh-fingerprinted persistent bank and warmup DAG as every other
  serving program;
* **fail-soft**: any plan shape this module cannot prove fuseable raises
  :class:`Unfuseable` at build, and any dispatch-time error degrades the
  batch to the staged loop — both counted (``fusedFallbacks`` on
  compileStats, TPX008 in the plan audit) and evented. ``TPTPU_FUSED=0``
  opts out entirely.

The donated ingest argument is consumed by XLA on every path; run() is
written so the ingest name is never read after the dispatch — the TPX003
AST check in ``analysis/plan_audit.py`` scans this module for exactly
that bug class whenever a fused plan is audited.
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import threading
from typing import Any, Callable, Sequence

import numpy as np

log = logging.getLogger(__name__)

__all__ = [
    "FusedServingProgram",
    "MemberPlan",
    "PredictorPlan",
    "Unfuseable",
    "build_fused_plan",
]


class Unfuseable(Exception):
    """The fitted plan cannot be compiled into the fused graph; the
    message names the first unfuseable stage/shape (surfaced as TPX008)."""


@dataclasses.dataclass
class MemberPlan:
    """One combiner member's device twin: host ``ingest`` (codecs /
    interning only), traced ``kernel`` rebuilding the member's dense block
    on device, and its fit-static ``params`` arrays. ``quant`` is the
    builder's hint to the quantized-plane pass (``build_fused_plan(...,
    quantize=True)``): ``kind="numeric"`` members carry fit ranges so the
    value upload can shrink to uint8 codes + an in-graph dequant, and
    ``kind="codes"`` members advertise their code range so the int32
    upload can narrow to int8/int16. ``None`` means the member always
    ships as built."""

    stage: Any
    width: int
    up_bytes_per_row: float
    ingest: Callable[[list], dict]          # host: cols -> np arrays
    kernel: Callable[[dict, dict], Any]     # traced: (ingest, params) -> block
    params: dict
    dummy: Callable[[int], dict]            # n -> ShapeDtype-correct zeros
    descriptor: str = ""
    quant: dict | None = None

    @property
    def output_name(self) -> str:
        return self.stage.output_name


@dataclasses.dataclass
class PredictorPlan:
    """The model family's device core: ``core(plane, params)`` traced into
    the fused program, ``epilogue(core_np)`` the HOST numpy tail shared
    with the staged path (``predictions_from_core``)."""

    stage: Any
    in_dim: int | None
    params: dict
    core: Callable[[Any, dict], Any]
    epilogue: Callable[[np.ndarray], tuple]
    descriptor: str = ""


class _Spec:
    """Hashable-by-identity static argument of the fused jit: the traced
    member kernels + predictor core. ``str()`` is the structural
    fingerprint so the persistent-bank key is stable across processes."""

    __slots__ = ("kernels", "core", "fingerprint")

    def __init__(self, kernels, core, fingerprint):
        self.kernels = kernels
        self.core = core
        self.fingerprint = fingerprint

    def __repr__(self) -> str:  # the aot_call static-key contribution
        return f"FusedSpec({self.fingerprint})"


# --------------------------------------------------------------------------
# the traced programs (module level so donating() can build jit twins)
# --------------------------------------------------------------------------
def _assemble_plane(ingest, params, spec):
    import jax.numpy as jnp

    blocks = [
        k(ing, p)
        for k, ing, p in zip(spec.kernels, ingest, params["members"])
    ]
    plane = blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=1)
    for idx in params["gathers"]:
        plane = plane[:, idx]
    return plane


def _fused_eval(ingest, params, *, spec):
    """ingest codecs -> plane -> predictor core. ONE dispatch."""
    plane = _assemble_plane(ingest, params, spec)
    return spec.core(plane, params["predictor"])


def _fused_eval_explain(ingest, params, masks, *, spec):
    """Base core + LOCO perturbation-lane cores in the SAME dispatch:
    lane g is the plane with the columns of ``masks[g]`` zeroed in-graph
    (``jnp.where`` — exact zeros, matching the staged sweep)."""
    import jax.numpy as jnp

    plane = _assemble_plane(ingest, params, spec)
    core = spec.core(plane, params["predictor"])
    lanes = masks.shape[0]
    n, width = plane.shape
    lane_planes = jnp.where(
        masks[:, None, :] > 0, jnp.float32(0.0), plane[None, :, :]
    ).reshape(lanes * n, width)
    lane_core = spec.core(lane_planes, params["predictor"])
    return core, lane_core


_JIT_LOCK = threading.Lock()
_JIT: dict[str, Any] = {}


def _plain_jit(name: str, fn) -> Any:
    import jax

    with _JIT_LOCK:
        got = _JIT.get(name)
        if got is None:
            got = _JIT[name] = jax.jit(  # tp: disable=TPL003 — cached
                fn, static_argnames=("spec",)
            )
    return got


# --------------------------------------------------------------------------
# plan compilation
# --------------------------------------------------------------------------
def build_fused_plan(
    plan: Sequence,
    raw_features,
    result_names: Sequence[str],
    fusion=None,
    quantize: bool = False,
) -> "FusedServingProgram":
    """Compile the fitted serving ``plan`` into a :class:`FusedServingProgram`
    or raise :class:`Unfuseable` naming the obstruction.

    Fuseable shape: host prefix stages feeding a single dense
    ``VectorsCombiner`` plane (every member exposing ``fused_member_spec``),
    an optional chain of ``FeatureRemovalModel`` gathers, and ONE terminal
    predictor exposing ``fused_predict_spec``. ``fusion`` (the closure's
    FusionPlanner) cross-checks learned widths when it has any.

    ``quantize=True`` rewrites eligible members onto the quantized plane
    (``featurize/quantize.py``): numeric value columns upload as uint8
    codes with a traced reps-table dequant ahead of the member kernel —
    bin-aligned against a tree predictor's ``fused_bin_thresholds`` (bit
    identical), affine over the fit ranges otherwise — and code-typed
    members narrow their int32 codes to the smallest integer dtype. A
    member that cannot be quantized keeps its f32 plane; the program
    still builds."""
    from ..models.base import PredictorModel
    from ..ops.combiner import VectorsCombiner
    from ..prep.derived_filter import FeatureRemovalModel

    plan = list(plan)
    predictors = [t for t in plan if isinstance(t, PredictorModel)]
    if len(predictors) != 1:
        raise Unfuseable(
            f"plan has {len(predictors)} predictor stages (need exactly 1)"
        )
    predictor = predictors[0]
    if plan[-1] is not predictor:
        raise Unfuseable("predictor is not the terminal stage of the plan")

    by_output = {t.output_name: t for t in plan}
    chain: list = []
    cur = by_output.get(predictor.input_names[-1]) if predictor.input_names \
        else None
    while isinstance(cur, FeatureRemovalModel):
        chain.append(cur)
        cur = by_output.get(cur.input_names[-1])
    if not isinstance(cur, VectorsCombiner):
        raise Unfuseable(
            "predictor feature plane is not a VectorsCombiner output "
            f"(found {type(cur).__name__})"
        )
    combiner = cur
    chain.reverse()

    members: list[MemberPlan] = []
    for nm in combiner.input_names:
        t = by_output.get(nm)
        spec_fn = getattr(t, "fused_member_spec", None)
        if t is None or spec_fn is None:
            raise Unfuseable(
                f"combiner member '{nm}' "
                f"({type(t).__name__ if t else 'raw'}) has no fused kernel"
            )
        members.append(spec_fn())  # may itself raise Unfuseable
    if not members:
        raise Unfuseable("combiner has no members")

    covered = {m.output_name for m in members}
    covered.add(combiner.output_name)
    covered.update(c.output_name for c in chain)
    covered.add(predictor.output_name)
    fused_stages = [t for t in plan if t.output_name in covered]
    prefix = [t for t in plan if t.output_name not in covered]
    for t in prefix:
        bad = [nm for nm in (t.input_names or ()) if nm in covered]
        if bad:
            raise Unfuseable(
                f"host stage '{t.output_name}' consumes fused "
                f"intermediate(s) {bad}"
            )
    for nm in result_names:
        if nm in covered and nm != predictor.output_name:
            raise Unfuseable(
                f"result feature '{nm}' is a fused intermediate — only the "
                "prediction leaves the device"
            )

    # widths: provable from the member specs alone; the FusionPlanner's
    # learned/primed widths cross-check them when present
    if fusion is not None:
        for m in members:
            learned = getattr(fusion, "widths", {}).get(
                getattr(m.stage, "uid", None)
            )
            if learned is not None and int(learned) != int(m.width):
                raise Unfuseable(
                    f"member '{m.output_name}' width {m.width} disagrees "
                    f"with the fusion planner's learned width {learned}"
                )
    plane_width = int(sum(m.width for m in members))
    gathers: list[np.ndarray] = []
    width = plane_width
    for c in chain:
        idx = c.fused_gather_indices()
        if idx is None:
            continue
        idx = np.asarray(idx, dtype=np.int32)
        if idx.size and (idx.min() < 0 or idx.max() >= width):
            raise Unfuseable(
                f"feature removal '{c.output_name}' keeps indices outside "
                f"[0, {width})"
            )
        gathers.append(idx)
        width = int(idx.size)

    pp_fn = getattr(predictor, "fused_predict_spec", None)
    if pp_fn is None:
        raise Unfuseable(
            f"model family {type(predictor).__name__} has no fused device "
            "predict"
        )
    pspec = pp_fn()  # may raise Unfuseable
    if pspec.in_dim is not None and int(pspec.in_dim) != width:
        raise Unfuseable(
            f"predictor expects width {pspec.in_dim}, fused plane is "
            f"{width}"
        )

    quant_plans: dict[str, Any] = {}
    quantized_members: list[str] = []
    if quantize:
        # map plane columns through the composed gather chain to the
        # predictor's input positions — a tree predictor's per-input
        # thresholds then give exact bin-aligned codes for the value
        # columns that survive the feature removals
        composed = np.arange(plane_width)
        for idx in gathers:
            composed = composed[idx]
        plane_to_pred = {int(p): k for k, p in enumerate(composed)}
        thr_fn = getattr(predictor, "fused_bin_thresholds", None)
        pred_thr = thr_fn() if thr_fn is not None else None
        out_members: list[MemberPlan] = []
        off = 0
        for m in members:
            kind = (m.quant or {}).get("kind")
            if kind == "numeric":
                new_m, qp = _quantize_numeric_member(
                    m, off, plane_to_pred, pred_thr
                )
                if qp is not None:
                    quant_plans[m.output_name] = qp
                    quantized_members.append(m.output_name)
                out_members.append(new_m)
            elif kind == "codes":
                new_m, changed = _shrink_codes_member(m)
                if changed:
                    quantized_members.append(m.output_name)
                out_members.append(new_m)
            else:
                out_members.append(m)
            off += m.width
        members = out_members

    descriptor = "|".join(
        [m.descriptor or f"{type(m.stage).__name__}:{m.width}"
         for m in members]
        + [f"gather:{g.size}" for g in gathers]
        + [pspec.descriptor or type(predictor).__name__]
    )
    fingerprint = hashlib.sha1(descriptor.encode()).hexdigest()[:16]
    return FusedServingProgram(
        members=members,
        prefix=prefix,
        fused_stages=fused_stages,
        combiner=combiner,
        chain=chain,
        predictor=predictor,
        pspec=pspec,
        gathers=tuple(gathers),
        plane_width=plane_width,
        width=width,
        fingerprint=fingerprint,
        quant_plans=quant_plans,
        quantized_members=tuple(quantized_members),
    )


def _quantize_numeric_member(member, offset, plane_to_pred, pred_thr):
    """Rewrite one numeric member onto uint8 codes + in-graph dequant.
    Per value column (plane col = offset + j·stride): bin-aligned codes
    when the gather chain maps it onto a predictor input with thresholds,
    affine over the fit range otherwise; a column the gathers DROP decodes
    to an exact constant (nothing downstream reads it). Returns
    ``(member, None)`` unchanged when any column has neither thresholds
    nor a fit range — partial members would split the upload for no win."""
    from ..featurize.quantize import ColumnQuant, QuantPlan, dequantize

    hint = member.quant
    n_feats = int(hint["n_feats"])
    track_nulls = bool(hint["track_nulls"])
    ranges = hint.get("ranges")
    stride = 2 if track_nulls else 1
    cols: list = []
    for j in range(n_feats):
        k = plane_to_pred.get(offset + j * stride)
        cq = None
        if k is not None and pred_thr is not None and k < pred_thr.shape[0]:
            cq = ColumnQuant.bins(pred_thr[k])
        if cq is None and ranges is not None:
            cq = ColumnQuant.affine(float(ranges[j][0]), float(ranges[j][1]))
        if cq is None and k is None:
            cq = ColumnQuant.affine(0.0, 0.0)
        if cq is None:
            return member, None
        cols.append(cq)
    qplan = QuantPlan(cols)
    orig_ingest = member.ingest
    orig_kernel = member.kernel
    orig_dummy = member.dummy

    def ingest(raw_cols: list) -> dict:
        d = orig_ingest(raw_cols)
        return {"codes": qplan.encode(d["vals"]), "mask": d["mask"]}

    def kernel(ing: dict, p: dict):
        vals = dequantize(ing["codes"], p["qreps"])
        return orig_kernel({"vals": vals, "mask": ing["mask"]}, p)

    def dummy(n: int) -> dict:
        d = orig_dummy(n)
        return {
            "codes": np.zeros(d["vals"].shape, dtype=np.uint8),
            "mask": d["mask"],
        }

    return dataclasses.replace(
        member,
        # 1 B code + 1 B mask per feature (was 4 + 1)
        up_bytes_per_row=float(n_feats * 2),
        ingest=ingest, kernel=kernel,
        params={**member.params, "qreps": qplan.reps_table()},
        dummy=dummy,
        descriptor=member.descriptor + ":" + qplan.descriptor(),
        quant=None,
    ), qplan


def _shrink_codes_member(member):
    """Narrow a code-typed member's int32 upload to the smallest integer
    dtype its advertised code range fits (the kernel widens back to int32
    before the original kernel runs, so the trace is unchanged past the
    cast). Returns ``(member, False)`` when int32 is already required."""
    import jax.numpy as jnp

    hint = member.quant
    lo = int(hint.get("min_code", 0))
    hi = int(hint["max_code"])
    if -128 <= lo and hi <= 127:
        dt = np.int8
    elif -32768 <= lo and hi <= 32767:
        dt = np.int16
    else:
        return member, False
    itemsize = int(np.dtype(dt).itemsize)
    codes_per_row = int(hint["codes_per_row"])
    orig_ingest = member.ingest
    orig_kernel = member.kernel
    orig_dummy = member.dummy

    def ingest(raw_cols: list) -> dict:
        d = orig_ingest(raw_cols)
        d["codes"] = d["codes"].astype(dt)
        return d

    def kernel(ing: dict, p: dict):
        ing = dict(ing)
        ing["codes"] = ing["codes"].astype(jnp.int32)
        return orig_kernel(ing, p)

    def dummy(n: int) -> dict:
        d = orig_dummy(n)
        d["codes"] = d["codes"].astype(dt)
        return d

    return dataclasses.replace(
        member,
        up_bytes_per_row=float(
            member.up_bytes_per_row - codes_per_row * (4 - itemsize)
        ),
        ingest=ingest, kernel=kernel, dummy=dummy,
        descriptor=member.descriptor + f":qi{8 * itemsize}",
        quant=None,
    ), True


class FusedServingProgram:
    """A compiled fused serving plan. Thread-safe: the only mutable state
    (device-resident params) is built once under a lock."""

    def __init__(
        self, members, prefix, fused_stages, combiner, chain, predictor,
        pspec, gathers, plane_width, width, fingerprint,
        quant_plans=None, quantized_members=(),
    ):
        self.members = members
        self.prefix = prefix
        self.fused_stages = fused_stages
        self.combiner = combiner
        self.chain = chain
        self.predictor = predictor
        self.pspec = pspec
        self.gathers = gathers
        self.plane_width = plane_width
        self.width = width
        self.fingerprint = fingerprint
        #: member output -> featurize.quantize.QuantPlan (numeric members
        #: rewritten onto uint8 codes); code-narrowed members appear in
        #: quantized_members without a plan
        self.quant_plans = dict(quant_plans or {})
        self.quantized_members = tuple(quantized_members)
        self.quantized = bool(self.quantized_members)
        self.covered = frozenset(t.output_name for t in fused_stages)
        self.up_bytes_per_row = float(
            sum(m.up_bytes_per_row for m in members)
        )
        self._spec = _Spec(
            kernels=tuple(m.kernel for m in members),
            core=pspec.core,
            fingerprint=fingerprint,
        )
        self._params_host = {
            "members": tuple(m.params for m in members),
            "gathers": self.gathers,
            "predictor": pspec.params,
        }
        self._params_dev = None
        self._params_lock = threading.Lock()
        # core shape per row via abstract evaluation — no compile, no data
        import jax

        aval = jax.eval_shape(
            functools.partial(_fused_eval, spec=self._spec),
            tuple(m.dummy(4) for m in members),
            self._params_host,
        )
        per_row = 1
        for d in aval.shape[1:]:
            per_row *= int(d)
        self.core_dtype = np.dtype(aval.dtype)
        self.down_bytes_per_row = float(per_row * self.core_dtype.itemsize)

    # ------------------------------------------------------------- reporting
    @property
    def static_widths(self) -> dict[str, int]:
        out = {m.output_name: int(m.width) for m in self.members}
        out[self.combiner.output_name] = self.plane_width
        w = self.plane_width
        gi = 0
        for c in self.chain:
            if c.fused_gather_indices() is not None:
                w = int(self.gathers[gi].size)
                gi += 1
            out[c.output_name] = w
        out[self.predictor.output_name] = 1
        return out

    @property
    def predictor_input_meta(self):
        """Fit-static VectorMetadata of the plane the predictor consumes
        (what explain groups by)."""
        from ..analysis.plan_audit import _meta_of

        producer = self.chain[-1] if self.chain else self.combiner
        return _meta_of(producer)

    def describe(self) -> dict[str, Any]:
        out = {
            "fingerprint": self.fingerprint,
            "members": [
                {"stage": m.stage.operation_name, "output": m.output_name,
                 "width": int(m.width)}
                for m in self.members
            ],
            "planeWidth": self.plane_width,
            "predictorWidth": self.width,
            "gathers": [int(g.size) for g in self.gathers],
            "upBytesPerRow": self.up_bytes_per_row,
            "downBytesPerRow": self.down_bytes_per_row,
            "coveredStages": sorted(self.covered),
            "hostPrefixStages": [t.output_name for t in self.prefix],
            "quantized": self.quantized,
        }
        if self.quantized:
            out["quantizedMembers"] = list(self.quantized_members)
            # per-column max reconstruction error ledger (0.0 for
            # bin-aligned / constant columns — predictions unaffected)
            out["quantError"] = {
                nm: qp.errors() for nm, qp in self.quant_plans.items()
            }
            out["quantPlans"] = {
                nm: qp.to_json() for nm, qp in self.quant_plans.items()
            }
        return out

    # ------------------------------------------------------------- dispatch
    def _device_params(self):
        import jax

        from ..telemetry import runlog as _runlog
        from ..telemetry import spans as _tspans

        with self._params_lock:
            if self._params_dev is None:
                # one-time model-constant upload (fills, weights, tree
                # stacks) — counted once, at program bring-up. Leaves
                # that are ALREADY device arrays (a tree model's _dev
                # cache) transfer nothing under device_put and must not
                # inflate the census
                nbytes = sum(
                    int(getattr(a, "nbytes", 0))
                    for a in jax.tree_util.tree_leaves(self._params_host)
                    if not isinstance(a, jax.Array)
                )
                t0 = _tspans.clock()
                self._params_dev = jax.device_put(self._params_host)
                _runlog.record_upload(nbytes, _tspans.clock() - t0)
            return self._params_dev

    def run(
        self, cols: dict, b: int, n: int, lane_masks: np.ndarray | None = None
    ) -> tuple[np.ndarray, np.ndarray | None, dict]:
        """Execute the fused program over already-built raw columns
        (``b`` bucketed rows, ``n`` real). Returns ``(core, lane_core,
        info)`` — host numpy arrays; callers apply the shared epilogue.

        Census contract: exactly ONE host→device crossing here (the
        donated ingest upload; model params counted once at bring-up) and
        ONE device→host crossing (the core download at render —
        ``down_bytes_per_row × n`` by the same real-rows convention as the
        staged census).
        """
        import jax

        from . import stats as cstats
        from ..telemetry import runlog as _runlog
        from ..telemetry import spans as _tspans

        params = self._device_params()
        ingest = tuple(
            m.ingest([cols[nm] for nm in m.stage.input_names])
            for m in self.members
        )
        # the ingest arrays' sizes are fully determined by the member
        # specs — the analytic per-row figure times the bucketed rows IS
        # sum(leaf.nbytes), without a per-batch pytree walk. Explain lane
        # masks upload with the ingest and count in the SAME crossing:
        # the census contract is one recorded h2d per batch, and the
        # masks are part of that ingest, not a second boundary trip
        up_bytes = int(round(self.up_bytes_per_row * b))
        lanes = 0
        masks = None
        if lane_masks is not None:
            lanes = int(lane_masks.shape[0])
            masks = np.asarray(lane_masks, dtype=np.float32)
            up_bytes += int(masks.nbytes)
        t0 = _tspans.clock()
        ingest = jax.device_put(ingest)
        if masks is not None:
            masks = jax.device_put(masks)
        _runlog.record_upload(up_bytes, _tspans.clock() - t0)
        t1 = _tspans.clock()
        if masks is None:
            core = self._dispatch_base(ingest, params)
            lane_core = None
        else:
            core, lane_core = self._dispatch_explain(ingest, params, masks)
        del ingest  # DONATED — consumed by the dispatch, never read again
        t2 = _tspans.clock()
        core = np.asarray(core)
        down_bytes = int(round(self.down_bytes_per_row * n))
        if lane_core is not None:
            lane_core = np.asarray(lane_core)
            down_bytes += int(round(self.down_bytes_per_row * n * lanes))
        dl = _tspans.clock() - t2
        _runlog.record_download(down_bytes, dl)
        cstats.stats().record_fused(lanes=lanes)
        return core, lane_core, {
            "upBytes": up_bytes,
            "downBytes": down_bytes,
            "dispatchSeconds": (t2 - t1) + dl,
            "lanes": lanes,
        }

    def _dispatch_base(self, ingest, params):
        """ONE donated dispatch; ``ingest`` is consumed — the TPX003 AST
        check scans this function for a read-after-donate."""
        from ..utils.aot import aot_call
        from .dispatch import donating

        call = donating(
            "fused_serve", _plain_jit("fused_serve", _fused_eval),
            (0,), static_argnames=("spec",),
        )
        statics = {"spec": self._spec}
        return aot_call("fused_serve", call, (ingest, params), statics)

    def _dispatch_explain(self, ingest, params, masks):
        """Base + explain lanes in ONE donated dispatch (see
        ``_dispatch_base`` for the donation contract)."""
        from ..utils.aot import aot_call
        from .dispatch import donating

        call = donating(
            "fused_serve_explain",
            _plain_jit("fused_serve_explain", _fused_eval_explain),
            (0,), static_argnames=("spec",),
        )
        statics = {"spec": self._spec}
        return aot_call("fused_serve_explain", call, (ingest, params, masks), statics)

    def epilogue(self, core: np.ndarray) -> tuple:
        """The HOST numpy tail mapping the downloaded core to
        ``(prediction, probability, raw)`` — the same
        ``predictions_from_core`` the staged path runs, pinning parity."""
        return self.pspec.epilogue(core)


# --------------------------------------------------------------------------
# member-plan builders (called by the stage classes' fused_member_spec)
# --------------------------------------------------------------------------
def numeric_member(
    stage, fills: np.ndarray, track_nulls: bool, ranges=None
) -> MemberPlan:
    """Impute + null-track on device. Host ingest = f32 values + validity
    mask; ``where(mask, value, fill)`` matches the staged
    ``_impute_block`` bit for bit once both land in the f32 plane.
    ``ranges`` (per-column fit-time [lo, hi]) rides the quant hint so a
    quantized build can shrink the value upload to uint8 codes."""
    fills = np.asarray(fills, dtype=np.float32)
    n_feats = int(fills.shape[0])
    width = n_feats * (2 if track_nulls else 1)

    def ingest(cols: list) -> dict:
        vals = np.stack(
            [np.asarray(c.values, dtype=np.float32) for c in cols], axis=1
        )
        mask = np.stack(
            [np.asarray(c.mask, dtype=bool) for c in cols], axis=1
        )
        return {"vals": vals, "mask": mask}

    def kernel(ing: dict, p: dict):
        import jax.numpy as jnp

        vals = jnp.where(ing["mask"], ing["vals"], p["fills"][None, :])
        if not track_nulls:
            return vals
        nulls = (~ing["mask"]).astype(jnp.float32)
        # staged layout interleaves [value, null] per feature
        return jnp.stack([vals, nulls], axis=2).reshape(
            vals.shape[0], width
        )

    def dummy(n: int) -> dict:
        return {
            "vals": np.zeros((n, n_feats), dtype=np.float32),
            "mask": np.zeros((n, n_feats), dtype=bool),
        }

    return MemberPlan(
        stage=stage, width=width,
        up_bytes_per_row=float(n_feats * (4 + 1)),
        ingest=ingest, kernel=kernel, params={"fills": fills}, dummy=dummy,
        descriptor=(
            f"numeric:{n_feats}:{'nulls' if track_nulls else 'plain'}"
        ),
        quant={
            "kind": "numeric", "n_feats": n_feats,
            "track_nulls": track_nulls, "ranges": ranges,
        },
    )


def passthrough_member(stage, n_feats: int) -> MemberPlan:
    """RealNN passthrough columns (no nulls possible)."""

    def ingest(cols: list) -> dict:
        return {
            "vals": np.stack(
                [np.asarray(c.values, dtype=np.float32) for c in cols],
                axis=1,
            )
        }

    def kernel(ing: dict, p: dict):
        return ing["vals"]

    def dummy(n: int) -> dict:
        return {"vals": np.zeros((n, n_feats), dtype=np.float32)}

    return MemberPlan(
        stage=stage, width=n_feats, up_bytes_per_row=float(4 * n_feats),
        ingest=ingest, kernel=kernel, params={}, dummy=dummy,
        descriptor=f"passthrough:{n_feats}",
    )


def onehot_member(stage, vocabs, track_nulls, clean_text) -> MemberPlan:
    """Pivot one-hot rebuilt as a device scatter over interned codes: the
    host CSR text-interning kernels resolve each DISTINCT raw value to a
    vocab code once (``_pivot_codes``: -1 null, -2 OTHER, >=0 vocab); the
    kernel maps codes to [vocab..., OTHER(, null)] columns exactly as the
    staged ``pivot_block``. Set-valued pivots (member counts > 1) are not
    fuseable — the caller's build raises before constructing this."""
    from ..ops.categorical import _pivot_codes

    widths = [
        len(v) + 1 + (1 if track_nulls else 0) for v in vocabs
    ]
    indexes = [{v: i for i, v in enumerate(vocab)} for vocab in vocabs]
    total = int(sum(widths))
    n_feats = len(vocabs)

    def ingest(cols: list) -> dict:
        from ..types.columns import TextColumn

        codes = np.empty((len(cols[0]), n_feats), dtype=np.int32)
        for j, (c, index) in enumerate(zip(cols, indexes)):
            if not isinstance(c, TextColumn):
                raise Unfuseable(
                    f"pivot member expected a text column, got "
                    f"{type(c).__name__}"
                )
            codes[:, j] = _pivot_codes(c.to_list(), index, clean_text)
        return {"codes": codes}

    def kernel(ing: dict, p: dict):
        import jax.numpy as jnp

        blocks = []
        for j, vocab in enumerate(vocabs):
            w = widths[j]
            other_col = len(vocab)
            null_col = other_col + 1 if track_nulls else -1
            codes = ing["codes"][:, j]
            col_idx = jnp.where(
                codes >= 0, codes,
                jnp.where(codes == -2, other_col, null_col),
            )
            blocks.append(
                (col_idx[:, None] == jnp.arange(w)[None, :]).astype(
                    jnp.float32
                )
            )
        return blocks[0] if len(blocks) == 1 else jnp.concatenate(
            blocks, axis=1
        )

    def dummy(n: int) -> dict:
        return {"codes": np.zeros((n, n_feats), dtype=np.int32)}

    return MemberPlan(
        stage=stage, width=total, up_bytes_per_row=float(4 * n_feats),
        ingest=ingest, kernel=kernel, params={}, dummy=dummy,
        descriptor=(
            "onehot:" + ",".join(map(str, widths))
            + (":nulls" if track_nulls else "")
        ),
        quant={
            "kind": "codes", "min_code": -2,
            "max_code": max(len(v) for v in vocabs) - 1,
            "codes_per_row": n_feats,
        },
    )


def hashed_text_member(
    stage, methods, num_hashes: int, track_nulls: bool, binary_freq: bool,
    to_lowercase: bool, min_token_length: int, seed: int,
) -> MemberPlan:
    """HashingTF text planes rebuilt as a device scatter (leg of ROADMAP
    item 1 that previously raised :class:`Unfuseable` and forced text
    flows back to the staged loop). The host side stays a codec — tokenize
    + murmur3 yields at most ``TPTPU_TEXT_FUSED_TOKENS`` (default 16)
    DISTINCT hash buckets per row per slot as int32 codes with f32
    occurrence weights — and the kernel scatters them into the
    ``num_hashes``-wide block in-graph, exactly like the OneHot code
    path. Binary term frequency applies ``> 0`` after the scatter so
    duplicate-bucket collisions match the staged set semantics; rows with
    more distinct buckets than the cap raise at ingest, which the serving
    seam counts as a dispatch fallback (the batch degrades, the program
    stays). ``Pivot`` slots are not handled here — the SmartText wrapper
    composes those separately or refuses."""
    import os

    from ..ops import text as _text_ops

    hash_slots = [
        i for i, m in enumerate(methods) if m == _text_ops.HASH
    ]
    if not hash_slots:
        raise Unfuseable("smart-text member has no hashed slots")
    if any(m == _text_ops.PIVOT for m in methods):
        raise Unfuseable(
            "smart-text member mixes Pivot and Hash slots — not fuseable"
        )
    n_slots = len(methods)
    n_hash = len(hash_slots)
    k_cap = int(os.environ.get("TPTPU_TEXT_FUSED_TOKENS", "16"))
    widths = [
        (num_hashes if m == _text_ops.HASH else 0)
        + (1 if track_nulls else 0)
        for m in methods
    ]
    total = int(sum(widths))
    if total <= 0:
        raise Unfuseable("smart-text member has zero fused width")

    def _slot_codes(values, n: int):
        """One slot's (codes [n, k_cap] int32, weights [n, k_cap] f32,
        null flags [n] uint8). Sentinel code ``num_hashes`` routes to a
        dump column sliced off after the scatter."""
        from .. import native as _native
        from ..utils import text as _text_util

        texts, rows_idx = _text_ops._partition_nulls(values)
        nulls = np.ones(n, dtype=np.uint8)
        nulls[rows_idx] = 0
        coo = None
        if texts:
            coo = _native.tokenize_hash_coo(
                texts, rows_idx, num_hashes, seed=seed, binary=binary_freq,
                to_lowercase=to_lowercase, min_token_length=min_token_length,
                prefix="",
            )
        if coo is not None:
            rows, hcols = coo
            rows = np.asarray(rows, dtype=np.int64)
            hcols = np.asarray(hcols, dtype=np.int64)
        else:
            r_parts, c_parts = [], []
            for raw, row in zip(texts, rows_idx):
                toks = _text_util.tokenize(
                    raw, to_lowercase=to_lowercase,
                    min_token_length=min_token_length,
                )
                if not toks:
                    continue
                h = _native.murmur3_batch(toks, seed=seed)
                j = (h % np.uint32(num_hashes)).astype(np.int64)
                if binary_freq:
                    j = np.unique(j)
                r_parts.append(np.full(j.shape[0], row, dtype=np.int64))
                c_parts.append(j)
            rows = (
                np.concatenate(r_parts) if r_parts
                else np.zeros(0, dtype=np.int64)
            )
            hcols = (
                np.concatenate(c_parts) if c_parts
                else np.zeros(0, dtype=np.int64)
            )
        codes = np.full((n, k_cap), num_hashes, dtype=np.int32)
        weights = np.zeros((n, k_cap), dtype=np.float32)
        if rows.size:
            # collapse duplicate (row, bucket) pairs to one slot with an
            # occurrence count; rank-within-row via the sorted row runs
            pair = rows * np.int64(num_hashes) + hcols
            uniq, counts = np.unique(pair, return_counts=True)
            ur = uniq // np.int64(num_hashes)
            uc = uniq % np.int64(num_hashes)
            pos = np.arange(uniq.size) - np.searchsorted(ur, ur)
            k_max = int(pos.max()) + 1
            if k_max > k_cap:
                raise Unfuseable(
                    f"text row needs {k_max} distinct hash buckets "
                    f"(> TPTPU_TEXT_FUSED_TOKENS={k_cap})"
                )
            codes[ur, pos] = uc.astype(np.int32)
            weights[ur, pos] = counts.astype(np.float32)
        return codes, weights, nulls

    def ingest(cols: list) -> dict:
        from ..types.columns import TextColumn

        n = len(cols[0])
        raw = [
            c.values if isinstance(c, TextColumn) else c.to_list()
            for c in cols
        ]
        codes = np.empty((n, n_hash, k_cap), dtype=np.int32)
        weights = np.empty((n, n_hash, k_cap), dtype=np.float32)
        nulls = np.zeros((n, n_slots), dtype=np.uint8)
        hs = 0
        for s in range(n_slots):
            if methods[s] == _text_ops.HASH:
                codes[:, hs], weights[:, hs], nulls[:, s] = _slot_codes(
                    raw[s], n
                )
                hs += 1
            else:  # Ignore: null indicator only
                _, rows_idx = _text_ops._partition_nulls(raw[s])
                nulls[:, s] = 1
                nulls[rows_idx, s] = 0
        out = {"codes": codes, "weights": weights}
        if track_nulls:
            out["nulls"] = nulls
        return out

    def kernel(ing: dict, p: dict):
        import jax.numpy as jnp

        n = ing["codes"].shape[0]
        rows = jnp.arange(n)[:, None]
        blocks = []
        hs = 0
        for s in range(n_slots):
            if methods[s] == _text_ops.HASH:
                acc = jnp.zeros((n, num_hashes + 1), jnp.float32).at[
                    rows, ing["codes"][:, hs, :]
                ].add(ing["weights"][:, hs, :])
                block = acc[:, :num_hashes]
                if binary_freq:
                    block = (block > 0).astype(jnp.float32)
                blocks.append(block)
                hs += 1
            if track_nulls:
                blocks.append(ing["nulls"][:, s:s + 1].astype(jnp.float32))
        return blocks[0] if len(blocks) == 1 else jnp.concatenate(
            blocks, axis=1
        )

    def dummy(n: int) -> dict:
        out = {
            "codes": np.full((n, n_hash, k_cap), num_hashes, np.int32),
            "weights": np.zeros((n, n_hash, k_cap), np.float32),
        }
        if track_nulls:
            out["nulls"] = np.zeros((n, n_slots), np.uint8)
        return out

    return MemberPlan(
        stage=stage, width=total,
        up_bytes_per_row=float(
            n_hash * k_cap * 8 + (n_slots if track_nulls else 0)
        ),
        ingest=ingest, kernel=kernel, params={}, dummy=dummy,
        descriptor=(
            f"hashtext:{num_hashes}x{n_hash}:k{k_cap}"
            + (":bin" if binary_freq else "")
            + (":nulls" if track_nulls else "")
        ),
        quant={
            "kind": "codes", "min_code": 0, "max_code": num_hashes,
            "codes_per_row": n_hash * k_cap,
        },
    )


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py, TPJ0xx)
# --------------------------------------------------------------------------
def _trace_members():
    """Synthetic member plans for auditing the fused BUILDERS without a
    fitted plan: one numeric member (3 features, null-tracked) + one
    pivot member (vocab of 3) — the two kernel families every fitted
    fused program composes. The fitted program itself is audited by
    ``analysis.program.audit_fused_program`` with its real params."""
    import types as _types

    num_stage = _types.SimpleNamespace(
        output_name="trace_num", input_names=("a", "b", "c"),
        operation_name="TraceNumeric", uid="trace_num",
    )
    oh_stage = _types.SimpleNamespace(
        output_name="trace_oh", input_names=("p",),
        operation_name="TraceOneHot", uid="trace_oh",
    )
    m1 = numeric_member(num_stage, np.zeros(3, np.float32), True)
    m2 = onehot_member(oh_stage, [("x", "y", "z")], True, False)
    return m1, m2


def _trace_build(n: int, explain: bool = False):
    m1, m2 = _trace_members()
    width = int(m1.width + m2.width)
    spec = _Spec(
        kernels=(m1.kernel, m2.kernel),
        core=lambda plane, p: plane @ p["w"] + p["b"],
        fingerprint="trace",
    )
    params = {
        "members": (m1.params, m2.params),
        "gathers": (),
        "predictor": {
            "w": np.zeros((width,), np.float32), "b": np.float32(0.0),
        },
    }
    ingest = (m1.dummy(n), m2.dummy(n))
    if explain:
        masks = np.zeros((4, width), np.float32)
        return (ingest, params, masks), {"spec": spec}
    return (ingest, params), {"spec": spec}


def program_trace_specs():
    """The fused serving builders over representative synthetic members,
    bucketed on the BATCH axis (the scoring closure's pow2 row buckets)."""
    return [
        dict(
            name="fused_serve",
            fn=_fused_eval, base_fn=_fused_eval,
            build=lambda n: _trace_build(n),
            buckets=(8, 16),
            donate_argnums=(0,), static_argnames=("spec",),
            scoring=True,
        ),
        dict(
            name="fused_serve_explain",
            fn=_fused_eval_explain, base_fn=_fused_eval_explain,
            build=lambda n: _trace_build(n, explain=True),
            buckets=(8, 16),
            donate_argnums=(0,), static_argnames=("spec",),
            scoring=True,
        ),
    ]
