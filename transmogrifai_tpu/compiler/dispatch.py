"""Donated-buffer, pipelined dispatch helpers.

Two seams that cut hot-path dispatch cost without touching any math:

* **Donation** — ``donating(name, jit_fn, ...)`` builds a ``jax.jit`` twin
  of a module-level jitted function with ``donate_argnums`` set, so a
  carried buffer (the boosting margin between chunk programs, a sweep's
  fresh mask stack) is aliased into the output instead of copied. Callers
  must treat donated args as CONSUMED — every wired call site passes a
  buffer it never reads again. ``TPTPU_DONATE=0`` falls back to the
  undonated original.

* **Transfer prefetch** — ``prefetch_f32(arr)`` starts the async
  host→device upload of a float32 view of ``arr`` while host-side work
  (layer transforms, checkpoint saves, row codecs) is still running;
  ``device_f32(arr)`` picks the in-flight buffer up at dispatch time (or
  falls back to a plain ``jnp.asarray``). This is how layer k+1's input
  transfer overlaps layer k's compute on the tunneled chip. Prefetch is a
  no-op under an active execution mesh — GSPMD placement stays with the
  sharding helpers in ``parallel/mesh.py``.
"""
from __future__ import annotations

import logging
import os
import threading
import weakref
from typing import Any, Callable, Sequence

import numpy as np

log = logging.getLogger(__name__)

_DONATED: dict[str, Any] = {}
_DONATED_LOCK = threading.Lock()


def donating(
    name: str,
    jit_fn: Callable,
    donate_argnums: tuple[int, ...],
    static_argnames: Sequence[str] = (),
) -> Callable:
    """Donation-enabled twin of ``jit_fn`` (cached by ``name``). Returns
    ``jit_fn`` unchanged when donation is disabled or the wrapped python
    function is not recoverable."""
    if os.environ.get("TPTPU_DONATE", "1") == "0":
        return jit_fn
    with _DONATED_LOCK:
        got = _DONATED.get(name)
    if got is not None:
        return got
    base = getattr(jit_fn, "__wrapped__", None)
    if base is None:
        got = jit_fn
    else:
        import jax

        try:
            got = jax.jit(  # tp: disable=TPL003 — cached in _DONATED
                base,
                static_argnames=tuple(static_argnames),
                donate_argnums=donate_argnums,
            )
        except Exception as e:  # donation must never break a fit
            log.info("donated twin of %s unavailable (%s)", name, e)
            got = jit_fn
    with _DONATED_LOCK:
        _DONATED.setdefault(name, got)
        return _DONATED[name]


# ---------------------------------------------------------------- prefetch
# id -> (weakref-to-source, device buffer); small FIFO — entries exist only
# between a prefetch and the dispatch that consumes them
_PREFETCH: dict[int, tuple] = {}
_PREFETCH_LOCK = threading.Lock()
_PREFETCH_CAP = 8


def _mesh_active() -> bool:
    try:
        from ..parallel.mesh import execution_mesh

        return execution_mesh() is not None
    except Exception:
        return False


def prefetch_f32(arr) -> None:
    """Start the async device upload of ``np.asarray(arr, float32)``;
    ``device_f32`` on the SAME object (by identity) picks it up. Errors are
    swallowed — prefetch is purely an overlap optimization."""
    try:
        if _mesh_active():
            return
        src = arr
        key = id(src)
        with _PREFETCH_LOCK:
            if key in _PREFETCH:
                return
        import jax

        from ..telemetry import runlog as _runlog
        from ..telemetry import spans as _tspans

        nbytes = int(getattr(arr, "nbytes", 0))
        with _tspans.span("compile/prefetch", bytes=nbytes):
            t0 = _tspans.clock()
            buf = jax.device_put(np.asarray(arr, dtype=np.float32))
            # runtime transfer census (telemetry/runlog.py): every upload
            # through this seam is one host->device crossing the run
            # ledger counts — the live counterpart of the static TPX
            # census in analysis/plan_audit.py
            _runlog.record_upload(
                buf.nbytes if hasattr(buf, "nbytes") else nbytes,
                _tspans.clock() - t0,
            )
        try:
            ref = weakref.ref(src)
        except TypeError:  # source not weakref-able: skip (no way to
            return         # detect the id being recycled)
        with _PREFETCH_LOCK:
            _PREFETCH[key] = (ref, buf)
            while len(_PREFETCH) > _PREFETCH_CAP:
                _PREFETCH.pop(next(iter(_PREFETCH)))
    except Exception as e:
        log.debug("prefetch skipped: %s", e)


def device_f32(arr):
    """The prefetched device buffer for ``arr`` if one is in flight (and
    the source object is still alive — a dead ref means the id may have
    been recycled), else a plain float32 ``jnp.asarray``. Entries are NOT
    consumed: several model families dispatch on the same training matrix.
    Callers must not mutate ``arr`` between prefetch and dispatch."""
    import jax.numpy as jnp

    key = id(arr)
    with _PREFETCH_LOCK:
        hit = _PREFETCH.get(key)
        # purge dead refs opportunistically so recycled ids cannot alias
        # (r is a weakref deref — runs no user code, takes no locks)
        for k in [k for k, (r, _) in _PREFETCH.items() if r() is None]:  # tp: disable=TPC004
            _PREFETCH.pop(k, None)
    if hit is not None:
        ref, buf = hit
        if ref() is arr and not _mesh_active():
            # the upload was already counted at prefetch time — a pickup
            # is not a second transfer
            return buf
    import jax

    if isinstance(arr, jax.Array):
        # already-device: re-wraps without crossing the boundary — no
        # census entry, no clock reads on this fast path
        return jnp.asarray(arr, dtype=jnp.float32)
    from ..telemetry import runlog as _runlog
    from ..telemetry import spans as _tspans

    t0 = _tspans.clock()
    if isinstance(arr, np.ndarray):
        # dtype-convert on HOST: an eager device-side convert compiles a
        # per-process program on the axon backend (see gbdt._binned)
        out = jnp.asarray(np.asarray(arr, dtype=np.float32))
    else:
        out = jnp.asarray(arr, dtype=jnp.float32)
    # fresh upload (no prefetch in flight): one host->device crossing
    # on the run ledger's runtime transfer census
    _runlog.record_upload(
        int(getattr(out, "nbytes", getattr(arr, "nbytes", 0))),
        _tspans.clock() - t0,
    )
    return out


def clear_prefetch() -> None:
    """Release every prefetched device buffer. The phases that prefetch
    (DAG fit, columnar scoring) call this when they finish — without it a
    long-lived process would pin up to ``_PREFETCH_CAP`` training-matrix
    buffers in device memory for its lifetime."""
    with _PREFETCH_LOCK:
        _PREFETCH.clear()
