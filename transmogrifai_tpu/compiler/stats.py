"""``compileStats`` — the compile plane's process-wide ledger.

One thread-safe counter object records every program-acquisition event:
compiles (AOT misses that paid trace+compile), memory/disk cache hits,
candidate-dedup lane hits (lanes that rode an already-acquired batched
program), shape-bucket pad lanes, warmup loads and their overlap seconds,
and the corruption/version-invalidation drops from the persistent bank.

Counters are cumulative per process. Consumers that want a per-phase view
(the model selector's summary, the bench's cold-run probe) take a
``snapshot()`` before and report ``delta(before)`` after.
"""
from __future__ import annotations

import threading

_COUNTER_KEYS = (
    "programsCompiled",      # AOT misses: paid a trace + compile (or a
                             # persistent-compile-cache load) this process
    "cacheHitsMemory",       # same-process repeats served from _MEM
    "cacheHitsDisk",         # deserialized a banked executable (no trace,
                             # no compile)
    "dedupHits",             # candidate lanes beyond the first that shared
                             # one batched program (cross-candidate dedup)
    "laneBucketPads",        # inert lanes added by shape-bucket padding
    "bucketedSweeps",        # sweeps whose lane count was padded to a bucket
    "corruptBlobsDropped",   # unreadable/torn blobs deleted + recompiled
    "versionInvalidations",  # blobs dropped for a source/backend change
    "savesFailed",           # background executable saves that errored
    "warmupPrograms",        # executables loaded by the async warmup thread
)


class CompileStats:
    """Thread-safe counters; ``warmupOverlapSeconds`` rides along as a
    float (seconds of program acquisition overlapped with host-side work by
    the background warmup thread)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {k: 0 for k in _COUNTER_KEYS}
        self._warmup_overlap_s = 0.0
        #: per-program-name compile counts — lets tests pin "this sweep
        #: compiled exactly one logistic program" without global noise
        self._compiled_by_name: dict[str, int] = {}

    # ------------------------------------------------------------ recording
    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n

    def record_compile(self, name: str) -> None:
        with self._lock:
            self._counts["programsCompiled"] += 1
            self._compiled_by_name[name] = (
                self._compiled_by_name.get(name, 0) + 1
            )

    def record_sweep(self, lanes: int, padded: int = 0) -> None:
        """One batched candidate sweep dispatched: ``lanes`` logical
        candidate lanes shared one program (dedup = lanes - 1), ``padded``
        inert lanes were added to land on a shape bucket."""
        with self._lock:
            if lanes > 1:
                self._counts["dedupHits"] += lanes - 1
            if padded > 0:
                self._counts["laneBucketPads"] += padded
                self._counts["bucketedSweeps"] += 1

    def record_warmup(self, programs: int, overlap_s: float) -> None:
        with self._lock:
            self._counts["warmupPrograms"] += programs
            self._warmup_overlap_s += overlap_s

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> dict:
        """JSON-able view. ``compileCacheHitRate`` is hits / acquisitions
        (acquisition = any aot_call that needed a program: hit or
        compile)."""
        with self._lock:
            out: dict = dict(self._counts)
            out["warmupOverlapSeconds"] = round(self._warmup_overlap_s, 3)
            out["programsCompiledByName"] = dict(self._compiled_by_name)
        hits = out["cacheHitsMemory"] + out["cacheHitsDisk"]
        total = hits + out["programsCompiled"]
        out["compileCacheHitRate"] = round(hits / total, 4) if total else None
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts = {k: 0 for k in _COUNTER_KEYS}
            self._warmup_overlap_s = 0.0
            self._compiled_by_name = {}


_STATS = CompileStats()


def stats() -> CompileStats:
    return _STATS


def snapshot() -> dict:
    return _STATS.snapshot()


def delta(before: dict) -> dict:
    """Per-phase view: current snapshot minus a ``snapshot()`` taken
    earlier (rates recomputed from the deltas, not differenced)."""
    now = _STATS.snapshot()
    out: dict = {}
    for k in _COUNTER_KEYS:
        out[k] = now[k] - before.get(k, 0)
    out["warmupOverlapSeconds"] = round(
        now["warmupOverlapSeconds"] - before.get("warmupOverlapSeconds", 0.0),
        3,
    )
    by_name_before = before.get("programsCompiledByName", {})
    out["programsCompiledByName"] = {
        name: n - by_name_before.get(name, 0)
        for name, n in now["programsCompiledByName"].items()
        if n - by_name_before.get(name, 0)
    }
    hits = out["cacheHitsMemory"] + out["cacheHitsDisk"]
    total = hits + out["programsCompiled"]
    out["compileCacheHitRate"] = round(hits / total, 4) if total else None
    return out
