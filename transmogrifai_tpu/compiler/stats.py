"""``compileStats`` — the compile plane's process-wide ledger.

One thread-safe counter object records every program-acquisition event:
compiles (AOT misses that paid trace+compile), memory/disk cache hits,
candidate-dedup lane hits (lanes that rode an already-acquired batched
program), shape-bucket pad lanes, warmup loads and their overlap seconds,
and the corruption/version-invalidation drops from the persistent bank.

Counters are cumulative per process. Consumers that want a per-phase view
(the model selector's summary, the bench's cold-run probe) take a
``snapshot()`` before and report ``delta(before)`` after.

The counter dict, its lock, and the snapshot/delta arithmetic live on the
shared :class:`telemetry.metrics.LedgerCore` — one re-entrant lock across
every ledger (consistent cross-ledger snapshots) and one copy of the
delta helpers instead of three. The ledger registers itself as the
``compile`` source of ``telemetry.render_prometheus()``.
"""
from __future__ import annotations

from ..telemetry import metrics as _tm

_COUNTER_KEYS = (
    "programsCompiled",      # AOT misses: paid a trace + compile (or a
                             # persistent-compile-cache load) this process
    "cacheHitsMemory",       # same-process repeats served from _MEM
    "cacheHitsDisk",         # deserialized a banked executable (no trace,
                             # no compile)
    "dedupHits",             # candidate lanes beyond the first that shared
                             # one batched program (cross-candidate dedup)
    "laneBucketPads",        # inert lanes added by shape-bucket padding
    "bucketedSweeps",        # sweeps whose lane count was padded to a bucket
    "corruptBlobsDropped",   # unreadable/torn blobs deleted + recompiled
    "versionInvalidations",  # blobs dropped for a source/backend change
    "savesFailed",           # background executable saves that errored
    "warmupPrograms",        # executables loaded by the async warmup thread
    "fusedDispatches",       # steady-state batches scored as ONE fused
                             # donated XLA dispatch (compiler/fused.py)
    "fusedExplainLanes",     # LOCO perturbation lanes that rode a fused
                             # dispatch (in-graph, no separate sweep)
    "fusedFallbacks",        # batches that degraded from the fused graph
                             # to the staged loop (TPX008 in the audit)
    "programsAudited",       # bank admissions run through the TPJ
                             # compiled-program audit (TPTPU_PROGRAM_AUDIT=1)
    "programAuditRejected",  # admissions refused a persisted blob because
                             # the audit found a contract violation
)


class CompileStats(_tm.LedgerCore):
    """Thread-safe counters; ``warmupOverlapSeconds`` rides along as a
    float (seconds of program acquisition overlapped with host-side work by
    the background warmup thread)."""

    def __init__(self) -> None:
        super().__init__(_COUNTER_KEYS)
        self._warmup_overlap_s = 0.0
        #: per-program-name compile counts — lets tests pin "this sweep
        #: compiled exactly one logistic program" without global noise
        self._compiled_by_name: dict[str, int] = {}
        #: per-reason fused degradations — keys are the fallback reason
        #: strings the serving seam counts (``unfuseable``,
        #: ``dispatch_error``, ``prefix_degraded``, ...)
        self._fused_fallback_reasons: dict[str, int] = {}

    # ------------------------------------------------------------ recording
    def record_compile(self, name: str) -> None:
        with self._lock:
            self._counts["programsCompiled"] += 1
            self._compiled_by_name[name] = (
                self._compiled_by_name.get(name, 0) + 1
            )

    def record_sweep(self, lanes: int, padded: int = 0) -> None:
        """One batched candidate sweep dispatched: ``lanes`` logical
        candidate lanes shared one program (dedup = lanes - 1), ``padded``
        inert lanes were added to land on a shape bucket."""
        with self._lock:
            if lanes > 1:
                self._counts["dedupHits"] += lanes - 1
            if padded > 0:
                self._counts["laneBucketPads"] += padded
                self._counts["bucketedSweeps"] += 1

    def record_fused(self, lanes: int = 0) -> None:
        """One fused serving dispatch (``lanes`` > 0 when LOCO explain
        lanes rode the same program)."""
        with self._lock:
            self._counts["fusedDispatches"] += 1
            if lanes > 0:
                self._counts["fusedExplainLanes"] += lanes

    def record_fused_fallback(self, reason: str | None = None) -> None:
        with self._lock:
            self._counts["fusedFallbacks"] += 1
            if reason:
                self._fused_fallback_reasons[reason] = (
                    self._fused_fallback_reasons.get(reason, 0) + 1
                )

    def record_unfused_batch(self, reason: str) -> None:
        """A batch that *could not even attempt* the fused graph (the plan
        raised ``Unfuseable`` at build) — counted only in the per-reason
        sub-map so the global ``fusedFallbacks`` counter keeps its
        degraded-at-dispatch semantics."""
        with self._lock:
            self._fused_fallback_reasons[reason] = (
                self._fused_fallback_reasons.get(reason, 0) + 1
            )

    def record_warmup(self, programs: int, overlap_s: float) -> None:
        with self._lock:
            self._counts["warmupPrograms"] += programs
            self._warmup_overlap_s += overlap_s

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> dict:
        """JSON-able view. ``compileCacheHitRate`` is hits / acquisitions
        (acquisition = any aot_call that needed a program: hit or
        compile)."""
        with self._lock:
            out: dict = dict(self._counts)
            out["warmupOverlapSeconds"] = round(self._warmup_overlap_s, 3)
            out["programsCompiledByName"] = dict(self._compiled_by_name)
            out["fusedFallbackReasons"] = dict(self._fused_fallback_reasons)
        out["compileCacheHitRate"] = _hit_rate(out)
        return out

    def reset(self) -> None:
        with self._lock:
            self._reset_counts()
            self._warmup_overlap_s = 0.0
            self._compiled_by_name = {}
            self._fused_fallback_reasons = {}


def _hit_rate(counts: dict) -> float | None:
    hits = counts["cacheHitsMemory"] + counts["cacheHitsDisk"]
    return _tm.ratio(hits, hits + counts["programsCompiled"])


_STATS = CompileStats()
_tm.REGISTRY.register_source("compile", _STATS.snapshot)


def stats() -> CompileStats:
    return _STATS


def snapshot() -> dict:
    return _STATS.snapshot()


def delta(before: dict) -> dict:
    """Per-phase view: current snapshot minus a ``snapshot()`` taken
    earlier (rates recomputed from the deltas, not differenced)."""
    now = _STATS.snapshot()
    out: dict = _tm.counter_delta(now, before, _COUNTER_KEYS)
    out["warmupOverlapSeconds"] = _tm.float_delta(
        now, before, "warmupOverlapSeconds"
    )
    out["programsCompiledByName"] = _tm.named_delta(
        now["programsCompiledByName"],
        before.get("programsCompiledByName", {}),
    )
    out["fusedFallbackReasons"] = _tm.named_delta(
        now["fusedFallbackReasons"],
        before.get("fusedFallbackReasons", {}),
    )
    out["compileCacheHitRate"] = _hit_rate(out)
    return out
