"""Shape buckets for batched candidate sweeps (cross-candidate dedup).

A GLM sweep's compiled program is keyed by its LANE COUNT K (folds ×
same-static grid points + refit lanes): a 24-lane and a 28-lane sweep are
different XLA programs even though every lane runs identical math. On the
tunneled chip each extra program costs seconds of acquisition, so near-miss
lane counts are padded up to a small set of buckets — the padded sweep
replays lane 0 in the inert lanes and the caller slices the real lanes
back out.

Lanes in the batched GLM solvers are independent GEMM columns, so padding
changes no real lane's math; any residual difference is at the level of
XLA's per-shape GEMM tiling (measured bit-identical on XLA:CPU, documented
as <=1e-6 relative tolerance in docs/tpu.md for other backends). Tree
sweeps do NOT bucket: split decisions are discrete, and a reassociated
histogram sum can flip a borderline split — there the lane count already
equals the static-group size, which the dedup ledger records instead.

Buckets: powers of two up to 64, then multiples of 32 (<=2x compute
blowup, bounded program count). ``TPTPU_LANE_BUCKETS=0`` disables padding.
"""
from __future__ import annotations

import os

import numpy as np

_POW2_CAP = 64
_STEP = 32


def enabled() -> bool:
    return os.environ.get("TPTPU_LANE_BUCKETS", "1") != "0"


def lane_bucket(k: int) -> int:
    """Smallest bucket >= k (identity when padding is disabled or k<=1)."""
    if k <= 1 or not enabled():
        return k
    if k <= _POW2_CAP:
        b = 1
        while b < k:
            b *= 2
        return b
    return -(-k // _STEP) * _STEP


def mesh_lane_bucket(k: int, multiple: int = 1) -> int:
    """Smallest lane bucket >= k that ``multiple`` divides evenly — the
    sharded sweep's variant of :func:`lane_bucket`: lanes shard over the
    mesh's model axis, so the padded lane count must split into equal
    per-device blocks. With padding disabled the bucket degrades to the
    plain ceiling multiple (divisibility is a correctness requirement of
    the sharded dispatch, not an optimization)."""
    multiple = max(1, int(multiple))
    b = max(lane_bucket(k), multiple)
    while b % multiple:
        nb = lane_bucket(b + 1)
        b = nb if nb > b else b + 1
    return b


def bucket_sweep_lanes(
    *arrays: np.ndarray, multiple: int = 1
) -> tuple[int, tuple]:
    """The whole per-sweep sequence in one place (shared by the logistic
    and linear batched-masks sweeps, so the pad/record semantics cannot
    drift between them): bucket the lane count of axis 0 (rounded up to
    ``multiple`` when the lanes shard over a model axis of that size),
    pad every array onto it by replicating lane 0, and record
    (lanes, padded) in the compileStats ledger. Returns
    ``(k, padded_arrays)`` — callers slice program outputs back with
    ``[:k]``."""
    from . import stats

    arrays = tuple(np.asarray(a) for a in arrays)
    k = arrays[0].shape[0]
    bucket = (
        mesh_lane_bucket(k, multiple) if multiple > 1 else lane_bucket(k)
    )
    stats.stats().record_sweep(lanes=k, padded=max(0, bucket - k))
    return k, pad_lane_arrays(bucket, *arrays)


def pad_lane_arrays(bucket: int, *arrays: np.ndarray) -> tuple:
    """Pad each array's axis 0 from K to ``bucket`` by replicating entry 0
    (a real lane, so the padded program computes nothing undefined).
    Returns the arrays unchanged when no padding is needed."""
    if not arrays:
        return arrays
    k = arrays[0].shape[0]
    if bucket <= k:
        return arrays
    out = []
    for a in arrays:
        reps = np.repeat(a[:1], bucket - k, axis=0)
        out.append(np.concatenate([a, reps], axis=0))
    return tuple(out)
