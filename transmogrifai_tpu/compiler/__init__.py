"""Compile plane — the shared compilation-and-dispatch subsystem.

Program ACQUISITION (tracing, XLA compilation, executable loading) is the
wall-clock cost of small-data training on the tunneled chip (BASELINE.md):
a fresh process paid 5.0-6.7 s where the steady state runs 2.8 s. This
package is the one place that cost is managed:

* :mod:`.stats` — the ``compileStats`` ledger (programs compiled / cache
  hits / dedup hits / warmup overlap), surfaced in the selector summary,
  ``summary_pretty()``, ``score_fn.metadata()``, and the bench JSON;
* :mod:`.warmup` — async background warmup: ``Workflow.train`` and the
  serving closure start a thread that loads the banked executables the
  traced DAG will actually need, overlapping acquisition with host-side
  ingest/prep instead of serializing it;
* :mod:`.bucketing` — cross-candidate lane buckets: GLM sweeps that differ
  only in lane COUNT pad onto a small set of shape buckets so near-miss
  sweeps reuse one executable;
* :mod:`.dispatch` — donated-buffer dispatch (backend-aware ``jit`` twins
  with ``donate_argnums``) and the transfer-prefetch seam that overlaps
  device uploads for layer k+1 with layer k's host work;
* :mod:`.fused` — the fused end-to-end scoring graph: the fitted serving
  plan (member vectorizers + plane assembly + feature removal + model
  predict) compiled into ONE donated, bucketed XLA dispatch per
  steady-state batch, with a counted staged-loop fallback (TPX008). See
  docs/tpu.md "The fused scoring graph".

The persistent on-disk program cache itself lives in ``utils/aot.py``
(``aot_call`` / ``prewarm``); every model family and the serving path route
through it, and it reports here. See docs/tpu.md for cache location,
``TPTPU_COMPILE_CACHE`` override, and invalidation rules.
"""
from __future__ import annotations

# NOTE: `compiler.stats` must stay the SUBMODULE (call sites do
# `from ..compiler import stats as cstats; cstats.stats()`), so the
# module-level accessor function is re-exported as `get_stats` only.
from . import stats  # noqa: F401
from .stats import CompileStats, delta, snapshot  # noqa: F401
from .stats import stats as get_stats  # noqa: F401
