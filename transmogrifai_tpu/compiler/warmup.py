"""Async background warmup of the persistent program bank.

``Workflow.train`` and the serving closure know — before any data is read —
which model families the traced DAG will exercise, and therefore which
banked executables the run will need. Warmup starts a daemon thread that
loads exactly those (``utils.aot.prewarm(names=...)``) while the main
thread runs host-side ingest/feature prep, so program acquisition overlaps
work instead of serializing in front of the first fit dispatch (the cold
5.0-6.7 s vs steady 2.8 s gap of BENCH_r05).

One warmup runs per (scope, names) per process; repeats are free no-ops.
The loaded-program count and overlapped seconds land in the
``compileStats`` ledger (``warmupPrograms`` / ``warmupOverlapSeconds``).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Iterable

from . import stats as _stats

log = logging.getLogger(__name__)

_LOCK = threading.Lock()
_STARTED: dict[tuple, threading.Thread] = {}

#: programs the serving path dispatches (tree predicts bin + traverse on
#: device above the host-predict cutoff; serve_trees is the Pallas
#: multi-tree traversal kernel of models/serve_pallas.py; stack_lane
#: materializes a sweep winner's lane; fused_serve* are the end-to-end
#: fused scoring graphs of compiler/fused.py — banked per structural
#: fingerprint)
SCORE_PROGRAMS = frozenset(
    {
        "predict_boosted", "predict_forest", "bin_data", "stack_lane",
        "serve_trees", "fused_serve", "fused_serve_explain",
    }
)

_TREE_PROGRAMS = frozenset(
    {
        "bin_data", "boost_chunk", "forest_scan", "sweep_boost_outputs",
        "sweep_forest_outputs", "stack_lane", "predict_boosted",
        "predict_forest",
    }
)

#: estimator class name -> banked program names its fit/predict path routes
#: through ``aot_call``. Families absent here (GLM/IRLS, NaiveBayes, SVC,
#: MLP) compile through the plain jit cache and bank nothing.
_FAMILY_PROGRAMS: dict[str, frozenset] = {
    "LogisticRegression": frozenset({"logistic_binary_batched"}),
    "LinearRegression": frozenset({"linear_batched"}),
    "XGBoostClassifier": _TREE_PROGRAMS,
    "XGBoostRegressor": _TREE_PROGRAMS,
    "GBTClassifier": _TREE_PROGRAMS,
    "GBTRegressor": _TREE_PROGRAMS,
    "RandomForestClassifier": _TREE_PROGRAMS,
    "RandomForestRegressor": _TREE_PROGRAMS,
    "DecisionTreeClassifier": _TREE_PROGRAMS,
    "DecisionTreeRegressor": _TREE_PROGRAMS,
    "OpWord2Vec": frozenset({"sgns_scan2"}),
    "OpLDA": frozenset({"lda_scan"}),
}


def train_programs(stages: Iterable) -> set[str] | None:
    """Banked-program names the given DAG stages will need, or ``None``
    (= warm everything) when an unmapped model family is present."""
    names: set[str] = set()
    unknown_family = False
    for stage in stages:
        cls = type(stage).__name__
        if cls == "ModelSelector":
            for est, _grid in getattr(stage, "models", []):
                fam = _FAMILY_PROGRAMS.get(type(est).__name__)
                if fam is None:
                    unknown_family = True
                else:
                    names.update(fam)
            # the winner's standalone scoring program is banked too
            names.update(SCORE_PROGRAMS)
        else:
            names.update(_FAMILY_PROGRAMS.get(cls, ()))
    if unknown_family:
        return None
    return names


def start_warmup(
    names: set[str] | frozenset | None = None, scope: str = "train"
) -> threading.Thread | None:
    """Kick the background bank load (once per (scope, names) per process
    — a later train over DIFFERENT model families warms again; loading is
    idempotent, already-resident programs are skipped by ``_MEM``);
    returns the thread (callers/tests may join) or None when this exact
    warmup already ran or the bank is disabled."""
    from ..utils import aot

    if not aot._enabled():
        return None
    key = (scope, None if names is None else tuple(sorted(names)))
    with _LOCK:
        if key in _STARTED:
            return None
        th = threading.Thread(
            target=_run, args=(names,), daemon=True,
            name=f"tptpu-warmup-{scope}",
        )
        _STARTED[key] = th
    th.start()
    return th


def _run(names) -> None:
    from ..telemetry import events as _tevents
    from ..telemetry import spans as _tspans
    from ..utils import aot

    t0 = time.monotonic()
    try:
        with _tspans.span(
            "compile/warmup", programs=-1 if names is None else len(names)
        ):
            n = aot.prewarm(names=names)
    except Exception as e:  # warmup must never take a train down
        log.info("warmup failed: %s", e)
        return
    overlap = time.monotonic() - t0
    _stats.stats().record_warmup(n, overlap)
    _tevents.emit(
        "warmup_complete", programs=n, overlapSeconds=round(overlap, 3)
    )


def join_warmup(timeout: float | None = None) -> bool:
    """Block until every started warmup thread finishes loading (the
    standing service's ``start(wait_warmup=True)`` — a service that wants
    its first batch warm, not overlapped). Returns False when a thread is
    still alive after ``timeout`` seconds."""
    with _LOCK:
        threads = list(_STARTED.values())
    deadline = None if timeout is None else time.monotonic() + timeout
    ok = True
    for th in threads:
        left = (
            None if deadline is None
            else max(0.0, deadline - time.monotonic())
        )
        th.join(timeout=left)
        ok = ok and not th.is_alive()
    return ok


def reset_for_tests() -> None:
    """Forget started scopes so a test can exercise warmup repeatedly."""
    with _LOCK:
        _STARTED.clear()
