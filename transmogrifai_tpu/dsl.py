"""dsl — the user-facing feature-transformation vocabulary.

Reference: core/.../dsl/Rich{Numeric,Text,Date,List,Map,Set,Vector}Feature
.scala + RichFeaturesCollection.scala — implicit enrichments that give
features methods like ``tokenize``, ``vectorize``, ``sanityCheck`` and
arithmetic operators. Python equivalent: importing this module attaches the
same vocabulary onto ``Feature`` (done once at package import), so

    pred = (f1 + f2).z_normalize()
    toks = text.tokenize()
    vec  = toks.tf_idf(num_terms=512)

mirror the Scala one-liners.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

from .features.feature import Feature
from .ops import math as _math
from .ops import phone as _phone
from .ops import simple as _simple
from .ops.bucketizers import (
    DecisionTreeNumericBucketizer,
    NumericBucketizer,
)
from .ops.domains import EmailToPickListTransformer, UrlMapToPickListMapTransformer
from .ops.embeddings import OpLDA, OpWord2Vec
from .ops.scalers import (
    FillMissingWithMean,
    OpScalarStandardScaler,
    PercentileCalibrator,
    ScalerTransformer,
    DescalerTransformer,
)
from .ops.dates import DateToUnitCircleTransformer
from .ops.text_stages import (
    JaccardSimilarity,
    LangDetector,
    MimeTypeDetector,
    MimeTypeMapDetector,
    NameEntityRecognizer,
    NGramSimilarity,
    OpCountVectorizer,
    OpHashingTF,
    OpIDF,
    OpNGram,
    OpStopWordsRemover,
    OpStringIndexer,
    TextTokenizer,
    ValidEmailTransformer,
)
from .ops.time_period import (
    TimePeriodListTransformer,
    TimePeriodMapTransformer,
    TimePeriodTransformer,
)


def _unary(stage_factory: Callable[..., Any]) -> Callable[..., Feature]:
    def method(self: Feature, *args: Any, **kwargs: Any) -> Feature:
        return self.transform_with(stage_factory(*args, **kwargs))

    return method


def _binary(stage_factory: Callable[..., Any]) -> Callable[..., Feature]:
    def method(self: Feature, other: Feature, *args: Any, **kwargs: Any) -> Feature:
        return self.transform_with(stage_factory(*args, **kwargs), other)

    return method


def _scalar_or_feature(
    feature_cls: type, scalar_cls: type
) -> Callable[..., Feature]:
    def method(self: Feature, other: Any) -> Feature:
        if isinstance(other, Feature):
            return self.transform_with(feature_cls(), other)
        return self.transform_with(scalar_cls(float(other)))

    return method


# ---------------------------------------------------------------- numeric dsl
# RichNumericFeature.scala: +, -, *, / with feature or scalar operands
Feature.__add__ = _scalar_or_feature(_math.AddTransformer, _math.ScalarAddTransformer)
Feature.__sub__ = _scalar_or_feature(
    _math.SubtractTransformer, _math.ScalarSubtractTransformer
)
Feature.__mul__ = _scalar_or_feature(
    _math.MultiplyTransformer, _math.ScalarMultiplyTransformer
)
Feature.__truediv__ = _scalar_or_feature(
    _math.DivideTransformer, _math.ScalarDivideTransformer
)
Feature.abs = _unary(_math.AbsoluteValueTransformer)
Feature.ceil = _unary(_math.CeilTransformer)
Feature.floor = _unary(_math.FloorTransformer)
Feature.round = _unary(_math.RoundTransformer)
Feature.round_digits = _unary(_math.RoundDigitsTransformer)
Feature.exp = _unary(_math.ExpTransformer)
Feature.sqrt = _unary(_math.SqrtTransformer)
Feature.log = _unary(_math.LogTransformer)
Feature.power = _unary(_math.PowerTransformer)
Feature.z_normalize = _unary(OpScalarStandardScaler)
Feature.fill_missing_with_mean = _unary(FillMissingWithMean)
Feature.bucketize = _unary(NumericBucketizer)
Feature.scale = _unary(ScalerTransformer)
Feature.descale = _binary(DescalerTransformer)
Feature.calibrate_percentile = _unary(PercentileCalibrator)


def _auto_bucketize(
    self: Feature, label: Feature, **kwargs: Any
) -> Feature:
    """Supervised decision-tree binning (RichNumericFeature.autoBucketize;
    numeric MAPS route to the per-key variant, RichMapFeature
    .autoBucketize)."""
    from . import types as _T
    from .ops.maps import DecisionTreeNumericMapBucketizer

    cls = (
        DecisionTreeNumericMapBucketizer
        if _T.is_subtype(self.ftype, _T.OPMap)
        else DecisionTreeNumericBucketizer
    )
    return label.transform_with(cls(**kwargs), self)


Feature.auto_bucketize = _auto_bucketize

# ------------------------------------------------------------------- text dsl
# RichTextFeature.scala
Feature.tokenize = _unary(TextTokenizer)
Feature.ngram = _unary(OpNGram)
Feature.remove_stop_words = _unary(OpStopWordsRemover)
Feature.tf = _unary(OpHashingTF)
Feature.count_vectorize = _unary(OpCountVectorizer)
Feature.idf = _unary(OpIDF)
Feature.string_indexed = _unary(OpStringIndexer)
Feature.detect_languages = _unary(LangDetector)
Feature.detect_mime_types = _unary(MimeTypeDetector)
Feature.detect_mime_types_map = _unary(MimeTypeMapDetector)
Feature.is_valid_email = _unary(ValidEmailTransformer)
Feature.email_to_pick_list = _unary(EmailToPickListTransformer)
Feature.url_map_to_pick_list_map = _unary(UrlMapToPickListMapTransformer)
Feature.recognize_entities = _unary(NameEntityRecognizer)
Feature.word2vec = _unary(OpWord2Vec)
Feature.lda = _unary(OpLDA)
Feature.jaccard_similarity = _binary(JaccardSimilarity)
Feature.ngram_similarity = _binary(NGramSimilarity)


def _tf_idf(self: Feature, num_terms: int = 512) -> Feature:
    """tokenized text → hashed TF → IDF (RichTextFeature.tfidf)."""
    return self.transform_with(OpHashingTF(num_features=num_terms)).transform_with(
        OpIDF()
    )


Feature.tf_idf = _tf_idf

# ------------------------------------------------------------------- date dsl
Feature.to_unit_circle = _unary(DateToUnitCircleTransformer)
Feature.to_time_period = _unary(TimePeriodTransformer)
Feature.to_time_period_list = _unary(TimePeriodListTransformer)
Feature.to_time_period_map = _unary(TimePeriodMapTransformer)

# ---------------------------------------------------------------- generic dsl
Feature.alias = _unary(_simple.AliasTransformer)
Feature.filter_values = _unary(_simple.FilterTransformer)
Feature.replace_values = _unary(_simple.ReplaceTransformer)
Feature.substring_of = _binary(_simple.SubstringTransformer)
Feature.occurs = _unary(_simple.ToOccurTransformer)
Feature.exists = _unary(_simple.ExistsTransformer)
Feature.filter_map = _unary(_simple.FilterMap)


# ---------------------------------------------------------------- map dsl
# RichMapFeature.scala (1,157 LoC): per-map-type vectorize/smartVectorize
# with explicit knobs, key filtering, map-specific transforms. Here ONE
# type-directed ``vectorize`` covers every feature type (the reference's
# per-type overloads differ only in which knobs exist — unknown knobs for
# a type raise TypeError from the stage ctor), with the per-type stages
# also directly importable from ops.*.

#: vectorize() knobs that live on TransmogrifierDefaults rather than the
#: stage ctor (RichMapFeature's topK/minSupport/cleanText/cleanKeys/...)
_DEFAULTS_KNOBS = {
    "top_k": "TopK",
    "min_support": "MinSupport",
    "clean_text": "CleanText",
    "clean_keys": "CleanKeys",
    "track_nulls": "TrackNulls",
    "num_hashes": "DefaultNumOfFeatures",
    "max_cardinality": "MaxCategoricalCardinality",
    "coverage_pct": "CoveragePct",
    "fill_with_mean": "FillWithMean",
    "fill_with_mode": "FillWithMode",
    "fill_value": "FillValue",
    "binary_freq": "BinaryFreq",
    "reference_date_ms": "ReferenceDateMs",
}


def _vectorize_feature(self: Feature, **kwargs: Any) -> Feature:
    """Type-directed single-feature vectorization with explicit knobs —
    ``realMap.vectorize(top_k=5, allow_keys=["a"])`` etc.
    (RichMapFeature.vectorize and the scalar Rich*Feature.vectorize
    overloads). Knobs shared with TransmogrifierDefaults override the
    defaults; any remaining keyword goes to the type's vectorizer ctor
    (e.g. ``default_region`` for phones); unknown knobs raise."""
    import dataclasses

    from .ops.defaults import DEFAULTS
    from .ops.transmogrify import _vectorizer_for

    allow = kwargs.pop("allow_keys", None)
    block = kwargs.pop("block_keys", None)
    d = DEFAULTS
    defaults_knobs = {
        k: kwargs.pop(k) for k in list(kwargs) if k in _DEFAULTS_KNOBS
    }
    if defaults_knobs:
        d = dataclasses.replace(
            d, **{_DEFAULTS_KNOBS[k]: v for k, v in defaults_knobs.items()}
        )
    src = self
    if allow or block:
        # RichMapFeature.filter(allowList, blockList) folded in
        src = src.transform_with(
            _simple.FilterMap(allow_keys=allow or (), block_keys=block or ())
        )
    stage = _vectorizer_for(src.ftype, d)
    # a defaults knob the chosen vectorizer never reads is a typo or a
    # wrong-type knob — silently accepting it would let the user believe
    # it took effect (the reference's per-type overloads reject it at
    # compile time)
    params = stage.get_params()
    _ALIASES = {
        "fill_with_mean": ("fill", "fill_with_mean"),
        "fill_with_mode": ("fill", "fill_with_mode"),
        "num_hashes": ("num_hashes", "num_terms", "num_features"),
        "binary_freq": ("binary_freq", "binary"),
    }
    for k in defaults_knobs:
        accepted = _ALIASES.get(k, (k,))
        if not any(a in params for a in accepted):
            raise TypeError(
                f"{type(stage).__name__} (for {src.ftype.__name__}) does "
                f"not take vectorize knob {k!r}"
            )
    if kwargs:  # stage-specific extras beyond the shared defaults
        stage = type(stage)(**{**params, **kwargs})
    return src.transform_with(stage)


Feature.vectorize = _vectorize_feature
#: smartVectorize is the text/text-map vectorize (the dispatch already
#: routes Text/TextArea/TextMap/TextAreaMap to the Smart* stages)
Feature.smart_vectorize = _vectorize_feature


def _map_keys_filtered(
    self: Feature,
    allow_keys: Sequence[str] = (),
    block_keys: Sequence[str] = (),
) -> Feature:
    """RichMapFeature.filter(allowList, blockList)."""
    return self.transform_with(
        _simple.FilterMap(allow_keys=allow_keys, block_keys=block_keys)
    )


Feature.filter_keys = _map_keys_filtered
Feature.is_valid_phone_map = _unary(_phone.IsValidPhoneMapDefaultCountry)
Feature.parse_phone = _unary(_phone.ParsePhoneDefaultCountry)
Feature.is_valid_phone = _unary(_phone.IsValidPhoneDefaultCountry)


def _prediction_field(key: str):
    """Prediction map accessors (RichMapFeature.scala:1118-1152):
    pred.prediction_value() → RealNN; probability()/raw_prediction() →
    OPVector (the output type comes from PredictionFieldExtractor)."""
    def method(self: Feature) -> Feature:
        from .ops.prediction import PredictionFieldExtractor

        return self.transform_with(PredictionFieldExtractor(field=key))

    return method


Feature.prediction_value = _prediction_field("prediction")
Feature.probability_vector = _prediction_field("probability")
Feature.raw_prediction_vector = _prediction_field("rawPrediction")


def _tupled(self: Feature) -> tuple[Feature, Feature, Feature]:
    """pred.tupled() → (prediction RealNN, rawPrediction OPVector,
    probability OPVector) — RichMapFeature.scala:1118."""
    return (
        self.prediction_value(),
        self.raw_prediction_vector(),
        self.probability_vector(),
    )


Feature.tupled = _tupled


def _vectorize_collection(features: Sequence[Feature], **kwargs: Any) -> Feature:
    """RichFeaturesCollection.transmogrify on a plain list."""
    from .ops import transmogrify

    return transmogrify(list(features), **kwargs)


def _sanity_check(
    self: Feature, feature_vector: Feature, **kwargs: Any
) -> Feature:
    """label.sanity_check(vector) (RichNumericFeature.scala:469)."""
    from .prep import SanityChecker

    return self.transform_with(SanityChecker(**kwargs), feature_vector)


Feature.sanity_check = _sanity_check

transmogrify_features = _vectorize_collection
