"""DAG fitting engine — fit estimators layer by layer, transform through.

Reference: core/.../utils/stages/FitStagesUtil.scala:212-290
(fitAndTransformDAG / fitAndTransformLayer): per layer, fit every estimator
on the current dataset, then apply all of the layer's (fitted) transformers.
The reference bulk-applies row-level transformers in one RDD map; here a
layer's transforms append columns to the columnar Dataset (the numeric plane
stays in arrays; XLA fusion happens in the compiled scoring path).

Fault tolerance (resilience/): when a ``CheckpointManager`` is supplied,
every completed layer's fitted stages are persisted atomically, so a killed
run resumes via the ``prefitted`` warm-start seam instead of refitting the
whole DAG. An installed ``FaultPlan`` gets a hook before each estimator
fit, after each transform, and at each layer boundary.
"""
from __future__ import annotations

from typing import Iterable

from ..dataset import Dataset
from ..features.feature import Feature
from ..resilience import distributed, faults
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from .dag import compute_dag


def fit_and_transform_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    prefitted: dict[str, PipelineStage] | None = None,
    checkpoint=None,
) -> tuple[Dataset, dict[str, PipelineStage]]:
    """Fit the whole DAG; returns (transformed dataset, fitted stage by
    original-stage uid). Fitted models replace their estimators keyed by the
    estimator uid (FitStagesUtil.scala:251-290). ``prefitted`` supplies
    already-fitted models by estimator uid — those estimators are skipped
    (warm start, OpWorkflow.withModelStages OpWorkflow.scala:468-472).
    ``checkpoint`` (a resilience.CheckpointManager) persists each completed
    layer's fitted estimators so an interrupted run can resume."""
    layers = compute_dag(list(result_features))
    fitted: dict[str, PipelineStage] = {}
    prefitted = prefitted or {}
    plan = faults.active()
    signature = None
    if checkpoint is not None:
        from ..resilience.checkpoint import dag_signature, dataset_fingerprint

        signature = dag_signature(layers, dataset_fingerprint(dataset))
    for li, layer in enumerate(layers):
        transformers: list[Transformer] = []
        newly_fitted = False
        for stage in layer:
            if stage.uid in prefitted:
                model = prefitted[stage.uid]
                assert isinstance(model, Transformer)
                fitted[stage.uid] = model
                transformers.append(model)
            elif isinstance(stage, Estimator):
                if plan is not None:
                    plan.on_stage_fit(stage)
                model = stage.fit(dataset)
                fitted[stage.uid] = model
                transformers.append(model)
                newly_fitted = True
            elif isinstance(stage, Transformer):
                fitted[stage.uid] = stage
                transformers.append(stage)
            else:
                raise TypeError(f"Cannot fit {stage}")
        for t in transformers:
            dataset = t.transform(dataset)
            if plan is not None:
                corrupted = plan.on_stage_output(t, dataset[t.output_name])
                if corrupted is not None:
                    dataset = dataset.with_column(t.output_name, corrupted)
        if checkpoint is not None and (
            newly_fitted or not checkpoint.has_layer(li)
        ):
            from ..parallel.mesh import execution_mesh

            # resume skips re-serializing layers restored intact from disk
            # (large fitted arrays make that pure wasted compression/IO)
            checkpoint.save_layer(
                li,
                signature,
                [
                    (pos, s.uid, fitted[s.uid])
                    for pos, s in enumerate(layer)
                    if isinstance(fitted[s.uid], Model)
                ],
                mesh_info=distributed.mesh_fingerprint(execution_mesh()),
            )
        if plan is not None:
            plan.on_layer_end(li)
        # heartbeat pulse at the layer boundary: the checkpoint for this
        # layer is on disk, so a host declared dead here fails over with
        # zero lost work
        controller = distributed.active_controller()
        if controller is not None:
            controller.on_layer_end(li)
    return dataset, fitted


def apply_transformations_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    fitted: dict[str, PipelineStage],
) -> Dataset:
    """Scoring path: apply the fitted DAG (OpWorkflowCore.applyTransformationsDAG,
    core/.../OpWorkflowCore.scala:324)."""
    layers = compute_dag(list(result_features))
    for layer in layers:
        for stage in layer:
            t = fitted.get(stage.uid, stage)
            if isinstance(t, Estimator):
                raise ValueError(f"Stage {t} was never fitted")
            assert isinstance(t, Transformer)
            dataset = t.transform(dataset)
    return dataset
