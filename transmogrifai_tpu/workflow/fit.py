"""DAG fitting engine — fit estimators layer by layer, transform through.

Reference: core/.../utils/stages/FitStagesUtil.scala:212-290
(fitAndTransformDAG / fitAndTransformLayer): per layer, fit every estimator
on the current dataset, then apply all of the layer's (fitted) transformers.
The reference bulk-applies row-level transformers in one RDD map; here a
layer's transforms append columns to the columnar Dataset (the numeric plane
stays in arrays; XLA fusion happens in the compiled scoring path).
"""
from __future__ import annotations

from typing import Iterable

from ..dataset import Dataset
from ..features.feature import Feature
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from .dag import compute_dag


def fit_and_transform_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    prefitted: dict[str, PipelineStage] | None = None,
) -> tuple[Dataset, dict[str, PipelineStage]]:
    """Fit the whole DAG; returns (transformed dataset, fitted stage by
    original-stage uid). Fitted models replace their estimators keyed by the
    estimator uid (FitStagesUtil.scala:251-290). ``prefitted`` supplies
    already-fitted models by estimator uid — those estimators are skipped
    (warm start, OpWorkflow.withModelStages OpWorkflow.scala:468-472)."""
    layers = compute_dag(list(result_features))
    fitted: dict[str, PipelineStage] = {}
    prefitted = prefitted or {}
    for layer in layers:
        transformers: list[Transformer] = []
        for stage in layer:
            if stage.uid in prefitted:
                model = prefitted[stage.uid]
                assert isinstance(model, Transformer)
                fitted[stage.uid] = model
                transformers.append(model)
            elif isinstance(stage, Estimator):
                model = stage.fit(dataset)
                fitted[stage.uid] = model
                transformers.append(model)
            elif isinstance(stage, Transformer):
                fitted[stage.uid] = stage
                transformers.append(stage)
            else:
                raise TypeError(f"Cannot fit {stage}")
        for t in transformers:
            dataset = t.transform(dataset)
    return dataset, fitted


def apply_transformations_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    fitted: dict[str, PipelineStage],
) -> Dataset:
    """Scoring path: apply the fitted DAG (OpWorkflowCore.applyTransformationsDAG,
    core/.../OpWorkflowCore.scala:324)."""
    layers = compute_dag(list(result_features))
    for layer in layers:
        for stage in layer:
            t = fitted.get(stage.uid, stage)
            if isinstance(t, Estimator):
                raise ValueError(f"Stage {t} was never fitted")
            assert isinstance(t, Transformer)
            dataset = t.transform(dataset)
    return dataset
