"""DAG fitting engine — fit estimators layer by layer, transform through.

Reference: core/.../utils/stages/FitStagesUtil.scala:212-290
(fitAndTransformDAG / fitAndTransformLayer): per layer, fit every estimator
on the current dataset, then apply all of the layer's (fitted) transformers.
The reference bulk-applies row-level transformers in one RDD map; here a
layer's transforms append columns to the columnar Dataset (the numeric plane
stays in arrays; XLA fusion happens in the compiled scoring path).

Fault tolerance (resilience/): when a ``CheckpointManager`` is supplied,
every completed layer's fitted stages are persisted atomically, so a killed
run resumes via the ``prefitted`` warm-start seam instead of refitting the
whole DAG. An installed ``FaultPlan`` gets a hook before each estimator
fit, after each transform, and at each layer boundary.
"""
from __future__ import annotations

from typing import Iterable

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..resilience import distributed, faults
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from ..telemetry import runlog as _runlog
from ..telemetry import spans as _tspans
from .dag import compute_dag


def fit_and_transform_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    prefitted: dict[str, PipelineStage] | None = None,
    checkpoint=None,
) -> tuple[Dataset, dict[str, PipelineStage]]:
    """Fit the whole DAG; returns (transformed dataset, fitted stage by
    original-stage uid). Fitted models replace their estimators keyed by the
    estimator uid (FitStagesUtil.scala:251-290). ``prefitted`` supplies
    already-fitted models by estimator uid — those estimators are skipped
    (warm start, OpWorkflow.withModelStages OpWorkflow.scala:468-472).
    ``checkpoint`` (a resilience.CheckpointManager) persists each completed
    layer's fitted estimators so an interrupted run can resume."""
    layers = compute_dag(list(result_features))
    fitted: dict[str, PipelineStage] = {}
    prefitted = prefitted or {}
    from ..compiler import dispatch as _dispatch

    plan = faults.active()
    signature = None
    if checkpoint is not None:
        from ..resilience.checkpoint import dag_signature, dataset_fingerprint

        signature = dag_signature(layers, dataset_fingerprint(dataset))
    dataset_box = [dataset]
    try:
        _fit_layers(
            layers, dataset_box, fitted, prefitted, plan, checkpoint,
            signature,
        )
    finally:
        # release the prefetched device buffers: the last layer's fits
        # consumed them, and keeping them would pin training matrices in
        # device memory for the process lifetime
        _dispatch.clear_prefetch()
    return dataset_box[0], fitted


def _fit_layers(
    layers, dataset_box, fitted, prefitted, plan, checkpoint, signature
) -> None:
    """The layer loop of fit_and_transform_dag (split out so the caller
    can bound the prefetch-buffer lifetime with one try/finally).
    ``dataset_box`` is a 1-element list carrying the evolving dataset."""
    dataset = dataset_box[0]
    # run-ledger pulses (telemetry/runlog.py): layer boundaries feed the
    # flight recorder's per-layer timings, device-memory polls, and the
    # seconds-per-layer EWMA behind the live train(progress=...) ETA
    recorder = _runlog.active_recorder()
    for li, layer in enumerate(layers):
        if recorder is not None:
            recorder.on_layer_start(li, total=len(layers))
        # telemetry: one span per DAG layer, child spans per estimator fit
        # and per transform — the layer/stage hierarchy in the Chrome trace
        with _tspans.span("train/layer", index=li, stages=len(layer)):
            dataset = _fit_one_layer(
                li, layer, dataset, fitted, prefitted, plan, checkpoint,
                signature, layers,
            )
        if recorder is not None:
            recorder.on_layer_end(
                li, total=len(layers), stages=len(layer),
                rows=dataset.num_rows,
            )
    dataset_box[0] = dataset


def _fit_one_layer(
    li, layer, dataset, fitted, prefitted, plan, checkpoint, signature,
    layers,
) -> Dataset:
    """One DAG layer: fit estimators, apply transforms, prefetch the next
    layer's inputs, checkpoint, heartbeat. Returns the evolved dataset."""
    transformers: list[Transformer] = []
    newly_fitted = False
    for stage in layer:
        if stage.uid in prefitted:
            model = prefitted[stage.uid]
            assert isinstance(model, Transformer)
            fitted[stage.uid] = model
            transformers.append(model)
        elif isinstance(stage, Estimator):
            if plan is not None:
                plan.on_stage_fit(stage)
            with _tspans.span("train/fit", stage=type(stage).__name__):
                model = stage.fit(dataset)
            fitted[stage.uid] = model
            transformers.append(model)
            newly_fitted = True
        elif isinstance(stage, Transformer):
            fitted[stage.uid] = stage
            transformers.append(stage)
        else:
            raise TypeError(f"Cannot fit {stage}")
    for t in transformers:
        with _tspans.span("train/transform", stage=type(t).__name__):
            dataset = t.transform(dataset)
        if plan is not None:
            corrupted = plan.on_stage_output(t, dataset[t.output_name])
            if corrupted is not None:
                dataset = dataset.with_column(t.output_name, corrupted)
            # slow-stage chaos rides the TRAIN timings too: simulated
            # extra seconds land on the flight recorder's in-flight
            # phase/layer durations (the serving path's breaker-elapsed
            # convention — no real sleep), so a seeded slow_stage plan
            # drives the cross-run regression sentinel deterministically
            extra = plan.on_stage_duration(t)
            if extra:
                recorder = _runlog.active_recorder()
                if recorder is not None:
                    recorder.add_simulated(extra)
    # pipelined layer execution (compiler.dispatch): layer li's
    # transforms just materialized the feature matrices layer li+1's
    # estimators will fit on — start their device uploads NOW so the
    # transfer overlaps the checkpoint save and remaining host work
    # instead of serializing in front of the first fit dispatch
    _prefetch_next_layer_inputs(layers, li, dataset, prefitted)
    if checkpoint is not None and (
        newly_fitted or not checkpoint.has_layer(li)
    ):
        from ..parallel.mesh import execution_mesh

        # resume skips re-serializing layers restored intact from disk
        # (large fitted arrays make that pure wasted compression/IO)
        checkpoint.save_layer(
            li,
            signature,
            [
                (pos, s.uid, fitted[s.uid])
                for pos, s in enumerate(layer)
                if isinstance(fitted[s.uid], Model)
            ],
            mesh_info=distributed.mesh_fingerprint(execution_mesh()),
        )
    if plan is not None:
        plan.on_layer_end(li)
    # heartbeat pulse at the layer boundary: the checkpoint for this
    # layer is on disk, so a host declared dead here fails over with
    # zero lost work
    controller = distributed.active_controller()
    if controller is not None:
        controller.on_layer_end(li)
    return dataset


def _prefetch_next_layer_inputs(layers, li, dataset, prefitted) -> None:
    """Start async device transfers for the 2-D (vector) inputs of the
    NEXT layer's still-unfitted estimators (model-family fits dispatch on
    exactly these matrices — logistic/linear solvers and tree binning pick
    the in-flight buffer up via ``compiler.dispatch.device_f32``). Purely
    an overlap optimization: failures are swallowed inside the dispatch
    helpers and every consumer falls back to its own upload."""
    if li + 1 >= len(layers):
        return
    from ..compiler.dispatch import prefetch_f32

    for stage in layers[li + 1]:
        if not isinstance(stage, Estimator) or stage.uid in prefitted:
            continue
        for nm in getattr(stage, "input_names", ()):
            if nm not in dataset:
                continue
            vals = getattr(dataset[nm], "values", None)
            # f32-only: consumers re-key a dtype-converted COPY, so a
            # non-f32 prefetch would upload bytes nobody ever picks up
            if (
                vals is not None
                and getattr(vals, "ndim", 0) == 2
                and getattr(vals, "dtype", None) == np.float32
            ):
                prefetch_f32(vals)


def apply_transformations_dag(
    dataset: Dataset,
    result_features: Iterable[Feature],
    fitted: dict[str, PipelineStage],
) -> Dataset:
    """Scoring path: apply the fitted DAG (OpWorkflowCore.applyTransformationsDAG,
    core/.../OpWorkflowCore.scala:324)."""
    layers = compute_dag(list(result_features))
    for layer in layers:
        for stage in layer:
            t = fitted.get(stage.uid, stage)
            if isinstance(t, Estimator):
                raise ValueError(f"Stage {t} was never fitted")
            assert isinstance(t, Transformer)
            dataset = t.transform(dataset)
    return dataset
