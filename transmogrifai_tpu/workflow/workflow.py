"""Workflow + WorkflowModel: result-feature-driven training and scoring.

Reference: core/.../OpWorkflow.scala (train :347, DAG assembly :90-110,
validation :280-338) and core/.../OpWorkflowModel.scala (score :259,
summary :187-223).

The user declares result features; the workflow reconstructs the stage DAG
from lineage, materializes raw data through a reader, reserves a holdout via
the model selector's splitter (OpWorkflow.scala:380-384), fits the DAG layer
by layer, evaluates the selected model on the holdout, and returns a fitted
WorkflowModel that can score/evaluate/summarize/save.
"""
from __future__ import annotations

import logging
from typing import Any, Sequence

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..readers.core import DataReader, DatasetReader
from ..selector.model_selector import ModelSelector, SelectedModel
from ..stages.base import Estimator, PipelineStage
from ..types.columns import NumericColumn, VectorColumn
from .dag import compute_dag, raw_features_of, validate_stages
from .fit import apply_transformations_dag, fit_and_transform_dag

log = logging.getLogger(__name__)


class Workflow:
    def __init__(self):
        self.result_features: tuple[Feature, ...] = ()
        self.reader: DataReader | None = None
        self._stage_overrides: dict[str, dict[str, Any]] = {}

    # ----------------------------------------------------------- configure
    def set_result_features(self, *features: Feature) -> "Workflow":
        self.result_features = tuple(features)
        return self

    def set_input_dataset(self, dataset: Dataset) -> "Workflow":
        self.reader = DatasetReader(dataset)
        return self

    def set_reader(self, reader: DataReader) -> "Workflow":
        self.reader = reader
        return self

    def set_stage_parameters(self, overrides: dict[str, dict[str, Any]]) -> "Workflow":
        """Per-stage param overrides keyed by stage uid or class name,
        applied reflectively before fit (OpWorkflow.setStageParameters,
        OpWorkflow.scala:179-201)."""
        self._stage_overrides.update(overrides)
        return self

    # --------------------------------------------------------------- train
    def _stages(self) -> list[PipelineStage]:
        layers = compute_dag(self.result_features)
        validate_stages(layers)
        return [s for layer in layers for s in layer]

    def _apply_overrides(self, stages: Sequence[PipelineStage]) -> None:
        for stage in stages:
            for key in (stage.uid, type(stage).__name__):
                if key in self._stage_overrides:
                    stage.set_params(**self._stage_overrides[key])

    def train(self) -> "WorkflowModel":
        if not self.result_features:
            raise ValueError("setResultFeatures must be called before train")
        if self.reader is None:
            raise ValueError("No input data: call set_input_dataset or set_reader")
        stages = self._stages()
        self._apply_overrides(stages)
        selectors = [s for s in stages if isinstance(s, ModelSelector)]
        if len(selectors) > 1:
            raise ValueError(
                "Only one ModelSelector is allowed per workflow "
                f"(found {len(selectors)})"  # FitStagesUtil.cutDAG:310 parity
            )
        selector = selectors[0] if selectors else None

        raw_features = raw_features_of(self.result_features)
        raw = self.reader.generate_dataset(raw_features)
        if raw.num_rows == 0:
            raise ValueError("Input dataset cannot be empty")
        log.info("Generated raw data: %d rows, %d features", raw.num_rows, len(raw_features))

        train_data, holdout_data = raw, None
        if selector is not None and selector.splitter is not None:
            train_idx, holdout_idx = selector.splitter.split(raw.num_rows)
            if len(holdout_idx):
                train_data = raw.take(train_idx)
                holdout_data = raw.take(holdout_idx)

        fitted_data, fitted = fit_and_transform_dag(train_data, self.result_features)

        holdout_metrics = None
        if selector is not None and holdout_data is not None:
            sel_model = fitted[selector.uid]
            assert isinstance(sel_model, SelectedModel)
            transformed = apply_transformations_dag(
                holdout_data, self.result_features, fitted
            )
            label_name, vec_name = selector.input_names
            label = transformed[label_name]
            vec = transformed[vec_name]
            assert isinstance(label, NumericColumn) and isinstance(vec, VectorColumn)
            holdout_metrics = sel_model.evaluate_holdout(
                np.asarray(vec.values, dtype=np.float32),
                label.values.astype(np.float64),
                selector.evaluator,
            )
            log.info("Holdout metrics: %s", holdout_metrics)

        return WorkflowModel(
            result_features=self.result_features,
            raw_features=tuple(raw_features),
            fitted=fitted,
            selector=selector,
            train_rows=train_data.num_rows,
            holdout_rows=0 if holdout_data is None else holdout_data.num_rows,
        )


class WorkflowModel:
    def __init__(
        self,
        result_features: tuple[Feature, ...],
        raw_features: tuple[Feature, ...],
        fitted: dict[str, PipelineStage],
        selector: ModelSelector | None,
        train_rows: int = 0,
        holdout_rows: int = 0,
    ):
        self.result_features = result_features
        self.raw_features = raw_features
        self.fitted = fitted
        self.selector = selector
        self.train_rows = train_rows
        self.holdout_rows = holdout_rows

    # --------------------------------------------------------------- score
    def _prepare_raw(self, dataset: Dataset | None, reader: DataReader | None) -> Dataset:
        if dataset is not None:
            reader = DatasetReader(self._with_missing_response(dataset))
        if reader is None:
            raise ValueError("score requires a dataset or reader")
        return reader.generate_dataset(list(self.raw_features))

    def _with_missing_response(self, dataset: Dataset) -> Dataset:
        """Scoring data often lacks the response column; synthesize zeros
        (the reference reader produces null labels at score time)."""
        for f in self.raw_features:
            if f.is_response and f.name not in dataset:
                col = NumericColumn(
                    f.ftype,
                    np.zeros(dataset.num_rows, dtype=np.float64),
                    np.ones(dataset.num_rows, dtype=bool),
                )
                dataset = dataset.with_column(f.name, col)
        return dataset

    def score(
        self,
        dataset: Dataset | None = None,
        reader: DataReader | None = None,
        keep_raw_features: bool = False,
        keep_intermediate_features: bool = False,
    ) -> Dataset:
        """Apply the fitted DAG (OpWorkflowModel.score, OpWorkflowModel.scala:259)."""
        raw = self._prepare_raw(dataset, reader)
        transformed = apply_transformations_dag(raw, self.result_features, self.fitted)
        if keep_intermediate_features:
            return transformed
        keep = [f.name for f in self.result_features if f.name in transformed]
        if keep_raw_features:
            keep = [f.name for f in self.raw_features] + keep
        return transformed.select(keep)

    def score_and_evaluate(
        self, dataset: Dataset, evaluator=None
    ) -> tuple[Dataset, dict[str, Any]]:
        scores = self.score(dataset, keep_intermediate_features=True)
        metrics = self._evaluate_transformed(scores, evaluator)
        keep = [f.name for f in self.result_features if f.name in scores]
        return scores.select(keep), metrics

    def evaluate(self, dataset: Dataset, evaluator=None) -> dict[str, Any]:
        """Score + evaluate against the true labels present in ``dataset``."""
        transformed = self.score(dataset, keep_intermediate_features=True)
        return self._evaluate_transformed(transformed, evaluator)

    def _evaluate_transformed(self, transformed: Dataset, evaluator=None) -> dict[str, Any]:
        if self.selector is None:
            raise ValueError("evaluate requires a ModelSelector in the workflow")
        evaluator = evaluator or self.selector.evaluator
        label_name = self.selector.input_names[0]
        pred_name = self.selector.output_name
        label = transformed[label_name]
        pred = transformed[pred_name]
        return evaluator.evaluate(label, pred)

    # ------------------------------------------------------------- summary
    def summary_json(self) -> dict[str, Any]:
        sel_summary = None
        if self.selector is not None:
            model = self.fitted.get(self.selector.uid)
            if isinstance(model, SelectedModel):
                sel_summary = model.summary
        stage_meta = {
            uid: s.metadata
            for uid, s in self.fitted.items()
            if s.metadata
        }
        return {
            "trainRows": self.train_rows,
            "holdoutRows": self.holdout_rows,
            "rawFeatures": [f.name for f in self.raw_features],
            "resultFeatures": [f.name for f in self.result_features],
            "modelSelectorSummary": sel_summary,
            "stageMetadata": stage_meta,
        }

    def summary_pretty(self) -> str:
        """Human-readable training summary (OpWorkflowModel.summaryPretty,
        rendered like the reference README tables)."""
        from ..utils.table import render_table

        s = self.summary_json()
        lines: list[str] = []
        sel = s.get("modelSelectorSummary")
        if sel:
            lines.append("Evaluated model candidates (CV means):")
            by_family: dict[str, list[float]] = {}
            for r in sel["validationResults"]:
                by_family.setdefault(r["modelName"], []).append(r["metricMean"])
            rows = [
                [name, str(len(vals)),
                 f"[{min(vals):.4f}, {max(vals):.4f}]"]
                for name, vals in sorted(by_family.items())
            ]
            lines.append(
                render_table(
                    ["Model", "Candidates", f"{sel['evaluationMetric']} range"], rows
                )
            )
            lines.append(f"Selected model: {sel['bestModelType']} {sel['bestGrid']}")
            for split_name, key in (
                ("Train", "trainEvaluation"),
                ("Holdout", "holdoutEvaluation"),
            ):
                m = sel.get(key)
                if m:
                    scalars = {
                        k: v for k, v in m.items() if isinstance(v, (int, float))
                    }
                    lines.append(
                        render_table(
                            ["Metric", split_name],
                            [[k, f"{v:.4f}"] for k, v in scalars.items()],
                        )
                    )
        lines.append(
            f"Trained on {s['trainRows']} rows (holdout {s['holdoutRows']}); "
            f"{len(s['rawFeatures'])} raw features"
        )
        return "\n".join(lines)
