"""Workflow + WorkflowModel: result-feature-driven training and scoring.

Reference: core/.../OpWorkflow.scala (train :347, DAG assembly :90-110,
validation :280-338) and core/.../OpWorkflowModel.scala (score :259,
summary :187-223).

The user declares result features; the workflow reconstructs the stage DAG
from lineage, materializes raw data through a reader, reserves a holdout via
the model selector's splitter (OpWorkflow.scala:380-384), fits the DAG layer
by layer, evaluates the selected model on the holdout, and returns a fitted
WorkflowModel that can score/evaluate/summarize/save.
"""
from __future__ import annotations

import logging
import os
from typing import Any, Sequence

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..readers.core import DataReader, DatasetReader
from ..selector.model_selector import ModelSelector, SelectedModel
from ..stages.base import Estimator, PipelineStage
from ..telemetry import runlog as _runlog
from ..telemetry import spans as _tspans
from ..types.columns import NumericColumn, VectorColumn
from .dag import compute_dag, raw_features_of, validate_stages
from .fit import apply_transformations_dag, fit_and_transform_dag

log = logging.getLogger(__name__)

#: one-shot latch for the summary-degradation warning (further failures
#: still count on the run ledger and the event log, just without the
#: per-call log noise)
_SUMMARY_DEGRADED_WARNED = [False]


def _report_summary_degraded(section: str, e: Exception) -> None:
    """A ``summary_pretty`` section failed to render: count it on the run
    ledger (``summaryDegraded``), land a ``summary_degraded`` event in the
    structured log, and warn ONCE per process — a broken summary section
    must be observable, not a silent debug-level swallow."""
    detail = f"{type(e).__name__}: {e}"
    try:
        from ..telemetry import events as _tevents

        _runlog.stats().bump("summaryDegraded")
        _tevents.emit("summary_degraded", section=section, error=detail)
    except Exception:  # the degradation report must not break the summary
        pass
    if not _SUMMARY_DEGRADED_WARNED[0]:
        _SUMMARY_DEGRADED_WARNED[0] = True
        log.warning(
            "summary_pretty %s section degraded (%s) — counted as "
            "summaryDegraded on the run ledger; further degradations "
            "log at debug level", section, detail,
        )
    else:
        log.debug("summary_pretty %s section skipped: %s", section, detail)


class Workflow:
    def __init__(self):
        self.result_features: tuple[Feature, ...] = ()
        self.reader: DataReader | None = None
        self._stage_overrides: dict[str, dict[str, Any]] = {}
        self._raw_feature_filter = None
        self._rff_score_reader: DataReader | None = None
        self.blocklisted_features: list[str] = []
        self._prefitted: dict[str, PipelineStage] = {}
        self._workflow_cv = False
        self._detect_sensitive = False
        self._mesh: Any = "auto"

    # ----------------------------------------------------------- configure
    def set_result_features(self, *features: Feature) -> "Workflow":
        self.result_features = tuple(features)
        return self

    def set_input_dataset(self, dataset: Dataset) -> "Workflow":
        self.reader = DatasetReader(dataset)
        return self

    def set_reader(self, reader: DataReader) -> "Workflow":
        self.reader = reader
        return self

    def set_stage_parameters(self, overrides: dict[str, dict[str, Any]]) -> "Workflow":
        """Per-stage param overrides keyed by stage uid or class name,
        applied reflectively before fit (OpWorkflow.setStageParameters,
        OpWorkflow.scala:179-201)."""
        self._stage_overrides.update(overrides)
        return self

    def with_model_stages(self, model: "WorkflowModel") -> "Workflow":
        """Warm start (OpWorkflow.withModelStages, OpWorkflow.scala:468-472):
        fitted stages from a previous model are swapped in by estimator uid,
        so only new estimators train."""
        self._prefitted.update(model.fitted)
        return self

    def with_workflow_cv(self) -> "Workflow":
        """Workflow-level cross-validation (OpWorkflow.withWorkflowCV,
        OpWorkflow.scala:403-453): label-dependent estimators upstream of the
        model selector are re-fit inside every CV fold, so their statistics
        cannot leak validation rows into candidate selection."""
        self._workflow_cv = True
        return self

    def set_parallelism(self, mesh) -> "Workflow":
        """Pin the execution mesh for train/score. Default "auto": all
        visible devices data-parallel (the reference row-partitions every
        stage by construction — FitStagesUtil.scala:96-118); on a single
        device this resolves to None and everything is plain jit. Pass None
        to force single-device execution."""
        self._mesh = mesh
        return self

    def _resolve_mesh(self):
        from ..parallel.mesh import default_execution_mesh

        return default_execution_mesh() if self._mesh == "auto" else self._mesh

    def with_sensitive_feature_detection(self) -> "Workflow":
        """Scan raw text features for personal data at train time and record
        SensitiveFeatureInformation in the model summary
        (SensitiveFeatureInformation.scala, SURVEY.md §5.5)."""
        self._detect_sensitive = True
        return self

    def with_raw_feature_filter(
        self,
        score_dataset: Dataset | None = None,
        score_reader: DataReader | None = None,
        **params: Any,
    ) -> "Workflow":
        """Attach a RawFeatureFilter (OpWorkflow.withRawFeatureFilter):
        before fitting, raw features failing fill/drift/leakage rules are
        blocklisted and the DAG is rewritten without them."""
        from ..prep.raw_feature_filter import RawFeatureFilter

        self._raw_feature_filter = RawFeatureFilter(**params)
        if score_dataset is not None:
            score_reader = DatasetReader(score_dataset)
        self._rff_score_reader = score_reader
        return self

    def _apply_blocklist(self, blocklist: list[str]) -> None:
        """DAG rewrite minus blocklisted raw features (OpWorkflow.setBlocklist,
        OpWorkflow.scala:118-167): stages lose blocklisted inputs; stages with
        no inputs left are dropped and their outputs blocklisted in turn."""
        if not blocklist:
            return
        dead = set(blocklist)
        layers = compute_dag(self.result_features)
        for layer in layers:
            for stage in layer:
                kept = tuple(
                    f for f in stage.input_features if f.name not in dead
                )
                if len(kept) == len(stage.input_features):
                    continue
                if not kept or stage.input_types is not None:
                    # variable-arity (sequence) stages shrink; fixed-arity
                    # stages cannot lose a positional input — they die and
                    # their output is blocklisted in turn
                    dead.add(stage.output_name)
                else:
                    stage.input_features = kept
        for rf in self.result_features:
            if rf.name in dead:
                raise ValueError(
                    f"RawFeatureFilter removed everything feeding result "
                    f"feature '{rf.name}'"
                )
        self.blocklisted_features = sorted(dead)

    # ----------------------------------------------------------- pre-flight
    def validate(self) -> "Report":
        """Pre-flight static analysis of the declared DAG (no data needed):
        feature-type compatibility per stage edge, response-lineage leakage
        into predictors, duplicate/orphan outputs, cycles and layer
        consistency — the eager equivalent of the reference's compile-time
        typed pipelines (analysis/preflight.py; docs/analysis.md catalogues
        the TPA codes). Returns the :class:`~transmogrifai_tpu.analysis.Report`;
        ``train()`` runs the same pass and refuses on errors."""
        from ..analysis.preflight import preflight

        return preflight(self.result_features, mode="train")

    # --------------------------------------------------------------- train
    def _stages(self, validate: bool = True) -> list[PipelineStage]:
        layers = compute_dag(self.result_features)
        if validate:
            validate_stages(layers)
        return [s for layer in layers for s in layer]

    def _apply_overrides(self, stages: Sequence[PipelineStage]) -> None:
        for stage in stages:
            for key in (stage.uid, type(stage).__name__):
                if key in self._stage_overrides:
                    stage.set_params(**self._stage_overrides[key])

    def compute_data_up_to(self, *features: Feature) -> Dataset:
        """Materialize the DAG up to the given features without running the
        full train (OpWorkflowCore.computeDataUpTo; used by the runner's
        Features run type, OpWorkflowRunner.scala:190)."""
        targets = list(features) or list(self.result_features)
        if not targets:
            raise ValueError("computeDataUpTo needs target features")
        if self.reader is None:
            raise ValueError("No input data: call set_input_dataset or set_reader")
        stages = list({s.uid: s for f in targets for s in f.parent_stages()}.values())
        self._apply_overrides(stages)
        raw = self.reader.generate_dataset(raw_features_of(targets))
        data, _ = fit_and_transform_dag(raw, targets, prefitted=self._prefitted)
        return data

    def train(
        self,
        checkpoint_dir: str | None = None,
        resume: bool = False,
        on_mesh_mismatch: str = "reshard",
        progress: Any = None,
        run_dir: str | None = None,
        stream: bool | None = None,
    ) -> "WorkflowModel":
        """Fit the DAG. With ``checkpoint_dir``, every completed layer (and
        every finished CV candidate sweep) is persisted atomically there;
        ``resume=True`` restores completed layers into the ``prefitted``
        warm-start dict so only unfinished work re-runs (docs/robustness.md).

        Checkpoints record the device topology they were written under;
        resuming on a different mesh (N→M devices, including M=1)
        reshards the saved arrays onto the current mesh by default —
        ``on_mesh_mismatch="raise"`` turns a topology change into a
        ``CheckpointMeshMismatch`` instead. Training also runs inside an
        elastic failover loop (resilience/distributed.py): a declared host
        loss (heartbeat timeout, exhausted collective retries, injected
        ``fail_host``) degrades the mesh to the surviving hosts' devices
        and re-enters the fit from the last completed layer checkpoint
        instead of aborting.

        Every train is flight-recorded (telemetry/runlog.py): per-phase
        and per-layer/fold/candidate timings, compile/featurize ledger
        deltas, the runtime host<->device transfer census, and device-
        memory high-water gauges land in a schema-versioned RunReport on
        the returned model (``model.run_report``, ``summary_json()["run"]``,
        the manifest). ``progress`` is an optional callback receiving
        phase/layer/fold pulse dicts with a live seconds-per-layer EWMA
        ETA. ``run_dir`` (default None = fall back to ``$TPTPU_RUN_DIR``;
        pass ``""`` to disable persistence even when the env var is set)
        persists the report as a ``RUN_*.json`` artifact and auto-diffs
        it against the directory's latest run, warning on TPR-coded
        regressions (``python -m transmogrifai_tpu runs --diff`` compares
        any two).

        ``stream=True`` (or automatically when the reader declares
        ``is_unbounded()``) routes ingest through the out-of-core chunked
        fit (workflow/stream.py): fit-time stats fold through streaming
        monoid aggregation chunk by chunk, the featurize pool pipelines
        chunk k+1 while chunk k reduces under a bounded in-flight window
        (``TPTPU_STREAM_INFLIGHT``), torn/corrupt chunks quarantine
        instead of folding, and with ``checkpoint_dir`` a per-chunk
        stream cursor makes a mid-ingest crash resume with < 1 chunk of
        rework. ``stream=False`` forces full materialization even for an
        unbounded reader. See docs/robustness.md "Out-of-core fit"."""
        if not self.result_features:
            raise ValueError("setResultFeatures must be called before train")
        if self.reader is None:
            raise ValueError("No input data: call set_input_dataset or set_reader")
        if resume and checkpoint_dir is None:
            raise ValueError("resume=True requires checkpoint_dir")
        if on_mesh_mismatch not in ("reshard", "raise"):
            # an unrecognized policy must not silently mean "reshard" for
            # a caller who asked to fail on topology changes
            raise ValueError(
                f"unknown on_mesh_mismatch {on_mesh_mismatch!r} "
                "(choose 'reshard' or 'raise')"
            )
        # flight recorder (telemetry/runlog.py): one RunReport per train —
        # phases/layers/folds, ledger deltas, runtime transfer census,
        # device-memory high-water, live progress/ETA. Purely
        # observability: every recorder path is exception-contained.
        recorder = _runlog.RunRecorder(progress=progress).start()
        # pre-flight static analysis: refuse a provably-broken DAG (type
        # clash, leakage, cycle, ...) BEFORE reading any data — the eager
        # stand-in for the reference's compile-time typed pipelines. The
        # report (incl. surviving warnings) rides the model summary.
        preflight_report = self.validate().raise_if_errors()
        # preflight already covered the structural checks — skip the
        # second validate_stages pass inside _stages()
        stages = self._stages(validate=False)
        self._apply_overrides(stages)
        # async warmup (compiler.warmup): load the banked executables the
        # model families in THIS DAG will need on a background thread, so
        # program acquisition overlaps the reader/feature phases below
        # instead of serializing in front of the first fit dispatch
        from ..compiler import warmup as _warmup
        from ..featurize import stats as _fstats

        _warmup.start_warmup(_warmup.train_programs(stages), scope="train")
        # featurize-plane ledger for THIS train (rows/s per stage, pool
        # utilization, interning + fallback-kernel counts) — the delta
        # over the whole ingest lands in the selector summary
        featurize_baseline = _fstats.snapshot()
        selectors = [s for s in stages if isinstance(s, ModelSelector)]
        if len(selectors) > 1:
            raise ValueError(
                "Only one ModelSelector is allowed per workflow "
                f"(found {len(selectors)})"  # FitStagesUtil.cutDAG:310 parity
            )
        selector = selectors[0] if selectors else None

        raw_features = raw_features_of(self.result_features)
        use_stream = (
            stream if stream is not None else self.reader.is_unbounded()
        )
        ckpt = None
        stream_summary = None
        if use_stream:
            if not hasattr(self.reader, "stream_batches"):
                raise ValueError(
                    "stream=True requires a chunked reader exposing "
                    "stream_batches() (readers/streaming.py); "
                    f"{type(self.reader).__name__} does not"
                )
            if checkpoint_dir is not None:
                # created BEFORE ingest: the stream cursor persists per
                # chunk so a mid-ingest crash resumes instead of
                # re-ingesting; a fresh train wipes stale state once here
                from ..resilience.checkpoint import CheckpointManager

                ckpt = CheckpointManager(checkpoint_dir)
                if not resume:
                    ckpt.clear()
            from .stream import stream_ingest

            with recorder.phase("ingest"):
                with _tspans.span(
                    "train/ingest", features=len(raw_features), stream=1
                ):
                    raw, stream_summary = stream_ingest(
                        self.reader, raw_features,
                        recorder=recorder, checkpoint=ckpt, resume=resume,
                    )
            recorder.set_phase_rows("ingest", stream_summary["rowsSeen"])
            recorder.set_stream_summary(stream_summary)
            log.info(
                "Streamed raw data: %d rows over %d chunks "
                "(%d quarantined), %d buffered for fit",
                stream_summary["rowsSeen"], stream_summary["chunksDone"],
                stream_summary["quarantinedTotal"], raw.num_rows,
            )
        else:
            with recorder.phase("ingest"):
                with _tspans.span(
                    "train/ingest", features=len(raw_features)
                ):
                    raw = self.reader.generate_dataset(raw_features)
            recorder.set_phase_rows("ingest", raw.num_rows)
        if raw.num_rows == 0:
            raise ValueError("Input dataset cannot be empty")
        log.info("Generated raw data: %d rows, %d features", raw.num_rows, len(raw_features))

        sensitive_info = None
        if self._detect_sensitive:
            from ..prep.sensitive import detect_sensitive_features

            sensitive_info = [
                s.to_json()
                for s in detect_sensitive_features(raw, raw_features)
            ]
            if sensitive_info:
                log.info("Sensitive features detected: %s", sensitive_info)

        rff_results = None
        if self._raw_feature_filter is not None:
            label_names = [f.name for f in raw_features if f.is_response]
            score_data = (
                self._rff_score_reader.generate_dataset(
                    [f for f in raw_features if not f.is_response]
                )
                if self._rff_score_reader is not None
                else None
            )
            blocklist = self._raw_feature_filter.compute_exclusions(
                raw,
                raw_features,
                score=score_data,
                label_name=label_names[0] if label_names else None,
            )
            rff_results = self._raw_feature_filter.results
            if blocklist:
                log.info("RawFeatureFilter blocklisted: %s", blocklist)
                self._apply_blocklist(blocklist)
                raw_features = raw_features_of(self.result_features)
                raw = raw.drop(blocklist)
                validate_stages(compute_dag(self.result_features))

        train_data, holdout_data = raw, None
        if selector is not None and selector.splitter is not None:
            train_idx, holdout_idx = selector.splitter.split(raw.num_rows)
            if len(holdout_idx):
                train_data = raw.take(train_idx)
                holdout_data = raw.take(holdout_idx)

        # checkpoint/resume (resilience/): completed layers restore into the
        # prefitted warm-start dict; the selector checkpoints CV candidates
        signature = None
        dag_layers = None
        base_prefitted = dict(self._prefitted)
        if checkpoint_dir is not None:
            from ..resilience.checkpoint import (
                CheckpointManager,
                dag_signature,
                dataset_fingerprint,
            )

            fresh_ckpt = ckpt is None  # stream mode created + cleared it
            if fresh_ckpt:
                ckpt = CheckpointManager(checkpoint_dir)
            dag_layers = compute_dag(self.result_features)
            signature = dag_signature(
                dag_layers, dataset_fingerprint(train_data)
            )
            if fresh_ckpt and not resume:
                # fresh train: stale entries from a previous run in the
                # same dir must never mix into a later crash + resume
                ckpt.clear()
            if selector is not None:
                selector._checkpoint = ckpt
                # candidate RESULTS are only consumed on an explicit resume;
                # a fresh train always re-sweeps (and overwrites the files)
                selector._checkpoint_resume = resume

        # every estimator fit below runs under the ambient execution mesh:
        # tree fits shard_map rows with psum'd histograms, solver fits ride
        # GSPMD row sharding; None (single device) = plain jit. The
        # FailoverController wraps the whole fit phase: on a declared host
        # loss the mesh degrades to the surviving hosts' devices and the
        # fit re-enters from the last completed layer checkpoint.
        import contextlib

        from ..parallel.mesh import use_execution_mesh
        from ..resilience import distributed
        from ..resilience.distributed import HostLostError

        controller = distributed.active_controller()
        own_controller = controller is None
        if own_controller:
            controller = distributed.FailoverController()
        controller.bind(self._resolve_mesh(), checkpoint=ckpt)

        def load_checkpointed_layers() -> dict[str, Any]:
            pf = dict(base_prefitted)
            if ckpt is not None and (
                resume or controller.counters["failovers"]
            ):
                # the strict policy applies to the user-initiated resume
                # only: after a failover THIS run changed the mesh on
                # purpose, so the reload must reshard, not crash
                policy = (
                    "reshard" if controller.counters["failovers"]
                    else on_mesh_mismatch
                )
                pf.update(ckpt.load_layers(
                    signature, dag_layers,
                    mesh_info=distributed.mesh_fingerprint(controller.mesh),
                    mesh_policy=policy,
                ))
                controller.counters["reshardEvents"] += ckpt.reshard_events
            return pf

        # the fit phase runs with the recorder INSTALLED so the layer /
        # fold / candidate pulses in fit.py, cv.py and validators.py land
        # on this run; an ExitStack keeps the existing failover-loop
        # structure intact (a re-entered fit phase accumulates seconds)
        _rec_stack = contextlib.ExitStack()
        _rec_stack.enter_context(_runlog.recording(recorder))
        _rec_stack.enter_context(
            recorder.phase("fit", rows=train_data.num_rows)
        )
        try:
            install = (
                distributed.installed_controller(controller)
                if own_controller
                else contextlib.nullcontext()
            )
            with install:
                prefitted = load_checkpointed_layers()
                cv_results = None
                while True:
                    try:
                        with use_execution_mesh(controller.mesh):
                            if self._workflow_cv and selector is not None:
                                if cv_results is None:
                                    from .cv import workflow_cv_results

                                    # NOTE: checkpoint-restored stages stay
                                    # OUT of the per-fold refits — they were
                                    # fit on the full training split, and
                                    # prefitting them here would leak
                                    # validation rows into candidate
                                    # selection; only the user's explicit
                                    # warm-start stages are honored (same
                                    # semantics as an uninterrupted
                                    # withWorkflowCV train)
                                    cv_results = workflow_cv_results(
                                        selector, train_data,
                                        prefitted=self._prefitted,
                                    )
                                    log.info(
                                        "Workflow-level CV: %d candidate "
                                        "results from per-fold DAG refits",
                                        len(cv_results),
                                    )
                                # re-handed on every attempt: the selector
                                # consumes them, and a failover AFTER the
                                # sweep finished must not re-run training's
                                # most expensive phase
                                selector.precomputed_results = cv_results

                            fitted_data, fitted = fit_and_transform_dag(
                                train_data, self.result_features,
                                prefitted=prefitted, checkpoint=ckpt,
                            )
                        break
                    except HostLostError as e:
                        # elastic degraded-mesh failover: shrink the mesh to
                        # the survivors (raises when no failover is left),
                        # restore every completed layer from the checkpoint,
                        # and re-enter the fit instead of aborting
                        controller.failover(e)
                        prefitted = load_checkpointed_layers()
        finally:
            _rec_stack.close()
            if selector is not None:
                selector._checkpoint = None
                selector._checkpoint_resume = False
        dist_summary = controller.summary()

        selector_info = None
        if selector is not None:
            selector_info = {
                "estimatorUid": selector.uid,
                "labelName": selector.input_names[0],
                "vectorName": selector.input_names[1],
                "predName": selector.output_name,
                "evaluator": selector.evaluator.name,
                "problemKind": selector.problem_kind,
            }
            sel_stage = fitted.get(selector.uid)
            if isinstance(sel_stage, SelectedModel):
                # failover counters ride the selector summary next to the
                # PR-1 candidateAttempts ledger (same reporting convention);
                # the featurize ledger here covers the WHOLE train ingest
                # (the delta captured inside fit_arrays only sees the
                # selector's own array work)
                sel_stage.summary["distributedResilience"] = dist_summary
                sel_stage.summary["featurizeStats"] = _fstats.delta(
                    featurize_baseline
                )
                if stream_summary is not None:
                    # the reduced fit stats are large (per-field exact
                    # partials); the selector summary carries the chunk /
                    # quarantine / window accounting only
                    sel_stage.summary["streamIngest"] = {
                        k: v for k, v in stream_summary.items()
                        if k != "fitStats"
                    }

        holdout_metrics = None
        if selector is not None and holdout_data is not None:
            sel_model = fitted[selector.uid]
            assert isinstance(sel_model, SelectedModel)
            with recorder.phase("eval", rows=len(holdout_data)):
                with _tspans.span("train/eval", rows=len(holdout_data)):
                    transformed = apply_transformations_dag(
                        holdout_data, self.result_features, fitted
                    )
                    label_name, vec_name = selector.input_names
                    label = transformed[label_name]
                    vec = transformed[vec_name]
                    assert isinstance(label, NumericColumn) and isinstance(
                        vec, VectorColumn
                    )
                    holdout_metrics = sel_model.evaluate_holdout(
                        np.asarray(vec.values, dtype=np.float32),
                        label.values.astype(np.float64),
                        selector.evaluator,
                    )
            log.info("Holdout metrics: %s", holdout_metrics)

        label_summary = None
        if selector_info is not None:
            label_summary = _label_summary(
                fitted_data, selector_info, self.result_features
            )

        # serving-drift profiles (resilience/sentinel.py): per-raw-feature
        # fill rate + value histogram over the training rows, persisted in
        # the model artifact so score_function's drift sentinel can compare
        # the live stream against what the model was trained on
        from ..resilience.sentinel import compute_serving_profiles

        serving_profiles = compute_serving_profiles(train_data, raw_features)

        # attribution baseline (insights/drift.py): one batched LOCO sweep
        # over a bounded training sample, sketching each feature group's
        # contribution distribution — the serve-time attribution drift
        # monitor compares explain=k sweeps against this. Persisted next
        # to servingProfiles; TPTPU_ATTRIBUTION_PROFILE_ROWS=0 disables.
        attribution_profiles = None
        if selector_info is not None:
            with recorder.phase("attribution"):
                attribution_profiles = _attribution_baseline(
                    fitted, selector_info, fitted_data
                )

        # freeze the flight recorder into the run report, persist it as a
        # RUN_*.json artifact when a run dir is configured, and auto-diff
        # against the directory's previous run (the regression sentinel)
        run_report = _finalize_run_report(
            recorder, holdout_metrics, train_data.num_rows,
            run_dir if run_dir is not None else os.environ.get("TPTPU_RUN_DIR"),
        )

        model = WorkflowModel(
            result_features=self.result_features,
            raw_features=tuple(raw_features),
            fitted=fitted,
            selector_info=selector_info,
            train_rows=train_data.num_rows,
            holdout_rows=0 if holdout_data is None else holdout_data.num_rows,
            rff_results=None if rff_results is None else rff_results.to_json(),
            blocklisted=list(self.blocklisted_features),
            sensitive_info=sensitive_info,
            label_summary=label_summary,
            training_params=dict(self._stage_overrides),
            serving_profiles=serving_profiles,
            attribution_profiles=attribution_profiles,
            dist_summary=dist_summary,
            analysis=preflight_report.to_json(),
            run_report=run_report,
        )
        if selector is not None:
            # keep the live evaluator object so custom evaluators keep working
            # on the in-memory model (the name in selector_info covers load)
            model._live_evaluator = selector.evaluator
        return model


def _finalize_run_report(
    recorder: "_runlog.RunRecorder",
    holdout_metrics: dict[str, Any] | None,
    train_rows: int,
    run_dir: str | None,
) -> dict[str, Any] | None:
    """Freeze the flight recorder into its RunReport; with a run dir,
    diff against the directory's latest run FIRST (the regression verdict
    rides inside the new artifact), then persist ``RUN_*.json``. Contained:
    a capture failure degrades to ``run_report=None``, never a failed
    train."""
    try:
        recorder.record_quality(holdout_metrics)
        report = recorder.finalize(train_rows=train_rows)
        if run_dir:
            baseline = _runlog.latest_run_report(run_dir)
            if baseline is not None:
                diff = _runlog.diff_runs(baseline, report)
                report["run"]["regression"] = {
                    "baselineRunId": (baseline.get("run") or {}).get("runId"),
                    "baselineFile": (baseline.get("run") or {}).get("file"),
                    "findings": [f.to_json() for f in diff.findings],
                }
                if diff.findings:
                    log.warning(
                        "train run regressed vs %s:\n%s",
                        (baseline.get("run") or {}).get("file", "<baseline>"),
                        diff.pretty(),
                    )
            path = _runlog.save_run_report(report, run_dir)
            log.info("run report written: %s", path)
        return report
    except Exception as e:  # observability must never fail a train
        log.warning("run report capture failed: %s", e)
        return None


def _attribution_baseline(
    fitted: dict[str, Any],
    selector_info: dict[str, Any],
    fitted_data: Dataset,
) -> dict[str, Any] | None:
    """Train-time baseline attribution profile (insights/drift.py) — a
    best-effort capture that must never fail a train; one bounded batched
    LOCO sweep, counted under the ``train/attribution`` span so
    ``phase_breakdown()`` attributes its seconds to ``explain``."""
    import os

    try:
        max_rows = int(os.environ.get("TPTPU_ATTRIBUTION_PROFILE_ROWS", "256"))
    except ValueError:
        max_rows = 256
    if max_rows <= 0:
        return None
    sel_model = fitted.get(selector_info["estimatorUid"])
    vec_name = selector_info["vectorName"]
    if sel_model is None or vec_name not in fitted_data:
        return None
    vec = fitted_data[vec_name]
    if not isinstance(vec, VectorColumn):
        return None
    try:
        from ..insights.drift import compute_attribution_profile

        with _tspans.span("train/attribution", rows=min(max_rows, len(vec))):
            return compute_attribution_profile(
                sel_model,
                np.asarray(vec.values, dtype=np.float32),
                vec.metadata,
                max_rows=max_rows,
            )
    except Exception as e:  # observability must never break training
        log.warning("attribution baseline capture skipped: %s", e)
        return None


def _label_summary(
    fitted_data: Dataset,
    selector_info: dict[str, Any],
    result_features: Sequence[Feature],
) -> dict[str, Any] | None:
    """LabelSummary (ModelInsights.scala:293-325): raw lineage + sample size
    + distribution — Discrete {domain, prob} for classification problems,
    Continuous {min, max, mean, variance} for regression."""
    name = selector_info["labelName"]
    if name not in fitted_data:
        return None
    col = fitted_data[name]
    vals = np.asarray(col.values, dtype=np.float64)
    mask = np.asarray(col.mask, dtype=bool) if hasattr(col, "mask") else np.ones(len(vals), bool)
    present = vals[mask]
    label_feat = next((f for f in result_features if f.name == name), None)
    raw = label_feat.raw_features() if label_feat is not None else []
    summary: dict[str, Any] = {
        "labelName": name,
        "rawFeatureName": [f.name for f in raw],
        "rawFeatureType": [f.ftype.__name__ for f in raw],
        "stagesApplied": (
            label_feat.history()["stages"] if label_feat is not None else []
        ),
        "sampleSize": float(len(present)),
    }
    if len(present) == 0:
        summary["distribution"] = None
    elif selector_info["problemKind"] == "Regression":
        summary["distribution"] = {
            "type": "Continuous",
            "min": float(present.min()),
            "max": float(present.max()),
            "mean": float(present.mean()),
            "variance": float(present.var()),
        }
    else:
        uniq, counts = np.unique(present, return_counts=True)
        summary["distribution"] = {
            "type": "Discrete",
            "domain": [str(int(u)) if u == int(u) else str(u) for u in uniq],
            "prob": (counts / counts.sum()).tolist(),
        }
    return summary


class WorkflowModel:
    def __init__(
        self,
        result_features: tuple[Feature, ...],
        raw_features: tuple[Feature, ...],
        fitted: dict[str, PipelineStage],
        selector_info: dict[str, Any] | None,
        train_rows: int = 0,
        holdout_rows: int = 0,
        rff_results: dict[str, Any] | None = None,
        blocklisted: list[str] | None = None,
        sensitive_info: list[dict[str, Any]] | None = None,
        label_summary: dict[str, Any] | None = None,
        training_params: dict[str, Any] | None = None,
        serving_profiles: dict[str, Any] | None = None,
        attribution_profiles: dict[str, Any] | None = None,
        dist_summary: dict[str, Any] | None = None,
        analysis: dict[str, Any] | None = None,
        run_report: dict[str, Any] | None = None,
    ):
        self.result_features = result_features
        self.raw_features = raw_features
        self.fitted = fitted
        self.selector_info = selector_info
        self.train_rows = train_rows
        self.holdout_rows = holdout_rows
        self.rff_results = rff_results
        self.blocklisted = blocklisted or []
        self.sensitive_info = sensitive_info
        self.label_summary = label_summary
        self.training_params = training_params or {}
        #: per-raw-feature training distributions for the serve-time drift
        #: sentinel (fill rate + StreamingHistogram JSON); None on models
        #: saved before this field existed
        self.serving_profiles = serving_profiles
        #: per-feature-group baseline LOCO contribution histograms for the
        #: serve-time attribution drift monitor (insights/drift.py); None
        #: on models saved before the explainability plane existed
        self.attribution_profiles = attribution_profiles
        #: distributed-resilience ledger from training (hosts lost,
        #: failovers, collective retries, stragglers, reshard events, mesh
        #: history); None on models saved before this field existed
        self.dist_summary = dist_summary
        #: pre-flight static-analysis report from train() (JSON form of
        #: analysis.Report — findings that survived as warnings/info);
        #: None on models saved before the analysis plane existed
        self.analysis = analysis
        #: training-run flight-recorder report (telemetry/runlog.py):
        #: per-phase/layer/fold timings, ledger deltas, runtime transfer
        #: census, device-memory high-water; None on models saved before
        #: the run ledger existed (or when capture degraded)
        self.run_report = run_report

    # --------------------------------------------------------- persistence
    def save(self, path: str) -> None:
        """OpWorkflowModelWriter equivalent: manifest.json + arrays.npz."""
        from .persistence import save_workflow_model

        save_workflow_model(self, path)

    @staticmethod
    def load(path: str) -> "WorkflowModel":
        """Standalone load (OpWorkflowModel.load, OpWorkflowModel.scala:456)."""
        from .persistence import load_workflow_model

        return load_workflow_model(path)

    # --------------------------------------------------------------- score
    def _prepare_raw(self, dataset: Dataset | None, reader: DataReader | None) -> Dataset:
        if dataset is not None:
            reader = DatasetReader(self._with_missing_response(dataset))
        if reader is None:
            raise ValueError("score requires a dataset or reader")
        try:
            raw = reader.generate_dataset(list(self.raw_features))
        except KeyError:
            # scoring data typically lacks the response column: generate the
            # predictors only and synthesize null labels
            raw = reader.generate_dataset(
                [f for f in self.raw_features if not f.is_response]
            )
        return self._with_missing_response(raw)

    def _with_missing_response(self, dataset: Dataset) -> Dataset:
        """Scoring data often lacks the response column; synthesize NULL
        labels of the right physical type (mask=False / None — the reference
        reader produces null labels at score time). Evaluation rejects
        all-null labels loudly."""
        from ..types.columns import empty_like

        for f in self.raw_features:
            if f.is_response and f.name not in dataset:
                dataset = dataset.with_column(
                    f.name, empty_like(f.ftype, dataset.num_rows)
                )
        return dataset

    def score(
        self,
        dataset: Dataset | None = None,
        reader: DataReader | None = None,
        keep_raw_features: bool = False,
        keep_intermediate_features: bool = False,
    ) -> Dataset:
        """Apply the fitted DAG (OpWorkflowModel.score, OpWorkflowModel.scala:259)."""
        from ..compiler import warmup as _warmup

        # overlap loading the banked scoring executables with raw-data prep
        _warmup.start_warmup(_warmup.SCORE_PROGRAMS, scope="score")
        raw = self._prepare_raw(dataset, reader)
        transformed = apply_transformations_dag(raw, self.result_features, self.fitted)
        if keep_intermediate_features:
            return transformed
        keep = [f.name for f in self.result_features if f.name in transformed]
        if keep_raw_features:
            keep = [f.name for f in self.raw_features] + keep
        return transformed.select(keep)

    def score_and_evaluate(
        self,
        dataset: Dataset | None = None,
        evaluator=None,
        reader: DataReader | None = None,
    ) -> tuple[Dataset, dict[str, Any]]:
        scores = self.score(dataset, reader=reader, keep_intermediate_features=True)
        metrics = self._evaluate_transformed(scores, evaluator)
        keep = [f.name for f in self.result_features if f.name in scores]
        return scores.select(keep), metrics

    def evaluate(
        self,
        dataset: Dataset | None = None,
        evaluator=None,
        reader: DataReader | None = None,
    ) -> dict[str, Any]:
        """Score + evaluate against the true labels present in the data."""
        transformed = self.score(
            dataset, reader=reader, keep_intermediate_features=True
        )
        return self._evaluate_transformed(transformed, evaluator)

    def _evaluate_transformed(self, transformed: Dataset, evaluator=None) -> dict[str, Any]:
        if self.selector_info is None:
            raise ValueError("evaluate requires a ModelSelector in the workflow")
        if evaluator is None:
            evaluator = getattr(self, "_live_evaluator", None)
        if evaluator is None:
            from ..evaluators import (
                BinaryClassificationEvaluator,
                ForecastEvaluator,
                MultiClassificationEvaluator,
                RegressionEvaluator,
            )

            by_name = {
                e.name: e
                for e in (
                    BinaryClassificationEvaluator(),
                    MultiClassificationEvaluator(),
                    RegressionEvaluator(),
                    ForecastEvaluator(),
                )
            }
            name = self.selector_info["evaluator"]
            if name not in by_name:
                raise ValueError(
                    f"Evaluator '{name}' is not a builtin; pass the evaluator "
                    "object explicitly to evaluate()/score_and_evaluate()"
                )
            evaluator = by_name[name]
        label = transformed[self.selector_info["labelName"]]
        if isinstance(label, NumericColumn) and not label.mask.any():
            raise ValueError(
                "evaluate requires true labels, but the response column "
                f"'{self.selector_info['labelName']}' is absent/all-null in "
                "the provided data"
            )
        pred = transformed[self.selector_info["predName"]]
        return evaluator.evaluate(label, pred)

    # ------------------------------------------------------------- summary
    def summary_json(self) -> dict[str, Any]:
        sel_summary = None
        if self.selector_info is not None:
            model = self.fitted.get(self.selector_info["estimatorUid"])
            if isinstance(model, SelectedModel):
                sel_summary = model.summary
        stage_meta = {
            uid: s.metadata
            for uid, s in self.fitted.items()
            if s.metadata
        }
        analysis = self.analysis
        if analysis is not None:
            # the TPC static-concurrency and TPS SPMD summaries ride
            # beside the TPA/TPX reports (lru-cached per process;
            # contained — a broken analyzer must never break a training
            # summary)
            analysis = dict(analysis)
            try:
                from ..analysis.concurrency import package_summary

                analysis["concurrency"] = package_summary()
            except Exception:  # pragma: no cover - defensive
                pass
            try:
                from ..analysis.spmd import package_summary as spmd_summary

                analysis["spmd"] = spmd_summary()
            except Exception:  # pragma: no cover - defensive
                pass
        try:
            from ..resilience.retrain import ledger_snapshot

            retrain_ledger = ledger_snapshot()
        except Exception:  # pragma: no cover - defensive
            retrain_ledger = None
        return {
            "trainRows": self.train_rows,
            "holdoutRows": self.holdout_rows,
            "rawFeatures": [f.name for f in self.raw_features],
            "resultFeatures": [f.name for f in self.result_features],
            "blocklistedFeatures": self.blocklisted,
            "rawFeatureFilterResults": self.rff_results,
            "sensitiveFeatures": self.sensitive_info,
            "modelSelectorSummary": sel_summary,
            "stageMetadata": stage_meta,
            "distributedResilience": self.dist_summary,
            "retrainLedger": retrain_ledger,
            "analysis": analysis,
            "run": getattr(self, "run_report", None),
        }

    def summary_pretty(self) -> str:
        """Human-readable training summary matching the reference
        README's summaryPretty rendering (/root/reference/README.md:63-96):
        the evaluated-families lead, the selected model's PARAMETER table,
        one combined holdout/training metric table, and the
        correlation-ranked top-insights + contributions tables."""
        from ..utils.table import render_table

        s = self.summary_json()
        lines: list[str] = []
        sel = s.get("modelSelectorSummary")
        if sel:
            results = sel["validationResults"]
            by_family: dict[str, list[float]] = {}
            for r in results:
                by_family.setdefault(r["modelName"], []).append(r["metricMean"])
            metric = sel["evaluationMetric"]
            n_folds = len(results[0].get("metricValues", [])) if results else 0
            lines.append(
                f"Evaluated {', '.join(sorted(by_family))} models with "
                f"{n_folds} folds and {metric} metric."
            )
            for name, vals in sorted(by_family.items()):
                lines.append(
                    f"Evaluated {len(vals)} {name} models with {metric} "
                    f"between [{min(vals)}, {max(vals)}]"
                )
            # retry/exclusion ledger (resilience): candidates that needed
            # more than one attempt, or were excluded after exhausting them
            for a in sel.get("candidateAttempts") or []:
                if a.get("excluded"):
                    lines.append(
                        f"Excluded {a['modelName']} after "
                        f"{a.get('attempts', 1)} attempt(s): {a.get('error')}"
                    )
                elif a.get("attempts", 1) > 1:
                    lines.append(
                        f"Retried {a['modelName']}: succeeded on attempt "
                        f"{a['attempts']}"
                    )
            lines.append("")
            # selected-model parameter table (README: "Selected model Random
            # Forest classifier with parameters")
            lines.append(
                f"Selected model {sel['bestModelType']} with parameters:"
            )
            params: dict[str, Any] = {"modelType": sel["bestModelType"]}
            best_model = None
            if self.selector_info is not None:
                stage = self.fitted.get(self.selector_info["estimatorUid"])
                best_model = getattr(stage, "best_model", None)
            if best_model is not None:
                params.update(best_model.get_params())
            params.update(sel.get("bestGrid", {}))
            lines.append(
                render_table(
                    ["Model Param", "Value"],
                    [[k, str(v)] for k, v in sorted(params.items())],
                )
            )
            lines.append("")
            # ONE combined metric table, holdout + training side by side
            train_m = sel.get("trainEvaluation") or {}
            hold_m = sel.get("holdoutEvaluation") or {}
            keys = [
                k for k in {**hold_m, **train_m}
                if isinstance((hold_m.get(k, train_m.get(k))), (int, float))
            ]
            if keys:
                lines.append("Model evaluation metrics:")
                lines.append(
                    render_table(
                        ["Metric Name", "Hold Out Set Value",
                         "Training Set Value"],
                        [
                            [k, str(hold_m.get(k, "")), str(train_m.get(k, ""))]
                            for k in keys
                        ],
                    )
                )
                lines.append("")
            # top insights by label correlation + model contributions
            # (README: "Top model insights computed using correlation")
            try:
                from ..insights.model_insights import model_insights

                ins = model_insights(self)
                derived = [
                    d
                    for f in ins.get("features", [])
                    for d in f.get("derivedFeatures", [])
                ]
                ilines: list[str] = []
                with_corr = [
                    d for d in derived
                    if isinstance(d.get("corr"), (int, float))
                    and np.isfinite(d["corr"])
                ]
                with_corr.sort(key=lambda d: -d["corr"])
                pos = [d for d in with_corr if d["corr"] >= 0]
                if with_corr:
                    ilines.append(
                        "Top model insights computed using correlation:"
                    )
                    if pos:
                        ilines.append(render_table(
                            ["Top Positive Insights", "Correlation"],
                            [[d["derivedFeatureName"], f"{d['corr']:.4f}"]
                             for d in pos[:7]],
                        ))
                    negs = [d for d in reversed(with_corr) if d["corr"] < 0]
                    if negs:
                        ilines.append(render_table(
                            ["Top Negative Insights", "Correlation"],
                            [[d["derivedFeatureName"], f"{d['corr']:.4f}"]
                             for d in negs[:7]],
                        ))
                    ilines.append("")
                with_contrib = [
                    d for d in derived
                    if isinstance(d.get("contribution"), (int, float))
                ]
                with_contrib.sort(key=lambda d: -abs(d["contribution"]))
                if with_contrib and any(d["contribution"] for d in with_contrib):
                    ilines.append("Top Contributions:")
                    ilines.append(render_table(
                        ["Top Contributions", "Value"],
                        [[d["derivedFeatureName"], f"{d['contribution']:.4f}"]
                         for d in with_contrib[:7]],
                    ))
                    ilines.append("")
                lines.extend(ilines)  # all-or-nothing: no dangling headers
            except Exception as e:  # insights stay best-effort, but a
                # broken section must be observable, not invisible:
                # counted on the run ledger + a summary_degraded event +
                # a one-shot warning (was a silent debug-level swallow)
                _report_summary_degraded("insights", e)
        comp = (sel or {}).get("compileStats") or {}
        if comp.get("programsCompiled") or comp.get("cacheHitsMemory") or \
                comp.get("cacheHitsDisk") or comp.get("dedupHits"):
            hits = comp.get("cacheHitsMemory", 0) + comp.get("cacheHitsDisk", 0)
            rate = comp.get("compileCacheHitRate")
            rate_s = f", {rate:.0%} hit rate" if rate is not None else ""
            lines.append(
                f"Compile plane: {comp.get('programsCompiled', 0)} "
                f"program(s) compiled, {hits} cache hit(s){rate_s}, "
                f"{comp.get('dedupHits', 0)} dedup lane(s), "
                f"{comp.get('laneBucketPads', 0)} pad lane(s), "
                f"{comp.get('warmupPrograms', 0)} warmed "
                f"({comp.get('warmupOverlapSeconds', 0.0):.2f}s overlapped)"
            )
        if comp.get("fusedDispatches") or comp.get("fusedFallbacks") or \
                comp.get("fusedFallbackReasons"):
            reasons = comp.get("fusedFallbackReasons") or {}
            reason_s = ""
            if reasons:
                top = sorted(reasons.items(), key=lambda kv: -kv[1])[:3]
                reason_s = " (" + ", ".join(
                    f"{k}: {v}" for k, v in top
                ) + ")"
            lines.append(
                f"Fused serving: {comp.get('fusedDispatches', 0)} "
                f"dispatch(es), {comp.get('fusedExplainLanes', 0)} "
                f"explain lane(s), {comp.get('fusedFallbacks', 0)} "
                f"fallback(s){reason_s}"
            )
        feat = (sel or {}).get("featurizeStats") or {}
        if feat.get("rowsFeaturized") or feat.get("poolTasks"):
            util = feat.get("poolUtilization")
            util_s = f", pool {util:.0%} util" if util is not None else ""
            per_stage = feat.get("stageRowsPerSec") or {}
            slow = min(
                (
                    (c.get("rowsPerSec"), name)
                    for name, c in per_stage.items()
                    if c.get("rowsPerSec")
                ),
                default=(None, ""),
            )
            top_s = (
                f", bottleneck stage {slow[1]} @ {slow[0]:,} rows/s"
                if slow[0] else ""
            )
            lines.append(
                f"Featurize plane: {feat.get('rowsFeaturized', 0):,} "
                f"row(s) through {feat.get('stagesExecuted', 0)} stage "
                f"pass(es), {feat.get('fusedAssemblies', 0)} fused, "
                f"{feat.get('poolTasks', 0)} pool task(s){util_s}, "
                f"{feat.get('fallbackKernels', 0)} fallback kernel(s)"
                f"{top_s}"
            )
        # explainability plane: the attribution ledger's one-line view
        # (train-time baseline sweeps + any serve-time explain=k work)
        try:
            from ..insights import ledger as _attr_ledger

            att = _attr_ledger.snapshot()
            if att.get("rowsExplained") or att.get("profilesCaptured"):
                rate = att.get("explainRowsPerSec")
                rate_s = f" @ {rate:,} rows/s" if rate else ""
                profiled = len(
                    (getattr(self, "attribution_profiles", None) or {})
                    .get("groups", {})
                )
                lines.append(
                    f"Record insights: {att.get('rowsExplained', 0):,} "
                    f"row(s) explained{rate_s}, "
                    f"{att.get('laneDispatches', 0)} lane(s) dispatched "
                    f"({att.get('lanesDeduped', 0)} deduped, "
                    f"{att.get('lanesPadded', 0)} padded), "
                    f"{profiled} group(s) profiled, "
                    f"{att.get('attributionDriftAlerts', 0)} attribution "
                    f"drift alert(s), {att.get('explainShedRows', 0)} "
                    f"row(s) shed"
                )
        except Exception as e:  # observability must never break summaries
            log.debug("record-insights summary line skipped: %s", e)
        dist = getattr(self, "dist_summary", None) or {}
        if any(
            dist.get(k)
            for k in (
                "hostsLost", "failovers", "stragglersDetected",
                "collectivesRetried", "reshardEvents",
            )
        ):
            lines.append(
                f"Distributed resilience: {dist.get('hostsLost', 0)} "
                f"host(s) lost, {dist.get('failovers', 0)} failover(s), "
                f"{dist.get('collectivesRetried', 0)} collective "
                f"retry(ies), {dist.get('stragglersDetected', 0)} "
                f"straggler(s), {dist.get('reshardEvents', 0)} reshard "
                f"event(s)"
            )
        serve = self._serving_resilience_line()
        if serve:
            lines.append(serve)
        run_line = self._run_report_lines()
        if run_line:
            lines.extend(run_line)
        # one consolidated telemetry line (span/event counts + serve
        # latency quantiles) pointing at the full export surfaces
        try:
            from ..telemetry import summary_line as _tel_line

            tel = _tel_line()
            if tel:
                lines.append(tel)
        except Exception as e:  # telemetry must never break the summary
            log.debug("telemetry summary line skipped: %s", e)
        analysis = getattr(self, "analysis", None) or {}
        if analysis.get("findings"):
            codes: dict[str, int] = {}
            for f in analysis["findings"]:
                codes[f["code"]] = codes.get(f["code"], 0) + 1
            code_s = ", ".join(
                f"{c}×{n}" if n > 1 else c for c, n in sorted(codes.items())
            )
            lines.append(
                f"Static analysis: {analysis.get('errors', 0)} error(s), "
                f"{analysis.get('warnings', 0)} warning(s) ({code_s}) — "
                "see docs/analysis.md"
            )
        lines.append(
            f"Trained on {s['trainRows']} rows (holdout {s['holdoutRows']}); "
            f"{len(s['rawFeatures'])} raw features"
        )
        return "\n".join(lines)

    def _run_report_lines(self) -> list[str]:
        """The flight recorder's summary lines: one "Run report:" line
        (wall, phases, layers, transfer census, device high-water, the
        artifact file when persisted) plus a regression line when the
        auto-diff against the run dir's previous run found TPR findings."""
        report = getattr(self, "run_report", None) or {}
        run = report.get("run") or {}
        if not run:
            return []
        lines: list[str] = []
        phases = run.get("phases") or {}
        phase_s = ", ".join(
            f"{name} {cell.get('seconds', 0.0):.2f}s"
            for name, cell in phases.items()
        )
        census = run.get("transferCensus") or {}
        h2d = census.get("hostToDevice") or {}
        d2h = census.get("deviceToHost") or {}
        mem = run.get("deviceMemory") or {}
        line = (
            f"Run report: {run.get('wallSeconds', 0.0):.2f}s wall"
            + (f" ({phase_s})" if phase_s else "")
            + f", {len(run.get('layers') or [])} layer(s), "
            f"h2d {h2d.get('count', 0)}x/{h2d.get('bytes', 0):,} B, "
            f"d2h {d2h.get('count', 0)}x/{d2h.get('bytes', 0):,} B, "
            f"device high-water {mem.get('highWaterBytes', 0):,} B "
            f"({mem.get('backend', '?')})"
        )
        if run.get("file"):
            line += f" — {run['file']}"
        lines.append(line)
        regression = run.get("regression") or {}
        findings = regression.get("findings") or []
        if findings:
            codes: dict[str, int] = {}
            for f in findings:
                codes[f["code"]] = codes.get(f["code"], 0) + 1
            code_s = ", ".join(
                f"{c}×{n}" if n > 1 else c for c, n in sorted(codes.items())
            )
            lines.append(
                f"Run regression: {len(findings)} finding(s) vs "
                f"{regression.get('baselineFile', 'previous run')} "
                f"({code_s}) — see docs/observability.md"
            )
        return lines

    def _serving_resilience_line(self) -> str | None:
        """Aggregate serve-side counters from every live score function
        built off this model (local.scoring keeps weak references), so one
        report covers train-side retries AND serve-side degradation."""
        quarantined = guarded = drift_alerts = breaker_trips = 0
        seen = False
        for ref in getattr(self, "_serving_monitors", []):
            fn = ref()
            if fn is None:
                continue
            try:
                md = fn.metadata()
            except Exception as e:  # monitoring must never break the summary
                log.debug("serving monitor skipped: %s", e)
                continue
            seen = True
            quarantined += md["quarantine"]["quarantinedRows"]
            guarded += md["scoreGuard"]["guardedRows"]
            drift = md.get("drift") or {}
            drift_alerts += drift.get("driftAlertsTotal", 0)
            for br in md["breakers"].values():
                t = br["transitions"]
                breaker_trips += t.get("closed->open", 0) + t.get(
                    "half_open->open", 0
                )
        if not seen:
            return None
        return (
            f"Serving resilience: {quarantined} quarantined row(s), "
            f"{guarded} guarded row(s), {drift_alerts} drift alert(s), "
            f"{breaker_trips} breaker trip(s)"
        )
