"""Workflow model persistence.

Reference: core/.../OpWorkflowModelWriter.scala:54-212 / OpWorkflowModelReader
(JSON manifest + per-stage JSON + MLeap bundles) and features/.../
OpPipelineStageReaderWriter.scala:131-196 (ctor params by reflection).

TPU-native format (SURVEY.md §5.4): ONE directory with
  * ``manifest.json`` — features (name/uid/type/response/lineage), stages in
    topological order (class, uid, ctor params, wiring), selector info,
    summary metadata;
  * ``arrays.npz`` — every fitted array, keyed ``<stage_uid>__<name>``.
No MLeap equivalent is needed: the fitted DAG is already a pure function of
arrays + params.

Stages participate via ``get_params()`` / ``get_arrays()`` and a
``from_params(params, arrays)`` classmethod (default: ctor(**params)).
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any

import numpy as np

from .. import types as T
from ..features.feature import Feature, FeatureGeneratorStage
from ..stages.base import Model, PipelineStage, Transformer


class ModelLoadError(ValueError):
    """A saved model/checkpoint is missing or corrupt; the message names the
    offending file or npz member so a torn write is diagnosable."""


#: class-name -> class registry for stage reconstruction
_REGISTRY: dict[str, type] = {}
_BUILTINS_POPULATED = False


def register_stage(cls: type) -> type:
    _REGISTRY[cls.__name__] = cls
    return cls


def _registry() -> dict[str, type]:
    """Populate lazily from the known stage modules (avoids import cycles)."""
    global _BUILTINS_POPULATED
    if _BUILTINS_POPULATED:
        return _REGISTRY
    _BUILTINS_POPULATED = True
    from ..insights import correlation as insights_corr, loco
    from ..models import glm, gbdt, isotonic, linear, logistic, mlp, naive_bayes, svc
    from ..models.base import PredictorModel
    from ..ops import (
        bucketizers, categorical, combiner, dates, domains, embeddings,
        lists, maps, numeric, phone, scalers, simple, text, text_stages,
        time_period,
    )
    from ..ops import math as ops_math
    from ..prep import derived_filter, sanity_checker
    from ..selector import combiner as selector_combiner
    from ..selector import model_selector

    for module in (
        glm, gbdt, isotonic, linear, logistic, mlp, naive_bayes, svc,
        categorical, combiner, dates, lists,
        maps, numeric, phone, text, derived_filter, sanity_checker,
        model_selector, selector_combiner, loco, insights_corr,
        bucketizers, domains, embeddings, ops_math, scalers, simple,
        text_stages, time_period,
    ):
        for name in dir(module):
            obj = getattr(module, name)
            if isinstance(obj, type) and issubclass(obj, (PipelineStage,)):
                _REGISTRY.setdefault(name, obj)
    return _REGISTRY


def construct_stage(
    class_name: str, params: dict[str, Any], arrays: dict[str, np.ndarray]
) -> PipelineStage:
    cls = _registry().get(class_name)
    if cls is None:
        raise ValueError(f"Unknown stage class '{class_name}' at load time")
    from_params = getattr(cls, "from_params", None)
    if from_params is not None:
        return from_params(params, arrays)
    return cls(**params)


def stage_to_entry(
    est_uid: str, stage: PipelineStage, arrays_out: dict[str, np.ndarray]
) -> dict[str, Any]:
    """One manifest entry for a fitted stage; fitted arrays are collected
    into ``arrays_out`` keyed ``<stage_uid>__<name>`` (shared by model
    persistence and layer checkpoints)."""
    if isinstance(stage, Model):
        for k, v in stage.get_arrays().items():
            arrays_out[f"{stage.uid}__{k}"] = np.asarray(v)
    return {
        "estimatorUid": est_uid,
        "class": type(stage).__name__,
        "uid": stage.uid,
        "operationName": stage.operation_name,
        "params": stage.get_params(),
        "inputFeatures": [f.name for f in stage.input_features],
        "outputName": stage.output_name,
        "metadata": stage.metadata,
    }


def stage_arrays_from_npz(npz: Any, uid: str, source: str) -> dict[str, np.ndarray]:
    """Extract a stage's arrays from an open npz, naming the corrupt member
    on failure instead of surfacing a raw zlib/KeyError."""
    prefix = f"{uid}__"
    out: dict[str, np.ndarray] = {}
    for k in npz.files:
        if not k.startswith(prefix):
            continue
        try:
            out[k[len(prefix):]] = npz[k]
        except Exception as e:
            raise ModelLoadError(
                f"{source}: member '{k}' (stage {uid}) is corrupt or "
                f"truncated: {e}"
            ) from e
    return out


def construct_stage_checked(
    entry: dict[str, Any], arrays: dict[str, np.ndarray], source: str
) -> PipelineStage:
    """``construct_stage`` with torn-write diagnostics: a KeyError from a
    stage's ``from_params`` means an expected array member is missing."""
    try:
        return construct_stage(entry["class"], entry["params"], arrays)
    except KeyError as e:
        raise ModelLoadError(
            f"{source}: stage {entry['uid']} ({entry['class']}) is missing "
            f"member {e} — the save was likely torn; delete and refit"
        ) from e


def atomic_write_model_dir(
    path: str, manifest: dict[str, Any], arrays: dict[str, np.ndarray]
) -> None:
    """Write a manifest.json + arrays.npz directory atomically: fill a temp
    sibling, then swap it in. An existing dir is renamed aside for the swap
    window (never rmtree'd first), so a kill at any instant leaves either
    the old complete dir, the new complete dir, or the old one parked at
    ``<path>.old-<pid>`` — never nothing. Unrelated files the user kept
    alongside the model (reports, notes) are carried over after the swap.
    Shared by model persistence and layer checkpoints."""
    base = path.rstrip(os.sep)
    tmp = f"{base}.tmp-{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    with open(os.path.join(tmp, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2, default=_json_default)
    np.savez_compressed(os.path.join(tmp, "arrays.npz"), **arrays)
    if os.path.exists(path):
        old = f"{base}.old-{os.getpid()}"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
        os.rename(tmp, path)
        for entry in os.listdir(old):
            if entry not in ("manifest.json", "arrays.npz"):
                os.rename(
                    os.path.join(old, entry), os.path.join(path, entry)
                )
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, path)


def save_workflow_model(model: "WorkflowModel", path: str) -> None:  # noqa: F821
    from .workflow import WorkflowModel  # noqa: F401

    arrays: dict[str, np.ndarray] = {}
    stages_json: list[dict[str, Any]] = [
        stage_to_entry(est_uid, stage, arrays)
        for est_uid, stage in model.fitted.items()
    ]

    manifest = {
        "version": 1,
        "rawFeatures": [
            {
                "name": f.name,
                "type": f.ftype.__name__,
                "isResponse": f.is_response,
                "uid": f.uid,
            }
            for f in model.raw_features
        ],
        "resultFeatures": [f.name for f in model.result_features],
        # stage application order = DAG order, which fitted-dict insertion
        # order already reflects (fit_and_transform_dag walks layers)
        "stages": stages_json,
        "selectorInfo": model.selector_info,
        "trainRows": model.train_rows,
        "holdoutRows": model.holdout_rows,
        "rffResults": model.rff_results,
        "blocklisted": model.blocklisted,
        "sensitiveFeatures": model.sensitive_info,
        "servingProfiles": model.serving_profiles,
        "attributionProfiles": getattr(model, "attribution_profiles", None),
        "distResilience": model.dist_summary,
        "analysis": getattr(model, "analysis", None),
        "runReport": getattr(model, "run_report", None),
    }
    atomic_write_model_dir(path, manifest, arrays)


def _json_default(o: Any):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def load_workflow_model(path: str) -> "WorkflowModel":  # noqa: F821
    from .workflow import WorkflowModel

    manifest_path = os.path.join(path, "manifest.json")
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        raise ModelLoadError(
            f"{path}: no manifest.json — not a saved model directory "
            "(or the save was interrupted before commit)"
        ) from None
    except json.JSONDecodeError as e:
        raise ModelLoadError(
            f"{manifest_path} is corrupt or truncated: {e}"
        ) from e
    npz_path = os.path.join(path, "arrays.npz")
    try:
        npz = np.load(npz_path, allow_pickle=False)
    except FileNotFoundError:
        raise ModelLoadError(f"{path}: missing arrays.npz") from None
    except Exception as e:
        raise ModelLoadError(
            f"{npz_path} is corrupt or truncated: {e}"
        ) from e

    raw_features = []
    feature_by_name: dict[str, Feature] = {}
    for rf in manifest["rawFeatures"]:
        ftype = T.feature_type_by_name(rf["type"])
        stage = FeatureGeneratorStage(
            rf["name"], ftype, is_response=rf["isResponse"]
        )
        feat = stage.get_output()
        feat.uid = rf["uid"]
        raw_features.append(feat)
        feature_by_name[feat.name] = feat

    fitted: dict[str, PipelineStage] = {}
    for entry in manifest["stages"]:
        stage_arrays = stage_arrays_from_npz(npz, entry["uid"], npz_path)
        stage = construct_stage_checked(entry, stage_arrays, npz_path)
        stage.uid = entry["uid"]
        stage.operation_name = entry["operationName"]
        stage.metadata = entry.get("metadata", {})
        inputs = []
        for name in entry["inputFeatures"]:
            if name not in feature_by_name:
                raise ValueError(f"Stage {entry['uid']} references unknown feature {name}")
            inputs.append(feature_by_name[name])
        stage.input_features = tuple(inputs)
        stage._fixed_output_name = entry["outputName"]  # type: ignore[attr-defined]
        out_feat = stage.get_output()
        out_feat = type(out_feat)(
            name=entry["outputName"],
            ftype=out_feat.ftype,
            origin_stage=stage,
            parents=tuple(inputs),
            is_response=out_feat.is_response,
        )
        feature_by_name[entry["outputName"]] = out_feat
        fitted[entry["estimatorUid"]] = stage

    result_features = tuple(
        feature_by_name[name] for name in manifest["resultFeatures"]
    )
    return WorkflowModel(
        result_features=result_features,
        raw_features=tuple(raw_features),
        fitted=fitted,
        selector_info=manifest.get("selectorInfo"),
        train_rows=manifest.get("trainRows", 0),
        holdout_rows=manifest.get("holdoutRows", 0),
        rff_results=manifest.get("rffResults"),
        blocklisted=manifest.get("blocklisted", []),
        sensitive_info=manifest.get("sensitiveFeatures"),
        # absent on pre-drift-sentinel saves: the sentinel just stays inert
        serving_profiles=manifest.get("servingProfiles"),
        # absent on pre-explainability saves: attribution drift stays inert
        attribution_profiles=manifest.get("attributionProfiles"),
        # absent on pre-failover saves: no dist ledger to report
        dist_summary=manifest.get("distResilience"),
        # absent on pre-analysis-plane saves: no findings ledger
        analysis=manifest.get("analysis"),
        # absent on pre-run-ledger saves: no flight-recorder report
        run_report=manifest.get("runReport"),
    )
