"""Workflow layer (reference: core/.../OpWorkflow.scala)."""
from .dag import compute_dag  # noqa: F401
