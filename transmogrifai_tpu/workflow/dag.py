"""DAG assembly from result features.

Reference: core/.../utils/stages/FitStagesUtil.scala:173-198 (computeDAG):
walk result features' lineage, map every stage to its max distance from a
result feature, and group into layers — deepest layer first, so a stage is
fitted only after all its ancestors. FeatureGeneratorStages (raw leaves) are
excluded: they run in the reader, not the fitted DAG.
"""
from __future__ import annotations

from typing import Iterable

from ..features.feature import Feature, FeatureGeneratorStage
from ..stages.base import PipelineStage


def compute_dag(result_features: Iterable[Feature]) -> list[list[PipelineStage]]:
    """Layers of stages, deepest (furthest from results) first."""
    dists: dict[PipelineStage, int] = {}
    for rf in result_features:
        for stage, d in rf.parent_stages().items():
            if isinstance(stage, FeatureGeneratorStage):
                continue
            if dists.get(stage, -1) < d:
                dists[stage] = d
    by_depth: dict[int, list[PipelineStage]] = {}
    for stage, d in dists.items():
        by_depth.setdefault(d, []).append(stage)
    layers = []
    for d in sorted(by_depth, reverse=True):
        layers.append(sorted(by_depth[d], key=lambda s: s.uid))
    return layers


def validate_stages(layers: list[list[PipelineStage]]) -> None:
    """Workflow-level stage validation (OpWorkflow.scala:280-338): distinct
    uids; every stage is an Estimator or Transformer; inputs wired and
    type-compatible; distinct output feature names.

    Implemented by the static-analysis plane (analysis/preflight.py) so
    every violation is TP-coded and names the offending stage AND feature
    — raises :class:`~transmogrifai_tpu.analysis.PreflightError` (a
    ``ValueError``) listing all findings, instead of the historical
    anonymous first-failure message."""
    from ..analysis.preflight import structural_findings

    structural_findings(layers).raise_if_errors()


def raw_features_of(result_features: Iterable[Feature]) -> list[Feature]:
    """All distinct raw-feature leaves required by the result features.
    Distinct raw features sharing a name across result features is an error
    (they would silently read each other's data)."""
    seen: dict[str, Feature] = {}
    for rf in result_features:
        for f in rf.raw_features():
            prior = seen.get(f.name)
            if prior is not None and prior.uid != f.uid:
                raise ValueError(
                    f"Two distinct raw features named '{f.name}' in one workflow"
                )
            seen[f.name] = f
    return list(seen.values())
