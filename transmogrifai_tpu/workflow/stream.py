"""Out-of-core streaming fit: bounded-memory chunked ingest.

The reference's whole L2 plane (DataReaders.Aggregate/Conditional,
SequenceAggregators — SURVEY §1 L0/L2) exists so training never
materializes the dataset. This module is the fit-side half: a
``Workflow.train(stream=True)`` ingest that folds fit-time statistics
through streaming monoid aggregation while the featurize pool
(featurize/parallel.py ``pipeline_tasks``) featurizes chunk k+1 as chunk
k reduces. The in-flight chunk window is the backpressure knob
(``TPTPU_STREAM_INFLIGHT``): host RSS and device high-water stay flat no
matter how many chunks the source produces.

Robust by construction:

* chunk fetches ride the reader's ``RetryPolicy`` with a typed
  :class:`~transmogrifai_tpu.readers.streaming.StreamExhausted` when the
  budget runs dry (readers/streaming.py);
* torn / corrupt chunks (``FaultPlan.tear_stream_chunk`` /
  ``corrupt_chunk``) are quarantined with counters, never folded;
* the checkpoint plane grows a **stream cursor** (chunks folded +
  reducer/buffer state snapshot, temp+rename atomic) so a crash
  mid-ingest resumes costing < 1 chunk of rework;
* a seeded memory-pressure fault (``oom_chunk``) halves the in-flight
  window instead of dying.

Exactness contract: the column-stat monoid (``ExactSum`` — Shewchuk
non-overlapping partials, the ``math.fsum`` algorithm kept mergeable)
makes count/sum/mean/min/max bit-identical for ANY chunk split or
permutation of the same rows; histograms fold value-by-value in row
order, so streamed histograms are bit-identical to the one-shot pass for
any chunk boundaries (and permutation-invariant while their bins stay
exact). tests/test_stream_property.py pins both.
"""
from __future__ import annotations

import json
import logging
import math
import os
import random
from fractions import Fraction
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from ..dataset import Dataset
from ..readers.core import SimpleReader
from ..telemetry import metrics as _tmetrics
from ..types.columns import NumericColumn
from ..utils.streaming_histogram import StreamingHistogram

log = logging.getLogger(__name__)


# ------------------------------------------------------------------ knobs
def stream_inflight() -> int:
    """Bounded in-flight chunk window (backpressure): how many chunks may
    be fetched + featurized ahead of the reducer. ``TPTPU_STREAM_INFLIGHT``
    overrides; the memory-pressure degradation halves the live value."""
    try:
        return max(1, int(os.environ.get("TPTPU_STREAM_INFLIGHT", "4")))
    except ValueError:
        return 4


def stream_buffer_rows() -> int:
    """Training-buffer row cap (the configured memory cap): sources that
    fit keep every row (streamed fit == materialized fit, bit for bit);
    larger sources degrade to a seeded reservoir sample while the monoid
    stats still cover EVERY folded row. ``TPTPU_STREAM_BUFFER_ROWS``
    overrides."""
    try:
        return max(1, int(os.environ.get("TPTPU_STREAM_BUFFER_ROWS", "100000")))
    except ValueError:
        return 100000


# -------------------------------------------------------------- exact sum
class ExactSum:
    """Exact mergeable float accumulator: Shewchuk's non-overlapping
    partials (the ``math.fsum`` algorithm) kept as monoid state. ``add``
    and ``merge`` lose no information, so the rounded :meth:`value` is
    identical for any grouping or ordering of the same multiset of
    floats — the invariance the chunk-boundary/permutation property
    tests pin. Inputs must be finite (callers screen non-finite values
    into their own counter)."""

    __slots__ = ("partials",)

    def __init__(self, partials: Sequence[float] | None = None):
        self.partials: list[float] = [float(p) for p in partials or ()]

    def add(self, x: float) -> None:
        partials = self.partials
        x = float(x)
        i = 0
        for y in partials:
            if abs(x) < abs(y):
                x, y = y, x
            hi = x + y
            lo = y - (hi - x)
            if lo:
                partials[i] = lo
                i += 1
            x = hi
        partials[i:] = [x]

    def merge(self, other: "ExactSum") -> None:
        for y in other.partials:
            self.add(y)

    def value(self) -> float:
        """The correctly rounded exact sum."""
        return math.fsum(self.partials)

    def exact(self) -> Fraction:
        """The exact rational sum (finalize-time variance arithmetic)."""
        return sum((Fraction(p) for p in self.partials), Fraction(0))

    def to_json(self) -> list[float]:
        # float repr round-trips exactly through json in Python
        return list(self.partials)

    @classmethod
    def from_json(cls, data: Sequence[float]) -> "ExactSum":
        return cls(data)


# ------------------------------------------------------------ column stats
class ColumnStat:
    """Per-column streaming monoid: row/present/non-finite counts exact;
    sum and sum-of-squares via :class:`ExactSum`; min/max; a
    :class:`StreamingHistogram` folded value-by-value in row order.
    Non-numeric columns keep the count plane only."""

    def __init__(self, numeric: bool, max_bins: int = 64):
        self.numeric = bool(numeric)
        self.max_bins = int(max_bins)
        self.rows = 0
        self.present = 0
        self.non_finite = 0
        self.sum = ExactSum()
        self.sumsq = ExactSum()
        self.min: float | None = None
        self.max: float | None = None
        self.hist = StreamingHistogram(max_bins)

    # ---------------------------------------------------------- building
    def update_column(self, col: Any) -> None:
        n = len(col)
        self.rows += n
        if not self.numeric or not isinstance(col, NumericColumn):
            if isinstance(col, NumericColumn):
                self.present += int(np.count_nonzero(col.mask))
            else:
                self.present += sum(
                    1 for v in col.to_list() if v is not None
                )
            return
        vals = np.asarray(col.values, dtype=np.float64)[
            np.asarray(col.mask, dtype=bool)
        ]
        self.present += int(vals.size)
        for v in vals.tolist():
            if not math.isfinite(v):
                self.non_finite += 1
                continue
            self.sum.add(v)
            self.sumsq.add(v * v)
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.hist.update(v)

    def merge(self, other: "ColumnStat") -> "ColumnStat":
        assert self.numeric == other.numeric
        self.rows += other.rows
        self.present += other.present
        self.non_finite += other.non_finite
        self.sum.merge(other.sum)
        self.sumsq.merge(other.sumsq)
        for v in (other.min,):
            if v is not None:
                self.min = v if self.min is None else min(self.min, v)
        for v in (other.max,):
            if v is not None:
                self.max = v if self.max is None else max(self.max, v)
        self.hist = self.hist.merge(other.hist)
        return self

    # ----------------------------------------------------------- queries
    def finalize(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "count": self.rows,
            "present": self.present,
        }
        if not self.numeric:
            return out
        out["nonFinite"] = self.non_finite
        n = self.present - self.non_finite
        if n > 0:
            s = self.sum.exact()
            sq = self.sumsq.exact()
            mean = s / n
            var = (sq - s * mean) / n
            out["sum"] = self.sum.value()
            out["mean"] = float(mean)
            out["variance"] = max(0.0, float(var))
            out["min"] = self.min
            out["max"] = self.max
        out["histogram"] = self.hist.to_json()
        return out

    def to_json(self) -> dict[str, Any]:
        return {
            "numeric": self.numeric,
            "maxBins": self.max_bins,
            "rows": self.rows,
            "present": self.present,
            "nonFinite": self.non_finite,
            "sum": self.sum.to_json(),
            "sumsq": self.sumsq.to_json(),
            "min": self.min,
            "max": self.max,
            "hist": self.hist.to_json(),
        }

    @classmethod
    def from_json(cls, data: dict[str, Any]) -> "ColumnStat":
        st = cls(data["numeric"], data["maxBins"])
        st.rows = int(data["rows"])
        st.present = int(data["present"])
        st.non_finite = int(data["nonFinite"])
        st.sum = ExactSum.from_json(data["sum"])
        st.sumsq = ExactSum.from_json(data["sumsq"])
        st.min = data["min"]
        st.max = data["max"]
        st.hist = StreamingHistogram.from_json(data["hist"])
        return st


class ChunkStatsReducer:
    """Field-name → :class:`ColumnStat` over per-chunk Datasets — the
    streaming analog of one ``pcolumn_stats`` pass, folded chunk by
    chunk. Serializable (the stream cursor snapshots it) and mergeable
    (per-chunk partials combine associatively)."""

    def __init__(self, max_bins: int = 64):
        self.max_bins = int(max_bins)
        self.fields: dict[str, ColumnStat] = {}

    def fold_dataset(self, ds: Dataset) -> None:
        for name, col in ds.columns.items():
            stat = self.fields.get(name)
            if stat is None:
                stat = ColumnStat(
                    isinstance(col, NumericColumn), self.max_bins
                )
                self.fields[name] = stat
            stat.update_column(col)

    def merge(self, other: "ChunkStatsReducer") -> "ChunkStatsReducer":
        for name, stat in other.fields.items():
            mine = self.fields.get(name)
            if mine is None:
                self.fields[name] = stat
            else:
                mine.merge(stat)
        return self

    def finalize(self) -> dict[str, dict[str, Any]]:
        return {
            name: self.fields[name].finalize()
            for name in sorted(self.fields)
        }

    def to_json(self) -> dict[str, Any]:
        return {
            "maxBins": self.max_bins,
            "fields": {
                name: st.to_json() for name, st in self.fields.items()
            },
        }

    @classmethod
    def from_json(cls, data: dict[str, Any]) -> "ChunkStatsReducer":
        red = cls(data["maxBins"])
        red.fields = {
            name: ColumnStat.from_json(st)
            for name, st in data["fields"].items()
        }
        return red


# ----------------------------------------------------------------- ledger
class _StreamIngestStats(_tmetrics.LedgerCore):
    """Process-wide out-of-core ingest ledger, merged into the
    ``resilience`` exposition source (resilience/distributed.py) next to
    the chunk-fetch retry counters."""

    KEYS = (
        "streamChunksFolded",
        "streamChunksTorn",
        "streamChunksCorrupt",
        "streamChunksQuarantined",
        "streamOomEvents",
        "streamWindowHalvings",
        "streamRowsFolded",
        "streamCursorSaves",
        "streamResumes",
        "streamChunksSkipped",
    )

    def __init__(self) -> None:
        super().__init__(self.KEYS)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset_for_tests(self) -> None:
        with self._lock:
            self._reset_counts()


STREAM_STATS = _StreamIngestStats()


# ------------------------------------------------------------------ cursor
def stream_signature(raw_features: Sequence[Any], seed: int) -> str:
    """What a stream cursor is valid for: the raw-feature schema (names +
    response flags, in order) and the reservoir seed. A resumed ingest
    under a different schema or seed re-ingests from chunk 0 instead of
    restoring the wrong reducer state."""
    import hashlib

    h = hashlib.sha256()
    h.update(f"seed={seed};".encode())
    for f in raw_features:
        h.update(f"{f.name}|{int(f.is_response)};".encode())
    return h.hexdigest()[:16]


def _rng_state_json(rng: random.Random) -> list:
    st = rng.getstate()
    return [st[0], list(st[1]), st[2]]


def _rng_restore(rng: random.Random, data: Sequence) -> None:
    rng.setstate((data[0], tuple(data[1]), data[2]))


# ------------------------------------------------------------------ engine
def stream_ingest(
    reader: Any,
    raw_features: Sequence[Any],
    *,
    recorder: Any = None,
    checkpoint: Any = None,
    resume: bool = False,
    max_buffer_rows: int | None = None,
    inflight: int | None = None,
    seed: int = 0,
    max_bins: int = 64,
) -> tuple[Dataset, dict[str, Any]]:
    """Drive the chunked out-of-core ingest: fetch → featurize (pipelined
    on the featurize pool, bounded in-flight window) → fold (monoid
    stats + bounded training buffer) → cursor. Returns the bounded
    training Dataset (every row when the source fits the buffer cap,
    else a seeded reservoir sample) and the ingest summary (chunk /
    quarantine / window accounting + the reduced fit stats).

    Fault semantics (resilience/faults.py): torn/corrupt chunks are
    quarantined — counted, never folded; memory pressure halves the
    in-flight window; ``SimulatedCrash`` propagates, and a later
    ``resume=True`` call restores the cursor and re-processes at most
    the one chunk that was in flight.
    """
    from ..featurize.parallel import pipeline_tasks
    from ..resilience import faults
    from ..resilience.faults import (
        CorruptChunkError,
        MemoryPressure,
        TornChunkError,
    )

    cap = stream_buffer_rows() if max_buffer_rows is None else int(max_buffer_rows)
    window = [stream_inflight() if inflight is None else max(1, int(inflight))]
    initial_window = window[0]
    sig = stream_signature(raw_features, seed)
    key_fn = getattr(reader, "key_fn", None)

    reducer = ChunkStatsReducer(max_bins)
    buffer: list[Any] = []
    rng = random.Random(seed)
    rows_seen = 0
    skip = 0
    torn: list[int] = []
    corrupt: list[int] = []
    oom_events = 0
    halvings = 0
    cursor_saves = 0
    resumed = False
    cursor_ok = [checkpoint is not None]

    if resume and checkpoint is not None:
        cur = checkpoint.load_stream_cursor(sig)
        if cur is not None:
            reducer = ChunkStatsReducer.from_json(cur["reducer"])
            buffer = list(cur["buffer"])
            rows_seen = int(cur["rowsSeen"])
            skip = int(cur["chunksDone"])
            torn = [int(i) for i in cur.get("torn", [])]
            corrupt = [int(i) for i in cur.get("corrupt", [])]
            _rng_restore(rng, cur["rngState"])
            resumed = True
            STREAM_STATS.bump("streamResumes")
            log.info(
                "stream ingest resumed at chunk %d (%d rows folded)",
                skip, rows_seen,
            )

    plan = faults.active()
    chunks_folded = 0
    chunks_skipped = 0
    chunks_done = skip  # source chunks consumed (folded OR quarantined)

    def _save_cursor() -> None:
        nonlocal cursor_saves
        if not cursor_ok[0]:
            return
        payload = {
            "version": 1,
            "signature": sig,
            "chunksDone": chunks_done,
            "rowsSeen": rows_seen,
            "reducer": reducer.to_json(),
            "buffer": buffer,
            "rngState": _rng_state_json(rng),
            "torn": torn,
            "corrupt": corrupt,
        }
        try:
            checkpoint.save_stream_cursor(payload)
        except TypeError as e:
            # non-JSON records: crash-resume degrades to re-ingest, the
            # ingest itself keeps going — warn once, not per chunk
            cursor_ok[0] = False
            log.warning(
                "stream cursor disabled (records not JSON-serializable: "
                "%s) — a crash re-ingests from chunk 0", e,
            )
            return
        cursor_saves += 1
        STREAM_STATS.bump("streamCursorSaves")

    def _fold_rows(batch: Sequence[Any]) -> None:
        nonlocal rows_seen
        for j, r in enumerate(batch):
            i = rows_seen + j
            if len(buffer) < cap:
                buffer.append(r)
            else:
                k = rng.randrange(i + 1)
                if k < cap:
                    buffer[k] = r
        rows_seen += len(batch)

    def _chunk_source() -> Iterator[tuple[int, Sequence[Any]]]:
        nonlocal chunks_skipped
        for idx, batch in enumerate(reader.stream_batches()):
            if idx < skip:
                # already folded before the crash: consumed and
                # discarded without featurize or fold — the < 1 chunk
                # rework guarantee
                chunks_skipped += 1
                STREAM_STATS.bump("streamChunksSkipped")
                continue
            yield idx, batch

    def _featurize_thunks() -> Iterator[Callable[[], tuple]]:
        for idx, batch in _chunk_source():
            def thunk(idx=idx, batch=batch):
                ds = SimpleReader(batch, key_fn).generate_dataset(
                    raw_features
                )
                return idx, batch, ds
            yield thunk

    for idx, batch, ds in pipeline_tasks(
        _featurize_thunks(), lambda: window[0]
    ):
        quarantine: str | None = None
        if plan is not None:
            try:
                plan.on_stream_fold(idx)
            except TornChunkError:
                quarantine = "torn"
                torn.append(idx)
                STREAM_STATS.bump("streamChunksTorn")
            except CorruptChunkError:
                quarantine = "corrupt"
                corrupt.append(idx)
                STREAM_STATS.bump("streamChunksCorrupt")
            except MemoryPressure as e:
                # degrade, don't die: shrink the in-flight window (takes
                # effect on the pipeline's next refill), fold the chunk
                oom_events += 1
                halved = max(1, window[0] // 2)
                if halved < window[0]:
                    halvings += 1
                    STREAM_STATS.bump("streamWindowHalvings")
                window[0] = halved
                STREAM_STATS.bump("streamOomEvents")
                log.warning(
                    "memory pressure on stream chunk %d (%s): in-flight "
                    "window now %d", idx, e, window[0],
                )
        chunks_done = idx + 1
        if quarantine is not None:
            STREAM_STATS.bump("streamChunksQuarantined")
            log.error(
                "stream chunk %d quarantined (%s) — not folded", idx,
                quarantine,
            )
            _save_cursor()
            continue
        reducer.fold_dataset(ds)
        _fold_rows(batch)
        chunks_folded += 1
        STREAM_STATS.bump("streamChunksFolded")
        STREAM_STATS.bump("streamRowsFolded", len(batch))
        if recorder is not None:
            try:
                recorder.poll_chunk_memory(idx)
            except Exception:  # observability must never break ingest
                pass
        _save_cursor()
        if plan is not None:
            plan.on_stream_chunk_end(idx)

    if not buffer:
        raise ValueError(
            "stream ingest produced no rows (every chunk empty or "
            "quarantined)"
        )
    train = SimpleReader(buffer, key_fn).generate_dataset(raw_features)
    summary = {
        "signature": sig,
        "resumed": resumed,
        "chunksDone": chunks_done,
        "chunksFolded": chunks_folded,
        "chunksSkippedOnResume": chunks_skipped,
        "chunksQuarantined": {"torn": torn, "corrupt": corrupt},
        "quarantinedTotal": len(torn) + len(corrupt),
        "rowsSeen": rows_seen,
        "rowsBuffered": len(buffer),
        "sampled": rows_seen > len(buffer),
        "window": {
            "initial": initial_window,
            "final": window[0],
            "halvings": halvings,
        },
        "oomEvents": oom_events,
        "cursorSaves": cursor_saves,
        "fitStats": reducer.finalize(),
    }
    return train, summary
