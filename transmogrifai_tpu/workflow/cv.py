"""Workflow-level cross-validation — refit the label-dependent DAG per fold.

Reference: core/.../OpWorkflow.scala:403-453 (fitStages withWorkflowCV) and
FitStagesUtil.cutDAG (core/.../utils/stages/FitStagesUtil.scala:302-355):
the DAG is cut into *before* (label-independent), *during* (label-dependent
estimators feeding the selector, e.g. SanityChecker), and *after*. Selector-
level CV would fit the during-stages once on all training rows — their
statistics (correlations, drop decisions) would then leak validation rows
into candidate selection. Workflow CV re-fits the during-DAG inside each
fold instead.

Mechanics here: for each fold, fit the DAG up to the selector's inputs on
the fold-train rows only, transform the fold-validation rows through those
fitted stages, and sweep every candidate × grid point on the resulting
arrays (per-candidate failure isolation as in OpValidator.scala:318-357).
The aggregated CandidateResults are handed to the ModelSelector, which then
skips its own validator and refits the winner on the full training data.

The sweep itself is pipelined: GLM families expose ``sweep_dispatch_masks``
(models/linear.py, models/logistic.py) which *dispatches* every grid lane
as one sharded program (SweepLayout PartitionSpecs over the execution
mesh's model axis, fold-level buffer donation — parallel/sweep.py) and
returns a collector closure. The fold loop dispatches all GLM lanes first,
fits the tree families while that device work is in flight, then collects.
Failure isolation is lane-granular: a lane whose predict/eval dies drops
only its own (uid, grid-point) entry; surviving lanes keep their results.

Fault tolerance: after each completed fold the aggregated results are
stashed (module-level, keyed by selector + fold plan + label hash). When a
mid-sweep host loss unwinds this function (HostLostError, a BaseException,
sails past the candidate handlers into the workflow failover loop) the
re-entry resumes from the last completed fold — strictly less than one
fold of rework. Any other exception clears the stash.
"""
from __future__ import annotations

import hashlib
import logging
import threading
import weakref
from typing import Any, Sequence

import numpy as np

from ..compiler import stats as _cstats
from ..dataset import Dataset
from ..evaluators.base import Evaluator
from ..resilience import distributed
from ..selector.model_selector import ModelSelector
from ..selector.validators import CandidateResult, expand_grid
from ..telemetry import runlog as _runlog
from ..telemetry import spans as _tspans
from ..types.columns import NumericColumn, VectorColumn
from .fit import apply_transformations_dag, fit_and_transform_dag

log = logging.getLogger(__name__)

#: completed-fold resume stash: key -> {"fold", "per_candidate", "failed",
#: "failed_lanes", "selector" (weakref — the stash serves only the same
#: selector instance)}. Written after every fold, consumed when the workflow
#: failover loop re-enters after a host loss, dropped on normal completion
#: or non-host-loss failure. Plain threading.Lock on purpose: the traced
#: lock census (analysis/schedule.py) covers device-side ordering, and
#: this host-only stash must not widen that static surface.
_RESUME: dict[tuple, dict] = {}
_RESUME_LOCK = threading.Lock()
_RESUME_MAX = 4


def _resume_key(selector: ModelSelector, n_folds: int, y_all: np.ndarray):
    label_h = hashlib.blake2s(
        np.ascontiguousarray(y_all).tobytes()
    ).hexdigest()[:16]
    return (selector.uid, n_folds, label_h)


def _copy_results(per_candidate: dict) -> dict:
    """Deep enough a post-stash mutation can't corrupt the stash: the
    metric lists are the only thing the fold loop appends to."""
    return {
        k: CandidateResult(
            model_name=v.model_name, model_uid=v.model_uid,
            grid=v.grid, metric_values=list(v.metric_values),
        )
        for k, v in per_candidate.items()
    }


def workflow_cv_results(
    selector: ModelSelector,
    train_data: Dataset,
    prefitted: dict[str, Any] | None = None,
) -> list[CandidateResult]:
    """Run the per-fold DAG refit + candidate sweep; returns aggregated
    candidate results for the selector to consume."""
    label_feature, vector_feature = selector.input_features
    targets = [label_feature, vector_feature]

    # label per row (labels may be derived; fit a throwaway label-only DAG)
    label_data, _ = fit_and_transform_dag(
        train_data, [label_feature], prefitted=prefitted
    )
    label_col = label_data[label_feature.name]
    assert isinstance(label_col, NumericColumn)
    y_all = label_col.values.astype(np.float64)

    # pre-validation prepare, mirroring ModelSelector.fit_arrays: DataCutter
    # trims rare labels BEFORE folds so fold-train and fold-val draw from
    # the same label universe the final refit will see
    from ..prep.splitters import DataCutter

    if isinstance(selector.splitter, DataCutter):
        keep = np.nonzero(selector.splitter.prepare(y_all))[0]
        train_data = train_data.take(keep)
        y_all = y_all[keep]

    folds = selector.validator.split_masks(y_all)
    evaluator = selector.evaluator
    per_candidate: dict[tuple[str, int], CandidateResult] = {}
    failed: set[str] = set()
    failed_lanes: set[tuple[str, int]] = set()

    resume_key = _resume_key(selector, len(folds), y_all)
    with _RESUME_LOCK:
        stash = _RESUME.get(resume_key)
        # the key alone is not proof of identity: selector uids restart
        # after uid_util.reset(), so an unrelated later run over the same
        # labels can collide. The stash only ever serves the failover
        # loop re-entering with the SAME selector instance — anything
        # else is stale and must refit from fold 0.
        if stash is not None and stash["selector"]() is not selector:
            _RESUME.pop(resume_key, None)
            stash = None
    start_fold = 0
    if stash is not None:
        start_fold = stash["fold"] + 1
        per_candidate = _copy_results(stash["per_candidate"])
        failed = set(stash["failed"])
        failed_lanes = set(stash["failed_lanes"])
        log.warning(
            "workflow CV resuming at fold %d/%d from the post-fold stash "
            "(host loss re-entry)", start_fold, len(folds),
        )

    try:
        for fold_i, (train_mask, val_mask) in enumerate(folds):
            if fold_i < start_fold:
                continue  # completed before the host loss; zero rework
            _run_fold(
                selector, train_data, prefitted, targets, label_feature,
                vector_feature, evaluator, folds, fold_i, train_mask,
                val_mask, per_candidate, failed, failed_lanes,
            )
            with _RESUME_LOCK:
                _RESUME.pop(resume_key, None)  # re-insert as newest
                _RESUME[resume_key] = {
                    "fold": fold_i,
                    "per_candidate": _copy_results(per_candidate),
                    "failed": set(failed),
                    "failed_lanes": set(failed_lanes),
                    "selector": weakref.ref(selector),
                }
                while len(_RESUME) > _RESUME_MAX:
                    _RESUME.pop(next(iter(_RESUME)))
    except BaseException as e:
        # keep the stash ONLY for host loss — the failover loop re-enters
        # this function and resumes. Real errors (and KeyboardInterrupt)
        # must not leave a stale stash to poison an unrelated later run.
        if not isinstance(e, distributed.HostLostError):
            with _RESUME_LOCK:
                _RESUME.pop(resume_key, None)
        raise
    with _RESUME_LOCK:
        _RESUME.pop(resume_key, None)

    results = list(per_candidate.values())
    if not results:
        raise RuntimeError("All model candidates failed workflow-level CV")
    return results


def _run_fold(
    selector,
    train_data,
    prefitted,
    targets,
    label_feature,
    vector_feature,
    evaluator,
    folds,
    fold_i: int,
    train_mask,
    val_mask,
    per_candidate: dict,
    failed: set,
    failed_lanes: set,
) -> None:
    """One fold: DAG refit, pipelined candidate sweep, ledger pulses."""
    # fold-boundary heartbeat pulse: a silent host is declared dead
    # between folds, and HostLostError (a BaseException) sails past the
    # candidate-isolation handlers below into the workflow failover loop
    controller = distributed.active_controller()
    if controller is not None:
        controller.on_fold(fold_i)
    # run-ledger pulse: fold boundaries land in the flight recorder's
    # per-fold timings and progress/ETA stream (telemetry/runlog.py)
    recorder = _runlog.active_recorder()
    if recorder is not None:
        recorder.on_fold_start(fold_i, total=len(folds))
    # compile-plane snapshot: the fold's lane occupancy / pad waste is the
    # delta of the sweep counters across this fold (per-fold run ledger)
    sweep_before = _cstats.snapshot()
    with _tspans.span("cv/fold", fold=fold_i):
        tr_idx = np.nonzero(train_mask)[0]
        va_idx = np.nonzero(val_mask)[0]
        fold_train = train_data.take(tr_idx)
        fold_val = train_data.take(va_idx)

        # the leak-free part: every estimator up to the selector's
        # inputs is re-fit on the fold's training rows only
        fitted_t, fitted_stages = fit_and_transform_dag(
            fold_train, targets, prefitted=prefitted
        )
        transformed_v = apply_transformations_dag(
            fold_val, targets, fitted_stages
        )

        xt, yt = _arrays(fitted_t, label_feature.name, vector_feature.name)
        xv, yv = _arrays(
            transformed_v, label_feature.name, vector_feature.name
        )
        ones = np.ones(len(yt), dtype=np.float32)

        # pipelined lanes: dispatch every GLM family's sweep first (async
        # device work behind a collector closure), fit the tree families
        # on the host while those lanes are in flight, then collect
        pending: list[tuple[Any, list[dict], Any, float]] = []
        host_side: list[tuple[Any, list[dict]]] = []
        for est, grid in selector.models:
            if est.uid in failed:
                continue
            points = expand_grid(grid)
            dispatcher = getattr(est, "sweep_dispatch_masks", None)
            if dispatcher is None:
                host_side.append((est, points))
                continue
            cand_t0 = _tspans.clock()
            try:
                handle = dispatcher(xt, yt, [ones], points)
                pending.append((est, points, handle, cand_t0))
            except Exception as e:  # dispatch-level (whole family)
                _drop_family(
                    est, points, e, per_candidate, failed, recorder,
                    fold_i, cand_t0, len(yt),
                )

        for est, points in host_side:
            cand_t0 = _tspans.clock()
            try:
                with _tspans.span(
                    "cv/candidate",
                    model=type(est).__name__, points=len(points),
                ):
                    _sweep_fold(
                        est, points, xt, yt, xv, yv, evaluator,
                        per_candidate, fold_i, failed_lanes,
                    )
                if recorder is not None:
                    recorder.on_candidate(
                        type(est).__name__, len(points),
                        _tspans.clock() - cand_t0,
                        rows=len(yt), fold=fold_i,
                    )
            except Exception as e:  # candidate-level isolation
                _drop_family(
                    est, points, e, per_candidate, failed, recorder,
                    fold_i, cand_t0, len(yt),
                )

        for est, points, handle, cand_t0 in pending:
            try:
                with _tspans.span(
                    "cv/candidate",
                    model=type(est).__name__, points=len(points),
                ):
                    models = handle()[0]
                    _eval_lanes(
                        est, points, models, xv, yv, evaluator,
                        per_candidate, failed_lanes,
                    )
                if recorder is not None:
                    recorder.on_candidate(
                        type(est).__name__, len(points),
                        _tspans.clock() - cand_t0,
                        rows=len(yt), fold=fold_i,
                    )
            except Exception as e:  # collect-level (whole family)
                _drop_family(
                    est, points, e, per_candidate, failed, recorder,
                    fold_i, cand_t0, len(yt),
                )

    if recorder is not None:
        recorder.on_fold_end(
            fold_i, total=len(folds),
            rows=int(train_mask.sum() + val_mask.sum()),
            sweep=_cstats.delta(sweep_before),
        )


def _drop_family(
    est, points, e, per_candidate, failed, recorder, fold_i, cand_t0, rows
) -> None:
    """Whole-family failure: lane-granular pops of exactly this family's
    grid keys (no full-dict rebuild — the sweep map scales with
    families × points × folds)."""
    log.warning(
        "Model %s failed workflow CV: %s", type(est).__name__, e,
    )
    if recorder is not None:
        recorder.on_candidate(
            type(est).__name__, len(points),
            _tspans.clock() - cand_t0,
            rows=rows, fold=fold_i, error=str(e),
        )
    failed.add(est.uid)
    for gi in range(len(points)):
        per_candidate.pop((est.uid, gi), None)


def _arrays(data: Dataset, label_name: str, vec_name: str):
    label = data[label_name]
    vec = data[vec_name]
    assert isinstance(label, NumericColumn) and isinstance(vec, VectorColumn)
    return (
        np.asarray(vec.values, dtype=np.float32),
        label.values.astype(np.float64),
    )


def _eval_lanes(
    est,
    points: list[dict[str, Any]],
    models: Sequence,
    xv: np.ndarray,
    yv: np.ndarray,
    evaluator: Evaluator,
    per_candidate: dict,
    failed_lanes: set,
) -> None:
    """Lane-granular scoring: a lane whose predict/eval dies loses only
    its own (uid, grid-point) entry; the other lanes of the same family
    keep their results and their earlier-fold metric values."""
    for gi, model in enumerate(models):
        key = (est.uid, gi)
        if key in failed_lanes:
            continue
        try:
            pred, prob, _ = model.predict_arrays(xv)
            metrics = evaluator.evaluate_arrays(yv, pred, prob)
            value = evaluator.metric_of(metrics)
        except Exception as e:  # lane-level isolation
            log.warning(
                "Lane %d (%s) of %s failed scoring: %s",
                gi, points[gi], type(est).__name__, e,
            )
            failed_lanes.add(key)
            per_candidate.pop(key, None)
            continue
        if key not in per_candidate:
            per_candidate[key] = CandidateResult(
                model_name=type(est).__name__,
                model_uid=est.uid,
                grid=points[gi],
                metric_values=[],
            )
        per_candidate[key].metric_values.append(value)


def _sweep_fold(
    est,
    points: list[dict[str, Any]],
    xt: np.ndarray,
    yt: np.ndarray,
    xv: np.ndarray,
    yv: np.ndarray,
    evaluator: Evaluator,
    per_candidate: dict,
    fold_i: int,
    failed_lanes: set | None = None,
) -> None:
    """One fold's fits for one model family. Fold vector widths can differ
    (per-fold SanityChecker drops differ) so models never cross folds."""
    ones = np.ones(len(yt), dtype=np.float32)
    batched = getattr(est, "fit_arrays_batched", None)
    if batched is not None:
        models = batched(xt, yt, ones, points)
    else:
        models = [est.with_params(**p).fit_arrays(xt, yt, ones) for p in points]
    _eval_lanes(
        est, points, models, xv, yv, evaluator, per_candidate,
        failed_lanes if failed_lanes is not None else set(),
    )
