"""Workflow-level cross-validation — refit the label-dependent DAG per fold.

Reference: core/.../OpWorkflow.scala:403-453 (fitStages withWorkflowCV) and
FitStagesUtil.cutDAG (core/.../utils/stages/FitStagesUtil.scala:302-355):
the DAG is cut into *before* (label-independent), *during* (label-dependent
estimators feeding the selector, e.g. SanityChecker), and *after*. Selector-
level CV would fit the during-stages once on all training rows — their
statistics (correlations, drop decisions) would then leak validation rows
into candidate selection. Workflow CV re-fits the during-DAG inside each
fold instead.

Mechanics here: for each fold, fit the DAG up to the selector's inputs on
the fold-train rows only, transform the fold-validation rows through those
fitted stages, and sweep every candidate × grid point on the resulting
arrays (per-candidate failure isolation as in OpValidator.scala:318-357).
The aggregated CandidateResults are handed to the ModelSelector, which then
skips its own validator and refits the winner on the full training data.
"""
from __future__ import annotations

import logging
from typing import Any, Sequence

import numpy as np

from ..dataset import Dataset
from ..evaluators.base import Evaluator
from ..resilience import distributed
from ..selector.model_selector import ModelSelector
from ..selector.validators import CandidateResult, expand_grid
from ..telemetry import runlog as _runlog
from ..telemetry import spans as _tspans
from ..types.columns import NumericColumn, VectorColumn
from .fit import apply_transformations_dag, fit_and_transform_dag

log = logging.getLogger(__name__)


def workflow_cv_results(
    selector: ModelSelector,
    train_data: Dataset,
    prefitted: dict[str, Any] | None = None,
) -> list[CandidateResult]:
    """Run the per-fold DAG refit + candidate sweep; returns aggregated
    candidate results for the selector to consume."""
    label_feature, vector_feature = selector.input_features
    targets = [label_feature, vector_feature]

    # label per row (labels may be derived; fit a throwaway label-only DAG)
    label_data, _ = fit_and_transform_dag(
        train_data, [label_feature], prefitted=prefitted
    )
    label_col = label_data[label_feature.name]
    assert isinstance(label_col, NumericColumn)
    y_all = label_col.values.astype(np.float64)

    # pre-validation prepare, mirroring ModelSelector.fit_arrays: DataCutter
    # trims rare labels BEFORE folds so fold-train and fold-val draw from
    # the same label universe the final refit will see
    from ..prep.splitters import DataCutter

    if isinstance(selector.splitter, DataCutter):
        keep = np.nonzero(selector.splitter.prepare(y_all))[0]
        train_data = train_data.take(keep)
        y_all = y_all[keep]

    folds = selector.validator.split_masks(y_all)
    evaluator = selector.evaluator
    per_candidate: dict[tuple[str, int], CandidateResult] = {}
    failed: set[str] = set()

    for fold_i, (train_mask, val_mask) in enumerate(folds):
        # fold-boundary heartbeat pulse: a silent host is declared dead
        # between folds, and HostLostError (a BaseException) sails past the
        # candidate-isolation handlers below into the workflow failover loop
        controller = distributed.active_controller()
        if controller is not None:
            controller.on_fold(fold_i)
        # run-ledger pulse: fold boundaries land in the flight recorder's
        # per-fold timings and progress/ETA stream (telemetry/runlog.py)
        recorder = _runlog.active_recorder()
        if recorder is not None:
            recorder.on_fold_start(fold_i, total=len(folds))
        with _tspans.span("cv/fold", fold=fold_i):
            tr_idx = np.nonzero(train_mask)[0]
            va_idx = np.nonzero(val_mask)[0]
            fold_train = train_data.take(tr_idx)
            fold_val = train_data.take(va_idx)

            # the leak-free part: every estimator up to the selector's
            # inputs is re-fit on the fold's training rows only
            fitted_t, fitted_stages = fit_and_transform_dag(
                fold_train, targets, prefitted=prefitted
            )
            transformed_v = apply_transformations_dag(
                fold_val, targets, fitted_stages
            )

            xt, yt = _arrays(fitted_t, label_feature.name, vector_feature.name)
            xv, yv = _arrays(
                transformed_v, label_feature.name, vector_feature.name
            )

            for est, grid in selector.models:
                if est.uid in failed:
                    continue
                points = expand_grid(grid)
                cand_t0 = _tspans.clock()
                try:
                    with _tspans.span(
                        "cv/candidate",
                        model=type(est).__name__, points=len(points),
                    ):
                        _sweep_fold(
                            est, points, xt, yt, xv, yv, evaluator,
                            per_candidate, fold_i,
                        )
                    if recorder is not None:
                        recorder.on_candidate(
                            type(est).__name__, len(points),
                            _tspans.clock() - cand_t0,
                            rows=len(yt), fold=fold_i,
                        )
                except Exception as e:  # candidate-level isolation
                    log.warning(
                        "Model %s failed workflow CV: %s",
                        type(est).__name__, e,
                    )
                    if recorder is not None:
                        recorder.on_candidate(
                            type(est).__name__, len(points),
                            _tspans.clock() - cand_t0,
                            rows=len(yt), fold=fold_i, error=str(e),
                        )
                    failed.add(est.uid)
                    per_candidate = {
                        k: v
                        for k, v in per_candidate.items()
                        if v.model_uid != est.uid
                    }

        if recorder is not None:
            recorder.on_fold_end(
                fold_i, total=len(folds),
                rows=int(train_mask.sum() + val_mask.sum()),
            )

    results = list(per_candidate.values())
    if not results:
        raise RuntimeError("All model candidates failed workflow-level CV")
    return results


def _arrays(data: Dataset, label_name: str, vec_name: str):
    label = data[label_name]
    vec = data[vec_name]
    assert isinstance(label, NumericColumn) and isinstance(vec, VectorColumn)
    return (
        np.asarray(vec.values, dtype=np.float32),
        label.values.astype(np.float64),
    )


def _sweep_fold(
    est,
    points: list[dict[str, Any]],
    xt: np.ndarray,
    yt: np.ndarray,
    xv: np.ndarray,
    yv: np.ndarray,
    evaluator: Evaluator,
    per_candidate: dict,
    fold_i: int,
) -> None:
    """One fold's fits for one model family. Fold vector widths can differ
    (per-fold SanityChecker drops differ) so models never cross folds."""
    ones = np.ones(len(yt), dtype=np.float32)
    batched = getattr(est, "fit_arrays_batched", None)
    if batched is not None:
        models = batched(xt, yt, ones, points)
    else:
        models = [est.with_params(**p).fit_arrays(xt, yt, ones) for p in points]
    for gi, model in enumerate(models):
        pred, prob, _ = model.predict_arrays(xv)
        metrics = evaluator.evaluate_arrays(yv, pred, prob)
        value = evaluator.metric_of(metrics)
        key = (est.uid, gi)
        if key not in per_candidate:
            per_candidate[key] = CandidateResult(
                model_name=type(est).__name__,
                model_uid=est.uid,
                grid=points[gi],
                metric_values=[],
            )
        per_candidate[key].metric_values.append(value)
