"""Chunked parallel featurization — a worker pool over row chunks.

The host kernels this plane leans on (native tokenize/intern/scatter in
``libtptpu.so`` via ctypes, numpy ufuncs) all release the GIL, so plain
threads scale the featurize plane across cores without pickling columns
to worker processes. Row-pointwise vectorizer transforms partition
perfectly: chunk outputs concatenate (or land in disjoint row slices of
one preallocated matrix) bit-identically to the single-threaded pass.

Env knobs:

* ``TPTPU_FEATURIZE_THREADS`` — worker count; ``0``/``1`` disables the
  pool (default: ``min(4, cpu_count)``).
* ``TPTPU_FEATURIZE_CHUNK`` — minimum rows per chunk (default 8192);
  batches smaller than two chunks run single-threaded.
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Sequence

import numpy as np

from . import stats as fstats

_LOCK = threading.Lock()
_POOL: ThreadPoolExecutor | None = None
_POOL_SIZE = 0


def featurize_threads() -> int:
    env = os.environ.get("TPTPU_FEATURIZE_THREADS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    return min(4, os.cpu_count() or 1)


def min_chunk_rows() -> int:
    try:
        return max(1, int(os.environ.get("TPTPU_FEATURIZE_CHUNK", "8192")))
    except ValueError:
        return 8192


def pool_enabled() -> bool:
    return featurize_threads() >= 2


def _pool() -> ThreadPoolExecutor:
    global _POOL, _POOL_SIZE
    n = featurize_threads()
    with _LOCK:
        if _POOL is None or _POOL_SIZE != n:
            if _POOL is not None:
                _POOL.shutdown(wait=False)
            _POOL = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="tptpu-featurize"
            )
            _POOL_SIZE = n
        return _POOL


def chunk_ranges(n: int, max_chunks: int | None = None) -> list[tuple[int, int]]:
    """Split ``n`` rows into at most ``workers`` contiguous chunks of at
    least ``min_chunk_rows()`` each; a single chunk means 'don't bother'."""
    workers = featurize_threads()
    if max_chunks is not None:
        workers = min(workers, max_chunks)
    if workers < 2 or n < 2 * min_chunk_rows():
        return [(0, n)]
    # floor division keeps every chunk AT LEAST min_chunk_rows tall
    chunks = max(1, min(workers, n // min_chunk_rows()))
    step = -(-n // chunks)
    return [(i, min(i + step, n)) for i in range(0, n, step)]


def run_tasks(tasks: Sequence[Callable[[], object]]) -> list:
    """Run thunks on the featurize pool (in-order results). Falls back to
    sequential execution for a single task or a disabled pool. Exceptions
    propagate (first failing task, like the sequential loop). Worker busy
    seconds and wall clock land in the featurizeStats ledger.

    Nested calls (a chunked stage inside an already-parallel fit) run
    sequentially instead of deadlocking the fixed-size pool."""
    if len(tasks) == 1 or not pool_enabled():
        return [t() for t in tasks]
    if getattr(_ON_POOL, "active", False):
        return [t() for t in tasks]
    busy = [0.0] * len(tasks)

    def _timed(i: int, t: Callable[[], object]):
        _ON_POOL.active = True
        try:
            t0 = time.perf_counter()
            out = t()
            busy[i] = time.perf_counter() - t0
            return out
        finally:
            _ON_POOL.active = False

    from ..telemetry import spans as _tspans

    with _tspans.span("featurize/pool", tasks=len(tasks)):
        t0 = time.perf_counter()
        futures = [_pool().submit(_timed, i, t) for i, t in enumerate(tasks)]
        results = [f.result() for f in futures]
        wall = time.perf_counter() - t0
    fstats.stats().record_pool(
        len(tasks), sum(busy), wall, featurize_threads()
    )
    return results


def pipeline_tasks(thunks, window) -> "Iterator[object]":
    """Sliding-window pipeline over an ITERATOR of thunks: at most
    ``window`` tasks are submitted ahead on the featurize pool while
    earlier results are consumed, and results yield in submission order.
    This is the out-of-core ingest's backpressure primitive
    (workflow/stream.py): chunk k+1 featurizes on the pool while chunk k
    reduces on the caller's thread, and the bounded window keeps host RSS
    flat regardless of how many chunks the source produces.

    ``window`` may be a callable re-read before every refill, so the
    caller can SHRINK the in-flight window mid-stream (the memory-
    pressure degradation path) and the change takes effect on the next
    submission. Sequential fallback when the pool is disabled or the
    caller already runs on it (same nested-call rule as ``run_tasks``).
    Pulling the next thunk from ``thunks`` happens on the caller's
    thread, so source-side effects (fetch retries, fault hooks) stay
    deterministic."""
    win = window if callable(window) else (lambda: window)
    it = iter(thunks)
    if not pool_enabled() or getattr(_ON_POOL, "active", False):
        for t in it:
            yield t()
        return

    import collections

    def _on_pool(t):
        _ON_POOL.active = True
        try:
            return t()
        finally:
            _ON_POOL.active = False

    pending: collections.deque = collections.deque()
    done = False
    tasks = 0
    busy = 0.0
    t0 = time.perf_counter()
    try:
        while True:
            target = max(1, int(win()))
            while not done and len(pending) < target:
                try:
                    t = next(it)
                except StopIteration:
                    done = True
                    break
                pending.append(_pool().submit(_on_pool, t))
                tasks += 1
            if not pending:
                break
            f = pending.popleft()
            b0 = time.perf_counter()
            out = f.result()
            busy += time.perf_counter() - b0
            yield out
    finally:
        # an abandoned generator must not leak queued work
        for f in pending:
            f.cancel()
        if tasks:
            fstats.stats().record_pool(
                tasks, busy, time.perf_counter() - t0, featurize_threads()
            )


_ON_POOL = threading.local()


def slice_rows(col, a: int, b: int):
    """Contiguous row slice of a column — the chunk-partition primitive.
    Unlike ``take(arange)``, list/object payloads slice at C speed and the
    interned CSR layout rebases offsets without a gather."""
    from ..types.columns import (
        ListColumn,
        MapColumn,
        NumericColumn,
        SetColumn,
        SparseMatrix,
        TextColumn,
        VectorColumn,
    )
    from .interning import InternedTextList, TokenCodes

    if isinstance(col, InternedTextList):
        tc = col.interned
        ta, tb = int(tc.offsets[a]), int(tc.offsets[b])
        return InternedTextList(
            col.feature_type,
            TokenCodes(
                tc.codes[ta:tb], tc.offsets[a:b + 1] - ta, tc.vocab
            ),
        )
    if isinstance(col, NumericColumn):
        return NumericColumn(
            col.feature_type, col.values[a:b], col.mask[a:b]
        )
    if isinstance(col, TextColumn):
        return TextColumn(col.feature_type, col.values[a:b])
    if isinstance(col, (ListColumn, MapColumn, SetColumn)):
        out = type(col)(col.feature_type, col.values[a:b])
        cached = getattr(col, "_extract_cache", None)
        if cached is not None:
            # per-key extraction (ops.maps.map_key_values) slices at C
            # speed — chunk workers must not re-walk the row dicts
            out._extract_cache = (
                cached[0],
                {k: lst[a:b] for k, lst in cached[1].items()},
            )
        return out
    if isinstance(col, VectorColumn):
        if isinstance(col.values, SparseMatrix):
            return col.take(np.arange(a, b, dtype=np.int64))
        return VectorColumn(
            col.feature_type, col.values[a:b], col.metadata
        )
    return col.take(np.arange(a, b, dtype=np.int64))
