"""Code-array kernels for the interned text plane.

Every transform over interned tokens reduces to the same shape: map the
batch vocabulary (small) to output columns once, then scatter per token
into a ``[N, width]`` count/presence block. The dense scatter runs in
``native.code_bincount`` (GIL released) with an exact numpy fallback; wide
blocks come back as :class:`types.columns.SparseMatrix` so a
``vocab_size = 2**18`` count vectorizer never materializes an
``N × 2^18`` dense matrix (the Spark-default width that used to allocate
~1 GB per 1k rows).

Also here: the vectorized calendar-period kernel backing the time-period
transformers (bit-identical to the scalar ``period_value``) and the
segment-mean kernel feeding the Word2Vec transform.
"""
from __future__ import annotations

import os

import numpy as np

from ..types.columns import SparseMatrix
from .interning import TokenCodes

#: vocabularies wider than this emit SparseMatrix blocks instead of dense
#: [N, W] float32 (override with TPTPU_DENSE_VOCAB_MAX)
DENSE_VOCAB_MAX = int(os.environ.get("TPTPU_DENSE_VOCAB_MAX", "4096"))


def dense_vocab_max() -> int:
    return DENSE_VOCAB_MAX


def map_vocab(vocab: list, index: dict) -> np.ndarray:
    """code → output column (−1 = dropped): one dict hit per UNIQUE token."""
    out = np.empty(len(vocab), dtype=np.int32)
    for i, t in enumerate(vocab):
        out[i] = index.get(t, -1)
    return out


def hash_vocab(
    vocab: list, num_buckets: int, seed: int = 42, prefix: str = ""
) -> np.ndarray:
    """code → murmur3 bucket: each UNIQUE token is hashed once (native
    batch hash), token occurrences then ride the code array."""
    from .. import native

    if not vocab:
        return np.zeros(0, dtype=np.int32)
    terms = [prefix + t for t in vocab] if prefix else list(vocab)
    h = native.murmur3_batch(terms, seed)
    return (h % np.uint32(num_buckets)).astype(np.int32)


def term_count_block(
    tc: TokenCodes,
    code_to_col: np.ndarray,
    width: int,
    binary: bool = False,
    out: np.ndarray | None = None,
    col_offset: int = 0,
) -> np.ndarray:
    """Dense [N, width] count/presence block from interned codes (written
    in place when ``out`` is given — the fused-assembly path)."""
    from .. import native

    if out is None:
        out = np.zeros((tc.num_rows, width), dtype=np.float32)
        col_offset = 0
    if tc.num_tokens:
        native.code_bincount(
            tc.codes, tc.offsets, code_to_col, out,
            binary=binary, col_offset=col_offset,
        )
    return out


def unique_pairs(
    rows: np.ndarray, cols: np.ndarray, width: int
) -> tuple[np.ndarray, np.ndarray]:
    """Distinct (row, col) pairs, sorted row-major — the shared dedup
    primitive behind binary term blocks and document frequencies."""
    flat = np.unique(
        rows.astype(np.int64) * np.int64(width) + cols.astype(np.int64)
    )
    return flat // width, flat % width


def distinct_pair_bincount(
    rows: np.ndarray, cols: np.ndarray, width: int
) -> np.ndarray:
    """Per-column count of DISTINCT (row, col) pairs — document frequency
    over token/bucket occurrences, one bincount, no densification."""
    _, cols_u = unique_pairs(rows, cols, width)
    return np.bincount(cols_u, minlength=width)


def term_count_sparse(
    tc: TokenCodes,
    code_to_col: np.ndarray,
    width: int,
    binary: bool = False,
) -> SparseMatrix:
    """Sparse (COO, implicit 1.0 per pair) variant of term_count_block —
    duplicates accumulate into counts; binary mode pre-dedupes per row."""
    if tc.num_tokens == 0:
        return SparseMatrix(
            np.zeros(0, np.int32), np.zeros(0, np.int32), (tc.num_rows, width)
        )
    cols = code_to_col[tc.codes]
    rows = tc.row_index()
    keep = cols >= 0
    rows, cols = rows[keep], cols[keep].astype(np.int64)
    if binary and len(rows):
        rows, cols = unique_pairs(rows, cols, width)
    return SparseMatrix(
        rows.astype(np.int32), cols.astype(np.int32), (tc.num_rows, width)
    )


# ------------------------------------------------------- calendar periods
_MS_PER_HOUR = 3_600_000
_MS_PER_DAY = 86_400_000


def calendar_periods(ms: np.ndarray, period: str) -> np.ndarray:
    """Vectorized twin of ``ops.time_period.period_value`` over an int64
    epoch-millis array (UTC, joda conventions: Monday=1, months 1-12,
    WeekOfMonth 1-based). Bit-identical to the scalar path — pinned by the
    featurize parity suite over a ±5000-year sweep."""
    ms = np.asarray(ms, dtype=np.int64)
    if period == "HourOfDay":
        return (ms // _MS_PER_HOUR) % 24
    if period == "DayOfWeek":
        return ((ms // _MS_PER_DAY + 3) % 7) + 1  # epoch day 0 = Thursday
    # calendar math via numpy datetime64 (floor division handles pre-epoch)
    days = (ms // _MS_PER_DAY).astype("datetime64[D]")
    if period == "DayOfMonth":
        return (days - days.astype("datetime64[M]")).astype(np.int64) + 1
    if period == "DayOfYear":
        return (days - days.astype("datetime64[Y]")).astype(np.int64) + 1
    if period == "MonthOfYear":
        return (days.astype("datetime64[M]").astype(np.int64) % 12) + 1
    if period == "WeekOfMonth":
        dom = (days - days.astype("datetime64[M]")).astype(np.int64)
        return dom // 7 + 1
    if period == "WeekOfYear":
        # ISO-8601 week number: the week containing this date's Thursday,
        # counted within that Thursday's year
        day_idx = ms // _MS_PER_DAY
        dow0 = (day_idx + 3) % 7  # 0 = Monday
        thursday = (day_idx + (3 - dow0)).astype("datetime64[D]")
        jan1 = thursday.astype("datetime64[Y]").astype("datetime64[D]")
        return (thursday - jan1).astype(np.int64) // 7 + 1
    raise ValueError(f"Unknown time period {period}")


def segment_mean_f32(
    vectors: np.ndarray, tc_codes: np.ndarray, offsets: np.ndarray
) -> np.ndarray:
    """Per-row mean of ``vectors[code]`` over each CSR segment, zeros for
    empty rows: the Word2Vec transform feed.

    Byte parity with the historical per-row ``vectors[ids].mean(axis=0)``
    requires BOTH the same float32 accumulation order (sequential over a
    segment's rows — ``np.add.reduceat`` associates differently) and
    np.mean's division semantics (float32 sums over INTEGER counts:
    float64 elementwise divide cast back to float32). The segment sums
    run as one vectorized add per token POSITION — position j of every
    row accumulates in the same step, so each segment sees the exact
    sequential association at a cost of max-tokens-per-row array ops."""
    n = len(offsets) - 1
    dim = vectors.shape[1] if vectors.size else 0
    out = np.zeros((n, dim), dtype=np.float32)
    counts = np.diff(offsets)
    if dim == 0 or not len(tc_codes):
        return out
    nonempty = np.nonzero(counts > 0)[0]
    seg_counts = counts[nonempty]
    starts = offsets[:-1][nonempty]
    gathered = vectors[tc_codes]  # [T, D] float32
    sums = np.zeros((len(nonempty), dim), dtype=np.float32)
    max_len = int(seg_counts.max())
    for j in range(max_len):
        sel = seg_counts > j
        sums[sel] += gathered[starts[sel] + j]
    out[nonempty] = (sums / seg_counts[:, None]).astype(np.float32)
    return out
