"""Columnar featurization engine — the raw→vector plane with zero
per-row Python in the hot path.

Four pillars (mirroring the compile plane of ``transmogrifai_tpu.compiler``):

* **token-code interning** (``interning``): each text column is tokenized
  ONCE into a flat int32 code array + row offsets (CSR layout) over a
  per-batch vocabulary; downstream text stages (n-grams, stop words,
  count/hashing TF, the embeddings feed) operate on the code arrays with
  numpy/native kernels instead of list-of-list-of-str;
* **fused block assembly** (``engine``): a planner walks the fitted DAG,
  groups the vectorizer sequence stages feeding ``VectorsCombiner``, and
  has them write straight into one preallocated ``[N, width]`` matrix —
  no per-stage output temporaries, no combiner concat;
* **chunked parallel featurization** (``parallel``): a thread pool over
  row chunks (the native kernels release the GIL) feeding both train-time
  ingest and batch/columnar serving, wired into the PR-4 ``prefetch_f32``
  seam;
* **featurizeStats** (``stats``): the process-wide ledger — per-stage
  rows/s, bytes assembled, pool utilization, interning and
  fallback-kernel counts — surfaced in the selector summary,
  ``summary_pretty()``, ``score_fn.metadata()`` and the bench JSON.

See ``docs/featurization.md``.
"""
from . import stats  # noqa: F401
from .interning import (  # noqa: F401
    InternedTextList,
    TokenCodes,
    interned_of,
    tokenize_text_column,
)
