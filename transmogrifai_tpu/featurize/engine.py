"""Featurize planner — fused block assembly over the fitted DAG.

``ops.base._assemble_values`` already assembles each SEQUENCE STAGE into
one buffer; the remaining full-plane copy is ``VectorsCombiner``
concatenating every stage's matrix into the final feature vector. The
:class:`FusionPlanner` kills that copy for dense planes:

* the plan owner (DAG fit ingest, the serving closure) builds one planner
  over its ordered fitted stage list; the planner walks it, finds the
  ``VectorsCombiner`` and the vectorizer sequence stages feeding it;
* the first batch runs unfused and *learns* each member's dense width;
* every later batch allocates ONE ``[N, total_width]`` float32 buffer;
  each member's ``transform_columns`` writes its blocks straight into its
  column slice (``ops.base._CachedMetaVectorizer`` asks
  :func:`current_sink`), and the combiner returns the shared buffer
  wholesale — zero per-stage output temporaries, zero concat.

Planes with sparse members (wide hashed text under the COO path) keep the
sparse end-to-end assembly — fusion only ever engages when every member
emits dense blocks. The sink is thread-local, so concurrent scoring
closures can't cross-write."""
from __future__ import annotations

import threading

import numpy as np

from . import stats as fstats

_TLS = threading.local()


class _Sink:
    """One batch's shared assembly buffer."""

    __slots__ = ("buf", "layout", "written")

    def __init__(self, buf: np.ndarray, layout: dict):
        self.buf = buf
        self.layout = layout  # stage uid -> (col offset, width)
        self.written: set[str] = set()


class FusionPlanner:
    """Per-plan fusion state (owned by one DAG execution context)."""

    def __init__(self, plan) -> None:
        from ..ops.base import _CachedMetaVectorizer
        from ..ops.combiner import VectorsCombiner

        self.disabled = True
        self.member_uids: list[str] = []
        self.combiner_uid: str | None = None
        #: uid -> width, learned from the first (unfused) batch
        self.widths: dict[str, int] = {}
        combiners = [t for t in plan if isinstance(t, VectorsCombiner)]
        if len(combiners) != 1:
            return
        combiner = combiners[0]
        by_output = {t.output_name: t for t in plan}
        members = []
        stages = []
        for name in combiner.input_names:
            t = by_output.get(name)
            if t is None or not isinstance(t, _CachedMetaVectorizer):
                return  # passthrough vector / non-sequence producer
            members.append(t.uid)
            stages.append(t)
        if not members:
            return
        self.combiner_uid = combiner.uid
        self.member_uids = members
        self._member_stages = stages
        self.disabled = False

    def prime(self) -> bool:
        """Learn member widths from fit-static metadata (each vectorizer's
        populated ``_meta_cache``) without waiting for a first unfused
        batch — the standing service calls this at start so batch #1
        already assembles into the single fused buffer. A member whose
        fit-time metadata is absent stays unlearned (that member's width
        arrives via :meth:`note_output` as before). Returns ``ready()``.

        Safe to over-prime: if a member later emits sparse at runtime it
        bypasses the sink, ``fused_result`` sees an incomplete write set,
        and the combiner falls back to plain assembly."""
        if self.disabled:
            return False
        for t in getattr(self, "_member_stages", ()):
            if t.uid in self.widths:
                continue
            cached = getattr(t, "_meta_cache", None)
            if cached is not None:
                try:
                    self.widths[t.uid] = int(cached[1].size)
                    continue
                except Exception:
                    pass
            meta = getattr(t, "new_metadata", None)
            if meta is not None:
                try:
                    self.widths[t.uid] = int(meta.size)
                except Exception:
                    pass
        return self.ready()

    # ------------------------------------------------------------- learning
    def note_output(self, uid: str, column) -> None:
        """Record a member's dense width from its first unfused output;
        a sparse member disables fusion for the whole plane."""
        if self.disabled or uid not in self.member_uids:
            return
        if getattr(column, "is_sparse", False):
            self.disabled = True
            return
        self.widths[uid] = int(column.values.shape[1])

    def ready(self) -> bool:
        return not self.disabled and all(
            u in self.widths for u in self.member_uids
        )

    def plane_width(self) -> int | None:
        """Total [N, width] plane width once every member width is known
        (the fused scoring graph cross-checks its statically-derived
        widths against this)."""
        if not self.ready():
            return None
        return sum(self.widths[u] for u in self.member_uids)

    # ------------------------------------------------------------- batches
    def batch(self, num_rows: int) -> "_BatchContext":
        return _BatchContext(self, num_rows)


class _BatchContext:
    def __init__(self, planner: FusionPlanner, num_rows: int):
        self.planner = planner
        self.num_rows = num_rows
        self.sink: _Sink | None = None

    def __enter__(self):
        p = self.planner
        if p.ready():
            total = p.plane_width()
            layout = {}
            off = 0
            for u in p.member_uids:
                layout[u] = (off, p.widths[u])
                off += p.widths[u]
            buf = np.empty((self.num_rows, total), dtype=np.float32)
            self.sink = _Sink(buf, layout)
            _TLS.sink = self.sink
            _TLS.planner = p
        else:
            _TLS.sink = None
            _TLS.planner = p
        return self

    def __exit__(self, *exc):
        _TLS.sink = None
        _TLS.planner = None
        return False


def current_sink(uid: str):
    """(buffer, col_offset, width) when a fused batch is active and the
    stage is a member, else None."""
    sink: _Sink | None = getattr(_TLS, "sink", None)
    if sink is None:
        return None
    got = sink.layout.get(uid)
    if got is None:
        return None
    sink.written.add(uid)
    return sink.buf, got[0], got[1]


def note_output(uid: str, column) -> None:
    planner = getattr(_TLS, "planner", None)
    if planner is not None:
        planner.note_output(uid, column)


def fused_result(uid: str, cols) -> np.ndarray | None:
    """The shared buffer, when ``uid`` is the combiner of the active sink
    and every member wrote its slice this batch (the combiner's zero-copy
    return)."""
    sink: _Sink | None = getattr(_TLS, "sink", None)
    planner = getattr(_TLS, "planner", None)
    if sink is None or planner is None or uid != planner.combiner_uid:
        return None
    if sink.written != set(sink.layout):
        return None
    # belt and braces: every input must be a view into the sink buffer
    for c in cols:
        vals = getattr(c, "values", None)
        if vals is None or getattr(vals, "base", None) is not sink.buf:
            return None
    fstats.stats().record_fused(sink.buf.nbytes)
    return sink.buf
