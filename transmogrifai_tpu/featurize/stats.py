"""``featurizeStats`` — the featurization plane's process-wide ledger
(the raw→vector counterpart of ``compiler.stats``).

One thread-safe counter object records every featurization event: rows
pushed through vectorizer stages (with per-stage wall-clock, so the
summary can report rows/s per stage), bytes assembled into output
matrices, fused-assembly buffers that skipped the combiner copy, pool
tasks with their busy seconds (utilization = busy / (wall × workers)),
interning builds (native vs fallback), numpy-fallback kernel calls (the
native library was absent or predates a kernel), and stale-library
detections from the ABI stamp in ``native.py``.

Counters are cumulative per process. Consumers that want a per-phase view
(the model selector's summary, the bench rows) take a ``snapshot()``
before and report ``delta(before)`` after.

Counter dict, lock, and snapshot/delta arithmetic come from the shared
:class:`telemetry.metrics.LedgerCore` — the same core under compileStats
and the resilience ledger, so a ``telemetry.snapshot_lock()`` read is
consistent across all of them. The ledger registers itself as the
``featurize`` source of ``telemetry.render_prometheus()``.
"""
from __future__ import annotations

from ..telemetry import metrics as _tm

_COUNTER_KEYS = (
    "rowsFeaturized",        # rows through instrumented vectorizer stages
    "bytesAssembled",        # bytes written into assembled output blocks
    "stagesExecuted",        # instrumented stage transform calls
    "fusedAssemblies",       # stage outputs written into a shared fusion
                             # buffer (combiner concat skipped)
    "fusedBytes",            # bytes that skipped the combiner copy
    "poolTasks",             # chunk tasks executed on the featurize pool
    "chunkedStages",         # stage transforms split across row chunks
    "internNativeBuilds",    # token/value interning served by libtptpu
    "internFallbackBuilds",  # interning built by the Python dict path
    "fallbackKernels",       # numpy-fallback kernel invocations
    "staleLibraryKernels",   # kernels missing from a stale cached .so
)


class FeaturizeStats(_tm.LedgerCore):
    """Thread-safe counters; per-stage rows/seconds and pool busy/wall
    seconds ride along as floats."""

    def __init__(self) -> None:
        super().__init__(_COUNTER_KEYS)
        #: operation name -> [rows, seconds] — rows/s per stage kind
        self._stage: dict[str, list[float]] = {}
        self._fallback_by_kernel: dict[str, int] = {}
        self._stale_kernels: list[str] = []
        self._pool_busy_s = 0.0
        self._pool_wall_s = 0.0
        self._pool_workers = 0

    # ------------------------------------------------------------ recording
    def record_stage(
        self, name: str, rows: int, seconds: float, out_bytes: int = 0
    ) -> None:
        with self._lock:
            self._counts["stagesExecuted"] += 1
            self._counts["rowsFeaturized"] += rows
            self._counts["bytesAssembled"] += out_bytes
            cell = self._stage.setdefault(name, [0.0, 0.0])
            cell[0] += rows
            cell[1] += seconds

    def record_fused(self, out_bytes: int) -> None:
        with self._lock:
            self._counts["fusedAssemblies"] += 1
            self._counts["fusedBytes"] += out_bytes

    def record_pool(
        self, tasks: int, busy_s: float, wall_s: float, workers: int
    ) -> None:
        with self._lock:
            self._counts["poolTasks"] += tasks
            self._counts["chunkedStages"] += 1
            self._pool_busy_s += busy_s
            self._pool_wall_s += wall_s
            self._pool_workers = max(self._pool_workers, workers)

    def record_intern(self, native: bool) -> None:
        key = "internNativeBuilds" if native else "internFallbackBuilds"
        with self._lock:
            self._counts[key] += 1

    def count_fallback(self, kernel: str) -> None:
        with self._lock:
            self._counts["fallbackKernels"] += 1
            self._fallback_by_kernel[kernel] = (
                self._fallback_by_kernel.get(kernel, 0) + 1
            )

    def count_stale_library(self, kernel: str) -> None:
        with self._lock:
            self._counts["staleLibraryKernels"] += 1
            self._stale_kernels.append(kernel)

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> dict:
        """JSON-able view. ``poolUtilization`` is busy seconds over
        wall × workers (1.0 = every worker busy for every chunked call);
        ``stageRowsPerSec`` reports per-operation throughput."""
        with self._lock:
            out: dict = dict(self._counts)
            out["poolBusySeconds"] = round(self._pool_busy_s, 3)
            out["poolWallSeconds"] = round(self._pool_wall_s, 3)
            out["poolWorkers"] = self._pool_workers
            out["fallbacksByKernel"] = dict(self._fallback_by_kernel)
            out["staleKernels"] = list(self._stale_kernels)
            stage = {
                name: {
                    "rows": int(rows),
                    "seconds": round(sec, 4),
                    "rowsPerSec": round(rows / sec) if sec > 0 else None,
                }
                for name, (rows, sec) in sorted(self._stage.items())
            }
        out["stageRowsPerSec"] = stage
        out["poolUtilization"] = _pool_utilization(out)
        return out

    def reset(self) -> None:
        with self._lock:
            self._reset_counts()
            self._stage = {}
            self._fallback_by_kernel = {}
            self._stale_kernels = []
            self._pool_busy_s = 0.0
            self._pool_wall_s = 0.0
            self._pool_workers = 0


def _pool_utilization(counts: dict) -> float | None:
    denom = counts["poolWallSeconds"] * max(counts["poolWorkers"], 1)
    return _tm.ratio(counts["poolBusySeconds"], denom) if denom > 0 else None


_STATS = FeaturizeStats()
_tm.REGISTRY.register_source("featurize", _STATS.snapshot)


def stats() -> FeaturizeStats:
    return _STATS


def snapshot() -> dict:
    return _STATS.snapshot()


def delta(before: dict) -> dict:
    """Per-phase view: current snapshot minus an earlier ``snapshot()``
    (utilization recomputed from the deltas, not differenced)."""
    now = _STATS.snapshot()
    out: dict = _tm.counter_delta(now, before, _COUNTER_KEYS)
    for k in ("poolBusySeconds", "poolWallSeconds"):
        out[k] = _tm.float_delta(now, before, k)
    out["poolWorkers"] = now["poolWorkers"]
    before_stage = before.get("stageRowsPerSec", {})
    stage = {}
    for name, cell in now["stageRowsPerSec"].items():
        prev = before_stage.get(name, {})
        rows = cell["rows"] - prev.get("rows", 0)
        sec = round(cell["seconds"] - prev.get("seconds", 0.0), 4)
        if rows or sec:
            stage[name] = {
                "rows": rows,
                "seconds": sec,
                "rowsPerSec": round(rows / sec) if sec > 0 else None,
            }
    out["stageRowsPerSec"] = stage
    out["fallbacksByKernel"] = _tm.named_delta(
        now["fallbacksByKernel"], before.get("fallbacksByKernel", {})
    )
    out["staleKernels"] = now["staleKernels"]
    out["poolUtilization"] = _pool_utilization(out)
    return out
