"""Token-code interning — tokenize a text column ONCE into a flat int32
code array + row offsets (CSR layout) over a per-batch vocabulary.

The reference's text stages pass ``Seq[Seq[String]]`` between every stage
(TextTokenizer → NGram → StopWordsRemover → CountVectorizer/HashingTF);
the CPython equivalent (list-of-list-of-str) makes every downstream stage
pay a per-row, per-token interpreter loop. Interning replaces the token
payload with three arrays:

* ``codes``   — int32 ``[T]``: one vocabulary code per token occurrence;
* ``offsets`` — int64 ``[N+1]``: row r's tokens are
  ``codes[offsets[r]:offsets[r+1]]``;
* ``vocab``   — the unique token strings, first-occurrence order — the
  ONLY per-token Python strings ever built.

Downstream transforms become vocabulary-sized dict work (tiny) plus numpy
/native array kernels over the codes (``featurize.kernels``). The build
itself runs in one native pass (``tp_intern_tokens``, GIL released) for
ASCII columns, with an exact-Unicode Python fallback.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..types.columns import ListColumn
from ..utils.text import tokenize
from . import stats as fstats


@dataclasses.dataclass
class TokenCodes:
    """CSR token layout of one text/token-list column."""

    codes: np.ndarray    # int32 [T]
    offsets: np.ndarray  # int64 [N+1]
    vocab: list[str]

    @property
    def num_rows(self) -> int:
        return len(self.offsets) - 1

    @property
    def num_tokens(self) -> int:
        return int(self.offsets[-1])

    def row_counts(self) -> np.ndarray:
        return np.diff(self.offsets)

    def row_index(self) -> np.ndarray:
        """int64 [T]: the row of each token occurrence."""
        return np.repeat(
            np.arange(self.num_rows, dtype=np.int64), self.row_counts()
        )

    def vocab_array(self) -> np.ndarray:
        arr = getattr(self, "_vocab_arr", None)
        if arr is None:
            arr = np.empty(len(self.vocab), dtype=object)
            arr[:] = self.vocab
            self._vocab_arr = arr
        return arr

    def to_lists(self) -> list[list[str]]:
        """Materialize list-of-list-of-str (row-dict scoring, tests)."""
        toks = self.vocab_array()[self.codes] if len(self.vocab) else self.codes
        off = self.offsets
        return [
            toks[off[r]:off[r + 1]].tolist() for r in range(self.num_rows)
        ]

    def take_rows(self, indices: np.ndarray) -> "TokenCodes":
        indices = np.asarray(indices)
        if indices.dtype == bool:
            indices = np.nonzero(indices)[0]
        indices = indices.astype(np.int64)
        counts = self.row_counts()[indices]
        offsets = np.zeros(len(indices) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        total = int(offsets[-1])
        starts = self.offsets[:-1][indices]
        pos = (
            np.arange(total, dtype=np.int64)
            - np.repeat(offsets[:-1], counts)
            + np.repeat(starts, counts)
        )
        return TokenCodes(self.codes[pos], offsets, self.vocab)


class InternedTextList(ListColumn):
    """A ``ListColumn`` whose payload is a :class:`TokenCodes` — the
    hot-path text stages read ``.interned`` and never materialize the
    list-of-lists; ``.values`` materializes lazily for anything else
    (row-dict rendering, tests, legacy consumers)."""

    def __init__(self, feature_type: type, interned: TokenCodes):
        self.feature_type = feature_type
        self.interned = interned
        self._values: list | None = None

    @property
    def values(self) -> list:  # type: ignore[override]
        if self._values is None:
            self._values = self.interned.to_lists()
        return self._values

    def __len__(self) -> int:
        return self.interned.num_rows

    def to_list(self) -> list:
        return list(self.values)

    def take(self, indices: np.ndarray) -> "InternedTextList":
        return InternedTextList(
            self.feature_type, self.interned.take_rows(indices)
        )


def _intern_lists(rows: list) -> TokenCodes:
    """Dict-based interner over already-tokenized rows (fallback, and the
    adapter for plain ListColumn inputs)."""
    index: dict[str, int] = {}
    vocab: list[str] = []
    codes: list[int] = []
    offsets = np.zeros(len(rows) + 1, dtype=np.int64)
    for r, row in enumerate(rows):
        if row:
            for t in row:
                code = index.get(t)
                if code is None:
                    code = index[t] = len(vocab)
                    vocab.append(t)
                codes.append(code)
        offsets[r + 1] = len(codes)
    fstats.stats().record_intern(native=False)
    return TokenCodes(np.asarray(codes, dtype=np.int32), offsets, vocab)


def tokenize_text_column(
    values,
    to_lowercase: bool = True,
    min_token_length: int = 1,
) -> TokenCodes:
    """Tokenize one text column (str | None per row) into interned codes.
    Null/empty rows get zero tokens (TextTokenizer semantics). ASCII
    columns ride one native pass; columns with non-ASCII rows keep those
    rows on the exact-Unicode Python tokenizer."""
    from .. import native

    n = len(values)
    texts: list[str] = []
    rows_idx: list[int] = []
    for r, v in enumerate(values):
        if v:
            texts.append(v if isinstance(v, str) else str(v))
            rows_idx.append(r)
    if not texts:
        return TokenCodes(
            np.zeros(0, dtype=np.int32), np.zeros(n + 1, dtype=np.int64), []
        )
    res = native.intern_tokens(
        texts, to_lowercase=to_lowercase, min_token_length=min_token_length
    )
    if res is not None and len(rows_idx) == n:
        codes, offsets, vocab = res
        fstats.stats().record_intern(native=True)
        return TokenCodes(codes, offsets, vocab)
    if res is not None:
        # nulls present: scatter the compact per-row counts onto all rows
        codes, sub_offsets, vocab = res
        counts = np.zeros(n, dtype=np.int64)
        counts[rows_idx] = np.diff(sub_offsets)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        fstats.stats().record_intern(native=True)
        return TokenCodes(codes, offsets, vocab)
    # non-ASCII rows (or no native lib): native pass over the ASCII rows,
    # exact-Unicode Python tokenizer for the rest, one shared vocabulary
    ascii_texts, ascii_rows = [], []
    slow: list[tuple[int, str]] = []
    for r, v in zip(rows_idx, texts):
        if v.isascii():
            ascii_texts.append(v)
            ascii_rows.append(r)
        else:
            slow.append((r, v))
    index: dict[str, int] = {}
    vocab = []
    row_payload: list = [None] * n
    if ascii_texts:
        res = native.intern_tokens(
            ascii_texts, to_lowercase=to_lowercase,
            min_token_length=min_token_length,
        )
        if res is None:  # no native lib at all: everything per-row
            slow = list(zip(ascii_rows, ascii_texts)) + slow
            slow.sort()
        else:
            a_codes, a_offsets, vocab = res
            index = {t: i for i, t in enumerate(vocab)}
            for i, r in enumerate(ascii_rows):
                row_payload[r] = a_codes[a_offsets[i]:a_offsets[i + 1]]
            fstats.stats().record_intern(native=True)
    for r, v in slow:
        toks = tokenize(v, to_lowercase, min_token_length)
        rc = np.empty(len(toks), dtype=np.int32)
        for i, t in enumerate(toks):
            code = index.get(t)
            if code is None:
                code = index[t] = len(vocab)
                vocab.append(t)
            rc[i] = code
        row_payload[r] = rc
    if slow:
        fstats.stats().record_intern(native=False)
    counts = np.asarray(
        [0 if p is None else len(p) for p in row_payload], dtype=np.int64
    )
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    nonempty = [p for p in row_payload if p is not None and len(p)]
    codes = (
        np.concatenate(nonempty).astype(np.int32, copy=False)
        if nonempty else np.zeros(0, dtype=np.int32)
    )
    return TokenCodes(codes, offsets, vocab)


def interned_of(col) -> TokenCodes:
    """The TokenCodes of a token-list column: pass-through for
    :class:`InternedTextList`, one cached dict-interning pass otherwise."""
    got = getattr(col, "interned", None)
    if got is not None:
        return got
    cached = getattr(col, "_interned_cache", None)
    if cached is not None:
        return cached
    tc = _intern_lists(col.values)
    try:
        col._interned_cache = tc
    except Exception:  # pragma: no cover - exotic column type
        pass
    return tc


def interned_output(feature_type: type, interned: TokenCodes) -> InternedTextList:
    return InternedTextList(feature_type, interned)


def intern_values(values: list) -> tuple[np.ndarray, list, np.ndarray]:
    """Whole-VALUE interning: ``(codes int32[n], uniques, counts int64[U])``
    with uniques in first-occurrence order — the capped-Counter primitive
    behind TextStats / one-hot fits / pivot transforms. Callers map None
    out first. Str values ride the native byte-exact pass when the
    library is present; non-str values (or no library) take the
    raw-keyed dict interner — the historical per-value semantics."""
    from .. import native

    res = native.intern_values(values)
    if res is not None:
        codes, first_rows, counts = res
        fstats.stats().record_intern(native=True)
        return codes, [values[int(i)] for i in first_rows], counts
    index: dict[str, int] = {}
    uniques: list[str] = []
    counts_l: list[int] = []
    codes = np.empty(len(values), dtype=np.int32)
    for i, v in enumerate(values):
        code = index.get(v)
        if code is None:
            code = index[v] = len(uniques)
            uniques.append(v)
            counts_l.append(0)
        counts_l[code] += 1
        codes[i] = code
    fstats.stats().record_intern(native=False)
    return codes, uniques, np.asarray(counts_l, dtype=np.int64)
